"""Token-level (continuous-batching) generation scheduler.

Reference parity: Orca's iteration-level scheduling + vLLM's block
tables — the serving loop the reference system gets from its vLLM
backend.  The repo's request/queue loop (``generation_service``'s
single worker) serves one whole batch to completion before admitting
the next request; here scheduling happens at TOKEN granularity:

- the batch is ``max_slots`` fixed LANES, each holding (or not) one
  live sequence — an active-mask, never a shape change;
- ONE jitted decode program (``models.llama.paged_decode_step`` over
  the ``rl/kv_cache`` block pool) advances every active lane by one
  token per iteration; admissions and evictions mutate host-side
  arrays (block tables, positions, masks) only, so the program
  compiles exactly once and never retraces across arbitrary traffic;
- prompts prefill in fixed-size CHUNKS (one chunk per iteration,
  round-robin) interleaved with running decodes — a 10k-token prompt
  costs the running sequences a bounded slice per iteration instead
  of stalling them for its whole prefill;
- a sequence leaves its slot the moment it hits EOS or its token
  budget, and the freed slot admits the next queued prompt on the
  SAME iteration — mixed-length traffic never waits for the longest
  sequence in a batch (the dense-batch pathology this replaces).

Allocation disciplines (``DLROVER_TPU_KV_INCREMENTAL``, default on):

- **incremental** (vLLM-style): admission reserves only the prompt's
  blocks plus ``DLROVER_TPU_KV_GROW_BLOCKS`` headroom and is gated by
  a free-pool watermark (``DLROVER_TPU_KV_ADMIT_WATERMARK``); block
  tables grow on demand at decode time, and when the pool runs dry
  the LOWEST-PRIORITY running sequence (fewest tokens generated,
  youngest admission) is PREEMPTED — its blocks freed, the request
  requeued at the queue head carrying its generated tail, so it
  re-prefills and resumes deterministically (sampling is a pure
  function of (seed, position), so the final tokens are identical —
  pinned by test).  Prefix caching rides this mode: full prompt
  blocks are content-hashed into the pool's ref-counted shared-block
  index, so a repeated system prompt maps the same physical blocks.
- **reservation** (``=0``, the PR-13 kill-switch path): admission
  reserves the worst case (prompt + max_new) up front — no growth, no
  preemption, no sharing; byte-for-byte the old behavior.

Multi-token decode (``DLROVER_TPU_DECODE_STEPS=K``, default 1): one
fused compiled program runs K greedy self-drafting decode steps plus
ONE batched verify forward (``models.llama.paged_verify_step``) per
iteration, then accepts the longest draft prefix the verify pass
agrees with — at temperature 0 the emitted stream is exactly the K=1
loop's (each draft step IS the K=1 computation), at sampled
temperatures acceptance is rejection-style (every emitted token is
sampled from its true conditional).  Host dispatch drops by up to K×
on the CPU-bound path — the ``dispatches`` counter measures it.

Determinism: each request's tokens are sampled with
``fold_in(PRNGKey(seed), position)`` — a function of (seed, position)
only, independent of which slot/iteration served it.  The same
request produces the same tokens whether it ran alone, continuously
batched, after a drain-requeue or preemption-resume, or on a
different replica; tests pin tail parity against an unbatched
reference on exactly this property.
"""

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import numpy as np

from dlrover_tpu.common.env import (
    decode_steps,
    fleet_interactive_slots,
    kv_admit_watermark,
    kv_grow_blocks,
    kv_incremental_enabled,
    kv_prefix_cache_enabled,
    serve_fleet_enabled,
    serve_obs_enabled,
)
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.rl.kv_cache import (
    BlockPool,
    OutOfBlocksError,
    PagedCacheConfig,
    extract_block_regions,
    init_block_pool,
    insert_block_regions,
    pool_can_ever_hold,
    prefix_block_keys,
)

SLO_INTERACTIVE = "interactive"
SLO_BATCH = "batch"

FINISH_EOS = "eos"
FINISH_LENGTH = "length"


def _empty_tokens() -> np.ndarray:
    return np.zeros((0,), np.int32)


def _empty_logprobs() -> np.ndarray:
    return np.zeros((0,), np.float32)


@dataclass
class GenRequest:
    """One generation request (prompt in, sampled tail out).

    ``resume_tokens`` carries a preempted sequence's generated tail:
    on re-admission the scheduler re-prefills prompt+tail and resumes
    sampling at the next position — (seed, position)-purity makes the
    continuation identical to the uninterrupted run."""

    req_id: int
    prompt: np.ndarray  # [P] int32
    max_new: int
    seed: int = 0
    submit_t: float = field(default_factory=time.monotonic)
    resume_tokens: np.ndarray = field(default_factory=_empty_tokens)
    # per-token logprobs of the resume tail (logprob capture mode;
    # same length as ``resume_tokens`` when known, NaN-padded when the
    # tail crossed a boundary that could not carry them)
    resume_logprobs: np.ndarray = field(
        default_factory=_empty_logprobs
    )
    # request-tracing state (ISSUE 16; inert when
    # DLROVER_TPU_SERVE_OBS=0).  ``submit_wall`` is the wall-clock
    # anchor that rode the dispatcher→replica ring (0 = in-process
    # submit, fall back to this process's anchored clock); the rest
    # survive preemption so the serve_request span tells the request's
    # WHOLE life, not its last incarnation's
    submit_wall: float = 0.0
    preempts: int = 0
    hit_blocks: int = 0
    queue_wait_s: float = 0.0
    token_times: List[float] = field(default_factory=list)
    # fleet-serving lanes (ISSUE 17; inert when
    # DLROVER_TPU_SERVE_FLEET=0): the SLO class steers admission
    # order, the reserved-slot quota, and preemption rank; the tenant
    # key drives weighted fair-share within a class.  ``shipped`` is
    # the disaggregated-decode adoption payload (prefilled KV block
    # regions + the first sampled token) — consumed at admission,
    # never carried through a preempt/requeue (the resume path
    # re-prefills deterministically instead).
    slo_class: str = SLO_BATCH
    tenant: str = ""
    shipped: Optional[Dict] = None
    # how the dispatcher picked this replica (least_outstanding /
    # affinity / ship); "local" for in-process submits — stamped on
    # the serve_request span so routing decisions are auditable
    route: str = "local"


@dataclass
class GenResult:
    req_id: int
    tokens: np.ndarray  # [P + new] int32 (prompt verbatim + tail)
    finish_reason: str
    new_tokens: int
    latency_s: float
    stats: Dict = field(default_factory=dict)
    # per-generated-token actor logprobs (length == new_tokens) when
    # the scheduler runs with ``capture_logprobs``; empty otherwise —
    # the flywheel's streamed ``old_logp``, eliminating the trainer's
    # recompute forward over the rollout
    logprobs: np.ndarray = field(default_factory=_empty_logprobs)


@dataclass(frozen=True)
class SchedulerConfig:
    """Serving geometry: every field is a STATIC shape input of the
    compiled programs — change one and you get (exactly) one new
    compile, change traffic and you get none."""

    max_slots: int = 8  # decode lanes
    block_size: int = 16  # tokens per KV block
    num_blocks: int = 256  # pool size incl. the null block
    max_seq_len: int = 512  # longest prompt+tail a slot may hold
    prefill_chunk: int = 32  # prompt tokens prefilled per iteration
    max_new_default: int = 64
    temperature: float = 1.0
    eos_id: Optional[int] = None

    @property
    def max_blocks_per_seq(self) -> int:
        return -(-self.max_seq_len // self.block_size)


@dataclass
class _Slot:
    req: Optional[GenRequest] = None
    phase: str = "free"  # free | prefill | decode
    prefill_pos: int = 0
    prefill_tokens: np.ndarray = field(default_factory=_empty_tokens)
    prefill_len: int = 0  # prompt + resume-tail tokens to prefill
    prefix_keys: List[str] = field(default_factory=list)
    shared_upto: int = 0  # prompt blocks registered in the index
    admit_seq: int = 0  # monotonic admission order (victim policy)
    generated: List[int] = field(default_factory=list)
    logprobs: List[float] = field(default_factory=list)
    first_token_t: float = 0.0


class ContinuousBatchingScheduler:
    """The token-level serving loop over a paged KV cache.

    ``model_cfg`` is a ``models.llama.LlamaConfig`` (or any config the
    supplied ``paged_decode_fn`` / ``paged_prefill_fn`` /
    ``paged_verify_fn`` accept — the same injection seam
    ``KVCacheBackend`` uses)."""

    def __init__(
        self,
        model_cfg,
        sched: Optional[SchedulerConfig] = None,
        paged_decode_fn: Optional[Callable] = None,
        paged_prefill_fn: Optional[Callable] = None,
        paged_verify_fn: Optional[Callable] = None,
        events=None,
        replica: str = "",
        role: str = "unified",
        capture_logprobs: bool = False,
        draft_cfg=None,
        draft_decode_fn: Optional[Callable] = None,
        draft_prefill_fn: Optional[Callable] = None,
        verify_write_fn: Optional[Callable] = None,
    ):
        import jax
        import jax.numpy as jnp
        from functools import partial

        from dlrover_tpu.models import llama

        self._jax, self._jnp = jax, jnp
        self.cfg = model_cfg
        self.sched = sched or SchedulerConfig()
        s = self.sched
        if s.prefill_chunk < 1 or s.max_slots < 1:
            raise ValueError("prefill_chunk and max_slots must be >= 1")
        self._events = events
        # request-lifecycle tracing (ISSUE 16): pinned at construction
        # like the allocation discipline — a scheduler never changes
        # observability personality mid-flight.  ``replica`` labels the
        # serve_request spans with where the request actually ran.
        self._serve_obs = serve_obs_enabled()
        self.replica = replica
        self._last_prefill_req = -1
        self._params = None
        self._decode_model = paged_decode_fn or partial(
            llama.paged_decode_step, cfg=model_cfg
        )
        self._prefill_model = paged_prefill_fn or partial(
            llama.paged_prefill_chunk, cfg=model_cfg
        )
        self._verify_model = paged_verify_fn or partial(
            llama.paged_verify_step, cfg=model_cfg
        )
        # flywheel extensions (ISSUE 20): both OFF by default — the
        # no-flag construction compiles exactly the closures above, so
        # DLROVER_TPU_FLYWHEEL=0 callers reproduce today's programs.
        # ``capture_logprobs``: every sampled token also returns its
        # actor logprob (log-softmax of the RAW fp32 logits — the
        # trainer's ``token_logprobs`` semantics, so streamed tails
        # replace the old_logp recompute forward bit-for-bit).
        # ``draft_cfg``: a separate small DRAFT model runs the K-step
        # draft loop against its OWN pool while the policy verifies
        # (and writes its K/V) in one ``paged_verify_write_step``.
        self.capture_logprobs = bool(capture_logprobs)
        self._draft_cfg = draft_cfg
        self._draft_params = None
        self._draft_decode_model = (
            draft_decode_fn
            or (
                partial(llama.paged_decode_step, cfg=draft_cfg)
                if draft_cfg is not None else None
            )
        )
        self._draft_prefill_model = (
            draft_prefill_fn
            or (
                partial(llama.paged_prefill_chunk, cfg=draft_cfg)
                if draft_cfg is not None else None
            )
        )
        self._verify_write_model = (
            verify_write_fn
            or partial(llama.paged_verify_write_step, cfg=model_cfg)
        )

        # allocation/decode discipline (env-pinned at construction so
        # a scheduler never changes personality mid-flight)
        self.incremental = kv_incremental_enabled()
        self.grow_blocks = kv_grow_blocks()
        self.admit_watermark = kv_admit_watermark()
        self.prefix_cache = (
            self.incremental and kv_prefix_cache_enabled()
        )
        self.decode_k = decode_steps()
        # fleet lanes (ISSUE 17) — pinned at construction like the
        # allocation discipline.  ``role``: "unified" (default) serves
        # prefill+decode in place; "prefill" stops at prefill
        # completion and parks the filled block regions + first token
        # on ``self.shipped`` for the worker loop to ship out.
        self.fleet = serve_fleet_enabled()
        if role not in ("unified", "prefill"):
            raise ValueError(f"unknown scheduler role {role!r}")
        self.role = role if self.fleet else "unified"
        self.interactive_slots = (
            min(fleet_interactive_slots(), s.max_slots - 1)
            if self.fleet else 0
        )
        self.shipped: List[Dict] = []
        self.shipped_out = 0
        self.shipped_in = 0
        # separate-drafter speculative decode needs a K>1 window and a
        # lane that both prefills and decodes locally (a prefill-role
        # worker never drafts; shipped adoptions degrade draft quality
        # for that prompt, never correctness — emission is always the
        # policy's verify stream in draft mode)
        self.draft = (
            draft_cfg is not None
            and self.decode_k > 1
            and self.role == "unified"
        )
        # results of adoptions that finished on their first token when
        # no finished-list was threaded in (drained by step())
        self._adopt_finished: List[GenResult] = []

        cache_cfg = PagedCacheConfig(
            n_layers=model_cfg.n_layers,
            n_kv_heads=model_cfg.n_kv_heads,
            head_dim=model_cfg.head_dim,
            num_blocks=s.num_blocks,
            block_size=s.block_size,
            dtype=model_cfg.dtype,
        )
        self.pool_cfg = cache_cfg
        self.block_pool = BlockPool(cache_cfg)
        self._pool = init_block_pool(cache_cfg)
        # the draft pool mirrors the policy pool's GEOMETRY (same
        # block ids, tables, block size) with the DRAFT model's shapes
        # — one host-side allocator drives both
        self._draft_pool = None
        if self.draft:
            self._draft_pool = init_block_pool(
                PagedCacheConfig(
                    n_layers=draft_cfg.n_layers,
                    n_kv_heads=draft_cfg.n_kv_heads,
                    head_dim=draft_cfg.head_dim,
                    num_blocks=s.num_blocks,
                    block_size=s.block_size,
                    dtype=draft_cfg.dtype,
                )
            )

        # host mirrors of the fixed-shape device inputs
        S, MB = s.max_slots, s.max_blocks_per_seq
        self._tables = np.zeros((S, MB), np.int32)
        self._positions = np.zeros((S,), np.int32)
        self._active = np.zeros((S,), bool)
        self._next_token = np.zeros((S,), np.int32)
        self._keys = np.zeros((S, 2), np.uint32)
        self._slots = [_Slot() for _ in range(S)]
        self._queue: List[GenRequest] = []
        # queued interactive requests, maintained at every queue
        # mutation: admission is per-step hot-loop work and a
        # saturated queue runs hundreds deep, so the common case
        # ("is anything interactive waiting?") must not scan it
        self._queued_interactive = 0
        # full-prompt block keys memoized per req_id: _admit probes
        # the blocked queue head every iteration, and SHA-1-hashing a
        # long system prompt 3x per step is hot-loop host work
        # (dropped at finish; preemption re-admits the same req_id)
        self._prompt_keys: Dict[int, List[str]] = {}
        self._next_req_id = 0
        self._prefill_rr = 0  # round-robin pointer over prefill slots
        self._admit_counter = 0
        self.draining = False

        # counters the serving gauges/bench read
        self.total_new_tokens = 0
        self.total_prefill_tokens = 0
        self.iterations = 0
        self.preemptions = 0
        self.grown_blocks = 0
        self.dispatches = 0  # jitted-program invocations (host cost)
        self.accepted_tokens = 0  # multi-token decode: tokens kept
        self.lane_windows = 0  # multi-token decode: (lane, window)s
        self._window_hit_blocks = 0  # prefix hits since last emit

        temp = float(s.temperature)

        def _sample_rows(logits, keys, sample_pos):
            """logits [S, V]; keys [S, 2] request base keys;
            sample_pos [S] the OUTPUT position each token will occupy
            — the (seed, position)-only sampling contract."""
            if temp <= 0:
                return jnp.argmax(logits, axis=-1).astype(jnp.int32)
            folded = jax.vmap(jax.random.fold_in)(keys, sample_pos)
            return jax.vmap(
                lambda k, l: jax.random.categorical(k, l / temp)
            )(folded, logits).astype(jnp.int32)

        def _sample_grid(logits, keys, sample_pos):
            """logits [S, K, V]; sample_pos [S, K] — the K-window
            version of ``_sample_rows`` (same contract per cell)."""
            if temp <= 0:
                return jnp.argmax(logits, axis=-1).astype(jnp.int32)
            folded = jax.vmap(
                lambda k, ps: jax.vmap(
                    lambda p: jax.random.fold_in(k, p)
                )(ps)
            )(keys, sample_pos)
            return jax.vmap(
                jax.vmap(
                    lambda k, l: jax.random.categorical(k, l / temp)
                )
            )(folded, logits).astype(jnp.int32)

        def _decode(params, pool, tokens, tables, positions, active,
                    keys):
            logits, pool = self._decode_model(
                params, tokens, pool, tables, positions, active
            )
            nxt = _sample_rows(logits, keys, positions + 1)
            return pool, nxt

        K = self.decode_k

        def _decode_multi(params, pool, tokens, tables, positions,
                          active, keys):
            """K fused decode steps: greedy self-drafting (each draft
            step IS the K=1 computation, so at temp 0 drafts are the
            reference stream) + ONE batched verify forward whose
            real-rule samples gate acceptance.  Returns (pool, drafts
            [S, K], verify samples [S, K], leading-match count [S])."""
            drafts = []
            tok, pos = tokens, positions
            for _ in range(K):
                logits, pool = self._decode_model(
                    params, tok, pool, tables, pos, active
                )
                d = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                drafts.append(d)
                tok, pos = d, pos + 1
            drafts = jnp.stack(drafts, axis=1)  # [S, K]
            # verify inputs: the window tokens actually occupying
            # positions p..p+K-1 (current token + first K-1 drafts) —
            # their K/V is already in the pool from the draft loop
            vin = jnp.concatenate(
                [tokens[:, None], drafts[:, :-1]], axis=1
            )
            vlogits = self._verify_model(
                params, vin, pool, tables, positions, active
            )  # [S, K, V]
            steps = jnp.arange(K, dtype=positions.dtype)
            ver = _sample_grid(
                vlogits, keys, positions[:, None] + 1 + steps[None]
            )
            eq = (ver == drafts).astype(jnp.int32)
            n_match = jnp.sum(jnp.cumprod(eq, axis=1), axis=1)
            return pool, drafts, ver, n_match

        def _prefill(params, pool, chunk, table, start):
            logits, pool = self._prefill_model(
                params, chunk, pool, table, start
            )
            return pool, logits

        def _sample_one(logits_row, key, sample_pos):
            return _sample_rows(
                logits_row[None], key[None], sample_pos[None]
            )[0]

        CAP = self.capture_logprobs

        def _lp_rows(logits, toks):
            """Actor logprob of each sampled token: log-softmax of
            the RAW fp32 logits (temperature-free — the trainer's
            ``token_logprobs`` contract), gathered at the token."""
            lp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
            return jnp.take_along_axis(
                lp, toks[..., None].astype(jnp.int32), axis=-1
            )[..., 0]

        def _decode_lp(params, pool, tokens, tables, positions,
                       active, keys):
            logits, pool = self._decode_model(
                params, tokens, pool, tables, positions, active
            )
            nxt = _sample_rows(logits, keys, positions + 1)
            return pool, nxt, _lp_rows(logits, nxt)

        def _decode_multi_lp(params, pool, tokens, tables, positions,
                             active, keys):
            """``_decode_multi`` + per-token logprobs: lp of each
            draft under its draft-step logits (the temp<=0 emission)
            and of each verify sample under the verify logits (the
            temp>0 emission)."""
            drafts, lps = [], []
            tok, pos = tokens, positions
            for _ in range(K):
                logits, pool = self._decode_model(
                    params, tok, pool, tables, pos, active
                )
                d = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                drafts.append(d)
                lps.append(_lp_rows(logits, d))
                tok, pos = d, pos + 1
            drafts = jnp.stack(drafts, axis=1)  # [S, K]
            lp_drafts = jnp.stack(lps, axis=1)  # [S, K]
            vin = jnp.concatenate(
                [tokens[:, None], drafts[:, :-1]], axis=1
            )
            vlogits = self._verify_model(
                params, vin, pool, tables, positions, active
            )
            steps = jnp.arange(K, dtype=positions.dtype)
            ver = _sample_grid(
                vlogits, keys, positions[:, None] + 1 + steps[None]
            )
            lp_ver = _lp_rows(vlogits, ver)
            eq = (ver == drafts).astype(jnp.int32)
            n_match = jnp.sum(jnp.cumprod(eq, axis=1), axis=1)
            return pool, drafts, ver, n_match, lp_drafts, lp_ver

        def _decode_multi_draft(params, draft_params, pool, dpool,
                                tokens, tables, positions, active,
                                keys):
            """Separate-drafter window: the DRAFT model runs the
            K-step greedy draft loop against its OWN pool; the policy
            scores the window with ONE ``paged_verify_write_step``
            that also writes the policy K/V the drafter no longer
            produces.  Emission is ALWAYS the verify stream (``ver``
            is the policy's true conditioned sample at every
            temperature — the drafts only gate how far the window is
            trusted)."""
            drafts = []
            tok, pos = tokens, positions
            for _ in range(K):
                dlogits, dpool = self._draft_decode_model(
                    draft_params, tok, dpool, tables, pos, active
                )
                d = jnp.argmax(dlogits, axis=-1).astype(jnp.int32)
                drafts.append(d)
                tok, pos = d, pos + 1
            drafts = jnp.stack(drafts, axis=1)  # [S, K]
            vin = jnp.concatenate(
                [tokens[:, None], drafts[:, :-1]], axis=1
            )
            vlogits, pool = self._verify_write_model(
                params, vin, pool, tables, positions, active
            )
            steps = jnp.arange(K, dtype=positions.dtype)
            ver = _sample_grid(
                vlogits, keys, positions[:, None] + 1 + steps[None]
            )
            lp_ver = (
                _lp_rows(vlogits, ver) if CAP
                else jnp.zeros(ver.shape, jnp.float32)
            )
            eq = (ver == drafts).astype(jnp.int32)
            n_match = jnp.sum(jnp.cumprod(eq, axis=1), axis=1)
            return pool, dpool, drafts, ver, n_match, lp_ver

        def _draft_prefill(dparams, dpool, chunk, table, start):
            logits, dpool = self._draft_prefill_model(
                dparams, chunk, dpool, table, start
            )
            return dpool, logits

        def _sample_one_lp(logits_row, key, sample_pos):
            tok = _sample_rows(
                logits_row[None], key[None], sample_pos[None]
            )
            return tok[0], _lp_rows(logits_row[None], tok)[0]

        self._decode_jit = jax.jit(
            _decode_lp if CAP else _decode, donate_argnums=(1,)
        )
        self._decode_multi_jit = (
            jax.jit(
                _decode_multi_lp if CAP else _decode_multi,
                donate_argnums=(1,),
            )
            if K > 1 else None
        )
        self._decode_multi_draft_jit = (
            jax.jit(_decode_multi_draft, donate_argnums=(2, 3))
            if self.draft else None
        )
        self._draft_prefill_jit = (
            jax.jit(_draft_prefill, donate_argnums=(1,))
            if self.draft else None
        )
        self._prefill_jit = jax.jit(_prefill, donate_argnums=(1,))
        self._sample_jit = jax.jit(
            _sample_one_lp if CAP else _sample_one
        )

    # ------------------------------------------------------------- API
    def sync_weights(self, params, draft_params=None):
        """Adopt the trainer's / publisher's current params (reference
        swap; in-flight sequences continue on the new weights — the
        vLLM-backend weight-refresh semantics).  ``draft_params`` is
        the co-published DRAFT model (flywheel separate-drafter mode);
        until the first draft publish arrives the scheduler falls back
        to self-drafting."""
        self._params = params
        if draft_params is not None:
            self._draft_params = draft_params

    def submit(
        self,
        prompt,
        max_new: Optional[int] = None,
        seed: int = 0,
        req_id: Optional[int] = None,
        submit_wall: Optional[float] = None,
        slo_class: str = SLO_BATCH,
        tenant: str = "",
        shipped: Optional[Dict] = None,
        route: str = "local",
        resume_tokens: Optional[np.ndarray] = None,
        resume_logprobs: Optional[np.ndarray] = None,
    ) -> int:
        """Queue one prompt; returns the request id results carry.

        ``submit_wall`` is the submitter's wall-clock anchor (epoch
        seconds) when the request crossed a process boundary — the
        dispatcher stamps it onto the shm ring so the ``queue_wait``
        and ``serve_request`` spans start at the TRUE submit time,
        ring transit included.  ``slo_class``/``tenant`` steer the
        fleet admission lanes (any class other than "interactive"
        normalizes to "batch"); ``shipped`` carries a disaggregated
        prefill's KV block regions (``{"k", "v", "first_token"}``) —
        the request then admits straight into the decode phase.

        ``resume_tokens`` re-admits a partially-generated sequence
        that crossed a PROCESS boundary (a drained / killed replica's
        hand-back): the scheduler re-prefills prompt+tail, reusing
        the prompt's cached prefix blocks via ``peek_prefix``, and
        resumes sampling at the next position — (seed, position)-
        purity makes the continuation identical to the uninterrupted
        run instead of regenerating the tail from scratch.
        ``resume_logprobs`` optionally carries the tail's captured
        logprobs alongside."""
        if self.draining:
            raise RuntimeError(
                "scheduler is draining: submissions belong on "
                "another replica (the dispatcher requeues them)"
            )
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            # position-0 sampling would condition on pool garbage —
            # there is no (seed, position)-pure answer for it
            raise ValueError("prompt must hold at least one token")
        max_new = int(
            self.sched.max_new_default if max_new is None else max_new
        )
        if max_new < 1:
            raise ValueError("max_new must be >= 1")
        if prompt.size + max_new > self.sched.max_seq_len:
            raise ValueError(
                f"prompt {prompt.size} + max_new {max_new} exceeds "
                f"max_seq_len {self.sched.max_seq_len}"
            )
        if self.incremental and not pool_can_ever_hold(
            self.pool_cfg.num_blocks,
            self.pool_cfg.block_size,
            prompt.size + max_new,
        ):
            # under incremental allocation a lone sequence must be
            # able to run to its budget after preempting everyone
            # else; a worst case bigger than the whole pool can't
            raise ValueError(
                f"prompt {prompt.size} + max_new {max_new} needs "
                f"{self.pool_cfg.blocks_for(prompt.size + max_new)} "
                f"blocks > pool of {self.pool_cfg.usable_blocks}"
            )
        if req_id is None:
            req_id = self._next_req_id
        self._next_req_id = max(self._next_req_id, req_id) + 1
        if slo_class != SLO_INTERACTIVE:
            slo_class = SLO_BATCH
        resume = (
            np.asarray(resume_tokens, np.int32).reshape(-1)
            if resume_tokens is not None else _empty_tokens()
        )
        if resume.size >= max_new:
            raise ValueError(
                f"resume tail of {resume.size} token(s) already "
                f"meets max_new {max_new} — nothing left to generate"
            )
        if resume.size:
            rlp = (
                np.asarray(resume_logprobs, np.float32).reshape(-1)
                if resume_logprobs is not None else _empty_logprobs()
            )
            # a tail whose logprobs did not survive the boundary is
            # NaN-padded so consumers can tell "unknown" from 0.0
            if rlp.size < resume.size:
                rlp = np.concatenate(
                    [rlp,
                     np.full(resume.size - rlp.size, np.nan,
                             np.float32)]
                )
            rlp = rlp[: resume.size]
        else:
            rlp = _empty_logprobs()
        self._queue.append(
            GenRequest(req_id=req_id, prompt=prompt, max_new=max_new,
                       seed=int(seed),
                       submit_wall=float(submit_wall or 0.0),
                       resume_tokens=resume, resume_logprobs=rlp,
                       slo_class=slo_class, tenant=str(tenant),
                       # a shipped prefill predates the tail — resumes
                       # re-prefill deterministically instead
                       shipped=(
                           shipped
                           if self.fleet and not resume.size else None
                       ),
                       route=str(route))
        )
        if slo_class == SLO_INTERACTIVE:
            self._queued_interactive += 1
        return req_id

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    @property
    def active_count(self) -> int:
        return sum(1 for sl in self._slots if sl.req is not None)

    @property
    def idle(self) -> bool:
        return not self._queue and self.active_count == 0

    def compile_counts(self) -> Dict[str, int]:
        """Compiled-program census: decode must stay at 1 across any
        admission/eviction/growth/preemption traffic (asserted by
        tier-1).  ``decode`` reports the ACTIVE decode program — the
        fused multi-token one when ``DLROVER_TPU_DECODE_STEPS>1``."""

        def n(f):
            try:
                return int(f._cache_size())
            except Exception:  # noqa: BLE001 - jax-version specific
                return -1

        if (
            self._decode_multi_draft_jit is not None
            and self._draft_params is not None
        ):
            active_decode = self._decode_multi_draft_jit
        elif self._decode_multi_jit is not None:
            active_decode = self._decode_multi_jit
        else:
            active_decode = self._decode_jit
        return {
            "decode": n(active_decode),
            "prefill": n(self._prefill_jit),
            "sample": n(self._sample_jit),
        }

    def stats(self) -> Dict:
        from dlrover_tpu.ops.paged_attention import paged_kernel_backend

        st = dict(self.block_pool.stats())
        st.update(
            kernel_backend=paged_kernel_backend(),
            queue_depth=self.queue_depth,
            active=self.active_count,
            iterations=self.iterations,
            total_new_tokens=self.total_new_tokens,
            total_prefill_tokens=self.total_prefill_tokens,
            preemptions=self.preemptions,
            grown_blocks=self.grown_blocks,
            dispatches=self.dispatches,
            decode_steps=self.decode_k,
            incremental=int(self.incremental),
            accepted_tokens=self.accepted_tokens,
            lane_windows=self.lane_windows,
            accepted_per_step=round(
                self.accepted_tokens / max(self.lane_windows, 1), 4
            ),
            draft_active=int(
                self.draft and self._draft_params is not None
            ),
        )
        return st

    # ------------------------------------------------------ scheduling
    def _full_prompt_keys(self, req: GenRequest) -> List[str]:
        """Content keys for every FULL block of the request's
        original prompt (computed once per req_id; prompts are
        immutable, resume tails never register)."""
        keys = self._prompt_keys.get(req.req_id)
        if keys is None:
            bs = self.sched.block_size
            keys = prefix_block_keys(
                req.prompt[: (int(req.prompt.size) // bs) * bs], bs
            )
            self._prompt_keys[req.req_id] = keys
        return keys

    def _admissible(self, req: GenRequest):
        """Decide admission and size the initial allocation.  Returns
        ``None`` (keep queued — FIFO head-of-line) or a dict the
        admission path consumes."""
        cfgp = self.pool_cfg
        bs = cfgp.block_size
        prefill_tokens = (
            np.concatenate([req.prompt, req.resume_tokens])
            if req.resume_tokens.size else req.prompt
        )
        plen = int(prefill_tokens.size)
        total = int(req.prompt.size) + int(req.max_new)
        if not self.incremental:
            # PR-13 reservation admission: the worst case must fit
            if not self.block_pool.can_allocate(total):
                return None
            return {
                "prefill_tokens": prefill_tokens,
                "n_tokens": total,
                "extra": 0,
                "keys": [],
                "peek_hits": 0,
            }
        keys: List[str] = []
        peek = peek_lru = 0
        if self.prefix_cache and req.shipped is None:
            # only blocks fully inside the ORIGINAL prompt are ever
            # registered, and at least one token must remain to
            # prefill (its logits seed the first sampled token)
            max_hit = min(
                (plen - 1) // bs, int(req.prompt.size) // bs
            )
            if max_hit > 0:
                keys = self._full_prompt_keys(req)[:max_hit]
                peek, peek_lru = self.block_pool.peek_prefix(keys)
        headroom = min(
            self.grow_blocks,
            max(cfgp.blocks_for(total) - cfgp.blocks_for(plen), 0),
        )
        if self.role == "prefill":
            # a prefill worker never decodes: no growth headroom, so
            # more concurrent prefills pack into the same pool
            headroom = 0
        need = cfgp.blocks_for(plen) - peek + headroom
        watermark_blocks = int(
            np.ceil(self.admit_watermark * cfgp.usable_blocks)
        )
        # hits parked in the LRU are consumed BY the acquire — they
        # must not double-count as evictable capacity
        avail = self.block_pool.available_blocks - peek_lru
        if self.block_pool.live_sequences > 0 and (
            avail - need < watermark_blocks
        ):
            return None  # watermark: keep headroom for running lanes
        if avail < need:
            return None
        return {
            "prefill_tokens": prefill_tokens,
            "n_tokens": plen,
            "extra": headroom,
            "keys": keys,
            "peek_hits": peek,
        }

    def _pick_next_index(self) -> Optional[int]:
        """Which queued request admits next.  Fleet OFF: index 0 —
        the PR-14 FIFO head-of-line rule exactly (pinned by tests).
        Fleet ON (SLO-class lanes): interactive before batch; while
        interactive work is in flight, batch admission is capped so
        ``interactive_slots`` decode slots stay reserved for the
        interactive lane (an idle interactive lane does NOT strand
        slots — batch fills every slot until the next interactive
        arrival, which admission then favors and which class-aware
        preemption can make room for); within a class the tenant with
        the fewest active slots wins (weighted fair share), FIFO
        breaking tenant ties."""
        if not self._queue:
            return None
        if not self.fleet:
            return 0
        active_cls: Dict[str, int] = {}
        active_tenant: Dict = {}
        for sl in self._slots:
            if sl.req is None:
                continue
            c = sl.req.slo_class
            active_cls[c] = active_cls.get(c, 0) + 1
            k = (c, sl.req.tenant)
            active_tenant[k] = active_tenant.get(k, 0) + 1
        if self._queued_interactive > 0:
            # interactive first — the O(queue) scan only runs while
            # an interactive request is actually waiting (the counter
            # keeps the saturated-queue common case scan-free)
            idxs = [
                i for i, r in enumerate(self._queue)
                if r.slo_class == SLO_INTERACTIVE
            ]
            return min(
                idxs,
                key=lambda i: (
                    active_tenant.get(
                        (SLO_INTERACTIVE, self._queue[i].tenant), 0
                    ),
                    i,
                ),
            )
        # batch only from here: while interactive work is in flight,
        # keep ``interactive_slots`` decode slots reserved for it
        if (
            active_cls.get(SLO_INTERACTIVE, 0) > 0
            and active_cls.get(SLO_BATCH, 0)
            >= self.sched.max_slots - self.interactive_slots
        ):
            return None
        # everything queued is batch; arbitrate tenant fair share
        # over a bounded FIFO window so a hundreds-deep saturated
        # queue costs O(window), not O(queue), per admission
        window = min(len(self._queue), 32)
        return min(
            range(window),
            key=lambda i: (
                active_tenant.get(
                    (SLO_BATCH, self._queue[i].tenant), 0
                ),
                i,
            ),
        )

    def _admit(self, finished: Optional[List[GenResult]] = None):
        s = self.sched
        while self._queue and not self.draining:
            free = [
                i for i, sl in enumerate(self._slots)
                if sl.req is None
            ]
            if not free:
                return
            qi = self._pick_next_index()
            if qi is None:
                return  # lane caps leave nothing admissible
            req = self._queue[qi]
            plan = self._admissible(req)
            if plan is None:
                # head-of-line (and, fleet on, pool-blocked pick):
                # later (smaller) requests must not starve it forever
                return
            admit_t0 = time.monotonic()
            self._queue.pop(qi)
            if req.slo_class == SLO_INTERACTIVE:
                self._queued_interactive -= 1
            slot = free[0]
            if req.shipped is not None:
                self._adopt(slot, req, plan, admit_t0, finished)
                continue
            hit_ids = (
                self.block_pool.acquire_prefix(plan["keys"])
                if plan["keys"] else []
            )
            self.block_pool.allocate(
                req.req_id,
                plan["n_tokens"],
                extra_blocks=plan["extra"],
                prefix_blocks=hit_ids,
            )
            row = self.block_pool.table_row(
                req.req_id, s.max_blocks_per_seq
            )
            self._tables[slot] = row
            self._positions[slot] = 0
            self._active[slot] = False  # decoding starts post-prefill
            key = self._jax.random.PRNGKey(req.seed)
            self._keys[slot] = np.asarray(
                self._jax.random.key_data(key), np.uint32
            ).reshape(-1)[:2]
            n_hit = len(hit_ids)
            self._admit_counter += 1
            sl = _Slot(
                req=req,
                phase="prefill",
                prefill_tokens=plan["prefill_tokens"],
                prefill_len=int(plan["prefill_tokens"].size),
                prefix_keys=(
                    self._full_prompt_keys(req)
                    if self.prefix_cache else []
                ),
                shared_upto=n_hit,
                admit_seq=self._admit_counter,
            )
            # cached prefix blocks are already filled: prefill starts
            # past them
            sl.prefill_pos = n_hit * s.block_size
            sl.generated = [int(t) for t in req.resume_tokens]
            if self.capture_logprobs and sl.generated:
                rlp = req.resume_logprobs
                sl.logprobs = [
                    float(rlp[i]) if i < rlp.size else float("nan")
                    for i in range(len(sl.generated))
                ]
            self._slots[slot] = sl
            self.block_pool.note_filled(req.req_id, sl.prefill_pos)
            self._window_hit_blocks += n_hit
            req.hit_blocks += n_hit
            if self._serve_obs:
                self._trace_admit(req, admit_t0)

    def _adopt(self, slot: int, req: GenRequest, plan: Dict,
               admit_t0: float,
               finished: Optional[List[GenResult]]):
        """Admit a disaggregated prefill straight into DECODE: splice
        the shipped block regions into freshly allocated pool blocks,
        point the slot's table at them, and run a pure token loop from
        the first token the prefill worker already sampled.  The
        shipped tiles are bitwise the prefill worker's pool content,
        so decode over them equals decode over a local prefill (pinned
        by test); a later preemption drops nothing — the payload is
        consumed here and resume re-prefills deterministically."""
        s = self.sched
        payload, req.shipped = req.shipped, None
        plen = int(req.prompt.size)
        n_ship = self.pool_cfg.blocks_for(plen)
        self.block_pool.allocate(
            req.req_id, plan["n_tokens"], extra_blocks=plan["extra"]
        )
        ids = self.block_pool.blocks_of(req.req_id)[:n_ship]
        self._pool = insert_block_regions(
            self._pool, ids, payload["k"], payload["v"]
        )
        self._tables[slot] = self.block_pool.table_row(
            req.req_id, s.max_blocks_per_seq
        )
        self._positions[slot] = plen
        self._active[slot] = True
        key = self._jax.random.PRNGKey(req.seed)
        self._keys[slot] = np.asarray(
            self._jax.random.key_data(key), np.uint32
        ).reshape(-1)[:2]
        self._admit_counter += 1
        sl = _Slot(req=req, phase="decode", prefill_len=plen,
                   admit_seq=self._admit_counter)
        self._slots[slot] = sl
        self.block_pool.note_filled(req.req_id, plen)
        self.shipped_in += 1
        if self.prefix_cache:
            # shipped FULL prompt blocks are immutable content — index
            # them so later local prompts with the same prefix share
            keys = self._full_prompt_keys(req)
            for idx in range(min(len(keys), n_ship)):
                self.block_pool.share_block(
                    req.req_id, idx, keys[idx]
                )
        if self._serve_obs:
            self._trace_admit(req, admit_t0)
        first = int(payload["first_token"])
        self._next_token[slot] = first
        self._append_token(
            slot, first,
            self._adopt_finished if finished is None else finished,
        )

    def _trace_admit(self, req: GenRequest, admit_t0: float):
        """Close the request's queue phase: a fresh admission emits
        ``queue_wait`` (from the submit wall anchor) + ``admit``; a
        preempted request's re-admission emits ``resume`` with the
        restored tail size instead."""
        from dlrover_tpu.observability.events import anchored_now

        t1 = time.monotonic()
        end_wall = anchored_now(admit_t0)
        fresh = not (req.resume_tokens.size or req.preempts)
        if fresh:
            start_wall = (
                req.submit_wall if req.submit_wall > 0.0
                else anchored_now(req.submit_t)
            )
            req.queue_wait_s = max(end_wall - start_wall, 0.0)
        if self._events is None or not self._events.enabled:
            return
        if fresh:
            self._events.complete(
                "queue_wait",
                start_wall,
                max(end_wall - start_wall, 1e-9),
                req_id=req.req_id,
            )
            self._events.complete(
                "admit",
                end_wall,
                max(t1 - admit_t0, 1e-9),
                req_id=req.req_id,
            )
        else:
            self._events.complete(
                "resume",
                end_wall,
                max(t1 - admit_t0, 1e-9),
                req_id=req.req_id,
                resume_tokens=int(req.resume_tokens.size),
            )

    def _finish(self, slot: int, reason: str,
                finished: List[GenResult]):
        sl = self._slots[slot]
        req = sl.req
        now = time.monotonic()
        tokens = np.concatenate(
            [req.prompt, np.asarray(sl.generated, np.int32)]
        )
        stats = {
            "ttft_s": round(
                max(sl.first_token_t - req.submit_t, 0.0), 6
            ),
        }
        if self._serve_obs:
            gaps = [
                req.token_times[i + 1] - req.token_times[i]
                for i in range(len(req.token_times) - 1)
            ]
            tbt_p99 = (
                float(np.percentile(gaps, 99)) if gaps else 0.0
            )
            stats.update(
                tbt_p99_s=round(tbt_p99, 6),
                queue_wait_s=round(req.queue_wait_s, 6),
                preempts=req.preempts,
                prefix_hit_blocks=req.hit_blocks,
            )
            if self._events is not None and self._events.enabled:
                from dlrover_tpu.observability.events import (
                    anchored_now,
                )

                end_wall = anchored_now(now)
                start_wall = (
                    req.submit_wall if req.submit_wall > 0.0
                    else anchored_now(req.submit_t)
                )
                self._events.complete(
                    "serve_request",
                    start_wall,
                    max(end_wall - start_wall, 1e-9),
                    req_id=req.req_id,
                    replica=self.replica,
                    prompt_tokens=int(req.prompt.size),
                    gen_tokens=len(sl.generated),
                    ttft_s=stats["ttft_s"],
                    tbt_p99_s=stats["tbt_p99_s"],
                    preempts=req.preempts,
                    prefix_hit_blocks=req.hit_blocks,
                    route=req.route,
                    slo_class=req.slo_class,
                    finish_reason=reason,
                )
        finished.append(
            GenResult(
                req_id=req.req_id,
                tokens=tokens,
                finish_reason=reason,
                new_tokens=len(sl.generated),
                latency_s=now - req.submit_t,
                stats=stats,
                logprobs=(
                    np.asarray(sl.logprobs, np.float32)
                    if self.capture_logprobs else _empty_logprobs()
                ),
            )
        )
        self.block_pool.free(req.req_id)
        self._prompt_keys.pop(req.req_id, None)
        # zero the table row: a freed block re-issued to another
        # sequence must never be gathered through this lane again
        self._tables[slot] = 0
        self._positions[slot] = 0
        self._active[slot] = False
        self._slots[slot] = _Slot()

    def _preempt(self, slot: int):
        """Evict the sequence in ``slot`` (pool pressure): free its
        blocks and requeue it AT THE HEAD carrying its generated tail
        — on re-admission it re-prefills prompt+tail and resumes the
        identical (seed, position)-pure continuation."""
        sl = self._slots[slot]
        req = sl.req
        t0 = time.monotonic()
        n_blocks = len(self.block_pool.blocks_of(req.req_id))
        self.block_pool.free(req.req_id)
        resume = np.asarray(sl.generated, np.int32)
        self._queue.insert(
            0,
            GenRequest(
                req_id=req.req_id,
                prompt=req.prompt,
                max_new=req.max_new,
                seed=req.seed,
                submit_t=req.submit_t,
                resume_tokens=resume,
                resume_logprobs=np.asarray(sl.logprobs, np.float32),
                submit_wall=req.submit_wall,
                preempts=req.preempts + 1,
                hit_blocks=req.hit_blocks,
                queue_wait_s=req.queue_wait_s,
                token_times=req.token_times,
                slo_class=req.slo_class,
                tenant=req.tenant,
                route=req.route,
            ),
        )
        if req.slo_class == SLO_INTERACTIVE:
            self._queued_interactive += 1
        self._tables[slot] = 0
        self._positions[slot] = 0
        self._active[slot] = False
        self._slots[slot] = _Slot()
        self.preemptions += 1
        if self._events is not None and self._events.enabled:
            from dlrover_tpu.observability.events import anchored_now

            dur = max(time.monotonic() - t0, 1e-9)
            extra = (
                {"req_id": req.req_id} if self._serve_obs else {}
            )
            self._events.complete(
                "preempt",
                anchored_now(t0),
                dur,
                blocks_freed=n_blocks,
                tokens_generated=int(resume.size),
                **extra,
            )
        logger.info(
            "preempted seq %d (pool dry): freed %d block(s), "
            "requeued with %d generated token(s)",
            req.req_id, n_blocks, resume.size,
        )

    def _pick_victim(self, exclude: int) -> Optional[int]:
        """Lowest-priority live sequence: fewest tokens generated,
        tie broken youngest-admission-first.  Fleet on, the rule is
        CLASS-AWARE first: every batch lane outranks every interactive
        lane as a victim (batch preempts before interactive, never the
        reverse at equal KV pressure — pinned by test); within a class
        the PR-14 rule applies unchanged."""
        candidates = [
            i for i, sl in enumerate(self._slots)
            if sl.req is not None and i != exclude
        ]
        if not candidates:
            return None
        if self.fleet:
            return min(
                candidates,
                key=lambda i: (
                    0 if self._slots[i].req.slo_class
                    != SLO_INTERACTIVE else 1,
                    len(self._slots[i].generated),
                    -self._slots[i].admit_seq,
                ),
            )
        return min(
            candidates,
            key=lambda i: (
                len(self._slots[i].generated),
                -self._slots[i].admit_seq,
            ),
        )

    def _ensure_blocks(self):
        """Incremental mode: before a decode window, every decoding
        lane must own blocks covering its next K write positions —
        grow on demand, preempt the lowest-priority lane when the pool
        (free + evictable shared) runs dry.  Oldest lanes grow first
        so pressure lands on the youngest."""
        if not self.incremental:
            return
        cfgp = self.pool_cfg
        order = sorted(
            (
                i for i, sl in enumerate(self._slots)
                if sl.phase == "decode"
            ),
            key=lambda i: self._slots[i].admit_seq,
        )
        for slot in order:
            sl = self._slots[slot]
            if sl.req is None:
                continue  # preempted while an older lane grew
            req = sl.req
            total = int(req.prompt.size) + int(req.max_new)
            need_tokens = min(
                int(self._positions[slot]) + self.decode_k, total
            )
            while (
                self.block_pool.covered_tokens(req.req_id)
                < need_tokens
            ):
                owned = len(self.block_pool.blocks_of(req.req_id))
                short = cfgp.blocks_for(need_tokens) - owned
                want = min(
                    max(short, self.grow_blocks),
                    cfgp.blocks_for(total) - owned,
                )
                try:
                    self.block_pool.extend(req.req_id, want)
                    self.grown_blocks += want
                except OutOfBlocksError:
                    victim = self._pick_victim(exclude=slot)
                    if victim is None:
                        raise OutOfBlocksError(
                            f"seq {req.req_id} cannot grow and no "
                            "victim remains — pool smaller than one "
                            "sequence's worst case"
                        ) from None
                    self._preempt(victim)
                    if self._slots[slot].req is None:
                        break  # defensive: we were the victim
            if self._slots[slot].req is not None:
                self._tables[slot] = self.block_pool.table_row(
                    req.req_id, self.sched.max_blocks_per_seq
                )

    def _append_token(self, slot: int, token: int,
                      finished: List[GenResult],
                      lp: Optional[float] = None) -> bool:
        """Append one sampled token; returns True when the sequence
        finished (EOS / budget) and left its slot."""
        sl = self._slots[slot]
        if not sl.generated:
            sl.first_token_t = time.monotonic()
        sl.generated.append(int(token))
        if self.capture_logprobs:
            sl.logprobs.append(
                float(lp) if lp is not None else float("nan")
            )
        if self._serve_obs:
            # per-token timestamps fold into ONE tbt_p99_s label at
            # finish — the only per-token tracing cost
            sl.req.token_times.append(time.monotonic())
        self.total_new_tokens += 1
        eos = self.sched.eos_id
        if eos is not None and int(token) == int(eos):
            self._finish(slot, FINISH_EOS, finished)
            return True
        if len(sl.generated) >= sl.req.max_new:
            self._finish(slot, FINISH_LENGTH, finished)
            return True
        return False

    def _share_filled_blocks(self, slot: int):
        """Register prompt blocks the prefill has just completed into
        the shared index (full blocks are immutable from here on)."""
        sl = self._slots[slot]
        if not sl.prefix_keys:
            return
        bs = self.sched.block_size
        full_now = min(
            sl.prefill_pos // bs, len(sl.prefix_keys)
        )
        for idx in range(sl.shared_upto, full_now):
            self.block_pool.share_block(
                sl.req.req_id, idx, sl.prefix_keys[idx]
            )
        sl.shared_upto = max(sl.shared_upto, full_now)

    def _prefill_one(self, finished: List[GenResult]) -> int:
        """Run ONE prompt chunk (round-robin over prefilling slots);
        returns the number of prompt tokens processed."""
        s = self.sched
        slots = [
            i for i, sl in enumerate(self._slots)
            if sl.phase == "prefill"
        ]
        if not slots:
            return 0
        slot = slots[self._prefill_rr % len(slots)]
        self._prefill_rr += 1
        sl = self._slots[slot]
        req = sl.req
        self._last_prefill_req = req.req_id
        plen = sl.prefill_len
        start = sl.prefill_pos
        chunk = sl.prefill_tokens[start:start + s.prefill_chunk]
        real = chunk.size
        if real < s.prefill_chunk:
            chunk = np.pad(chunk, (0, s.prefill_chunk - real))
        jnp = self._jnp
        self._pool, logits = self._prefill_jit(
            self._params,
            self._pool,
            jnp.asarray(chunk[None], jnp.int32),
            jnp.asarray(self._tables[slot]),
            jnp.int32(start),
        )
        self.dispatches += 1
        if self.draft and self._draft_params is not None:
            # mirror the chunk into the DRAFT pool (same table/blocks,
            # draft shapes) so the drafter decodes over a real prompt
            # cache; a drafter adopted mid-prefill just drafts worse
            # until the next prompt — emission never depends on it
            self._draft_pool, _ = self._draft_prefill_jit(
                self._draft_params,
                self._draft_pool,
                jnp.asarray(chunk[None], jnp.int32),
                jnp.asarray(self._tables[slot]),
                jnp.int32(start),
            )
            self.dispatches += 1
        sl.prefill_pos += real
        self.total_prefill_tokens += real
        self.block_pool.note_filled(req.req_id, sl.prefill_pos)
        self._share_filled_blocks(slot)
        if sl.prefill_pos >= plen:
            # sample the first new token from the last REAL prefill
            # position's logits (it lives inside this chunk)
            first_lp = None
            tok = self._sample_jit(
                logits[0, plen - 1 - start],
                jnp.asarray(self._keys[slot]),
                jnp.int32(plen),
            )
            if self.capture_logprobs:
                tok, first_lp = tok
                first_lp = float(first_lp)
            self.dispatches += 1
            if self.role == "prefill":
                # disaggregated split: the first token is sampled HERE
                # (same (seed, position) rule as a local prefill, so
                # the decode continuation is bit-identical), then the
                # filled block tiles ship out and the slot frees — a
                # prefill worker never decodes
                n_ship = self.pool_cfg.blocks_for(plen)
                ids = self.block_pool.blocks_of(req.req_id)[:n_ship]
                k_region, v_region = extract_block_regions(
                    self._pool, ids
                )
                self.shipped.append(
                    {
                        "req_id": req.req_id,
                        "first_token": int(tok),
                        "n_blocks": n_ship,
                        "prompt_len": plen,
                        "k": k_region,
                        "v": v_region,
                    }
                )
                self.shipped_out += 1
                self.block_pool.free(req.req_id)
                self._prompt_keys.pop(req.req_id, None)
                self._tables[slot] = 0
                self._positions[slot] = 0
                self._active[slot] = False
                self._slots[slot] = _Slot()
                return real
            sl.phase = "decode"
            self._positions[slot] = plen
            self._active[slot] = True
            self._next_token[slot] = int(tok)
            if self._append_token(slot, int(tok), finished,
                                  lp=first_lp):
                pass  # finished on its very first token
        return real

    def _decode_once(self, finished: List[GenResult]) -> int:
        """One decode iteration over every active lane; returns the
        number of tokens sampled."""
        decoding = [
            i for i, sl in enumerate(self._slots)
            if sl.phase == "decode"
        ]
        if not decoding:
            return 0
        jnp = self._jnp
        out = self._decode_jit(
            self._params,
            self._pool,
            jnp.asarray(self._next_token),
            jnp.asarray(self._tables),
            jnp.asarray(self._positions),
            jnp.asarray(self._active),
            jnp.asarray(self._keys),
        )
        if self.capture_logprobs:
            self._pool, nxt, lps = out
            lps = np.asarray(lps)
        else:
            self._pool, nxt = out
            lps = None
        self.dispatches += 1
        nxt = np.asarray(nxt)
        sampled = 0
        for slot in decoding:
            self._positions[slot] += 1
            self.block_pool.note_filled(
                self._slots[slot].req.req_id,
                int(self._positions[slot]),
            )
            tok = int(nxt[slot])
            sampled += 1
            lp = float(lps[slot]) if lps is not None else None
            if not self._append_token(slot, tok, finished, lp=lp):
                self._next_token[slot] = tok
        return sampled

    def _decode_multi_once(self, finished: List[GenResult]) -> int:
        """One fused K-step decode window (drafts + verify in ONE
        dispatch); returns the number of tokens accepted across
        lanes."""
        decoding = [
            i for i, sl in enumerate(self._slots)
            if sl.phase == "decode"
        ]
        if not decoding:
            return 0
        K = self.decode_k
        temp = float(self.sched.temperature)
        jnp = self._jnp
        t0 = time.monotonic()
        draft_mode = (
            self._decode_multi_draft_jit is not None
            and self._draft_params is not None
        )
        lp_drafts = lp_ver = None
        if draft_mode:
            (self._pool, self._draft_pool, drafts, ver, n_match,
             lp_ver) = self._decode_multi_draft_jit(
                self._params,
                self._draft_params,
                self._pool,
                self._draft_pool,
                jnp.asarray(self._next_token),
                jnp.asarray(self._tables),
                jnp.asarray(self._positions),
                jnp.asarray(self._active),
                jnp.asarray(self._keys),
            )
            lp_ver = np.asarray(lp_ver)
        elif self.capture_logprobs:
            (self._pool, drafts, ver, n_match, lp_drafts,
             lp_ver) = self._decode_multi_jit(
                self._params,
                self._pool,
                jnp.asarray(self._next_token),
                jnp.asarray(self._tables),
                jnp.asarray(self._positions),
                jnp.asarray(self._active),
                jnp.asarray(self._keys),
            )
            lp_drafts = np.asarray(lp_drafts)
            lp_ver = np.asarray(lp_ver)
        else:
            self._pool, drafts, ver, n_match = self._decode_multi_jit(
                self._params,
                self._pool,
                jnp.asarray(self._next_token),
                jnp.asarray(self._tables),
                jnp.asarray(self._positions),
                jnp.asarray(self._active),
                jnp.asarray(self._keys),
            )
        self.dispatches += 1
        drafts = np.asarray(drafts)
        ver = np.asarray(ver)
        n_match = np.asarray(n_match)
        sampled = 0
        for slot in decoding:
            sl = self._slots[slot]
            remaining = sl.req.max_new - len(sl.generated)
            if draft_mode:
                # separate drafter: ``ver`` is the policy's true
                # conditioned stream at EVERY temperature (at temp 0
                # it's the policy argmax); drafts only bound how far
                # the window stays conditioned on matched prefixes
                acc = min(int(n_match[slot]) + 1, K)
                emitted = ver[slot]
                emitted_lp = lp_ver
            elif temp <= 0:
                # drafts ARE the K=1 greedy stream (each draft step
                # is the K=1 computation); the verify pass gates how
                # far we trust the window, never what we emit
                acc = max(1, int(n_match[slot]))
                emitted = drafts[slot]
                emitted_lp = lp_drafts
            else:
                # rejection-style: every emitted token is the
                # real-rule sample conditioned on a prefix that
                # matched the drafts it was scored against
                acc = min(int(n_match[slot]) + 1, K)
                emitted = ver[slot]
                emitted_lp = lp_ver
            acc = min(acc, remaining, K)
            self.lane_windows += 1
            kept_last = None
            done = False
            for j in range(acc):
                tok = int(emitted[j])
                self._positions[slot] += 1
                self.block_pool.note_filled(
                    sl.req.req_id, int(self._positions[slot])
                )
                sampled += 1
                self.accepted_tokens += 1
                kept_last = tok
                lp = (
                    float(emitted_lp[slot, j])
                    if emitted_lp is not None else None
                )
                if self._append_token(slot, tok, finished, lp=lp):
                    done = True
                    break
            if not done and kept_last is not None:
                self._next_token[slot] = kept_last
        if self._events is not None and self._events.enabled:
            from dlrover_tpu.observability.events import anchored_now

            dur = max(time.monotonic() - t0, 1e-9)
            self._events.complete(
                "verify",
                anchored_now(t0),
                dur,
                drafted=K * len(decoding),
                accepted=sampled,
            )
        return sampled

    def step(self) -> List[GenResult]:
        """One scheduler iteration: admit -> one prefill chunk ->
        (grow/preempt) -> one decode window.  Returns the sequences
        that finished."""
        if self._params is None:
            raise RuntimeError(
                "sync_weights() before step() — the scheduler has no "
                "params to serve with"
            )
        t0 = time.monotonic()
        emit = self._events is not None and self._events.enabled
        finished: List[GenResult] = []
        if self._adopt_finished:
            finished.extend(self._adopt_finished)
            self._adopt_finished.clear()
        self._admit(finished)
        pre_t0 = time.monotonic()
        hit_blocks = self._window_hit_blocks
        self._window_hit_blocks = 0
        pre = self._prefill_one(finished)
        pre_t1 = time.monotonic()
        self._admit(finished)  # a first-token EOS may have freed a slot
        self._ensure_blocks()
        dec_t0 = time.monotonic()
        if self._decode_multi_jit is not None:
            dec = self._decode_multi_once(finished)
        else:
            dec = self._decode_once(finished)
        dec_t1 = time.monotonic()
        self._admit(finished)
        self.iterations += 1
        if emit and (pre or dec):
            from dlrover_tpu.observability.events import anchored_now

            if pre:
                # request labels on the iteration-level prefill span
                # (one chunk serves exactly one slot) — gated so
                # SERVE_OBS=0 keeps the PR-14 record byte-for-byte
                req_label = (
                    {"req_id": self._last_prefill_req}
                    if self._serve_obs else {}
                )
                self._events.complete(
                    "prefill",
                    anchored_now(pre_t0),
                    pre_t1 - pre_t0,
                    tokens=pre,
                    prefix_hit_blocks=hit_blocks,
                    **req_label,
                )
            if dec:
                self._events.complete(
                    "decode",
                    anchored_now(dec_t0),
                    dec_t1 - dec_t0,
                    new_tokens=dec,
                )
            dur = max(time.monotonic() - t0, 1e-9)
            self._events.complete(
                "serve_step",
                anchored_now(t0),
                dur,
                tokens=pre,
                new_tokens=dec,
                throughput_tps=round((pre + dec) / dur, 2),
            )
        return finished

    def run(self, max_iterations: int = 1_000_000) -> List[GenResult]:
        """Drive until idle (offline / bench mode)."""
        out: List[GenResult] = []
        for _ in range(max_iterations):
            if self.idle:
                break
            out.extend(self.step())
        return out

    def drain(self) -> List[GenRequest]:
        """Stop admitting and evict every in-flight sequence, handing
        back requeueable requests (the PR-9 preemption-drain dual for
        serving: nothing in flight is lost, it re-runs elsewhere and
        — sampling being (seed, position)-pure — reproduces the same
        tail).  Each handed-back request carries its generated tail
        as ``resume_tokens``, so an in-process requeue resumes instead
        of regenerating (cross-process dispatchers resubmit the
        original prompt; both are deterministic-identical)."""
        self.draining = True
        requeue: List[GenRequest] = list(self._queue)
        self._queue.clear()
        self._queued_interactive = 0
        for req in requeue:
            # a handed-back ship payload would outlive the weights it
            # was prefilled under — the dispatcher re-prefills instead
            req.shipped = None
        for slot, sl in enumerate(self._slots):
            if sl.req is None:
                continue
            self.block_pool.free(sl.req.req_id)
            self._tables[slot] = 0
            self._positions[slot] = 0
            self._active[slot] = False
            sl.req.resume_tokens = np.asarray(sl.generated, np.int32)
            sl.req.resume_logprobs = np.asarray(
                sl.logprobs, np.float32
            )
            requeue.append(sl.req)
            self._slots[slot] = _Slot()
        self._prompt_keys.clear()  # handed-back requests left us
        if requeue:
            logger.info(
                "scheduler drained: %d request(s) handed back",
                len(requeue),
            )
        return requeue
