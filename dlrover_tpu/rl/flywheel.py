"""The zero-copy RLHF flywheel (ISSUE 20).

Closes the train -> rollout -> train loop with zero
serialize/deserialize hops on either leg:

- **In-place weight publish** — every K optimizer steps the trainer
  commits its policy params (and, in draft mode, the small drafter
  trained alongside it) straight into the double-buffered shm
  snapshot segment serving replicas already adopt from
  (``ServingEngine.sync_weights``).  The publish of generation g+1
  overlaps training while replicas still read generation g; the
  generation side-segment (``agent/ckpt_shm``) makes replica probes
  one atomic-width load, and a publisher killed mid-save never bumps
  it — no replica ever observes a torn snapshot.  The trainer's
  stall is bounded by one chunk-parallel memcpy, not a pickle hop.

- **Trajectory streaming** — every completed rollout (prompt +
  sampled tail + per-token logprobs + the policy generation that
  sampled it) flows back to the trainer through the same shm-ring
  substrate the serving transport rides, arriving as a ready
  training sample.  Exactly-once by req-id dedup (an optional journal
  survives consumer restarts), and — sampling being
  (seed, position)-pure — a replayed round is bitwise-identical.
  Stale trajectories (generation lag beyond
  ``DLROVER_TPU_FLYWHEEL_MAX_LAG``) are dropped or importance-tagged
  per ``DLROVER_TPU_FLYWHEEL_STALENESS``.

- **Device arbitration** lives in
  ``master/flywheel_operator.FlywheelOperator`` (the Brain side);
  this module only exposes the plane gauges it consumes.

``DLROVER_TPU_FLYWHEEL=0`` disables the layer wholesale: the engine
strips capture/draft from its spec, never touches the generation
segment, and this coordinator refuses to build — today's separate
planes reproduce byte-for-byte.
"""

import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from dlrover_tpu.common.env import (
    flywheel_enabled,
    flywheel_max_lag,
    flywheel_publish_every,
    flywheel_staleness_policy,
)
from dlrover_tpu.common.log import default_logger as logger

#: trajectory-ring payload schema; bump on ANY layout change (the
#: serving rings carry their own independent RING_SCHEMA_VERSION)
TRAJ_SCHEMA_VERSION = 1


def _traj_spec(max_total: int):
    from dlrover_tpu.data.shm_dataloader import BatchSpec

    return BatchSpec(
        {
            # req_id, prompt_len, total_len, new_tokens, generation
            # (the policy generation whose weights sampled the tail),
            # seed, schema_version, finish_code
            "meta": ((8,), "<i8"),
            # [prompt | sampled tail], zero-padded
            "tokens": ((max_total,), "<i4"),
            # per sampled token: log p(token | prefix) under the
            # sampling policy (NaN where capture missed a position)
            "logprobs": ((max_total,), "<f4"),
        }
    )


@dataclass
class Trajectory:
    """One completed rollout as a ready training sample."""

    req_id: int
    tokens: np.ndarray  # [prompt | tail], int32
    prompt_len: int
    new_tokens: int
    logprobs: np.ndarray  # len == new_tokens, float32 (NaN = unknown)
    generation: int  # the policy generation that sampled the tail
    seed: int = 0
    finish_code: int = 0
    stale: bool = False  # tagged by the "tag" staleness policy
    lag: int = 0  # generations behind the newest publish at arrival


@dataclass
class FlywheelStats:
    published: int = 0
    last_stall_s: float = 0.0
    publish_bytes: int = 0
    streamed: int = 0
    duplicates: int = 0
    staleness_dropped: int = 0
    staleness_tagged: int = 0


class TrajectorySink:
    """Exactly-once, staleness-policed intake for streamed
    trajectories.

    Dedup is by req-id: the serving plane can answer a request twice
    across a drain/crash race, and a chaos-killed consumer may replay
    ring slots after restart — the second copy must never become a
    second gradient.  An optional append-only journal records every
    accepted req-id so a RESTARTED consumer (same journal path)
    resumes the dedup set instead of double-training."""

    def __init__(self, policy: Optional[str] = None,
                 max_lag: Optional[int] = None,
                 journal_path: Optional[str] = None):
        self.policy = policy or flywheel_staleness_policy()
        self.max_lag = (
            flywheel_max_lag() if max_lag is None else int(max_lag)
        )
        self._seen: set = set()
        self._journal_path = journal_path or ""
        self._journal_fd = None
        self.stats = FlywheelStats()
        if self._journal_path:
            if os.path.exists(self._journal_path):
                with open(self._journal_path) as f:
                    for line in f:
                        line = line.strip()
                        if line:
                            self._seen.add(int(line))
            self._journal_fd = os.open(
                self._journal_path,
                os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                0o644,
            )

    def accept(self, traj: Trajectory,
               current_generation: int) -> Optional[Trajectory]:
        """One trajectory through dedup + staleness; returns it
        (possibly tagged) or None when refused."""
        if traj.req_id in self._seen:
            self.stats.duplicates += 1
            return None
        traj.lag = max(int(current_generation) - traj.generation, 0)
        if traj.lag > self.max_lag:
            if self.policy == "drop":
                self.stats.staleness_dropped += 1
                # a dropped trajectory is still CONSUMED exactly once
                self._mark(traj.req_id)
                return None
            traj.stale = True
            self.stats.staleness_tagged += 1
        self._mark(traj.req_id)
        self.stats.streamed += 1
        return traj

    def _mark(self, req_id: int):
        self._seen.add(req_id)
        if self._journal_fd is not None:
            # O_APPEND + one write: atomic on POSIX, crash-safe line
            os.write(self._journal_fd, f"{req_id}\n".encode())

    def close(self):
        if self._journal_fd is not None:
            os.close(self._journal_fd)
            self._journal_fd = None


def _tree_nbytes(tree) -> int:
    import jax

    return int(
        sum(
            np.asarray(x).nbytes
            for x in jax.tree_util.tree_leaves(tree)
        )
    )


class FlywheelCoordinator:
    """The trainer-side hub of the flywheel: paced in-place weight
    publishes out, streamed trajectories in.

    Construction requires ``DLROVER_TPU_FLYWHEEL`` enabled — with the
    kill switch off the RLHF loop must run today's separate planes,
    and a half-built coordinator would silently re-enable part of the
    layer.

    The trajectory stream is an shm ring (the PR-4 substrate): the
    producer side (``offer_result`` — typically the thread collecting
    ``ServingEngine.result``) and the consumer side (``drain`` — the
    training loop) may live in different processes; both ends attach
    by the coordinator's name."""

    def __init__(
        self,
        engine,
        max_total: int,
        name: Optional[str] = None,
        publish_every: Optional[int] = None,
        staleness: Optional[str] = None,
        max_lag: Optional[int] = None,
        ring_slots: int = 64,
        journal_path: Optional[str] = None,
        create: bool = True,
    ):
        if not flywheel_enabled():
            raise RuntimeError(
                "DLROVER_TPU_FLYWHEEL=0: the flywheel layer is "
                "disabled; run the separate train/serve planes"
            )
        from dlrover_tpu.observability.events import get_event_logger
        from dlrover_tpu.rl.generation_service import _Ring

        self.engine = engine
        self.publish_every = int(
            flywheel_publish_every()
            if publish_every is None else publish_every
        )
        self._max_total = int(max_total)
        self._name = name or f"flywheel-{os.getpid()}"
        self._events = get_event_logger()
        self.sink = TrajectorySink(
            policy=staleness, max_lag=max_lag,
            journal_path=journal_path,
        )
        self.stats = self.sink.stats
        self.generation = 0
        self._ring = _Ring(
            f"{self._name}-traj",
            spec=_traj_spec(self._max_total),
            num_slots=int(ring_slots),
            create=create,
        )
        self._owns_ring = bool(create)
        self._round = 0
        self._window_t0 = time.monotonic()
        self._window_n = 0
        self._closed = False

    # ------------------------------------------------- weight publish
    def publish(self, params, draft_params=None,
                step: Optional[int] = None) -> float:
        """One in-place publish of the policy (+ drafter) into the
        serving plane's snapshot segment.  Returns the stall charged
        to the trainer (the save_state wall time — one chunk-parallel
        memcpy into the inactive slot; replicas keep reading the
        other slot throughout)."""
        from dlrover_tpu.observability.metrics import get_registry

        nbytes = _tree_nbytes(params)
        if draft_params is not None:
            nbytes += _tree_nbytes(draft_params)
        t0 = time.time()
        stall = self.engine.sync_weights(
            params, draft_params=draft_params
        ) if draft_params is not None else self.engine.sync_weights(
            params
        )
        self.generation = int(self.engine._version)
        self.stats.published += 1
        self.stats.last_stall_s = stall
        self.stats.publish_bytes = nbytes
        self._events.complete(
            "weight_publish",
            t0,
            stall,
            generation=self.generation,
            bytes=nbytes,
            stall_s=round(stall, 6),
            step=(-1 if step is None else int(step)),
        )
        reg = get_registry()
        reg.set_gauge(
            "dlrover_tpu_flywheel_generation", self.generation
        )
        reg.set_gauge(
            "dlrover_tpu_flywheel_publish_stall_s", stall
        )
        return stall

    def maybe_publish(self, step: int, params, draft_params=None):
        """Pace-gated publish: every ``publish_every`` steps (and on
        step 0, so replicas never serve the init template once
        training has params).  Returns the stall or None."""
        if int(step) % self.publish_every != 0:
            return None
        return self.publish(params, draft_params=draft_params,
                            step=step)

    # ---------------------------------------------- trajectory stream
    def offer_result(self, req_id: int, prompt, result: Dict,
                     seed: int = 0, timeout: float = 5.0) -> bool:
        """Producer side: pack one completed ``ServingEngine.result``
        payload onto the trajectory ring.  Returns False only when
        the ring stayed full for ``timeout`` (the consumer is gone or
        wedged — the caller decides whether to retry or drop)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        tokens = np.asarray(result["tokens"], np.int32).reshape(-1)
        new_tokens = int(result.get("new_tokens", 0))
        total = int(tokens.size)
        buf = np.zeros((self._max_total,), np.int32)
        buf[:total] = tokens[: self._max_total]
        lp_buf = np.full((self._max_total,), np.nan, np.float32)
        lp = np.asarray(
            result.get("logprobs", ()), np.float32
        ).reshape(-1)
        lp_buf[: min(lp.size, self._max_total)] = (
            lp[: self._max_total]
        )
        finish = 1 if result.get("finish_reason") == "eos" else 0
        msg = {
            "meta": np.asarray(
                [int(req_id), int(prompt.size), total, new_tokens,
                 int(result.get("version", -1)), int(seed),
                 TRAJ_SCHEMA_VERSION, finish],
                np.int64,
            ),
            "tokens": buf,
            "logprobs": lp_buf,
        }
        return self._ring.try_put(msg, timeout=timeout)

    def drain(self, max_n: int = 0) -> List[Trajectory]:
        """Consumer side: pull every queued trajectory through the
        sink (dedup + staleness) and return the accepted ones as
        ready training samples."""
        out: List[Trajectory] = []
        while not max_n or len(out) < max_n:
            msg = self._ring.try_get()
            if msg is None:
                break
            meta = msg["meta"]
            if int(meta[6]) != TRAJ_SCHEMA_VERSION:
                raise RuntimeError(
                    f"trajectory payload schema v{int(meta[6])} != "
                    f"reader schema v{TRAJ_SCHEMA_VERSION}"
                )
            total = int(meta[2])
            new_tokens = int(meta[3])
            traj = Trajectory(
                req_id=int(meta[0]),
                tokens=msg["tokens"][:total].copy(),
                prompt_len=int(meta[1]),
                new_tokens=new_tokens,
                logprobs=msg["logprobs"][:new_tokens].copy(),
                generation=int(meta[4]),
                seed=int(meta[5]),
                finish_code=int(meta[7]),
            )
            accepted = self.sink.accept(traj, self.generation)
            if accepted is None:
                continue
            self._events.complete(
                "trajectory",
                time.time(),
                0.0,
                req_id=accepted.req_id,
                generation=accepted.generation,
                tokens=accepted.new_tokens,
            )
            out.append(accepted)
        if out:
            self._window_n += len(out)
            now = time.monotonic()
            if now - self._window_t0 >= 1.0:
                from dlrover_tpu.observability.metrics import (
                    get_registry,
                )

                get_registry().set_gauge(
                    "dlrover_tpu_flywheel_trajectories_per_s",
                    self._window_n / (now - self._window_t0),
                )
                get_registry().set_gauge(
                    "dlrover_tpu_flywheel_staleness_dropped",
                    self.stats.staleness_dropped,
                )
                self._window_n = 0
                self._window_t0 = now
        return out

    # -------------------------------------------------- round harness
    def run_round(self, prompts, max_new: Optional[int] = None,
                  seed: int = 0, timeout: Optional[float] = None,
                  ) -> List[Trajectory]:
        """One whole rollout round: submit every prompt, collect
        every result as it completes, stream each through the ring
        and return the accepted trajectories.  The round is bracketed
        by a ``rollout_round`` span carrying the scoreboard."""
        self._round += 1
        t0 = time.time()
        dropped0 = self.stats.staleness_dropped
        ids = {}
        for i, row in enumerate(prompts):
            s = int(seed) + i * 1000003
            rid = self.engine.submit(row, max_new=max_new, seed=s)
            ids[rid] = (row, s)
        for rid, (row, s) in ids.items():
            res = self.engine.result(rid, timeout=timeout)
            self.offer_result(rid, row, res, seed=s)
        out = self.drain()
        self._events.complete(
            "rollout_round",
            t0,
            time.time() - t0,
            round=self._round,
            trajectories=len(out),
            staleness_dropped=(
                self.stats.staleness_dropped - dropped0
            ),
        )
        return out

    def close(self):
        if self._closed:
            return
        self._closed = True
        self.sink.close()
        try:
            self._ring.close(unlink=self._owns_ring)
        except Exception as e:  # noqa: BLE001 - already unlinked
            logger.warning("flywheel ring close failed: %s", e)
