"""Per-role model engine for RLHF.

Reference parity: ``atorch/atorch/rl/model_engine/model_engine.py`` —
builds each role (actor / critic / ref_model / reward_model) with its
own acceleration strategy; the actor additionally gets a generation
path (the reference plugs vLLM — here a jitted greedy/temperature
sampler on the actor params, which shares the training mesh).
"""

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp

from dlrover_tpu.accelerate import auto_accelerate, load_strategy
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.rl.config import RLConfig


class ModelEngine:
    def __init__(self, config: RLConfig):
        self.config = config
        self.roles: Dict[str, object] = {}
        self.states: Dict[str, object] = {}

    def build_role(
        self,
        name: str,
        loss_fn: Callable,
        optimizer,
        init_params_fn: Callable,
        param_axes,
        devices=None,
    ):
        """Accelerate one role with its configured strategy."""
        role_cfg = self.config.role(name)
        strategy = None
        if role_cfg and role_cfg.strategy:
            strategy = load_strategy(role_cfg.strategy)
        result = auto_accelerate(
            loss_fn=loss_fn,
            optimizer=optimizer,
            init_params_fn=init_params_fn,
            param_axes=param_axes,
            devices=devices,
            load_strategy=strategy,
        )
        self.roles[name] = result
        logger.info(
            "role %s -> strategy %s", name, result.strategy.describe()
        )
        return result

    def init_role_state(self, name: str, rng):
        state = self.roles[name].fns.init_state(rng)
        self.states[name] = state
        return state

    # --------------------------------------------------------- generation
    @staticmethod
    def make_sampler(
        forward_fn: Callable,  # (params, tokens) -> logits
        max_new_tokens: int,
        temperature: float = 1.0,
        eos_id: Optional[int] = None,
    ):
        """Jitted autoregressive sampler on the actor (no KV cache —
        fine for short RLHF responses; a cached decoder can swap in
        without changing callers).

        ``prompt_len`` (optional traced scalar) is the REAL prompt
        length when ``prompt`` is padded to a length bucket
        (``DLROVER_TPU_GEN_BUCKETS``): sampling starts there, and
        causal attention keeps the padded tail invisible to every
        sampled position."""

        def sample(params, prompt, rng, prompt_len=None):
            b, padded_len = prompt.shape
            # shapes come from the (possibly padded) static length;
            # only the sampling START position is traced
            start = padded_len if prompt_len is None else prompt_len

            def step(carry, _):
                tokens, cur_len, rng = carry
                logits = forward_fn(params, tokens)
                # gather the last real position's logits per row
                idx = jnp.clip(cur_len - 1, 0, tokens.shape[1] - 1)
                last = jnp.take_along_axis(
                    logits,
                    idx[:, None, None].repeat(logits.shape[-1], -1),
                    axis=1,
                )[:, 0]
                rng, sub = jax.random.split(rng)
                if temperature <= 0:
                    nxt = jnp.argmax(last, axis=-1)
                else:
                    nxt = jax.random.categorical(
                        sub, last / temperature, axis=-1
                    )
                tokens = jax.vmap(
                    lambda t, i, v: t.at[i].set(v)
                )(tokens, cur_len, nxt)
                return (tokens, cur_len + 1, rng), nxt

            total = padded_len + max_new_tokens
            padded = jnp.zeros((b, total), dtype=prompt.dtype)
            padded = padded.at[:, :padded_len].set(prompt)
            cur = jnp.full((b,), start, dtype=jnp.int32)
            (tokens, _, _), _ = jax.lax.scan(
                step, (padded, cur, rng), None, length=max_new_tokens
            )
            return tokens

        return jax.jit(sample)
