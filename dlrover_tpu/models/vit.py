"""Vision Transformer — second model family on the same substrate.

Role parity: the reference accelerates arbitrary user models (HF/
Megatron/vision) through ``auto_accelerate``; this framework ships
model families natively.  ViT demonstrates that the logical-axes
scheme, the strategy engine and the kernels are model-agnostic:
the same ``EMBED``/``HEADS``/``MLP`` rules shard it, the same Pallas
flash attention serves it (non-causal), and ``auto_accelerate``
consumes it unchanged.

TPU notes: patchify is one big reshape+matmul (MXU-friendly — no
im2col gather); layers are stacked on a leading dim and executed with
``lax.scan`` exactly like llama, so pipeline sharding works for free.
"""

from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

from dlrover_tpu.models.llama import rms_norm
from dlrover_tpu.parallel import sharding as sh


@dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    channels: int = 3
    dim: int = 768
    n_layers: int = 12
    n_heads: int = 12
    mlp_dim: int = 3072
    num_classes: int = 1000
    norm_eps: float = 1e-6
    dtype: Any = jnp.bfloat16

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def patch_dim(self) -> int:
        return self.patch_size * self.patch_size * self.channels

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @staticmethod
    def tiny(**overrides) -> "ViTConfig":
        base = dict(
            image_size=32, patch_size=8, dim=64, n_layers=2,
            n_heads=4, mlp_dim=128, num_classes=10,
        )
        base.update(overrides)
        return ViTConfig(**base)


def init_params(key, cfg: ViTConfig) -> Dict:
    """Stacked-layer pytree, fp32 masters (same conventions as llama:
    ``layers`` leading dim = the scan/pipeline axis)."""
    ks = jax.random.split(key, 6)
    d, mlp, L = cfg.dim, cfg.mlp_dim, cfg.n_layers

    def dense(key, *shape, in_axis=0):
        return jax.random.normal(key, shape, jnp.float32) * (
            shape[in_axis] ** -0.5
        )

    lk = jax.random.split(ks[2], 6)
    layer = {
        "attn_norm": jnp.ones((L, d), jnp.float32),
        "wqkv": dense(lk[0], L, d, 3 * d, in_axis=1),
        "wo": dense(lk[1], L, d, d, in_axis=1),
        "mlp_norm": jnp.ones((L, d), jnp.float32),
        "w_up": dense(lk[2], L, d, mlp, in_axis=1),
        "w_down": dense(lk[3], L, mlp, d, in_axis=1),
    }
    return {
        "patch_embed": dense(ks[0], cfg.patch_dim, d),
        "pos_embed": (
            jax.random.normal(
                ks[1], (cfg.n_patches + 1, d), jnp.float32
            )
            * 0.02
        ),
        "cls_token": jnp.zeros((d,), jnp.float32),
        "layers": layer,
        "final_norm": jnp.ones((d,), jnp.float32),
        "head": dense(ks[3], d, cfg.num_classes),
    }


def param_logical_axes(cfg: ViTConfig) -> Dict:
    return {
        "patch_embed": (None, sh.EMBED),
        "pos_embed": (None, sh.EMBED),
        "cls_token": (None,),
        "layers": {
            "attn_norm": (sh.LAYERS, None),
            "wqkv": (sh.LAYERS, sh.EMBED, sh.HEADS),
            "wo": (sh.LAYERS, sh.HEADS, sh.EMBED),
            "mlp_norm": (sh.LAYERS, None),
            "w_up": (sh.LAYERS, sh.EMBED, sh.MLP),
            "w_down": (sh.LAYERS, sh.MLP, sh.EMBED),
        },
        "final_norm": (None,),
        "head": (sh.EMBED, None),
    }


def patchify(images: jnp.ndarray, cfg: ViTConfig) -> jnp.ndarray:
    """[B, H, W, C] -> [B, n_patches, patch_dim] by reshape only."""
    b, h, w, c = images.shape
    p = cfg.patch_size
    x = images.reshape(b, h // p, p, w // p, p, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, (h // p) * (w // p), p * p * c)


def _layer_forward(cfg: ViTConfig, lp: Dict, x: jnp.ndarray):
    b, s, d = x.shape
    nh, hd = cfg.n_heads, cfg.head_dim
    dt = cfg.dtype

    def proj(a, w):
        return jnp.matmul(
            a, w.astype(dt), preferred_element_type=jnp.float32
        ).astype(dt)

    h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
    qkv = proj(h, lp["wqkv"]).reshape(b, s, 3, nh, hd)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    from dlrover_tpu.models.llama import _default_attention

    attn = _default_attention()(q, k, v, causal=False)
    x = x + proj(attn.reshape(b, s, nh * hd), lp["wo"])

    h = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
    x = x + proj(jax.nn.gelu(proj(h, lp["w_up"])), lp["w_down"])
    return x


def forward(
    params: Dict, images: jnp.ndarray, cfg: ViTConfig
) -> jnp.ndarray:
    """images [B, H, W, C] -> logits [B, num_classes] (fp32)."""
    dt = cfg.dtype
    patches = patchify(images.astype(dt), cfg)
    x = jnp.matmul(
        patches,
        params["patch_embed"].astype(dt),
        preferred_element_type=jnp.float32,
    ).astype(dt)
    b = x.shape[0]
    cls = jnp.broadcast_to(
        params["cls_token"].astype(dt), (b, 1, cfg.dim)
    )
    x = jnp.concatenate([cls, x], axis=1)
    x = x + params["pos_embed"].astype(dt)[None]
    x = sh.apply_sharding_constraint(
        x, (sh.BATCH, sh.SEQ, sh.EMBED), _rules()
    )

    block = partial(_layer_forward, cfg)

    def body(carry, lp):
        return block(lp, carry), None

    x, _ = lax.scan(body, x, params["layers"])
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    return jnp.matmul(
        x[:, 0],  # CLS token
        params["head"].astype(dt),
        preferred_element_type=jnp.float32,
    )


def _rules():
    from dlrover_tpu.models.llama import _current_rules

    return _current_rules()


def loss_fn(params: Dict, batch: Dict, cfg: ViTConfig) -> jnp.ndarray:
    """Softmax cross entropy; batch = {"images": [B,H,W,C],
    "labels": [B]}."""
    logits = forward(params, batch["images"], cfg)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(
        logp, batch["labels"][:, None], axis=-1
    ).squeeze(-1)
    return jnp.mean(nll)
