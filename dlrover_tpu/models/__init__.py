from dlrover_tpu.models.llama import (  # noqa: F401
    LlamaConfig,
    init_params,
    forward,
    loss_fn,
    param_logical_axes,
    count_params,
)
