from dlrover_tpu.models.llama import (  # noqa: F401
    LlamaConfig,
    init_params,
    forward,
    loss_fn,
    param_logical_axes,
    count_params,
)
from dlrover_tpu.models import vit  # noqa: F401
from dlrover_tpu.models.hf_convert import (  # noqa: F401
    config_from_hf,
    params_from_hf,
    params_to_hf,
)
