"""Mixture-of-Experts layer with expert-parallel dispatch.

Reference parity: ``atorch/atorch/modules/moe/moe_layer.py:161``
(``MOELayer`` with ``_AllToAll:87`` expert dispatch), top-k gating
(``topk_gating.py``) and grouped-GEMM experts (``grouped_gemm_moe.py``).

TPU-native design: experts live stacked on a leading dim annotated with
the "expert" logical axis; token routing is dense one-hot matmuls
(MXU-friendly, static shapes — no sorting/scatter, which XLA can't tile)
with a capacity factor, the canonical Switch/GShard formulation.  Under
expert parallelism the stacked dim is sharded over the "expert" mesh
axis and GSPMD turns the routing einsums into the all-to-all exchange;
``dlrover_tpu.parallel.collectives.expert_all_to_all`` is the explicit
shard_map form for custom schedules.
"""

from dataclasses import dataclass
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from dlrover_tpu.parallel import sharding as sh


@dataclass(frozen=True)
class MoEConfig:
    dim: int
    mlp_dim: int
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    router_aux_weight: float = 0.01
    dtype: object = jnp.bfloat16


def init_moe_params(key, cfg: MoEConfig) -> Dict:
    kr, kg, ku, kd = jax.random.split(key, 4)
    e, d, m = cfg.num_experts, cfg.dim, cfg.mlp_dim
    scale_in = d**-0.5
    scale_mid = m**-0.5
    return {
        "router": jax.random.normal(kr, (d, e), dtype=jnp.float32)
        * scale_in,
        "w_gate": jax.random.normal(kg, (e, d, m), dtype=jnp.float32)
        * scale_in,
        "w_up": jax.random.normal(ku, (e, d, m), dtype=jnp.float32)
        * scale_in,
        "w_down": jax.random.normal(kd, (e, m, d), dtype=jnp.float32)
        * scale_mid,
    }


def moe_param_logical_axes() -> Dict:
    return {
        "router": (sh.EMBED, None),
        "w_gate": (sh.EXPERT, sh.EMBED, sh.MLP),
        "w_up": (sh.EXPERT, sh.EMBED, sh.MLP),
        "w_down": (sh.EXPERT, sh.MLP, sh.EMBED),
    }


def _top_k_gating(
    logits: jnp.ndarray, top_k: int
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Returns (gates [T,E] with zeros off the top-k, aux_loss,
    router_probs [T,E])."""
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    e = logits.shape[-1]
    top_vals, top_idx = jax.lax.top_k(probs, top_k)
    gates = jnp.zeros_like(probs)
    one_hot = jax.nn.one_hot(top_idx, e, dtype=probs.dtype)  # [T,k,E]
    gates = jnp.einsum("tk,tke->te", top_vals, one_hot)
    # renormalize the kept gates
    gates = gates / jnp.maximum(
        jnp.sum(gates, axis=-1, keepdims=True), 1e-9
    )
    # Switch-style load-balancing loss: mean prob * mean assignment
    density = jnp.mean(one_hot[:, 0], axis=0)  # top-1 assignment share
    density_proxy = jnp.mean(probs, axis=0)
    aux = jnp.sum(density * density_proxy) * (e**2) / e
    return gates, aux, probs


def moe_forward(
    params: Dict,
    x: jnp.ndarray,
    cfg: MoEConfig,
    impl: str = "auto",
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """x [B, S, D] -> (y [B, S, D], aux_loss scalar).

    ``impl``: "dense" (one-hot capacity dispatch — composes with the
    expert mesh axis through GSPMD), "grouped" (dropless grouped-GEMM
    over sorted tokens — the fast single-device/expert-replicated
    path, ref ``grouped_gemm_moe.py``), or "auto" (grouped when no
    expert mesh axis is active, dense otherwise)."""
    if impl == "auto":
        from dlrover_tpu.parallel.mesh import get_mesh_context

        ctx = get_mesh_context()
        # grouped only when tokens are NOT sharded: its global
        # argsort/scatter over the flattened token dim would force
        # GSPMD to gather every token on a dp/fsdp/tp mesh (and it
        # changes capacity semantics — dropless vs dropping)
        single = ctx is None or ctx.num_devices <= 1
        impl = "grouped" if single else "dense"
    if impl == "grouped":
        return moe_forward_grouped(params, x, cfg)
    return _moe_forward_dense(params, x, cfg)


def _moe_forward_dense(
    params: Dict, x: jnp.ndarray, cfg: MoEConfig
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Dense-dispatch formulation: tokens -> per-expert capacity buffers
    via one-hot combine/dispatch tensors (static shapes; GSPMD shards
    the expert dim)."""
    b, s, d = x.shape
    t = b * s
    e, k = cfg.num_experts, cfg.top_k
    capacity = max(1, int(cfg.capacity_factor * t * k / e))
    dt = cfg.dtype

    flat = x.reshape(t, d)
    logits = flat.astype(jnp.float32) @ params["router"]
    gates, aux, _ = _top_k_gating(logits, k)  # [T,E]

    # position of each token in its expert's buffer (by arrival order)
    expert_mask = (gates > 0).astype(jnp.int32)  # [T,E]
    position = jnp.cumsum(expert_mask, axis=0) * expert_mask - 1
    in_capacity = (position < capacity) & (expert_mask > 0)
    dispatch = (
        jax.nn.one_hot(
            jnp.where(in_capacity, position, capacity), capacity + 1,
            dtype=dt,
        )[..., :capacity]
        * in_capacity[..., None].astype(dt)
    )  # [T,E,C]
    combine = dispatch * gates[..., None].astype(dt)  # [T,E,C]

    # dispatch tokens: [E, C, D]
    expert_in = jnp.einsum(
        "tec,td->ecd", dispatch, flat.astype(dt)
    )
    expert_in = sh.apply_sharding_constraint(
        expert_in, (sh.EXPERT, None, sh.EMBED), _moe_rules()
    )
    gate = jax.nn.silu(
        jnp.einsum("ecd,edm->ecm", expert_in, params["w_gate"].astype(dt))
    )
    up = jnp.einsum("ecd,edm->ecm", expert_in, params["w_up"].astype(dt))
    expert_out = jnp.einsum(
        "ecm,emd->ecd", gate * up, params["w_down"].astype(dt)
    )
    y = jnp.einsum("tec,ecd->td", combine, expert_out)
    return y.reshape(b, s, d), aux * cfg.router_aux_weight


def moe_forward_grouped(
    params: Dict, x: jnp.ndarray, cfg: MoEConfig
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Dropless grouped-GEMM MoE (megablocks formulation, ref
    ``grouped_gemm_moe.py``): token replicas sorted by expert feed ONE
    ragged GEMM per projection — no capacity buffers, no dropped
    tokens, no one-hot dispatch FLOPs.

    Capacity semantics differ from the dense path by design: every
    routed token is processed (megablocks' selling point); the dense
    path drops tokens past the capacity factor."""
    b, s, d = x.shape
    t = b * s
    e, k = cfg.num_experts, cfg.top_k
    dt = cfg.dtype

    from dlrover_tpu.ops.grouped_gemm import (
        grouped_gemm,
        sort_tokens_by_expert,
    )

    flat = x.reshape(t, d)
    logits = flat.astype(jnp.float32) @ params["router"]
    probs = jax.nn.softmax(logits, axis=-1)
    top_vals, top_idx = jax.lax.top_k(probs, k)  # [T,k]
    gate_vals = top_vals / jnp.maximum(
        jnp.sum(top_vals, axis=-1, keepdims=True), 1e-9
    )
    # same load-balancing loss as the dense path
    one_hot_top1 = jax.nn.one_hot(top_idx[:, 0], e, dtype=probs.dtype)
    density = jnp.mean(one_hot_top1, axis=0)
    density_proxy = jnp.mean(probs, axis=0)
    aux = jnp.sum(density * density_proxy) * (e**2) / e

    expert_ids = top_idx.reshape(-1)  # [T*k]
    order, group_sizes = sort_tokens_by_expert(expert_ids, e)
    tok_of_replica = jnp.arange(t * k) // k
    sorted_tok = tok_of_replica[order]
    sorted_in = flat.astype(dt)[sorted_tok]  # [T*k, D]

    gate_h = jax.nn.silu(
        grouped_gemm(sorted_in, params["w_gate"], group_sizes)
    )
    up_h = grouped_gemm(sorted_in, params["w_up"], group_sizes)
    out = grouped_gemm(gate_h * up_h, params["w_down"], group_sizes)
    out = out * gate_vals.reshape(-1)[order][:, None].astype(dt)
    y = jnp.zeros((t, d), out.dtype).at[sorted_tok].add(out)
    return y.astype(dt).reshape(b, s, d), aux * cfg.router_aux_weight


_rules_holder = {"rules": None}


def set_moe_rules(rules):
    _rules_holder["rules"] = rules


def _moe_rules():
    rules = _rules_holder["rules"]
    if rules is None:
        rules = sh.active_rules()
    if rules is None:
        from dlrover_tpu.parallel.mesh import get_mesh_context

        ctx = get_mesh_context()
        if ctx is not None and ctx.rules is not None:
            rules = ctx.rules
    if rules is None:
        rules = sh.default_rules(fsdp=False, expert_parallel=True)
    return rules
