"""Llama-family transformer, TPU-first functional JAX.

Role parity: the reference accelerates user-supplied HF/Megatron models
(``atorch`` injects FA/TP/MoE into them — SURVEY.md §2.6); a TPU
framework must ship the model family itself.  This is the flagship:
RMSNorm + RoPE + GQA + SwiGLU, bfloat16 activations, layers stacked on
a leading dim and executed with ``lax.scan`` (one compiled block for
all layers — fast compile, XLA-friendly), every parameter carrying a
logical-axes annotation consumed by
``dlrover_tpu.parallel.sharding.LogicalAxisRules``.

Design notes (TPU):
- params are a plain dict pytree; "layers" is a stacked leading axis —
  sharding it on the "pipe" mesh axis gives pipeline stages for free.
- attention is exposed through a pluggable kernel so
  ``dlrover_tpu.ops`` can swap in Pallas flash / ring attention.
- all matmuls run in bfloat16 with fp32 accumulation
  (``preferred_element_type``) — the MXU contract.
"""

from dataclasses import dataclass, field
from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from dlrover_tpu.parallel import sharding as sh


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    mlp_dim: int = 11008
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    # remat policy for the scanned block: "none" | "full" | "dots"
    remat: str = "full"
    # fused-CE row-chunk size (peak logits memory = chunk x vocab fp32;
    # larger chunks = fewer scan trips, bigger lm-head matmuls)
    ce_chunk_rows: int = 512
    # source checkpoint tied lm_head to the embedding (HF
    # tie_word_embeddings); the framework keeps them separate
    # (vocab-sharded lm_head), but HF export must honor the tie
    tie_word_embeddings: bool = False

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @staticmethod
    def tiny(**overrides) -> "LlamaConfig":
        """Test-sized config (virtual-device CI)."""
        base = dict(
            vocab_size=256,
            dim=64,
            n_layers=2,
            n_heads=4,
            n_kv_heads=2,
            mlp_dim=128,
            max_seq_len=128,
        )
        base.update(overrides)
        return LlamaConfig(**base)

    @staticmethod
    def llama2_7b(**overrides) -> "LlamaConfig":
        base = dict(
            vocab_size=32000,
            dim=4096,
            n_layers=32,
            n_heads=32,
            n_kv_heads=32,
            mlp_dim=11008,
            max_seq_len=4096,
        )
        base.update(overrides)
        return LlamaConfig(**base)


# ---------------------------------------------------------------- params


def init_params(key, cfg: LlamaConfig) -> Dict:
    """Stacked-layer param pytree; fp32 master weights."""
    k_embed, k_layers, k_out = jax.random.split(key, 3)
    d, hd = cfg.dim, cfg.head_dim
    nh, nkv, mlp, L = cfg.n_heads, cfg.n_kv_heads, cfg.mlp_dim, cfg.n_layers

    def norm_init(*shape):
        return jnp.ones(shape, dtype=jnp.float32)

    def dense_init(key, *shape, in_axis: int = 0):
        fan_in = shape[in_axis]
        return (
            jax.random.normal(key, shape, dtype=jnp.float32)
            * (fan_in**-0.5)
        )

    keys = jax.random.split(k_layers, 7)
    layer = {
        "attn_norm": norm_init(L, d),
        "wq": dense_init(keys[0], L, d, nh * hd, in_axis=1),
        "wk": dense_init(keys[1], L, d, nkv * hd, in_axis=1),
        "wv": dense_init(keys[2], L, d, nkv * hd, in_axis=1),
        "wo": dense_init(keys[3], L, nh * hd, d, in_axis=1),
        "mlp_norm": norm_init(L, d),
        "w_gate": dense_init(keys[4], L, d, mlp, in_axis=1),
        "w_up": dense_init(keys[5], L, d, mlp, in_axis=1),
        "w_down": dense_init(keys[6], L, mlp, d, in_axis=1),
    }
    return {
        "embed": dense_init(k_embed, cfg.vocab_size, d, in_axis=1),
        "layers": layer,
        "final_norm": norm_init(d),
        "lm_head": dense_init(k_out, d, cfg.vocab_size, in_axis=0),
    }


def loss_fn_ngrouped(
    parts,
    batch: Dict,
    cfg: LlamaConfig,
    attention_fn=None,
    fused_ce: Optional[bool] = None,
) -> jnp.ndarray:
    """``loss_fn`` over an N-group param split: group 0 carries the
    embedding + the first layer segment, middle groups a contiguous
    layer segment each, the last group the tail segment + final norm
    + lm head.  ``jax.grad(..., argnums=i)`` materializes only group
    i's dW carries — at ~3B params on a 16 GB chip the full grads
    tree cannot coexist with the params, so the offloaded step runs
    one backward per group
    (``optimizers.host_offload.build_grouped_offload_step``); more
    groups shrink the peak dW tree further."""
    parts = tuple(parts)
    if len(parts) == 1:
        return loss_fn(parts[0], batch, cfg, attention_fn, fused_ce)
    params = {
        "embed": parts[0]["embed"],
        "layers": tuple(p["layers"] for p in parts),
        "final_norm": parts[-1]["final_norm"],
        "lm_head": parts[-1]["lm_head"],
    }
    return loss_fn(params, batch, cfg, attention_fn, fused_ce)


def loss_fn_grouped(
    params_a: Dict,
    params_b: Dict,
    batch: Dict,
    cfg: LlamaConfig,
    attention_fn=None,
    fused_ce: Optional[bool] = None,
) -> jnp.ndarray:
    """Two-group form of :func:`loss_fn_ngrouped` (kept for the
    legacy ``build_grouped_offload_step`` calling convention)."""
    return loss_fn_ngrouped(
        (params_a, params_b), batch, cfg, attention_fn, fused_ce
    )


def init_ngrouped_params(key, cfg: LlamaConfig, boundaries):
    """Build an N-group layer split WITHOUT materializing the full
    stacked tree (at 3B the fp32 full tree plus its slices would not
    fit): each group initializes from a per-segment config.
    ``boundaries`` are the strictly-increasing layer split points
    (``len(boundaries) + 1`` groups; ``accelerate.solver.
    solve_offload_groups`` chooses them from the per-layer footprint).
    Returns a list of thunks so the caller can free each group's fp32
    source before the next materializes."""
    import dataclasses

    bounds = [0] + list(boundaries) + [cfg.n_layers]
    for lo, hi in zip(bounds, bounds[1:]):
        if hi <= lo:
            raise ValueError(
                f"boundaries {tuple(boundaries)} must be strictly "
                f"increasing within (0, {cfg.n_layers})"
            )
    n_groups = len(bounds) - 1
    keys = jax.random.split(key, n_groups)

    def make(i: int):
        seg_cfg = dataclasses.replace(
            cfg, n_layers=bounds[i + 1] - bounds[i]
        )

        def init() -> Dict:
            t = init_params(keys[i], seg_cfg)
            part = {"layers": t["layers"]}
            if i == 0:
                part["embed"] = t["embed"]
            if i == n_groups - 1:
                part["final_norm"] = t["final_norm"]
                part["lm_head"] = t["lm_head"]
            return part

        return init

    return [make(i) for i in range(n_groups)]


def init_grouped_params(key, cfg: LlamaConfig, boundary: int):
    """Two-group form of :func:`init_ngrouped_params`: returns
    ``(init_a, init_b)`` thunks splitting the stack at ``boundary``."""
    init_a, init_b = init_ngrouped_params(key, cfg, (boundary,))
    return init_a, init_b


def param_logical_axes(cfg: LlamaConfig) -> Dict:
    """Same structure as ``init_params``, leaves = logical-axes tuples
    (None = replicated dim)."""
    return {
        "embed": (sh.VOCAB, sh.EMBED),
        "layers": {
            "attn_norm": (sh.LAYERS, None),
            "wq": (sh.LAYERS, sh.EMBED, sh.HEADS),
            "wk": (sh.LAYERS, sh.EMBED, sh.KV_HEADS),
            "wv": (sh.LAYERS, sh.EMBED, sh.KV_HEADS),
            "wo": (sh.LAYERS, sh.HEADS, sh.EMBED),
            "mlp_norm": (sh.LAYERS, None),
            "w_gate": (sh.LAYERS, sh.EMBED, sh.MLP),
            "w_up": (sh.LAYERS, sh.EMBED, sh.MLP),
            "w_down": (sh.LAYERS, sh.MLP, sh.EMBED),
        },
        "final_norm": (None,),
        "lm_head": (sh.EMBED, sh.VOCAB),
    }


def count_params(params) -> int:
    return sum(
        x.size for x in jax.tree_util.tree_leaves(params)
    )


# --------------------------------------------------------------- modules


def rms_norm(x, weight, eps: float):
    # fused Pallas forward on TPU (saved-rstd backward); plain XLA
    # elsewhere — see ops/fused.py.  Both paths scale in fp32 and
    # cast once, so values are identical across backends.
    from dlrover_tpu.ops.fused import rms_norm as _fused

    return _fused(x, weight, eps)


def rope_frequencies(cfg: LlamaConfig, positions):
    """[S] -> cos/sin [S, head_dim/2] (fp32)."""
    half = cfg.head_dim // 2
    freqs = cfg.rope_theta ** (
        -jnp.arange(0, half, dtype=jnp.float32) / half
    )
    angles = positions.astype(jnp.float32)[:, None] * freqs[None, :]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x, cos, sin):
    """x: [B, S, H, D]; rotate pairs (split-half convention)."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    cos = cos[None, :, None, :]
    sin = sin[None, :, None, :]
    out1 = x1 * cos - x2 * sin
    out2 = x2 * cos + x1 * sin
    return jnp.concatenate([out1, out2], axis=-1).astype(x.dtype)


def dot_product_attention(q, k, v, causal: bool = True):
    """Reference attention kernel [B,S,H,D]x[B,S,KV,D]; the ops package
    swaps this for Pallas flash attention on real TPU."""
    b, s, nh, d = q.shape
    nkv = k.shape[2]
    group = nh // nkv
    q = q.reshape(b, s, nkv, group, d)
    logits = jnp.einsum(
        "bqkgd,bskd->bkgqs", q, k, preferred_element_type=jnp.float32
    ) * (d**-0.5)
    if causal:
        mask = jnp.tril(jnp.ones((s, s), dtype=bool))
        logits = jnp.where(mask[None, None, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum(
        "bkgqs,bskd->bqkgd",
        probs.astype(v.dtype),
        v,
        preferred_element_type=jnp.float32,
    ).astype(v.dtype)
    return out.reshape(b, s, nh, d)


AttentionFn = Callable[..., jnp.ndarray]


def _layer_forward(
    cfg: LlamaConfig,
    attention_fn: AttentionFn,
    lp: Dict,
    x: jnp.ndarray,
    cos: jnp.ndarray,
    sin: jnp.ndarray,
) -> jnp.ndarray:
    b, s, d = x.shape
    nh, nkv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    dt = cfg.dtype

    def proj(a, w):
        # fp32 MXU accumulation, bf16 storage (the contract above)
        return jnp.matmul(
            a, w.astype(dt), preferred_element_type=jnp.float32
        ).astype(dt)

    h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
    q = proj(h, lp["wq"]).reshape(b, s, nh, hd)
    k = proj(h, lp["wk"]).reshape(b, s, nkv, hd)
    v = proj(h, lp["wv"]).reshape(b, s, nkv, hd)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    q = sh.apply_sharding_constraint(
        q, (sh.BATCH, sh.SEQ, sh.HEADS, None), _current_rules()
    )
    attn = attention_fn(q, k, v, causal=True)
    x = x + proj(attn.reshape(b, s, nh * hd), lp["wo"])

    h = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
    gate = jax.nn.silu(proj(h, lp["w_gate"]))
    up = proj(h, lp["w_up"])
    x = x + proj(gate * up, lp["w_down"])
    return x


# activation-sharding rules used inside forward; set by the trainer
_rules_holder = {"rules": None}


def set_activation_rules(rules):
    _rules_holder["rules"] = rules


def _current_rules():
    rules = _rules_holder["rules"]
    if rules is None:
        rules = sh.active_rules()
    if rules is None:
        from dlrover_tpu.parallel.mesh import get_mesh_context

        ctx = get_mesh_context()
        if ctx is not None and ctx.rules is not None:
            rules = ctx.rules
    if rules is None:
        rules = sh.default_rules(fsdp=False)
    return rules


def _default_attention() -> AttentionFn:
    """Strategy-selected kernel (the module-replace pass, resolved at
    trace time): ring attention under seq>1 meshes, Pallas flash
    attention on TPU, dense reference otherwise.  See
    ``dlrover_tpu.accelerate.module_replace``."""
    from dlrover_tpu.accelerate.module_replace import select_attention
    from dlrover_tpu.parallel.mesh import get_mesh_context

    return select_attention(get_mesh_context(), _current_rules())


def forward_hidden(
    params: Dict,
    tokens: jnp.ndarray,
    cfg: LlamaConfig,
    attention_fn: Optional[AttentionFn] = None,
) -> jnp.ndarray:
    """tokens [B, S] int32 -> final-norm hidden states [B, S, D]
    (``cfg.dtype``) — the pre-lm-head activations, so the loss can fuse
    the vocab projection (``ops.fused.fused_linear_cross_entropy``)."""
    if attention_fn is None:
        attention_fn = _default_attention()
    dt = cfg.dtype
    b, s = tokens.shape
    # Gather over an fsdp-sharded embed dim would force the partitioner
    # to move the fsdp axis from dim -1 (table layout) to dim 0 (batch
    # layout) through the gather — an involuntary full remat.  Voluntarily
    # all-gather the (small) table's embed dim first; vocab stays sharded.
    table = sh.apply_sharding_constraint(
        params["embed"].astype(dt), (sh.VOCAB, None), _current_rules()
    )
    x = table[tokens]
    x = sh.apply_sharding_constraint(
        x, (sh.BATCH, sh.SEQ, sh.EMBED), _current_rules()
    )
    positions = jnp.arange(s)
    cos, sin = rope_frequencies(cfg, positions)

    block = partial(_layer_forward, cfg, attention_fn)
    if cfg.remat == "full":
        block = jax.checkpoint(block)
    elif cfg.remat == "dots":
        block = jax.checkpoint(
            block,
            policy=jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims,
        )

    # strategy-selected layer executor: lax.scan normally, the GPipe
    # shard_map pipeline when the mesh runs pipe > 1 (module-replace
    # pass, resolved at trace time like the attention kernel)
    from dlrover_tpu.accelerate.module_replace import (
        select_layer_executor,
    )
    from dlrover_tpu.parallel.mesh import get_mesh_context

    execute_layers = select_layer_executor(get_mesh_context())
    layers = params["layers"]
    # a tuple/list of stacked subtrees runs as SEQUENTIAL scan
    # segments — the grouped-backward path (host_offload
    # build_grouped_offload_step) splits the stack so each group's
    # dW carries materialize alone
    segments = (
        layers if isinstance(layers, (list, tuple)) else (layers,)
    )
    for seg in segments:
        x = execute_layers(block, seg, x, cos, sin)
    return rms_norm(x, params["final_norm"], cfg.norm_eps)


def forward(
    params: Dict,
    tokens: jnp.ndarray,
    cfg: LlamaConfig,
    attention_fn: Optional[AttentionFn] = None,
) -> jnp.ndarray:
    """tokens [B, S] int32 -> logits [B, S, vocab] (fp32)."""
    x = forward_hidden(params, tokens, cfg, attention_fn)
    logits = jnp.einsum(
        "bsd,dv->bsv",
        x,
        params["lm_head"].astype(cfg.dtype),
        preferred_element_type=jnp.float32,
    )
    return logits


# ------------------------------------------------------ KV-cache decode


def init_kv_cache(cfg: LlamaConfig, batch: int, max_len: int) -> Dict:
    """Per-layer K/V cache for autoregressive decode, stacked on the
    layer dim like the params ([L, B, max_len, KV, head_dim])."""
    shape = (cfg.n_layers, batch, max_len, cfg.n_kv_heads, cfg.head_dim)
    return {
        "k": jnp.zeros(shape, dtype=cfg.dtype),
        "v": jnp.zeros(shape, dtype=cfg.dtype),
    }


def decode_step(
    params: Dict,
    tokens: jnp.ndarray,  # [B] current position's token ids
    cache: Dict,
    pos: jnp.ndarray,  # scalar int32: position being decoded
    cfg: LlamaConfig,
) -> Tuple[jnp.ndarray, Dict]:
    """One cached decode step: logits [B, vocab] for position ``pos``
    plus the updated cache.  The inference dual of ``forward`` — prior
    positions' K/V are read from the cache instead of recomputed, so a
    T-token generation costs O(T) attention instead of O(T^2) forward
    passes (the vLLM-style serving path, on the training mesh)."""
    dt = cfg.dtype
    b = tokens.shape[0]
    nh, nkv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    x = params["embed"].astype(dt)[tokens][:, None]  # [B,1,D]
    cos, sin = rope_frequencies(cfg, pos[None])  # [1, hd/2]

    def body(x, layer_in):
        lp, k_cache, v_cache = layer_in

        def proj(a, w):
            return jnp.matmul(
                a, w.astype(dt), preferred_element_type=jnp.float32
            ).astype(dt)

        h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        q = apply_rope(proj(h, lp["wq"]).reshape(b, 1, nh, hd), cos, sin)
        k = apply_rope(
            proj(h, lp["wk"]).reshape(b, 1, nkv, hd), cos, sin
        )
        v = proj(h, lp["wv"]).reshape(b, 1, nkv, hd)
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k, (0, pos, 0, 0)
        )
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v, (0, pos, 0, 0)
        )
        # attention of the single query over the cached prefix
        group = nh // nkv
        qg = q.reshape(b, nkv, group, hd)
        logits = jnp.einsum(
            "bkgd,bskd->bkgs", qg, k_cache,
            preferred_element_type=jnp.float32,
        ) * (hd**-0.5)
        valid = (
            jnp.arange(k_cache.shape[1]) <= pos
        )  # causal: prefix only
        logits = jnp.where(valid[None, None, None], logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1)
        attn = jnp.einsum(
            "bkgs,bskd->bkgd", probs.astype(dt), v_cache,
            preferred_element_type=jnp.float32,
        ).astype(dt)
        x = x + proj(
            attn.reshape(b, 1, nh * hd), lp["wo"]
        )
        h = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
        gate = jax.nn.silu(proj(h, lp["w_gate"]))
        up = proj(h, lp["w_up"])
        x = x + proj(gate * up, lp["w_down"])
        return x, (k_cache, v_cache)

    x, (new_k, new_v) = lax.scan(
        body, x, (params["layers"], cache["k"], cache["v"])
    )
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = jnp.einsum(
        "bsd,dv->bsv", x, params["lm_head"].astype(dt),
        preferred_element_type=jnp.float32,
    )
    return logits[:, 0], {"k": new_k, "v": new_v}


def prefill(
    params: Dict,
    tokens: jnp.ndarray,  # [B, P] prompt tokens
    cache: Dict,
    cfg: LlamaConfig,
) -> Tuple[jnp.ndarray, Dict]:
    """Batched single-forward prefill: one causal pass over the whole
    prompt that writes every position's K/V into the cache — the
    replacement for feeding the prompt one token at a time through
    ``decode_step`` under ``lax.scan`` (P cached steps -> 1 forward).
    Returns (logits [B, P, vocab] fp32, cache); callers gather the
    last *real* position's row to sample the first new token."""
    dt = cfg.dtype
    b, p = tokens.shape
    nh, nkv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    x = params["embed"].astype(dt)[tokens]  # [B, P, D]
    cos, sin = rope_frequencies(cfg, jnp.arange(p))

    def body(x, layer_in):
        lp, k_cache, v_cache = layer_in

        def proj(a, w):
            return jnp.matmul(
                a, w.astype(dt), preferred_element_type=jnp.float32
            ).astype(dt)

        h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        q = apply_rope(proj(h, lp["wq"]).reshape(b, p, nh, hd), cos, sin)
        k = apply_rope(
            proj(h, lp["wk"]).reshape(b, p, nkv, hd), cos, sin
        )
        v = proj(h, lp["wv"]).reshape(b, p, nkv, hd)
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k, (0, 0, 0, 0)
        )
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v, (0, 0, 0, 0)
        )
        attn = dot_product_attention(q, k, v, causal=True)
        x = x + proj(attn.reshape(b, p, nh * hd), lp["wo"])
        h = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
        gate = jax.nn.silu(proj(h, lp["w_gate"]))
        up = proj(h, lp["w_up"])
        x = x + proj(gate * up, lp["w_down"])
        return x, (k_cache, v_cache)

    x, (new_k, new_v) = lax.scan(
        body, x, (params["layers"], cache["k"], cache["v"])
    )
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = jnp.einsum(
        "bsd,dv->bsv", x, params["lm_head"].astype(dt),
        preferred_element_type=jnp.float32,
    )
    return logits, {"k": new_k, "v": new_v}


# ----------------------------------------------- paged (block-table) decode


def _apply_rope_rows(x, cos, sin):
    """x: [B, 1, H, D] single position per row; cos/sin [B, D/2]
    (each row at its OWN position — the continuous-batching decode
    case, where slot b sits at position ``positions[b]``)."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    cos = cos[:, None, None, :]
    sin = sin[:, None, None, :]
    out1 = x1 * cos - x2 * sin
    out2 = x2 * cos + x1 * sin
    return jnp.concatenate([out1, out2], axis=-1).astype(x.dtype)


def paged_decode_step(
    params: Dict,
    tokens: jnp.ndarray,  # [B] current token per slot
    pool: Dict,  # {"k","v"}: [L, num_blocks, block_size, KV, D]
    block_tables: jnp.ndarray,  # [B, max_blocks] int32
    positions: jnp.ndarray,  # [B] int32 position being decoded per slot
    active: jnp.ndarray,  # [B] bool: slot holds a live sequence
    cfg: LlamaConfig,
) -> Tuple[jnp.ndarray, Dict]:
    """One continuous-batching decode step: every ACTIVE slot advances
    its own sequence by one token at its own position.  All shapes are
    functions of (max_slots, pool geometry) only — admissions and
    evictions change the *contents* of ``block_tables`` / ``positions``
    / ``active``, never the program, so this compiles exactly once.

    Inactive lanes write to the null block (id 0) and read garbage
    that callers discard; their table rows must be zeroed on eviction
    so a freed block re-issued to another sequence is never gathered
    through a stale table.

    The attention call dispatches per ``DLROVER_TPU_PAGED_KERNEL``
    (``ops/paged_attention.paged_kernel_backend``): the streamed Pallas
    decode kernel or the gather-based jnp reference.  The choice is
    resolved at trace time, so the compile-once contract above holds
    under either backend."""
    from dlrover_tpu.ops.paged_attention import (
        paged_decode_attention,
        write_block_kv,
    )

    dt = cfg.dtype
    b = tokens.shape[0]
    nh, nkv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    bs = pool["k"].shape[2]
    mb = block_tables.shape[1]
    x = params["embed"].astype(dt)[tokens][:, None]  # [B, 1, D]
    cos, sin = rope_frequencies(cfg, positions)  # [B, hd/2]
    # a position past the table (a multi-token draft window running
    # beyond the sequence's budget) must write to the null block — a
    # clamped gather would alias the LAST real block and scribble
    # draft garbage over real K/V
    blk_idx = positions // bs
    blk = jnp.where(
        active & (blk_idx < mb),
        jnp.take_along_axis(
            block_tables, jnp.minimum(blk_idx, mb - 1)[:, None],
            axis=1,
        )[:, 0],
        0,
    )
    off = jnp.where(active, positions % bs, 0)
    seq_lens = jnp.where(active, positions + 1, 1)

    def body(x, layer_in):
        lp, k_pool, v_pool = layer_in

        def proj(a, w):
            return jnp.matmul(
                a, w.astype(dt), preferred_element_type=jnp.float32
            ).astype(dt)

        h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        q = _apply_rope_rows(
            proj(h, lp["wq"]).reshape(b, 1, nh, hd), cos, sin
        )
        k = _apply_rope_rows(
            proj(h, lp["wk"]).reshape(b, 1, nkv, hd), cos, sin
        )
        v = proj(h, lp["wv"]).reshape(b, 1, nkv, hd)
        k_pool, v_pool = write_block_kv(
            k_pool, v_pool, k[:, 0], v[:, 0], blk, off
        )
        attn = paged_decode_attention(
            q[:, 0], k_pool, v_pool, block_tables, seq_lens
        )
        x = x + proj(attn.reshape(b, 1, nh * hd), lp["wo"])
        h = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
        gate = jax.nn.silu(proj(h, lp["w_gate"]))
        up = proj(h, lp["w_up"])
        x = x + proj(gate * up, lp["w_down"])
        return x, (k_pool, v_pool)

    x, (new_k, new_v) = lax.scan(
        body, x, (params["layers"], pool["k"], pool["v"])
    )
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = jnp.einsum(
        "bsd,dv->bsv", x, params["lm_head"].astype(dt),
        preferred_element_type=jnp.float32,
    )
    return logits[:, 0], {"k": new_k, "v": new_v}


def _apply_rope_grid(x, cos, sin):
    """x: [B, C, H, D]; cos/sin [B, C, D/2] — every (lane, window
    offset) pair rotated at its OWN position (the multi-token verify
    case, where lane b's window starts at ``positions[b]``)."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    cos = cos[:, :, None, :]
    sin = sin[:, :, None, :]
    out1 = x1 * cos - x2 * sin
    out2 = x2 * cos + x1 * sin
    return jnp.concatenate([out1, out2], axis=-1).astype(x.dtype)


def paged_verify_step(
    params: Dict,
    tokens: jnp.ndarray,  # [B, C]: window of C tokens per lane
    pool: Dict,  # {"k","v"}: [L, num_blocks, block_size, KV, D]
    block_tables: jnp.ndarray,  # [B, max_blocks] int32
    positions: jnp.ndarray,  # [B] int32: lane's first window position
    active: jnp.ndarray,  # [B] bool: lane holds a live sequence
    cfg: LlamaConfig,
) -> jnp.ndarray:
    """The speculative-decode verify forward: score a C-token draft
    window for every lane in ONE forward.  ``tokens[b, i]`` sits at
    position ``positions[b] + i``; its K/V must already be in the
    pool (the draft loop wrote it), so this is READ-ONLY — the pool is
    never touched, which keeps the drafted cache bit-identical whether
    or not verification ran.  Returns logits ``[B, C, vocab]`` (fp32);
    row ``i`` predicts the token at position ``positions[b] + i + 1``.
    Inactive lanes compute on garbage their caller discards.

    The attention call dispatches per ``DLROVER_TPU_PAGED_KERNEL``:
    the fused Pallas verify kernel shares one paged-prefix pass across
    the window's C positions; the jnp reference re-gathers the pool."""
    from dlrover_tpu.ops.paged_attention import paged_verify_attention

    dt = cfg.dtype
    b, c = tokens.shape
    nh, nkv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    pos_grid = positions[:, None] + jnp.arange(c)[None]  # [B, C]
    x = params["embed"].astype(dt)[tokens]  # [B, C, D]
    cos, sin = rope_frequencies(cfg, pos_grid.reshape(-1))
    cos = cos.reshape(b, c, -1)
    sin = sin.reshape(b, c, -1)
    safe_pos = jnp.where(active, positions, 0)

    def body(x, layer_in):
        lp, k_pool, v_pool = layer_in

        def proj(a, w):
            return jnp.matmul(
                a, w.astype(dt), preferred_element_type=jnp.float32
            ).astype(dt)

        h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        q = _apply_rope_grid(
            proj(h, lp["wq"]).reshape(b, c, nh, hd), cos, sin
        )
        attn = paged_verify_attention(
            q, k_pool, v_pool, block_tables, safe_pos
        )
        x = x + proj(attn.reshape(b, c, nh * hd), lp["wo"])
        h = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
        gate = jax.nn.silu(proj(h, lp["w_gate"]))
        up = proj(h, lp["w_up"])
        x = x + proj(gate * up, lp["w_down"])
        return x, None

    x, _ = lax.scan(
        body, x, (params["layers"], pool["k"], pool["v"])
    )
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = jnp.einsum(
        "bsd,dv->bsv", x, params["lm_head"].astype(dt),
        preferred_element_type=jnp.float32,
    )
    return logits


def paged_verify_write_step(
    params: Dict,
    tokens: jnp.ndarray,  # [B, C]: window of C tokens per lane
    pool: Dict,  # {"k","v"}: [L, num_blocks, block_size, KV, D]
    block_tables: jnp.ndarray,  # [B, max_blocks] int32
    positions: jnp.ndarray,  # [B] int32: lane's first window position
    active: jnp.ndarray,  # [B] bool: lane holds a live sequence
    cfg: LlamaConfig,
) -> Tuple[jnp.ndarray, Dict]:
    """Verify a C-token draft window AND write its K/V into the pool.

    The separate-drafter flywheel path: a small DRAFT model ran the
    draft loop against its OWN pool, so — unlike the self-drafting
    ``paged_verify_step`` — the POLICY's K/V for the window positions
    does not exist yet.  This forward scores the window exactly like
    ``paged_verify_step`` while also projecting k/v and scattering
    them at positions ``positions[b] + i`` (null-block routing for
    inactive lanes and past-table positions, the ``paged_decode_step``
    discipline), so the policy cache ends the step as if the policy
    had decoded the window itself.  Rejected draft tail positions are
    overwritten by later decode/draft writes before they become
    attendable — same garbage discipline as padded prefill tails.
    Returns (logits [B, C, vocab] fp32, pool)."""
    from dlrover_tpu.ops.paged_attention import (
        paged_verify_attention,
        write_block_kv,
    )

    dt = cfg.dtype
    b, c = tokens.shape
    nh, nkv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    bs = pool["k"].shape[2]
    mb = block_tables.shape[1]
    pos_grid = positions[:, None] + jnp.arange(c)[None]  # [B, C]
    x = params["embed"].astype(dt)[tokens]  # [B, C, D]
    cos, sin = rope_frequencies(cfg, pos_grid.reshape(-1))
    cos = cos.reshape(b, c, -1)
    sin = sin.reshape(b, c, -1)
    safe_pos = jnp.where(active, positions, 0)
    # per-(lane, offset) write routing — flattened to [B*C] for the
    # scatter; inactive lanes and past-table positions hit block 0
    blk_idx = pos_grid // bs  # [B, C]
    blks = jnp.where(
        active[:, None] & (blk_idx < mb),
        jnp.take_along_axis(
            block_tables, jnp.minimum(blk_idx, mb - 1), axis=1
        ),
        0,
    ).reshape(-1)
    offs = jnp.where(active[:, None], pos_grid % bs, 0).reshape(-1)

    def body(x, layer_in):
        lp, k_pool, v_pool = layer_in

        def proj(a, w):
            return jnp.matmul(
                a, w.astype(dt), preferred_element_type=jnp.float32
            ).astype(dt)

        h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        q = _apply_rope_grid(
            proj(h, lp["wq"]).reshape(b, c, nh, hd), cos, sin
        )
        k = _apply_rope_grid(
            proj(h, lp["wk"]).reshape(b, c, nkv, hd), cos, sin
        )
        v = proj(h, lp["wv"]).reshape(b, c, nkv, hd)
        k_pool, v_pool = write_block_kv(
            k_pool, v_pool,
            k.reshape(b * c, nkv, hd), v.reshape(b * c, nkv, hd),
            blks, offs,
        )
        attn = paged_verify_attention(
            q, k_pool, v_pool, block_tables, safe_pos
        )
        x = x + proj(attn.reshape(b, c, nh * hd), lp["wo"])
        h = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
        gate = jax.nn.silu(proj(h, lp["w_gate"]))
        up = proj(h, lp["w_up"])
        x = x + proj(gate * up, lp["w_down"])
        return x, (k_pool, v_pool)

    x, (new_k, new_v) = lax.scan(
        body, x, (params["layers"], pool["k"], pool["v"])
    )
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = jnp.einsum(
        "bsd,dv->bsv", x, params["lm_head"].astype(dt),
        preferred_element_type=jnp.float32,
    )
    return logits, {"k": new_k, "v": new_v}


def paged_prefill_chunk(
    params: Dict,
    tokens: jnp.ndarray,  # [1, C] one sequence's prompt chunk
    pool: Dict,  # {"k","v"}: [L, num_blocks, block_size, KV, D]
    block_table: jnp.ndarray,  # [max_blocks] int32
    start_pos: jnp.ndarray,  # scalar int32: chunk's first position
    cfg: LlamaConfig,
) -> Tuple[jnp.ndarray, Dict]:
    """Prefill C prompt positions of ONE sequence into its paged
    blocks (fixed chunk shape — a long prompt runs as several chunks
    interleaved with other sequences' decode steps, so it can never
    stall them).  Padded tail positions write ahead of the prompt into
    the sequence's own reservation; decode overwrites each position
    before it becomes visible, so the garbage is never attended.
    Returns (logits [1, C, vocab], pool)."""
    from dlrover_tpu.ops.paged_attention import (
        paged_prefill_attention,
        write_block_kv,
    )

    dt = cfg.dtype
    b, c = tokens.shape
    nh, nkv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    bs = pool["k"].shape[2]
    positions = start_pos + jnp.arange(c)  # [C]
    x = params["embed"].astype(dt)[tokens]  # [1, C, D]
    cos, sin = rope_frequencies(cfg, positions)
    # a padded chunk tail can run past the table: route those writes
    # to the null block explicitly — a clamped gather would alias the
    # sequence's LAST real block and let pad garbage race real K/V
    blk_idx = positions // bs
    mb = block_table.shape[0]
    blks = jnp.where(
        blk_idx < mb,
        block_table[jnp.minimum(blk_idx, mb - 1)],
        0,
    )  # [C]
    offs = positions % bs

    def body(x, layer_in):
        lp, k_pool, v_pool = layer_in

        def proj(a, w):
            return jnp.matmul(
                a, w.astype(dt), preferred_element_type=jnp.float32
            ).astype(dt)

        h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        q = apply_rope(proj(h, lp["wq"]).reshape(b, c, nh, hd), cos, sin)
        k = apply_rope(
            proj(h, lp["wk"]).reshape(b, c, nkv, hd), cos, sin
        )
        v = proj(h, lp["wv"]).reshape(b, c, nkv, hd)
        k_pool, v_pool = write_block_kv(
            k_pool, v_pool, k[0], v[0], blks, offs
        )
        attn = paged_prefill_attention(
            q[0], k_pool, v_pool, block_table, start_pos
        )
        x = x + proj(attn[None].reshape(b, c, nh * hd), lp["wo"])
        h = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
        gate = jax.nn.silu(proj(h, lp["w_gate"]))
        up = proj(h, lp["w_up"])
        x = x + proj(gate * up, lp["w_down"])
        return x, (k_pool, v_pool)

    x, (new_k, new_v) = lax.scan(
        body, x, (params["layers"], pool["k"], pool["v"])
    )
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = jnp.einsum(
        "bsd,dv->bsv", x, params["lm_head"].astype(dt),
        preferred_element_type=jnp.float32,
    )
    return logits, {"k": new_k, "v": new_v}


# fused CE kicks in for real vocabularies; tiny test configs keep the
# dense form so the loss is bit-identical to the naive reference
_FUSED_CE_MIN_VOCAB = 8192


def loss_fn(
    params: Dict,
    batch: Dict,
    cfg: LlamaConfig,
    attention_fn: Optional[AttentionFn] = None,
    fused_ce: Optional[bool] = None,
) -> jnp.ndarray:
    """Next-token cross entropy; batch = {"tokens": [B, S+1]} or
    {"inputs", "targets"} (+ optional "mask").

    ``fused_ce`` (default: auto — on when vocab >= 8192) routes the
    lm-head projection through
    ``ops.fused.fused_linear_cross_entropy`` so fp32 logits are never
    materialized at [B, S, V] — the dominant activation at long seq."""
    if "inputs" in batch:
        inputs, targets = batch["inputs"], batch["targets"]
    else:
        inputs, targets = batch["tokens"][:, :-1], batch["tokens"][:, 1:]
    mask = batch.get("mask")
    if fused_ce is None:
        fused_ce = cfg.vocab_size >= _FUSED_CE_MIN_VOCAB
    if fused_ce:
        from dlrover_tpu.ops.fused import fused_linear_cross_entropy

        hidden = forward_hidden(params, inputs, cfg, attention_fn)
        return fused_linear_cross_entropy(
            hidden,
            params["lm_head"],
            targets,
            mask,
            chunk_rows=cfg.ce_chunk_rows,
        )
    logits = forward(params, inputs, cfg, attention_fn)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(
        logp, targets[..., None], axis=-1
    ).squeeze(-1)
    if mask is not None:
        return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1)
    return jnp.mean(nll)
