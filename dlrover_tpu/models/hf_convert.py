"""HuggingFace Llama checkpoint interop.

Reference parity: DLRover accelerates user-supplied HF models and
ships an HF-Trainer flash-checkpoint adapter
(``dlrover/trainer/torch/flash_checkpoint/hf_trainer.py``); a user
switching to this framework brings HF Llama weights with them.  This
module converts ``transformers`` LlamaForCausalLM state dicts to and
from the framework's stacked-layer param pytree
(``models/llama.py:init_params``), so pretraining continues from (or
exports to) the HF ecosystem.

Layout notes (verified by the logits-parity test):
- torch Linear stores ``[out, in]``; the JAX params store ``[in,
  out]`` — every projection transposes.
- our ``layers`` subtree stacks all layers on a leading dim (scan
  executor), so per-layer HF tensors are stacked with ``np.stack``.
- RoPE: HF applies split-half rotate_half, the same convention as
  ``apply_rope`` — weights convert with no permutation.
- ``tie_word_embeddings=True`` models reuse the embedding as lm_head;
  the converter materializes the transpose (the framework keeps them
  separate — VOCAB-sharded lm_head).
"""

from typing import Dict, Optional, Tuple

import numpy as np

from dlrover_tpu.models.llama import LlamaConfig


def _t(x) -> np.ndarray:
    """torch tensor / array -> fp32 numpy (no torch import needed at
    module level; anything with ``.detach`` is treated as a tensor)."""
    if hasattr(x, "detach"):
        x = x.detach().cpu().float().numpy()
    return np.asarray(x, dtype=np.float32)


def config_from_hf(hf_config) -> LlamaConfig:
    """transformers LlamaConfig -> framework LlamaConfig.

    Raises ``ValueError`` for features the framework's RoPE does not
    implement (Llama-3.x ``rope_scaling``, decoupled ``head_dim``):
    converting those silently would produce a model whose position
    embeddings differ from the source — corrupted, not finetuned."""
    scaling = getattr(hf_config, "rope_scaling", None)
    if scaling and scaling.get("rope_type", scaling.get("type")) not in (
        None,
        "default",
    ):
        raise ValueError(
            f"unsupported rope_scaling {scaling!r}: the framework "
            "implements plain-theta RoPE only"
        )
    head_dim = getattr(hf_config, "head_dim", None)
    derived = hf_config.hidden_size // hf_config.num_attention_heads
    if head_dim not in (None, derived):
        raise ValueError(
            f"unsupported head_dim={head_dim} (hidden/heads={derived}):"
            " the framework derives head_dim from dim//n_heads"
        )
    return LlamaConfig(
        vocab_size=hf_config.vocab_size,
        dim=hf_config.hidden_size,
        n_layers=hf_config.num_hidden_layers,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=getattr(
            hf_config,
            "num_key_value_heads",
            hf_config.num_attention_heads,
        )
        or hf_config.num_attention_heads,
        mlp_dim=hf_config.intermediate_size,
        max_seq_len=hf_config.max_position_embeddings,
        rope_theta=float(getattr(hf_config, "rope_theta", 10000.0)),
        norm_eps=float(getattr(hf_config, "rms_norm_eps", 1e-5)),
        tie_word_embeddings=bool(
            getattr(hf_config, "tie_word_embeddings", False)
        ),
    )


def params_from_hf(
    state_dict: Dict,
    cfg: Optional[LlamaConfig] = None,
    hf_config=None,
) -> Tuple[Dict, LlamaConfig]:
    """HF ``LlamaForCausalLM.state_dict()`` (or a model instance) ->
    (framework params pytree, LlamaConfig).

    Pass either the target ``cfg`` or the source ``hf_config``; with a
    model instance both are derived."""
    if hasattr(state_dict, "state_dict"):  # a model instance
        if hf_config is None:
            hf_config = state_dict.config
        state_dict = state_dict.state_dict()
    if cfg is None:
        if hf_config is None:
            raise ValueError("need cfg or hf_config")
        cfg = config_from_hf(hf_config)

    sd = {k: _t(v) for k, v in state_dict.items()}
    L = cfg.n_layers

    def stack(fmt: str, transpose: bool) -> np.ndarray:
        tensors = []
        for i in range(L):
            w = sd[fmt.format(i)]
            tensors.append(w.T if transpose else w)
        return np.stack(tensors)

    embed = sd["model.embed_tokens.weight"]  # [V, D]
    if "lm_head.weight" in sd:
        lm_head = sd["lm_head.weight"].T  # [V, D] -> [D, V]
    else:  # tied embeddings
        lm_head = embed.T.copy()

    params = {
        "embed": embed,
        "layers": {
            "attn_norm": stack(
                "model.layers.{}.input_layernorm.weight", False
            ),
            "wq": stack(
                "model.layers.{}.self_attn.q_proj.weight", True
            ),
            "wk": stack(
                "model.layers.{}.self_attn.k_proj.weight", True
            ),
            "wv": stack(
                "model.layers.{}.self_attn.v_proj.weight", True
            ),
            "wo": stack(
                "model.layers.{}.self_attn.o_proj.weight", True
            ),
            "mlp_norm": stack(
                "model.layers.{}.post_attention_layernorm.weight",
                False,
            ),
            "w_gate": stack(
                "model.layers.{}.mlp.gate_proj.weight", True
            ),
            "w_up": stack("model.layers.{}.mlp.up_proj.weight", True),
            "w_down": stack(
                "model.layers.{}.mlp.down_proj.weight", True
            ),
        },
        "final_norm": sd["model.norm.weight"],
        "lm_head": lm_head,
    }
    import jax.numpy as jnp

    params = {
        k: (
            {kk: jnp.asarray(vv) for kk, vv in v.items()}
            if isinstance(v, dict)
            else jnp.asarray(v)
        )
        for k, v in params.items()
    }
    return params, cfg


def params_to_hf(
    params: Dict, cfg: LlamaConfig, tied: Optional[bool] = None
) -> Dict:
    """Framework params -> HF-layout numpy state dict (torch-free; feed
    to ``model.load_state_dict`` after ``torch.from_numpy``).

    ``tied=True`` omits ``lm_head.weight``, matching the
    ``save_pretrained`` artifact of a ``tie_word_embeddings=True``
    model (safetensors strips the shared tensor; ``from_pretrained``
    re-ties on load).  Default follows ``cfg.tie_word_embeddings``
    (set by ``config_from_hf``) — the config carries the truth;
    comparing tensors would misclassify an untied model whose weights
    have not yet diverged.  Pass ``tied=False`` when feeding a raw
    ``load_state_dict`` (a tied model's in-memory state dict KEEPS
    the duplicate key and a strict load requires it)."""
    lp = params["layers"]
    embed = _t(params["embed"])
    if tied is None:
        tied = cfg.tie_word_embeddings
    out: Dict[str, np.ndarray] = {
        "model.embed_tokens.weight": embed,
        "model.norm.weight": _t(params["final_norm"]),
    }
    if not tied:
        out["lm_head.weight"] = _t(params["lm_head"]).T  # [D,V]->[V,D]
    names = {
        "attn_norm": ("model.layers.{}.input_layernorm.weight", False),
        "wq": ("model.layers.{}.self_attn.q_proj.weight", True),
        "wk": ("model.layers.{}.self_attn.k_proj.weight", True),
        "wv": ("model.layers.{}.self_attn.v_proj.weight", True),
        "wo": ("model.layers.{}.self_attn.o_proj.weight", True),
        "mlp_norm": (
            "model.layers.{}.post_attention_layernorm.weight",
            False,
        ),
        "w_gate": ("model.layers.{}.mlp.gate_proj.weight", True),
        "w_up": ("model.layers.{}.mlp.up_proj.weight", True),
        "w_down": ("model.layers.{}.mlp.down_proj.weight", True),
    }
    for key, (fmt, transpose) in names.items():
        stacked = _t(lp[key])
        for i in range(cfg.n_layers):
            w = stacked[i]
            out[fmt.format(i)] = w.T.copy() if transpose else w.copy()
    return out
