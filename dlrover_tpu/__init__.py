"""dlrover_tpu: a TPU-native elastic distributed training framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of DLRover
(elastic master/agent control plane, flash checkpoint, auto-parallelism)
for TPU pod slices.  See SURVEY.md at the repo root for the blueprint.

Layering (mirrors the reference architecture roles, not its code):

- ``dlrover_tpu.common``   -- constants, node model, message envelope, IPC,
  checkpoint storage (reference: ``dlrover/python/common``).
- ``dlrover_tpu.master``   -- per-job master: rendezvous, dynamic data
  sharding, node supervision, autoscaling (reference:
  ``dlrover/python/master``).
- ``dlrover_tpu.agent``    -- per-host elastic agent: process supervision,
  master-backed rendezvous, async checkpoint saver, TPU health checks
  (reference: ``dlrover/python/elastic_agent``).
- ``dlrover_tpu.trainer``  -- user-facing API: ``dlrover-tpu-run`` CLI,
  ElasticTrainer, flash-checkpoint Checkpointer (reference:
  ``dlrover/trainer``).
- ``dlrover_tpu.parallel`` -- mesh / named-axis parallelism: DP, FSDP, TP,
  Ulysses + ring sequence parallel, MoE expert parallel, pipeline
  (reference: ``atorch/distributed`` + ``atorch/modules``).
- ``dlrover_tpu.accel``    -- ``auto_accelerate``-style strategy engine
  emitting sharding plans (reference: ``atorch/auto``).
- ``dlrover_tpu.models``   -- flagship model families (llama-style
  transformer, MoE) built on the parallel layer.
- ``dlrover_tpu.ops``      -- Pallas kernels (flash attention, ring
  attention, grouped GEMM) with XLA fallbacks.
- ``dlrover_tpu.optim``    -- optimizers (AGD, WSAM, low-bit states)
  as optax transforms (reference: ``atorch/optimizers``).
"""

__version__ = "0.1.0"
