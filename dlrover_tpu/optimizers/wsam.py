"""Weighted Sharpness-Aware Minimization (KDD'23), functional form.

Reference parity: ``atorch/atorch/optimizers/wsam.py:11``
(``WeightedSAM``) — two-pass SAM where the final gradient mixes the
base gradient and the sharpness gradient with weight
``alpha = gamma / (1 - gamma)``; the torch version is a closure-driven
optimizer wrapper, the JAX version is a gradient transform:
``wsam_gradients`` runs both passes and returns the combined gradient
for any optax optimizer (data-parallel mean included by the caller's
pjit — no explicit allreduce needed).
"""

from typing import Callable, Tuple

import jax
import jax.numpy as jnp
import optax


def _normalized_perturbation(grads, params, rho, adaptive, eps):
    if adaptive:
        scaled = jax.tree_util.tree_map(
            lambda p, g: jnp.abs(p) * g, params, grads
        )
    else:
        scaled = grads
    norm = optax.global_norm(scaled)
    scale = rho / (norm + eps)
    if adaptive:
        return jax.tree_util.tree_map(
            lambda p, g: (p**2) * g * scale, params, grads
        )
    return jax.tree_util.tree_map(lambda g: g * scale, grads)


def wsam_gradients(
    loss_and_grad_fn: Callable,
    params,
    batch,
    rho: float = 0.05,
    gamma: float = 0.9,
    adaptive: bool = False,
    decouple: bool = True,
    eps: float = 1e-12,
) -> Tuple[jnp.ndarray, optax.Updates, optax.Updates]:
    """Returns (loss, combined_grads, sharpness_grads).

    - coupled (decouple=False): combined = (1-alpha)*g_w + alpha*g_adv
    - decoupled (default): combined = g_w; sharpness = g_adv - g_w
      must be applied by the caller as an extra
      ``-lr * alpha * sharpness`` step (reference ``wsam.py:97-103``).
    """
    alpha = gamma / (1.0 - gamma)
    loss, g_w = loss_and_grad_fn(params, batch)
    e_w = _normalized_perturbation(g_w, params, rho, adaptive, eps)
    params_adv = jax.tree_util.tree_map(jnp.add, params, e_w)
    _, g_adv = loss_and_grad_fn(params_adv, batch)
    if decouple:
        sharpness = jax.tree_util.tree_map(
            jnp.subtract, g_adv, g_w
        )
        return loss, g_w, sharpness
    combined = jax.tree_util.tree_map(
        lambda gw, ga: (1.0 - alpha) * gw + alpha * ga, g_w, g_adv
    )
    zeros = jax.tree_util.tree_map(jnp.zeros_like, g_w)
    return loss, combined, zeros


def wsam_apply_sharpness(params, sharpness, learning_rate, gamma):
    """The decoupled sharpness correction step."""
    alpha = gamma / (1.0 - gamma)
    return jax.tree_util.tree_map(
        lambda p, s: p - learning_rate * alpha * s, params, sharpness
    )
