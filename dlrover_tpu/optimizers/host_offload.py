"""Host-offloaded AdamW: optimizer state lives in HOST memory.

Reference parity: ``atorch/atorch/optimizers/adam_offload.py`` (309
LoC: fp32 master params + Adam moments on the host, bucket-wise
grad D2H / param H2D around a CPU AVX update).  A v5e chip has 16 GB
HBM; fp32 AdamW costs 16 bytes/param of resident state (master + two
moments) + 2 bytes of bf16 compute params — host-resident state is
the standard lever past ~1B params/chip when int8 moments are not
enough.

TPU redesign (single-chip scale lever; on pods the same state is
SHARDED over the fsdp axis instead — ``parallel/train_step.py``):

- device holds only **bf16 compute params**; fp32 master params and
  fp32 moments live in HOST memory (host DRAM, no HBM).
- backward runs as one jit (bf16 params -> bf16 grads).
- the update streams CHUNKS of (master, mu, nu, grad) through the
  chip: H2D in, fused Adam math on device, bf16 param chunk + updated
  fp32 chunks out.  Chunking bounds the HBM transient to
  ``6 * chunk_bytes`` regardless of leaf size (the reference's bucket
  loop, same reason).

Two storage backends for the host state:

- ``pinned_host`` (default on TPU): chunks are jax arrays with
  ``memory_kind="pinned_host"`` — resident in the **TPU host's** RAM
  and DMA'd over its PCIe by XLA-compiled transfer programs, with
  donation recycling the host buffers.  This is the XLA-memories
  redesign of the reference's cudaMemcpy bucket loop, and the only
  correct choice when the Python client is NOT the TPU host (a
  remote/tunnel attachment would otherwise haul every chunk over the
  network).
- ``numpy`` (default on CPU/tests): plain in-process numpy buffers,
  updated in place, with a sliding in-flight window overlapping
  transfers and compute.

Either way the state checkpoints through the flash-ckpt engine:
leaves are ``device_get``-able (numpy ones already are).

``moments="int8"`` additionally stores the offloaded moments
blockwise-quantized (the host-offload dual of
``optimizers.quantized_moments``): the per-step stream drops from
~24 to ~12 bytes/param — the offload proof is PCIe-bound (~59% of
device time in chunk DMA), so halving the traffic is the single
biggest lever.  ``nu`` stores sqrt(nu) exactly like the resident int8
optimizer (dynamic-range rationale in ``optimizers/low_bit.py``).
"""

import functools
import os
from typing import Dict, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from dlrover_tpu.common.log import default_logger as logger

# 64M elements = 256 MB per fp32 chunk buffer; the update transient is
# ~6 buffers (3 in, 3 out) plus the resident bf16 params and grads
DEFAULT_CHUNK_ELEMS = 64 * 1024 * 1024

#: kill-switch: ``=0`` restores the pre-DMA-pipeline behavior exactly
#: (one-shot first-window prefetch instead of the rolling
#: double-buffered window)
OFFLOAD_BUFFERED_ENV = "DLROVER_TPU_OFFLOAD_BUFFERED"
#: kill-switch for the quantized optimizer-state TRANSFERS (fp32
#: moments moved across the host boundary as int8+scales): ``=0``
#: forces fp32 wire format, ``=1`` forces int8, unset = int8 only
#: where a real PCIe boundary exists (TPU backend)
OFFLOAD_QUANT_ENV = "DLROVER_TPU_OFFLOAD_QUANT"


def _buffered_enabled() -> bool:
    return os.getenv(OFFLOAD_BUFFERED_ENV, "1") != "0"


_HOST_KIND_PROBED: Optional[bool] = None


def _pinned_host_works() -> bool:
    """Whether this backend supports the ``pinned_host`` memory kind
    (TPU yes; the CPU test mesh no).  Probed once: a failed probe
    downgrades host shardings to plain device shardings so the SAME
    code path runs — with identical math — where no second memory
    space exists."""
    global _HOST_KIND_PROBED
    if _HOST_KIND_PROBED is None:
        from jax.sharding import SingleDeviceSharding

        try:
            dev = SingleDeviceSharding(jax.devices()[0])
            host = dev.with_memory_kind("pinned_host")
            x = jax.device_put(jnp.zeros((8,)), host)
            # the fused path moves between memory spaces INSIDE jit
            # (annotate_device_placement) — CPU accepts the plain
            # device_put above but cannot lower the in-program form,
            # so the probe must exercise it
            fn = jax.jit(
                lambda a: jax.device_put(
                    jax.device_put(a, dev) + 1.0, host
                ),
                in_shardings=host,
                out_shardings=host,
            )
            jax.block_until_ready(fn(x))
            _HOST_KIND_PROBED = True
        except Exception:  # noqa: BLE001 - any failure means "no"
            _HOST_KIND_PROBED = False
            logger.info(
                "pinned_host memory kind unavailable; host-offload "
                "shardings fall back to device memory"
            )
    return _HOST_KIND_PROBED


class OffloadState(NamedTuple):
    """Train state for the offloaded path.  ``params`` is the bf16
    device tree the forward consumes.  Host-state layout by
    configuration:

    - numpy + fp32 moments: master/mu/nu mirror the params tree with
      whole-leaf numpy arrays (updated in place);
    - pinned_host + fp32: per-leaf LISTS of host-memory chunk arrays;
    - int8 moments (either backend): master as above, mu/nu as
      per-leaf LISTS of ``(int8_payload, block_scales)`` tuples, one
      per chunk (payload padded to the quant block).
    """

    step: int
    params: Dict  # bf16, device
    master: Dict  # fp32, host
    mu: Dict      # fp32, host
    nu: Dict      # fp32, host


def _adamw_chunk_math(master, mu, nu, grad, bc1, bc2,
                      *, lr, b1, b2, eps, wd):
    """THE AdamW update over one fp32 chunk — the single source of
    the math for both storage backends (a fix applied to one must not
    silently miss the other).  ``wd`` may be a traced scalar (the
    fused delayed schedule gates decay off on its no-op first step);
    a static 0 still skips the term entirely."""
    g = grad.astype(jnp.float32)
    mu = b1 * mu + (1.0 - b1) * g
    nu = b2 * nu + (1.0 - b2) * g * g
    update = (mu / bc1) / (jnp.sqrt(nu / bc2) + eps)
    if not isinstance(wd, (int, float)) or wd:
        update = update + wd * master
    master = master - lr * update
    return master, mu, nu, master.astype(jnp.bfloat16)


# int8 moment quantization block — the SAME block the resident int8
# optimizer quantizes over (a retune there must not silently diverge)
from dlrover_tpu.ops.quantization import BLOCK as _QBLOCK  # noqa: E402


def _deq_chunk(q, scales, n):
    """int8 [padded] + per-1024-block scales -> fp32 [n]."""
    x = q.astype(jnp.float32).reshape(-1, _QBLOCK) * scales[:, None]
    return x.reshape(-1)[:n]


def _np_quant_chunk(x: np.ndarray):
    """Host-side mirror of :func:`_quant_chunk` (same block layout,
    same absmax/127 scales) for the quantized TRANSFER path: fp32
    moments that stay fp32 in host storage are quantized on the host
    right before the H2D dispatch, so only int8+scales cross the
    boundary."""
    x = np.asarray(x, dtype=np.float32)
    n = x.shape[0]
    pad = (-n) % _QBLOCK
    if pad:
        x = np.pad(x, (0, pad))
    blocks = x.reshape(-1, _QBLOCK)
    scales = np.maximum(
        np.max(np.abs(blocks), axis=1) / 127.0, 1e-12
    ).astype(np.float32)
    q = np.clip(
        np.round(blocks / scales[:, None]), -127, 127
    ).astype(np.int8)
    return q.reshape(-1), scales


def _np_deq_chunk(q: np.ndarray, scales: np.ndarray, n: int):
    """Host-side mirror of :func:`_deq_chunk` for the D2H writeback."""
    x = (
        np.asarray(q, np.float32).reshape(-1, _QBLOCK)
        * np.asarray(scales, np.float32)[:, None]
    )
    return x.reshape(-1)[:n]


def _quant_chunk(x):
    """fp32 [n] -> (int8 [padded], per-block scales).  Plain jnp: the
    op is memory-bound and lives inside the chunk jit, so XLA fuses it
    into the same pass as the update math."""
    n = x.shape[0]
    pad = (-n) % _QBLOCK
    if pad:
        x = jnp.pad(x, (0, pad))
    blocks = x.reshape(-1, _QBLOCK)
    scales = jnp.maximum(
        jnp.max(jnp.abs(blocks), axis=1) / 127.0, 1e-12
    )
    q = jnp.clip(
        jnp.round(blocks / scales[:, None]), -127, 127
    ).astype(jnp.int8)
    return q.reshape(-1), scales


def _adamw_chunk_math_q(master, mu_q, mu_s, nu_q, nu_s, grad,
                        bc1, bc2, *, lr, b1, b2, eps, wd):
    """AdamW over one chunk with int8-quantized moments: dequant ->
    THE shared math -> requant, all inside one jit pass.  nu is
    stored as sqrt(nu) (see optimizers/low_bit.py for the
    dynamic-range rationale); squaring it reconstructs the value the
    shared update consumes."""
    n = master.shape[0]
    mu = _deq_chunk(mu_q, mu_s, n)
    nu_root = _deq_chunk(nu_q, nu_s, n)
    master, mu, nu, p_bf16 = _adamw_chunk_math(
        master, mu, nu_root * nu_root, grad, bc1, bc2,
        lr=lr, b1=b1, b2=b2, eps=eps, wd=wd,
    )
    mu_q2, mu_s2 = _quant_chunk(mu)
    nu_q2, nu_s2 = _quant_chunk(jnp.sqrt(nu))
    return master, mu_q2, mu_s2, nu_q2, nu_s2, p_bf16


@functools.partial(
    jax.jit,
    static_argnames=("lr", "b1", "b2", "eps", "wd"),
    donate_argnums=(0, 1, 2),
)
def _chunk_update(master, mu, nu, grad, bc1, bc2,
                  *, lr, b1, b2, eps, wd):
    """numpy-backend entry: plain device in/out chunks."""
    return _adamw_chunk_math(
        master, mu, nu, grad, bc1, bc2,
        lr=lr, b1=b1, b2=b2, eps=eps, wd=wd,
    )


@functools.partial(
    jax.jit,
    static_argnames=("lr", "b1", "b2", "eps", "wd"),
    donate_argnums=(0, 1, 2, 3, 4),
)
def _chunk_update_q(master, mu_q, mu_s, nu_q, nu_s, grad, bc1, bc2,
                    *, lr, b1, b2, eps, wd):
    """numpy-backend entry, int8 moments."""
    return _adamw_chunk_math_q(
        master, mu_q, mu_s, nu_q, nu_s, grad, bc1, bc2,
        lr=lr, b1=b1, b2=b2, eps=eps, wd=wd,
    )


class _RollingPrefetch:
    """Double-buffered H2D stream over the chunk sequence.

    The one-shot prefetch only hid the FIRST ``window`` chunks' H2D
    under the backward; every later chunk's transfer was dispatched
    immediately before its own compute, serializing copy against math.
    This object keeps a rolling window: consuming chunk ``k``
    (:meth:`get`) dispatches the H2D of chunk ``k + window``, so the
    transfer of the next chunks always overlaps the in-flight chunks'
    update math — the out-of-program form of the fused path's
    barrier-windowed copy pipeline.  Per-chunk host buffers are READ
    ONLY ahead of their own writeback (each chunk is written exactly
    once, strictly after its own compute), so early staging can never
    observe a torn update."""

    def __init__(self, opt, leaves_m, leaves_mu, leaves_nu,
                 quant: bool):
        self._opt = opt
        self._m = leaves_m
        self._mu = leaves_mu
        self._nu = leaves_nu
        self._quant = quant
        self._entries: Dict = {}
        self._order = []
        for li, m in enumerate(leaves_m):
            for j, sl in enumerate(opt._chunk_slices(m.size)):
                self._order.append((li, j, sl))
        self._cursor = 0
        for _ in range(opt.window):
            self._dispatch_next()

    def _dispatch_next(self):
        if self._cursor >= len(self._order):
            return
        li, j, sl = self._order[self._cursor]
        self._cursor += 1
        self._entries[(li, j)] = self._opt._stage_chunk(
            self._m, self._mu, self._nu, li, j, sl,
            quant=self._quant,
        )

    def get(self, key):
        """Consume one chunk's staged inputs and refill the window."""
        entry = self._entries.pop(key, None)
        self._dispatch_next()
        return entry

    def __len__(self):
        return len(self._entries)


class _OneShotPrefetch(dict):
    """Legacy first-window prefetch dict.  Carries the staging-time
    quant flag so ``_apply_numpy`` unpacks the staged tuples with the
    arity they were built with, even if ``DLROVER_TPU_OFFLOAD_QUANT``
    flips between ``start_prefetch`` and ``apply_gradients``."""

    def __init__(self, quant: bool):
        super().__init__()
        self._quant = quant


class HostOffloadAdamW:
    """AdamW whose fp32 state never resides in HBM.

    Not an optax transformation on purpose: optax updates live inside
    one jit over device state, which is exactly what offload must
    avoid.  Use with :func:`build_offloaded_train_step` or drive
    ``init``/``apply_gradients`` directly.
    """

    def __init__(
        self,
        learning_rate: float = 1e-3,
        b1: float = 0.9,
        b2: float = 0.999,
        eps: float = 1e-8,
        weight_decay: float = 0.0,
        chunk_elems: int = DEFAULT_CHUNK_ELEMS,
        max_in_flight: int = 2,
        backend: str = "auto",
        moments: str = "fp32",
    ):
        self.lr = learning_rate
        self.b1 = b1
        self.b2 = b2
        self.eps = eps
        self.wd = weight_decay
        self.chunk = int(chunk_elems)
        self.window = max(1, int(max_in_flight))
        if moments not in ("fp32", "int8"):
            raise ValueError(f"unknown moments dtype {moments!r}")
        self.moments = moments
        if backend == "auto":
            backend = (
                "pinned_host"
                if jax.default_backend() == "tpu"
                else "numpy"
            )
        if backend not in ("numpy", "pinned_host"):
            raise ValueError(f"unknown offload backend {backend!r}")
        self.backend = backend

    # ------------------------------------------- pinned_host helpers
    def _shardings(self):
        from jax.sharding import SingleDeviceSharding

        dev = SingleDeviceSharding(jax.devices()[0])
        if _pinned_host_works():
            host = dev.with_memory_kind("pinned_host")
        else:
            host = dev
        return dev, host

    def _pinned_update_fn(self):
        """Chunk update compiled with host-memory in/out shardings;
        donation recycles the TPU-host buffers so steady state
        allocates nothing.

        The grad arrives as the WHOLE flat leaf plus a traced offset
        and is sliced INSIDE the program: slicing outside would
        materialize a second full copy of the grads as in-flight
        slice buffers (measured: the difference between the 1.8B
        accumulated proof fitting and OOMing)."""
        if getattr(self, "_pinned_fn", None) is not None:
            return self._pinned_fn
        from jax import lax

        dev, host = self._shardings()
        hyper = dict(
            lr=self.lr, b1=self.b1, b2=self.b2, eps=self.eps,
            wd=self.wd,
        )

        if self.moments == "int8":

            def body(master, mu_q, mu_s, nu_q, nu_s, grad_leaf, off,
                     bc1, bc2):
                # flatten + slice IN-program: an eager reshape outside
                # would materialize a full second copy of the grads
                # at dispatch time (jit specializes per leaf shape —
                # a handful of executables, not one per chunk)
                grad = lax.dynamic_slice(
                    grad_leaf.reshape(-1), (off,),
                    (master.shape[0],),
                )
                outs = _adamw_chunk_math_q(
                    jax.device_put(master, dev),
                    jax.device_put(mu_q, dev),
                    jax.device_put(mu_s, dev),
                    jax.device_put(nu_q, dev),
                    jax.device_put(nu_s, dev),
                    grad, bc1, bc2, **hyper,
                )
                return tuple(
                    jax.device_put(o, host) for o in outs[:5]
                ) + (outs[5],)

            self._pinned_fn = jax.jit(
                body,
                in_shardings=(host,) * 5 + (dev, None, None, None),
                out_shardings=(host,) * 5 + (dev,),
                donate_argnums=(0, 1, 2, 3, 4),
            )
        else:

            def body(master, mu, nu, grad_leaf, off, bc1, bc2):
                grad = lax.dynamic_slice(
                    grad_leaf.reshape(-1), (off,),
                    (master.shape[0],),
                )
                # host->HBM in, shared AdamW math, HBM->host out
                m_d, mu_d, nu_d, p_bf16 = _adamw_chunk_math(
                    jax.device_put(master, dev),
                    jax.device_put(mu, dev),
                    jax.device_put(nu, dev),
                    grad, bc1, bc2, **hyper,
                )
                return (
                    jax.device_put(m_d, host),
                    jax.device_put(mu_d, host),
                    jax.device_put(nu_d, host),
                    p_bf16,
                )

            self._pinned_fn = jax.jit(
                body,
                in_shardings=(host, host, host, dev, None, None,
                              None),
                out_shardings=(host, host, host, dev),
                donate_argnums=(0, 1, 2),
            )
        return self._pinned_fn

    @staticmethod
    def _q_padded(n: int) -> int:
        return ((n + _QBLOCK - 1) // _QBLOCK) * _QBLOCK

    def _chunk_slices(self, n: int):
        return [
            slice(lo, min(lo + self.chunk, n))
            for lo in range(0, n, self.chunk)
        ]

    # ----------------------------------------------------------- init
    def init(self, params) -> OffloadState:
        """``params``: any pytree of arrays (host or device).  Master
        copies and moments materialize on the host; the returned
        ``params`` tree is bf16 on device."""
        if self.backend == "pinned_host":
            return self._init_pinned(params)
        return self._init_numpy(params)

    def _init_pinned(self, params) -> OffloadState:
        _, host = self._shardings()
        leaves, treedef = jax.tree_util.tree_flatten(params)
        master, mu, nu, bf16 = [], [], [], []
        for leaf in leaves:
            arr = jnp.asarray(leaf)
            flat = arr.reshape(-1).astype(jnp.float32)
            m_chunks, mu_chunks, nu_chunks = [], [], []
            for sl in self._chunk_slices(flat.shape[0]):
                chunk = flat[sl]
                m_chunks.append(jax.device_put(chunk, host))
                # mu and nu get DISTINCT zero buffers: device_put of
                # the same array can return an aliased buffer, and
                # aliased leaves break donation in the fused step
                if self.moments == "int8":
                    padded = self._q_padded(chunk.shape[0])

                    def zq():
                        return jax.device_put(
                            jnp.zeros((padded,), jnp.int8), host
                        )

                    def zs():
                        return jax.device_put(
                            jnp.zeros(
                                (padded // _QBLOCK,), jnp.float32
                            ),
                            host,
                        )

                    mu_chunks.append((zq(), zs()))
                    nu_chunks.append((zq(), zs()))
                else:
                    mu_chunks.append(
                        jax.device_put(
                            jnp.zeros(chunk.shape, jnp.float32),
                            host,
                        )
                    )
                    nu_chunks.append(
                        jax.device_put(
                            jnp.zeros(chunk.shape, jnp.float32),
                            host,
                        )
                    )
            master.append(m_chunks)
            mu.append(mu_chunks)
            nu.append(nu_chunks)
            bf16.append(arr.astype(jnp.bfloat16))
            del arr, flat  # the fp32 device copy must not linger
        unf = jax.tree_util.tree_unflatten
        return OffloadState(
            step=0,
            params=unf(treedef, bf16),
            master=unf(treedef, master),
            mu=unf(treedef, mu),
            nu=unf(treedef, nu),
        )

    def _init_numpy(self, params) -> OffloadState:
        # np.array (not asarray/ascontiguousarray): a jax Array's
        # zero-copy numpy view is READ-ONLY, and the writeback path
        # updates reshape(-1) views of these buffers in place — they
        # must be owned, contiguous, writable host memory
        master = jax.tree_util.tree_map(
            lambda p: np.array(p, dtype=np.float32, order="C"),
            params,
        )
        if self.moments == "int8":
            def zq_chunks(p):
                out = []
                for sl in self._chunk_slices(p.size):
                    padded = self._q_padded(sl.stop - sl.start)
                    out.append(
                        (
                            np.zeros((padded,), np.int8),
                            np.zeros(
                                (padded // _QBLOCK,), np.float32
                            ),
                        )
                    )
                return out

            mu = jax.tree_util.tree_map(zq_chunks, master)
            nu = jax.tree_util.tree_map(zq_chunks, master)
        else:
            mu = jax.tree_util.tree_map(
                lambda p: np.zeros(p.shape, np.float32), master
            )
            nu = jax.tree_util.tree_map(
                lambda p: np.zeros(p.shape, np.float32), master
            )
        bf16 = jax.tree_util.tree_map(
            lambda p: jnp.asarray(p, dtype=jnp.bfloat16), master
        )
        return OffloadState(
            step=0, params=bf16, master=master, mu=mu, nu=nu
        )

    # --------------------------------------------------------- update
    def _transfer_quant(self) -> bool:
        """Whether fp32 moments cross the host boundary quantized
        (int8 payload + per-block scales — ~4x less moment traffic
        each way).  Host STORAGE stays fp32 (checkpoint format
        unchanged); only the wire format changes, which is why this
        is a per-step decision, not an init-time one.  Defaults on
        only where a real transfer link exists (TPU backend);
        ``DLROVER_TPU_OFFLOAD_QUANT=0/1`` overrides."""
        if self.moments != "fp32" or self.backend != "numpy":
            return False  # int8 moments already transfer quantized
        raw = os.getenv(OFFLOAD_QUANT_ENV, "")
        if raw == "0":
            return False
        if raw == "1":
            return True
        return jax.default_backend() == "tpu"

    def _stage_chunk(self, leaves_m, leaves_mu, leaves_nu,
                     li: int, j: int, sl: slice, quant: bool = False):
        """Dispatch the async H2D of ONE chunk's host state; returns
        the device-input tuple the chunk jit consumes.  With
        ``quant`` (fp32 moments, quantized transfers) the moments are
        blockwise-quantized host-side first — nu as sqrt(nu), the
        same wire convention as the int8-moment storage format — so
        the H2D carries 1 byte/elem instead of 4."""
        flat_m = leaves_m[li].reshape(-1)
        if self.moments == "int8":
            mu_q, mu_s = leaves_mu[li][j]
            nu_q, nu_s = leaves_nu[li][j]
            return (
                jnp.asarray(flat_m[sl]),
                jnp.asarray(mu_q), jnp.asarray(mu_s),
                jnp.asarray(nu_q), jnp.asarray(nu_s),
            )
        flat_mu = leaves_mu[li].reshape(-1)
        flat_nu = leaves_nu[li].reshape(-1)
        if quant:
            mu_q, mu_s = _np_quant_chunk(flat_mu[sl])
            nu_q, nu_s = _np_quant_chunk(np.sqrt(flat_nu[sl]))
            return (
                jnp.asarray(flat_m[sl]),
                jnp.asarray(mu_q), jnp.asarray(mu_s),
                jnp.asarray(nu_q), jnp.asarray(nu_s),
            )
        return (
            jnp.asarray(flat_m[sl]),
            jnp.asarray(flat_mu[sl]),
            jnp.asarray(flat_nu[sl]),
        )

    @staticmethod
    def _emit_stream_span(
        duration_s: float, nbytes: int, buffered: bool,
    ):
        """One ``offload_copy`` span per chunk-streamed update: the
        host<->device optimizer-state traffic with its measured
        throughput, tagged ``buffered`` so the double-buffered and
        serial pipelines stay distinguishable in the timeline (and in
        the ``dlrover_tpu_offload_gbps`` gauge).  Must be called at
        stream end: the span start is reconstructed as anchored "now"
        minus ``duration_s`` so it sits on the same clock as B/E
        records."""
        try:
            from dlrover_tpu.observability.events import (
                anchored_now,
                get_event_logger,
            )
            from dlrover_tpu.observability.metrics import (
                record_offload_io,
            )

            gbps = nbytes / 1e9 / max(duration_s, 1e-9)
            events = get_event_logger()
            events.complete(
                "offload_copy",
                anchored_now() - max(duration_s, 0.0),
                duration_s,
                bytes=int(nbytes),
                throughput_gbps=round(gbps, 3),
                buffered=bool(buffered),
            )
            record_offload_io(nbytes, duration_s, buffered)
        except Exception:  # noqa: BLE001 - observability only
            pass

    def start_prefetch(self, state: OffloadState):
        """Start the H2D stream of host state (numpy backend).
        Called BEFORE backward so the first window's transfers
        overlap the compute; the returned object feeds
        :meth:`apply_gradients`.

        Default: a :class:`_RollingPrefetch` — the window REFILLS as
        chunks are consumed, so every chunk's H2D (not just the first
        window's) overlaps the previous chunks' update math.
        ``DLROVER_TPU_OFFLOAD_BUFFERED=0`` restores the legacy
        one-shot first-window dict exactly.  The pinned_host backend
        overlaps via :func:`build_fused_offload_step` instead
        (out-of-program ``device_put`` dispatch overhead makes
        per-chunk prefetch a loss there)."""
        if self.backend != "numpy":
            return None
        leaves_m, treedef = jax.tree_util.tree_flatten(state.master)
        leaves_mu = treedef.flatten_up_to(state.mu)
        leaves_nu = treedef.flatten_up_to(state.nu)
        quant = self._transfer_quant()
        if _buffered_enabled():
            return _RollingPrefetch(
                self, leaves_m, leaves_mu, leaves_nu, quant
            )
        prefetched = _OneShotPrefetch(quant)
        budget = self.window
        for li, m in enumerate(leaves_m):
            for j, sl in enumerate(self._chunk_slices(m.size)):
                if budget <= 0:
                    return prefetched
                prefetched[(li, j)] = self._stage_chunk(
                    leaves_m, leaves_mu, leaves_nu, li, j, sl,
                    quant=quant,
                )
                budget -= 1
        return prefetched

    def apply_gradients(
        self, state: OffloadState, grads, prefetched=None
    ) -> OffloadState:
        """One AdamW step.  ``grads``: device pytree matching
        ``state.params``.  Streams chunks through the chip; host
        buffers are recycled (donation on pinned_host, in-place numpy
        otherwise).  ``prefetched``: optional chunk window from
        :meth:`start_prefetch`."""
        if self.backend == "pinned_host":
            return self._apply_pinned(state, grads)
        return self._apply_numpy(state, grads, prefetched)

    def _apply_pinned(
        self, state: OffloadState, grads
    ) -> OffloadState:
        step = state.step + 1
        bc1 = jnp.float32(1.0 - self.b1**step)
        bc2 = jnp.float32(1.0 - self.b2**step)
        fn = self._pinned_update_fn()
        leaves_m, treedef = jax.tree_util.tree_flatten(
            state.master, is_leaf=lambda x: isinstance(x, list)
        )
        leaves_mu = treedef.flatten_up_to(state.mu)
        leaves_nu = treedef.flatten_up_to(state.nu)
        leaves_p = treedef.flatten_up_to(state.params)
        leaves_g = treedef.flatten_up_to(grads)
        new_m, new_mu, new_nu, new_p = [], [], [], []
        for li, m_chunks in enumerate(leaves_m):
            shape = leaves_p[li].shape
            flat_g = leaves_g[li]  # flattened INSIDE the chunk jit
            slices = self._chunk_slices(flat_g.size)
            ms, mus, nus, ps = [], [], [], []
            for j, sl in enumerate(slices):
                off = jnp.int32(sl.start)
                if self.moments == "int8":
                    mu_q, mu_s = leaves_mu[li][j]
                    nu_q, nu_s = leaves_nu[li][j]
                    (m_h, mu_q2, mu_s2, nu_q2, nu_s2, p_d) = fn(
                        m_chunks[j], mu_q, mu_s, nu_q, nu_s,
                        flat_g, off, bc1, bc2,
                    )
                    mus.append((mu_q2, mu_s2))
                    nus.append((nu_q2, nu_s2))
                else:
                    m_h, mu_h, nu_h, p_d = fn(
                        m_chunks[j],
                        leaves_mu[li][j],
                        leaves_nu[li][j],
                        flat_g,
                        off,
                        bc1,
                        bc2,
                    )
                    mus.append(mu_h)
                    nus.append(nu_h)
                ms.append(m_h)
                ps.append(p_d)
            new_m.append(ms)
            new_mu.append(mus)
            new_nu.append(nus)
            flat_p = ps[0] if len(ps) == 1 else jnp.concatenate(ps)
            new_p.append(flat_p.reshape(shape))
        unf = jax.tree_util.tree_unflatten
        return OffloadState(
            step=step,
            params=unf(treedef, new_p),
            master=unf(treedef, new_m),
            mu=unf(treedef, new_mu),
            nu=unf(treedef, new_nu),
        )

    def _apply_numpy(
        self, state: OffloadState, grads, prefetched=None
    ) -> OffloadState:
        import time as _time

        prefetched = prefetched or {}
        step = state.step + 1
        bc1 = jnp.float32(1.0 - self.b1**step)
        bc2 = jnp.float32(1.0 - self.b2**step)

        leaves_m, treedef = jax.tree_util.tree_flatten(state.master)
        leaves_mu = treedef.flatten_up_to(state.mu)
        leaves_nu = treedef.flatten_up_to(state.nu)
        leaves_g = treedef.flatten_up_to(grads)

        new_param_chunks: Dict[int, list] = {}
        in_flight = []  # (leaf_idx, chunk_slice, chunk_idx, results)

        int8 = self.moments == "int8"
        # unpack staged chunks with the arity they were staged with:
        # the prefetch window pins the quant flag at start_prefetch
        # time, so an env flip between the two calls cannot mismatch
        # the in-flight tuples
        tq = getattr(prefetched, "_quant", None)
        if tq is None:
            tq = self._transfer_quant()
        buffered = isinstance(prefetched, _RollingPrefetch)
        hyper = dict(
            lr=self.lr, b1=self.b1, b2=self.b2, eps=self.eps,
            wd=self.wd,
        )
        t0 = _time.perf_counter()
        stream_bytes = 0

        def drain_one():
            li, sl, j, res = in_flight.pop(0)
            if int8:
                m_d, mu_q, mu_s, nu_q, nu_s, p_d = res
                np.copyto(
                    leaves_m[li].reshape(-1)[sl], np.asarray(m_d)
                )
                qb, sb = leaves_mu[li][j]
                np.copyto(qb, np.asarray(mu_q))
                np.copyto(sb, np.asarray(mu_s))
                qb, sb = leaves_nu[li][j]
                np.copyto(qb, np.asarray(nu_q))
                np.copyto(sb, np.asarray(nu_s))
            elif tq:
                # quantized wire, fp32 storage: dequantize back into
                # the SAME fp32 host buffers (nu travels as sqrt(nu),
                # the int8-moment wire convention)
                m_d, mu_q, mu_s, nu_q, nu_s, p_d = res
                np.copyto(
                    leaves_m[li].reshape(-1)[sl], np.asarray(m_d)
                )
                n = sl.stop - sl.start
                np.copyto(
                    leaves_mu[li].reshape(-1)[sl],
                    _np_deq_chunk(
                        np.asarray(mu_q), np.asarray(mu_s), n
                    ),
                )
                nu_root = _np_deq_chunk(
                    np.asarray(nu_q), np.asarray(nu_s), n
                )
                np.copyto(
                    leaves_nu[li].reshape(-1)[sl], nu_root * nu_root
                )
            else:
                m_d, mu_d, nu_d, p_d = res
                # d2h writebacks into the SAME host buffers
                np.copyto(
                    leaves_m[li].reshape(-1)[sl], np.asarray(m_d)
                )
                np.copyto(
                    leaves_mu[li].reshape(-1)[sl], np.asarray(mu_d)
                )
                np.copyto(
                    leaves_nu[li].reshape(-1)[sl], np.asarray(nu_d)
                )
            new_param_chunks.setdefault(li, []).append(p_d)

        for li in range(len(leaves_m)):
            flat_m = leaves_m[li].reshape(-1)
            flat_g = leaves_g[li].reshape(-1)
            n = flat_m.shape[0]
            for j, sl in enumerate(self._chunk_slices(n)):
                pre = prefetched.get((li, j))
                if pre is None:
                    pre = self._stage_chunk(
                        leaves_m, leaves_mu, leaves_nu, li, j, sl,
                        quant=tq,
                    )
                if int8 or tq:
                    res = _chunk_update_q(
                        *pre, flat_g[sl], bc1, bc2, **hyper
                    )
                else:
                    res = _chunk_update(
                        *pre, flat_g[sl], bc1, bc2, **hyper
                    )
                elems = sl.stop - sl.start
                # master fp32 both ways + moments (fp32 or int8 +
                # fp32 scales) both ways — the chunk-stream traffic
                # the span reports
                if int8 or tq:
                    padded = self._q_padded(elems)
                    stream_bytes += 2 * (
                        4 * elems
                        + 2 * (padded + 4 * (padded // _QBLOCK))
                    )
                else:
                    stream_bytes += 2 * (4 * elems + 2 * 4 * elems)
                in_flight.append((li, sl, j, res))
                # bounded window: older chunks' HBM buffers are freed
                # by the writeback before new ones are dispatched
                while len(in_flight) > self.window:
                    drain_one()
        while in_flight:
            drain_one()
        self._emit_stream_span(
            _time.perf_counter() - t0, stream_bytes, buffered,
        )

        new_params = []
        for li, m in enumerate(leaves_m):
            chunks = new_param_chunks[li]
            flat = (
                chunks[0]
                if len(chunks) == 1
                else jnp.concatenate(chunks)
            )
            new_params.append(flat.reshape(m.shape))
        return OffloadState(
            step=step,
            params=jax.tree_util.tree_unflatten(
                treedef, new_params
            ),
            master=state.master,
            mu=state.mu,
            nu=state.nu,
        )


def make_accumulated_grads_fn(loss_fn, micro_steps: int):
    """(params, batch) -> (mean loss, mean grads) over ``micro_steps``
    microbatches (batch leading dim splits evenly).  The stream update
    is the expensive part of an offloaded step (~6-12 B/param over
    PCIe each way), so amortizing it over K microbatches is the
    offload-native throughput lever — accumulation happens in bf16
    (an fp32 accumulator would cost 4 B/param of the HBM the offload
    exists to free)."""
    micro_steps = max(1, int(micro_steps))

    def grads_of(params, batch):
        if micro_steps <= 1:
            return jax.value_and_grad(loss_fn)(params, batch)
        split = jax.tree_util.tree_map(
            lambda x: x.reshape(
                (micro_steps, x.shape[0] // micro_steps)
                + x.shape[1:]
            ),
            batch,
        )
        loss_sum = jnp.float32(0.0)
        acc = None
        inv = 1.0 / micro_steps
        for k in range(micro_steps):
            mb = jax.tree_util.tree_map(lambda x: x[k], split)
            loss_k, g = jax.value_and_grad(loss_fn)(params, mb)
            loss_sum = loss_sum + loss_k
            if acc is None:
                acc = jax.tree_util.tree_map(
                    lambda a: (a * inv).astype(a.dtype), g
                )
            else:
                acc = jax.tree_util.tree_map(
                    lambda s, a: (s + a * inv).astype(s.dtype),
                    acc, g,
                )
        return loss_sum * inv, acc

    return grads_of


class FusedOffloadState(NamedTuple):
    """Train state for the FUSED offload path.  ``master``/``mu``/
    ``nu`` use the SAME chunked host layout as the pinned_host
    backend (per-leaf lists of host chunk arrays; int8 moments as
    ``(payload, scales)`` tuples) — chunking is what lets the fused
    program bound its HBM transient.  ``grads`` holds the previous
    step's gradients in delayed mode (``None`` in synchronous
    mode)."""

    step: jnp.ndarray  # int32 scalar, device
    params: Dict       # bf16, device
    master: Dict       # fp32 chunk lists, host memory kind
    mu: Dict           # fp32 chunks or (int8 payload, scales), host
    nu: Dict
    grads: Optional[Dict]  # bf16, device (delayed mode only)


def build_fused_offload_step(
    loss_fn,
    init_params_fn,
    optimizer: Optional[HostOffloadAdamW] = None,
    delayed: bool = True,
    window: int = 2,
    micro_steps: int = 1,
):
    """Host-offloaded train step as ONE jit program — the TPU-native
    overlap design.

    The reference overlaps its CPU-offloaded Adam with backward by
    registering per-module inner optimizers on grad hooks
    (``ref: atorch/atorch/optimizers/adam_offload.py:52-70``).  The
    XLA equivalent is to put the whole update INSIDE the train-step
    program with host-memory-kind shardings: the compiler turns each
    host transfer into an async copy-start / copy-done pair and
    overlaps it with the backward matmuls in the SAME program.
    Measured on v5e: out-of-program ``device_put`` transfers run at
    only 2.5-6 GB/s (per-dispatch overhead) while in-program copies
    stream at ~11 GB/s — fusing is what makes the DMA both fast and
    hidden.

    Memory discipline: left alone, XLA hoists EVERY chunk's H2D copy
    to the front of the program (measured: a 1.8B fused step demands
    32.8 GB of 15.75 GB HBM).  The update therefore streams the SAME
    chunked host layout the pinned backend uses, with a sliding
    window enforced by ``lax.optimization_barrier``: chunk ``i``'s
    host inputs are gated on chunk ``i-window``'s host OUTPUTS, so at
    most ``window`` chunks of fp32 state are in flight on device at
    once — the in-program form of the reference's bucket loop.

    Two scheduling modes:

    - ``delayed=True`` (default): backward runs on the CURRENT
      params while the update applies the PREVIOUS step's gradients
      to produce the next params — the two are data-independent, so
      every host copy (H2D in, D2H out) and the update math itself
      overlap the backward.  This is the delayed-parameter-update
      schedule of ZeRO-Offload (gradients are applied one step after
      they were computed).  Step 1 is a TRUE no-op: it has no
      previous gradients, weight decay is gated off (it would move
      every param before any real gradient) and bias correction
      counts real moment updates — the trajectory equals the
      synchronous one run on the shifted grad sequence, exactly.
    - ``delayed=False``: backward first, update after (exact
      synchronous AdamW).  H2D copies still hoist into the backward;
      the D2H tail is exposed but chunk-pipelined.

    Returns ``(init_state, train_step)``; ``train_step`` jit-compiles
    on first call (shardings are captured from the state built by
    ``init_state``).
    """
    from jax import lax

    opt = optimizer or HostOffloadAdamW()
    int8 = opt.moments == "int8"
    dev, host = opt._shardings()
    # env override for on-chip tuning: the window trades HBM
    # transient (~window * 5 * chunk_bytes) against copy/compute
    # pipelining depth
    env_window = os.getenv("DLROVER_TPU_OFFLOAD_WINDOW")
    if env_window:
        try:
            window = int(env_window)
        except ValueError:
            logger.warning(
                "ignoring malformed DLROVER_TPU_OFFLOAD_WINDOW=%r",
                env_window,
            )
    window = max(1, int(window))
    micro_steps = max(1, int(micro_steps))
    hyper = dict(
        lr=opt.lr, b1=opt.b1, b2=opt.b2, eps=opt.eps, wd=opt.wd
    )
    # when the backend has no second memory space (_shardings
    # degraded host to dev), in-program device_put is an unlowerable
    # no-op (CPU has no annotate_device_placement) — elide it
    two_spaces = host is not dev

    def _in(x):
        return jax.device_put(x, dev) if two_spaces else x

    def _out(x):
        return jax.device_put(x, host) if two_spaces else x

    def init_state(rng) -> FusedOffloadState:
        params = init_params_fn(rng)
        base = opt._init_pinned(params)  # chunked host layout
        del params
        grads = (
            jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.bfloat16),
                base.params,
            )
            if delayed
            else None
        )
        return FusedOffloadState(
            step=jnp.zeros((), jnp.int32),
            params=base.params,
            master=base.master,
            mu=base.mu,
            nu=base.nu,
            grads=grads,
        )

    def _apply(params, grads, master, mu, nu, step, wd):
        """Traced chunk-streamed update: barrier-windowed H2D, the
        shared AdamW math, D2H.  ``step`` is the bias-correction step
        (the number of REAL moment updates so far); ``wd`` may be a
        traced scalar (delayed mode gates decay off at step 1)."""
        hyper_t = dict(hyper, wd=wd)
        stepf = step.astype(jnp.float32)
        bc1 = 1.0 - jnp.power(jnp.float32(opt.b1), stepf)
        bc2 = 1.0 - jnp.power(jnp.float32(opt.b2), stepf)
        is_list = lambda x: isinstance(x, list)  # noqa: E731
        leaves_m, treedef = jax.tree_util.tree_flatten(
            master, is_leaf=is_list
        )
        leaves_mu = treedef.flatten_up_to(mu)
        leaves_nu = treedef.flatten_up_to(nu)
        leaves_g = treedef.flatten_up_to(grads)
        leaves_p = treedef.flatten_up_to(params)
        tokens = []  # chunk host outputs, in stream order
        new_p, new_m, new_mu, new_nu = [], [], [], []
        for li, m_chunks in enumerate(leaves_m):
            flat_g = leaves_g[li].reshape(-1)
            shape = leaves_p[li].shape
            slices = opt._chunk_slices(flat_g.shape[0])
            ms, mus, nus, ps = [], [], [], []
            for j, sl in enumerate(slices):
                if int8:
                    mu_q, mu_s = leaves_mu[li][j]
                    nu_q, nu_s = leaves_nu[li][j]
                    ins = (m_chunks[j], mu_q, mu_s, nu_q, nu_s)
                else:
                    ins = (
                        m_chunks[j],
                        leaves_mu[li][j],
                        leaves_nu[li][j],
                    )
                if len(tokens) >= window:
                    # gate this chunk's H2D on the D2H completion of
                    # the chunk `window` positions back: bounds the
                    # in-flight fp32 transient to ~window chunks
                    gated = lax.optimization_barrier(
                        ins + (tokens[len(tokens) - window],)
                    )
                    ins = gated[:-1]
                g = flat_g[sl]
                if int8:
                    (m2, mu_q2, mu_s2, nu_q2, nu_s2, pb) = (
                        _adamw_chunk_math_q(
                            _in(ins[0]), _in(ins[1]), _in(ins[2]),
                            _in(ins[3]), _in(ins[4]),
                            g, bc1, bc2, **hyper_t,
                        )
                    )
                    m2h = _out(m2)
                    mus.append((_out(mu_q2), _out(mu_s2)))
                    nus.append((_out(nu_q2), _out(nu_s2)))
                else:
                    m2, mu2, nu2, pb = _adamw_chunk_math(
                        _in(ins[0]), _in(ins[1]), _in(ins[2]),
                        g, bc1, bc2, **hyper_t,
                    )
                    m2h = _out(m2)
                    mus.append(_out(mu2))
                    nus.append(_out(nu2))
                ms.append(m2h)
                tokens.append(m2h)
                ps.append(pb)
            new_m.append(ms)
            new_mu.append(mus)
            new_nu.append(nus)
            flat_p = ps[0] if len(ps) == 1 else jnp.concatenate(ps)
            new_p.append(flat_p.reshape(shape))
        unf = jax.tree_util.tree_unflatten
        return (
            unf(treedef, new_p),
            unf(treedef, new_m),
            unf(treedef, new_mu),
            unf(treedef, new_nu),
        )

    _grads_of = make_accumulated_grads_fn(loss_fn, micro_steps)

    def step_fn(state: FusedOffloadState, batch):
        step = state.step + 1
        loss, grads = _grads_of(state.params, batch)
        # delayed: backward ran on the CURRENT params while the
        # update applies the PREVIOUS grads and only feeds the NEXT
        # step — the two are data-independent, so copies and update
        # math ride under the backward (ZeRO-Offload delayed
        # parameter update).  sync: this step's grads apply now.
        applied = state.grads if delayed else grads
        if delayed:
            # step 1 has no previous gradients, so its update must be
            # a TRUE no-op: weight decay is gated off (a bare
            # bias-corrected decay would move every param before any
            # real gradient), and bias correction counts REAL moment
            # updates (step t applies the grads computed at t-1, the
            # (t-1)-th update) — the delayed trajectory is exactly the
            # synchronous one run on the shifted grad sequence.
            upd_step = jnp.maximum(step - 1, 1)
            wd_t = (
                jnp.float32(opt.wd)
                * (step > 1).astype(jnp.float32)
                if opt.wd
                else opt.wd
            )
        else:
            upd_step, wd_t = step, opt.wd
        new_p, new_m, new_mu, new_nu = _apply(
            state.params, applied, state.master, state.mu,
            state.nu, upd_step, wd_t,
        )
        new_state = FusedOffloadState(
            step, new_p, new_m, new_mu, new_nu,
            grads if delayed else None,
        )
        return new_state, {"loss": loss}

    cache: Dict[object, object] = {}

    def train_step(state: FusedOffloadState, batch):
        jitted = cache.get("jit")
        if jitted is None:
            state_sh = jax.tree_util.tree_map(
                lambda a: a.sharding, state
            )
            jitted = jax.jit(
                step_fn,
                in_shardings=(state_sh, None),
                out_shardings=(state_sh, None),
                donate_argnums=(0,),
            )
            cache["jit"] = jitted
        # "k=v,k=v" -> per-program XLA overrides (scheduler tuning
        # for the copy/compute overlap without touching global
        # LIBTPU_INIT_ARGS).  AOT executables are shape-specialized,
        # so they cache PER BATCH SHAPE — a different eval/tail batch
        # must retrace, not crash
        opts = os.getenv("DLROVER_TPU_OFFLOAD_XLA_OPTS", "")
        if not opts:
            return jitted(state, batch)
        shape_key = tuple(
            (tuple(x.shape), str(x.dtype))
            for x in jax.tree_util.tree_leaves(batch)
        )
        fn = cache.get(shape_key)
        if fn is None:
            kv = dict(
                item.split("=", 1)
                for item in opts.split(",")
                if "=" in item
            )
            fn = jitted.lower(state, batch).compile(
                compiler_options=kv
            )
            cache[shape_key] = fn
        return fn(state, batch)

    return init_state, train_step


def _release_params(state: OffloadState) -> OffloadState:
    """Swap the bf16 params tree for ShapeDtypeStructs once backward
    has consumed it: the update stream only needs SHAPES, and the
    swap drops the last in-step reference so the runtime frees the
    old params the moment the backward finishes executing — without
    it, old params + grads + the new params chunks coexist, which is
    the OOM margin at 3B.  Callers must pass the state as a consumed
    temporary (``step(holder.pop(), batch)``)."""
    shapes = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
        state.params,
    )
    return state._replace(params=shapes)


def build_offloaded_train_step(
    loss_fn,
    init_params_fn,
    optimizer: Optional[HostOffloadAdamW] = None,
    mode: str = "auto",
    micro_steps: int = 1,
    window: int = 2,
):
    """Single-chip train step with host-resident optimizer state.

    ``mode`` selects the update scheduling:

    - ``"auto"`` (default): ``"fused_delayed"`` when the backend is
      ``pinned_host`` (TPU), else ``"chunked"``.
    - ``"fused_delayed"`` / ``"fused"``: one-program update via
      :func:`build_fused_offload_step` (overlapped; ``fused`` is the
      exact-synchronous variant).
    - any mode composes with ``micro_steps`` gradient accumulation
      (``make_accumulated_grads_fn``) — the chunked mode is what the
      accumulated 1.8B proofs use: per-chunk update programs keep
      peak HBM far below the one-program fused form.
    - ``"chunked"``: the streaming
      :meth:`HostOffloadAdamW.apply_gradients` path, with the numpy
      backend prefetching its first chunk window before backward.

    Returns ``(init_state, train_step)`` where ``train_step(state,
    batch) -> (state, metrics)``.
    """
    opt = optimizer or HostOffloadAdamW()
    if mode == "auto":
        mode = (
            "fused_delayed"
            if opt.backend == "pinned_host"
            else "chunked"
        )
    if mode in ("fused", "fused_delayed"):
        return build_fused_offload_step(
            loss_fn, init_params_fn, opt,
            delayed=(mode == "fused_delayed"),
            micro_steps=micro_steps,
            window=window,
        )
    if mode != "chunked":
        raise ValueError(f"unknown offload mode {mode!r}")

    def init_state(rng) -> OffloadState:
        params = init_params_fn(rng)
        state = opt.init(params)
        del params
        return state

    if micro_steps <= 1:
        grad_fn = jax.jit(
            lambda params, batch: jax.value_and_grad(loss_fn)(
                params, batch
            )
        )

        def train_step(state: OffloadState, batch):
            # dispatch the H2D prefetch of the first chunk window
            # BEFORE backward so the transfers ride under the compute
            prefetched = opt.start_prefetch(state)
            loss, grads = grad_fn(state.params, batch)
            state = _release_params(state)
            new_state = opt.apply_gradients(
                state, grads, prefetched=prefetched
            )
            return new_state, {"loss": loss}

        return init_state, train_step

    # accumulated chunked path: one PROGRAM per microbatch, NOT one
    # K-micro program — the fused accumulation program must co-reserve
    # the accumulator, the per-micro grads and the backward residuals
    # and exceeds a 16 GB chip at 1.8B (measured).  The accumulator is
    # DONATED into each micro's backward program so the grad add is an
    # epilogue on the aliased buffer: peak stays at the r4-proven
    # (params + one grads tree + residuals), not + a separate acc.
    inv = 1.0 / micro_steps
    scaled_vag = jax.value_and_grad(
        lambda p, b: loss_fn(p, b) * inv
    )
    first_grad = jax.jit(scaled_vag)

    @functools.partial(jax.jit, donate_argnums=(2, 3))
    def _grad_into(params, mb, acc, loss_sum):
        loss_k, g = scaled_vag(params, mb)
        return (
            loss_sum + loss_k,
            jax.tree_util.tree_map(
                lambda s, a: (s + a).astype(s.dtype), acc, g
            ),
        )

    pending: Dict[str, object] = {}

    def train_step(state: OffloadState, batch):
        # NOTE the state is CONSUMED (donation semantics): pass it as
        # a temporary — `state, m = train_step(state, batch)` keeps
        # the caller's binding alive through the whole dispatch and
        # pins the old params tree (6 GB at 3B) into the chunk-stream
        # window.  See _release_params.
        # completion barrier on the PREVIOUS step: async dispatch
        # otherwise pipelines steps, and at 1.8B two in-flight steps'
        # buffers exceed HBM (runtime OOM) — a one-element readback
        # of the previous step's assembled params serializes steps
        prev = pending.pop("probe", None)
        if prev is not None:
            float(prev)
        prefetched = opt.start_prefetch(state)
        split = jax.tree_util.tree_map(
            lambda x: x.reshape(
                (micro_steps, x.shape[0] // micro_steps)
                + x.shape[1:]
            ),
            batch,
        )
        mb0 = jax.tree_util.tree_map(lambda x: x[0], split)
        loss_sum, acc = first_grad(state.params, mb0)
        for k in range(1, micro_steps):
            mb = jax.tree_util.tree_map(lambda x: x[k], split)
            loss_sum, acc = _grad_into(
                state.params, mb, acc, loss_sum
            )
        state = _release_params(state)
        new_state = opt.apply_gradients(
            state, acc, prefetched=prefetched
        )
        # the LAST-dispatched leaf: its completion implies the whole
        # stream's on this serially-executing runtime
        last = jax.tree_util.tree_leaves(new_state.params)[-1]
        pending["probe"] = (
            last.reshape(-1)[-1].astype(jnp.float32)
        )
        return new_state, {"loss": loss_sum}

    return init_state, train_step


def build_grouped_offload_step(
    loss_grouped,
    init_a_fn=None,
    init_b_fn=None,
    optimizer_a: Optional[HostOffloadAdamW] = None,
    optimizer_b: Optional[HostOffloadAdamW] = None,
    *,
    init_fns: Optional[Sequence] = None,
    optimizers: Optional[Sequence] = None,
):
    """Offloaded train step with N param groups and one backward
    pass per group — the ceiling lever past ~2B params on a 16 GB
    chip, where a single backward's full dW tree cannot coexist with
    the bf16 params (measured: 3.0B needs ~19 GB).  More groups
    shrink the peak further: the largest resident dW tree is one
    group's, so N is the knob that trades backward passes for HBM
    headroom (``accelerate.solver.solve_offload_groups`` picks the
    smallest N that fits from the model's per-layer footprint).

    Semantics are EXACT single-step AdamW: every group's gradients
    are evaluated at the step-start params (groups ``0..N-2``'s
    gradients are staged to host memory while later backwards and
    the last group's update run, then brought back in reverse
    order) — not block-coordinate descent.

    Two calling conventions:

    - legacy two-group (positional, unchanged):
      ``build_grouped_offload_step(loss, init_a, init_b, opt_a,
      opt_b)`` with ``loss(params_a, params_b, batch)``;
    - N-group: ``build_grouped_offload_step(loss, init_fns=[...],
      optimizers=[...])`` with ``loss(*group_params, batch)``.

    ``init_fns[i]()`` builds group i's params tree lazily so each
    group's fp32 source frees before the next materializes.  Returns
    ``(init_state, train_step)`` with ``train_step(state, batch) ->
    (state, metrics)`` over a tuple of per-group states, CONSUMED
    like the chunked step (pass it as a temporary).
    """
    if init_fns is None:
        init_fns = [
            fn for fn in (init_a_fn, init_b_fn) if fn is not None
        ]
        optimizers = [optimizer_a, optimizer_b][: len(init_fns)]
    init_fns = list(init_fns)
    n_groups = len(init_fns)
    if n_groups < 1:
        raise ValueError("need at least one param group")
    if optimizers is None:
        optimizers = [None] * n_groups
    opts = [o or HostOffloadAdamW() for o in optimizers]
    if len(opts) != n_groups:
        raise ValueError(
            f"{len(opts)} optimizers for {n_groups} groups"
        )
    dev, host = opts[0]._shardings()

    vags = [
        jax.jit(jax.value_and_grad(loss_grouped, argnums=i))
        for i in range(n_groups)
    ]
    # host staging round-trip for the early groups' grads (identity
    # programs with host output/input layouts; on CPU test meshes
    # host==dev and these are no-ops)
    stage_out = jax.jit(lambda g: g, out_shardings=host)
    stage_in = jax.jit(lambda g: g, out_shardings=dev)
    two_spaces = host is not dev
    host_scalar = jax.jit(
        lambda l: jax.device_put(l, dev).reshape(-1)[0].astype(
            jnp.float32
        ),
        out_shardings=dev,
    )

    def _barrier(value):
        """Force completion of everything dispatched so far: at 3B
        the phases' OUTPUT buffers are allocated at dispatch on this
        runtime, so letting every phase enqueue at once demands
        every phase's outputs simultaneously (~16 GB of outputs
        alone).  Only needed where a second memory space exists —
        the CPU test mesh runs phases eagerly anyway."""
        if two_spaces and value is not None:
            float(value)

    def _last_leaf_probe(params):
        return (
            jax.tree_util.tree_leaves(params)[-1]
            .reshape(-1)[-1]
            .astype(jnp.float32)
        )

    def init_state(rng=None):
        del rng  # group inits carry their own keys
        return tuple(
            opts[i].init(init_fns[i]()) for i in range(n_groups)
        )

    pending: Dict[str, object] = {}

    debug = os.getenv("DLROVER_TPU_GROUPED_DEBUG", "") == "1"

    def _dbg(msg):
        if debug:
            import time as _time

            mem = ""
            try:
                stats = jax.local_devices()[0].memory_stats()
                mem = (
                    f" hbm={stats.get('bytes_in_use', 0) / 1e9:.2f}G"
                    f" peak={stats.get('peak_bytes_in_use', 0) / 1e9:.2f}G"
                )
            except Exception:  # noqa: BLE001
                pass
            print(
                f"[grouped {_time.strftime('%H:%M:%S')}] {msg}{mem}",
                flush=True,
            )

    def train_step(state, batch):
        states = list(state)
        del state
        prev = pending.pop("probe", None)
        if prev is not None:
            float(prev)  # serialize steps (HBM cannot hold two)
        _dbg("step start")
        step_params = [s.params for s in states]
        loss = None
        staged = []
        # passes 1..N-1: early groups' grads at step-start params ->
        # host staging (one dW tree resident at a time)
        for i in range(n_groups - 1):
            loss_i, g = vags[i](*step_params, batch)
            if loss is None:
                loss = loss_i
            _barrier(loss_i)
            _dbg(f"vag_{i} done")
            g = stage_out(g)
            _barrier(
                host_scalar(jax.tree_util.tree_leaves(g)[0])
                if two_spaces
                else None
            )
            staged.append(g)
        # final pass: last group's grads at the SAME step-start
        # params, updated immediately (no staging round-trip).  The
        # rolling H2D window starts AFTER the backward barrier: at
        # the 3B HBM edge the backward's residuals + dW leave no
        # margin for early-staged chunks, and the chunk stream still
        # pipelines copy against update math within the window.
        last = n_groups - 1
        loss_last, g_last = vags[last](*step_params, batch)
        if loss is None:
            loss = loss_last
        _barrier(loss_last)
        _dbg(f"vag_{last} done")
        pre_last = opts[last].start_prefetch(states[last])
        del step_params  # step-start refs live on in `states`
        # rebinding FIRST matters: inlining _release_params in the
        # call would keep the name bound to the original state (real
        # params pinned) for the whole dispatch
        states[last] = _release_params(states[last])
        states[last] = opts[last].apply_gradients(
            states[last], g_last, prefetched=pre_last
        )
        del g_last, pre_last
        # bring the staged grads back and update in reverse order;
        # between updates, force the LAST-dispatched leaf: programs
        # execute in dispatch order on this runtime, so its
        # completion implies the whole stream's (the first leaf
        # would only cover the head of the stream)
        for i in range(n_groups - 2, -1, -1):
            _barrier(
                _last_leaf_probe(states[i + 1].params)
                if two_spaces
                else None
            )
            _dbg(f"apply_{i + 1} done")
            g = stage_in(staged[i])
            staged[i] = None
            # rolling window for this group's chunk stream: its H2D
            # overlaps the previous group's still-draining update
            pre = opts[i].start_prefetch(states[i])
            states[i] = _release_params(states[i])
            states[i] = opts[i].apply_gradients(
                states[i], g, prefetched=pre
            )
            del g, pre
        _dbg("apply_0 dispatched")
        pending["probe"] = _last_leaf_probe(states[0].params)
        return tuple(states), {"loss": loss}

    return init_state, train_step
