from dlrover_tpu.optimizers.agd import agd  # noqa: F401
from dlrover_tpu.optimizers.low_bit import quantized_moments  # noqa: F401
from dlrover_tpu.optimizers.wsam import (  # noqa: F401
    wsam_gradients,
)
from dlrover_tpu.optimizers.schedules import (  # noqa: F401
    available_schedulers,
    get_scheduler,
)
