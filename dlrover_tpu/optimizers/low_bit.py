"""1-byte optimizer states: int8-quantized Adam moments.

Reference parity: atorch's low-bit optimizer
(``atorch/atorch/optimizers/low_bit/`` backed by the CUDA kernels in
``ops/csrc/quantization/quantization_optimizer.cu``) — Adam moments
stored quantized, dequantized transiently for the update.  Here the
quant/dequant are the Pallas kernels in
``dlrover_tpu.ops.quantization`` and the optimizer is an optax
transformation, so it composes with the sharded train step (states
inherit the params' sharding; the quantized payloads shard the same
way).
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax

from dlrover_tpu.ops.quantization import (
    dequantize_blockwise,
    fused_int8_adam_update,
    quantize_blockwise,
)


@jax.tree_util.register_pytree_node_class
class _QTensor:
    """Quantized payload; shape/n are static aux data so reshapes stay
    concrete under jit."""

    def __init__(self, q, scales, shape, n):
        self.q = q
        self.scales = scales
        self.shape = tuple(shape)
        self.n = n

    def tree_flatten(self):
        return (self.q, self.scales), (self.shape, self.n)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], *aux)


def _quant(x) -> _QTensor:
    q, scales, (shape, n) = quantize_blockwise(x)
    return _QTensor(q=q, scales=scales, shape=shape, n=n)


def dequantize_qtensor(t: _QTensor) -> jnp.ndarray:
    """Materialize a quantized moment in fp32 (debug/inspection; the
    training path never does this — the fused kernel dequantizes
    in-register)."""
    return dequantize_blockwise(t.q, t.scales, (t.shape, t.n))


class QuantizedMomentsState(NamedTuple):
    step: jnp.ndarray
    mu: optax.Updates  # _QTensor pytree
    nu: optax.Updates


def quantized_moments(
    learning_rate: float = 1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
) -> optax.GradientTransformation:
    """AdamW with int8 moments (1 byte/param/moment vs 4)."""

    def init_fn(params):
        def zq(p):
            return _quant(jnp.zeros(p.shape, jnp.float32))

        return QuantizedMomentsState(
            step=jnp.zeros((), jnp.int32),
            mu=jax.tree_util.tree_map(zq, params),
            nu=jax.tree_util.tree_map(zq, params),
        )

    def update_fn(grads, state, params=None):
        step = state.step + 1
        stepf = step.astype(jnp.float32)
        bc1 = 1.0 - b1**stepf
        bc2 = 1.0 - b2**stepf

        def moment_update(g, mu_q, nu_q):
            # single fused pallas pass per leaf: dequant -> moment
            # update -> update value -> requant.  The f32 moments
            # never round-trip through HBM and the 4-kernel+XLA-glue
            # chain collapses to one launch (the unfused path cost the
            # 0.9B scale proof ~24% of its step time).  nu is stored
            # as sqrt(nu): linear int8 on raw nu underflows small
            # second moments inside a block dominated by one large
            # value (blockwise absmax scale) and the rsqrt then
            # explodes the update — storing the root keeps 1e-8-class
            # moments representable (the reference's low-bit
            # optimizers use nonlinear quantization maps for the same
            # reason).
            update, mu_q2, mu_s2, nu_q2, nu_s2 = (
                fused_int8_adam_update(
                    g, mu_q.q, mu_q.scales, nu_q.q, nu_q.scales,
                    (mu_q.shape, mu_q.n), bc1, bc2,
                    lr=learning_rate, b1=b1, b2=b2, eps=eps,
                )
            )
            new_mu = _QTensor(mu_q2, mu_s2, mu_q.shape, mu_q.n)
            new_nu = _QTensor(nu_q2, nu_s2, nu_q.shape, nu_q.n)
            return update, new_mu, new_nu

        out = jax.tree_util.tree_map(
            moment_update, grads, state.mu, state.nu
        )
        # tree_map over 3 trees returns tuples at leaves; unzip
        treedef = jax.tree_util.tree_structure(grads)
        flat = treedef.flatten_up_to(out)
        updates = treedef.unflatten([u for u, _, _ in flat])
        mu = treedef.unflatten([m for _, m, _ in flat])
        nu = treedef.unflatten([n for _, _, n in flat])

        if weight_decay and params is not None:
            updates = jax.tree_util.tree_map(
                lambda u, p: u - learning_rate * weight_decay * p,
                updates,
                params,
            )
        return updates, QuantizedMomentsState(step, mu, nu)

    return optax.GradientTransformation(init_fn, update_fn)
