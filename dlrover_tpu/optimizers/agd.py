"""AGD optimizer (NeurIPS'23) as an optax transformation.

Reference parity: ``atorch/atorch/optimizers/agd.py:18`` — AGD
preconditions with the *stepwise gradient difference*: the second
moment tracks ``diff = m_t/bc1_t - m_{t-1}/bc1_{t-1}`` (difference of
bias-corrected first moments) instead of the raw gradient square,
auto-switching between SGD-like and Adam-like behavior.  Functional
re-derivation for JAX; same hyperparameters and update rule as the
reference's dense path (win=False).
"""

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax


class AGDState(NamedTuple):
    step: jnp.ndarray
    exp_avg: optax.Updates
    exp_avg_sq: optax.Updates
    max_exp_avg_sq: Optional[optax.Updates]


def agd(
    learning_rate: float = 1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    delta: float = 1e-5,
    weight_decay: float = 0.0,
    amsgrad: bool = False,
    clip: Optional[float] = None,
) -> optax.GradientTransformation:
    def init_fn(params):
        zeros = jax.tree_util.tree_map(
            lambda p: jnp.zeros_like(p, dtype=jnp.float32), params
        )
        return AGDState(
            step=jnp.zeros((), jnp.int32),
            exp_avg=zeros,
            exp_avg_sq=jax.tree_util.tree_map(jnp.copy, zeros),
            max_exp_avg_sq=(
                jax.tree_util.tree_map(jnp.copy, zeros)
                if amsgrad
                else None
            ),
        )

    def update_fn(grads, state, params=None):
        step = state.step + 1
        stepf = step.astype(jnp.float32)
        bc1_old = 1.0 - b1 ** (stepf - 1.0)
        bc1 = 1.0 - b1**stepf
        bc2 = 1.0 - b2**stepf

        exp_avg = jax.tree_util.tree_map(
            lambda m, g: b1 * m + (1 - b1) * g, state.exp_avg, grads
        )
        # stepwise gradient difference (first step: just m/bc1)
        def diff(m_new, m_old):
            d = m_new / bc1 - m_old / jnp.maximum(bc1_old, 1e-12)
            return jnp.where(step == 1, m_new / bc1, d)

        diffs = jax.tree_util.tree_map(diff, exp_avg, state.exp_avg)
        exp_avg_sq = jax.tree_util.tree_map(
            lambda v, d: b2 * v + (1 - b2) * d * d,
            state.exp_avg_sq,
            diffs,
        )
        if amsgrad:
            max_sq = jax.tree_util.tree_map(
                jnp.maximum, state.max_exp_avg_sq, exp_avg_sq
            )
            precond_sq = max_sq
        else:
            max_sq = None
            precond_sq = exp_avg_sq

        delta_adjust = delta * jnp.sqrt(bc2)
        lr_adjust = learning_rate * jnp.sqrt(bc2) / bc1

        def direction(m, v):
            denom = jnp.maximum(jnp.sqrt(v), delta_adjust)
            u = m / denom
            if clip is not None:
                u = jnp.clip(u, -clip, clip)
            return -lr_adjust * u

        updates = jax.tree_util.tree_map(
            direction, exp_avg, precond_sq
        )
        if weight_decay and params is not None:
            # decoupled decay (reference weight_decouple=True default)
            updates = jax.tree_util.tree_map(
                lambda u, p: u - learning_rate * weight_decay * p,
                updates,
                params,
            )
        return updates, AGDState(step, exp_avg, exp_avg_sq, max_sq)

    return optax.GradientTransformation(init_fn, update_fn)
