"""Learning-rate schedules: a name-keyed registry over optax.

Reference parity: ``atorch/atorch/trainer/atorch_trainer.py:654``
(``get_scheduler`` wiring HF ``SchedulerType`` names into the trainer)
and the HF ``transformers.optimization`` family it delegates to.  The
TPU-first design is simpler: an optax schedule is a pure
``step -> lr`` function that lives INSIDE the optimizer
(``optax.adamw(learning_rate=get_scheduler(...))``), so its position
is carried by the optimizer state's step count — flash-checkpoint
resume restores it with the opt_state, no separate scheduler state
object to save (the reference serializes ``lr_scheduler.state_dict()``
separately; here consistency is structural).

Supported names (HF-compatible plus TPU-pretraining staples):
``constant``, ``constant_with_warmup``, ``linear``, ``cosine``,
``cosine_with_min_lr``, ``polynomial``, ``inverse_sqrt``, ``wsd``
(warmup-stable-decay).
"""

from typing import Callable, Optional

import optax

SchedulerFn = Callable[..., optax.Schedule]

_REGISTRY = {}


def register_scheduler(name: str):
    def deco(fn: SchedulerFn) -> SchedulerFn:
        _REGISTRY[name] = fn
        return fn

    return deco


def available_schedulers():
    return sorted(_REGISTRY)


def get_scheduler(
    name: str,
    learning_rate: float,
    total_steps: Optional[int] = None,
    warmup_steps: int = 0,
    **kwargs,
) -> optax.Schedule:
    """Build a ``step -> lr`` schedule by name.

    ``total_steps`` is required by decaying schedules (linear/cosine/
    polynomial/wsd); warmup always ramps linearly from 0 over
    ``warmup_steps``.
    """
    if name not in _REGISTRY:
        raise ValueError(
            f"unknown scheduler {name!r}; available: "
            f"{available_schedulers()}"
        )
    decaying = name in (
        "linear", "cosine", "cosine_with_min_lr", "polynomial", "wsd"
    )
    if decaying and not total_steps:
        raise ValueError(f"scheduler {name!r} requires total_steps")
    return _REGISTRY[name](
        learning_rate=learning_rate,
        total_steps=total_steps,
        warmup_steps=warmup_steps,
        **kwargs,
    )


def _with_warmup(
    base: optax.Schedule, learning_rate: float, warmup_steps: int
) -> optax.Schedule:
    if warmup_steps <= 0:
        return base
    warmup = optax.linear_schedule(0.0, learning_rate, warmup_steps)
    return optax.join_schedules([warmup, base], [warmup_steps])


@register_scheduler("constant")
def _constant(learning_rate, total_steps, warmup_steps, **_):
    return _with_warmup(
        optax.constant_schedule(learning_rate),
        learning_rate,
        warmup_steps,
    )


@register_scheduler("constant_with_warmup")
def _constant_with_warmup(learning_rate, total_steps, warmup_steps, **_):
    return _with_warmup(
        optax.constant_schedule(learning_rate),
        learning_rate,
        max(warmup_steps, 1),
    )


@register_scheduler("linear")
def _linear(learning_rate, total_steps, warmup_steps, end_value=0.0, **_):
    decay = optax.linear_schedule(
        learning_rate, end_value, max(total_steps - warmup_steps, 1)
    )
    return _with_warmup(decay, learning_rate, warmup_steps)


@register_scheduler("cosine")
def _cosine(learning_rate, total_steps, warmup_steps, **_):
    decay = optax.cosine_decay_schedule(
        learning_rate, max(total_steps - warmup_steps, 1)
    )
    return _with_warmup(decay, learning_rate, warmup_steps)


@register_scheduler("cosine_with_min_lr")
def _cosine_min(
    learning_rate, total_steps, warmup_steps, min_lr_ratio=0.1, **_
):
    decay = optax.cosine_decay_schedule(
        learning_rate,
        max(total_steps - warmup_steps, 1),
        alpha=min_lr_ratio,
    )
    return _with_warmup(decay, learning_rate, warmup_steps)


@register_scheduler("polynomial")
def _polynomial(
    learning_rate, total_steps, warmup_steps, power=1.0,
    end_value=1e-7, **_,
):
    decay = optax.polynomial_schedule(
        learning_rate,
        end_value,
        power,
        max(total_steps - warmup_steps, 1),
    )
    return _with_warmup(decay, learning_rate, warmup_steps)


@register_scheduler("inverse_sqrt")
def _inverse_sqrt(learning_rate, total_steps, warmup_steps, **_):
    shift = max(warmup_steps, 1)

    def decay(step):
        return learning_rate * (shift / (step + shift)) ** 0.5

    # join at warmup boundary: optax.join_schedules rebases the second
    # schedule's step to 0 at the boundary, which is what shift expects
    return _with_warmup(decay, learning_rate, warmup_steps)


@register_scheduler("wsd")
def _wsd(
    learning_rate, total_steps, warmup_steps, decay_ratio=0.1,
    min_lr_ratio=0.0, **_,
):
    """Warmup-Stable-Decay: hold peak LR for most of training, decay
    linearly over the final ``decay_ratio`` fraction — the continual-
    pretraining-friendly schedule (checkpoints mid-plateau resume into
    longer runs without LR mismatch)."""
    decay_steps = max(int(total_steps * decay_ratio), 1)
    stable_steps = max(total_steps - warmup_steps - decay_steps, 0)
    stable = optax.constant_schedule(learning_rate)
    decay = optax.linear_schedule(
        learning_rate, learning_rate * min_lr_ratio, decay_steps
    )
    tail = optax.join_schedules([stable, decay], [stable_steps])
    return _with_warmup(tail, learning_rate, warmup_steps)
