"""``python -m dlrover_tpu.run`` — console entry for the elastic launcher.

Reference parity: the ``dlrover-run`` console script
(``dlrover/setup.py:57-59`` → ``dlrover/trainer/torch/main.py``).
"""

import sys

from dlrover_tpu.trainer.elastic_run import main

if __name__ == "__main__":
    sys.exit(main())
