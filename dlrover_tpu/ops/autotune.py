"""Shape-keyed autotuner for the paged-attention Pallas kernels.

The kernels in ``ops/paged_kernels.py`` have two tunables per shape:
``q_rows`` (padded query rows per KV head — the q-block) and
``kv_span`` (pool pages streamed per grid step — the kv-block; the
grid's KV extent is ``ceil(max_blocks / kv_span)``).  Which pair wins
depends on the device generation and the shape, so the choice is data,
not code:

- **Candidates** are derived from ``round_block_to_tile`` (PR 3's
  tile-legality helper), so every swept config is a legal Mosaic tile
  — the tuner never times a config that would fail to lower on TPU.
- **Timing** happens only when explicitly invoked (the
  ``scripts/bench_paged_attention.py`` micro-bench, or any caller of
  :func:`tune_kernel`), on the live backend, minimum-of-``reps`` wall
  time per candidate.  Tuning never runs inside a jit trace — the
  dispatcher only ever *looks up* a config, so the scheduler's
  compile-once invariant is untouched.
- **Cache**: winners land in a JSON table keyed by
  ``(kernel, shape-bucket, dtype, device-kind)`` at
  ``$DLROVER_TPU_AUTOTUNE_CACHE`` (default
  ``~/.cache/dlrover_tpu/paged_autotune.json``).  Lookup order is
  user cache -> checked-in ``ops/autotune_defaults.json`` (the
  deterministic table CPU CI resolves against) -> shape heuristic.
- Every tuning event is recorded on the timeline as a
  ``kernel_autotune`` span (labels ``kernel`` / ``best_config`` /
  ``candidates`` / ``best_us``, schema-linted) and publishes the
  winner's time as the ``dlrover_tpu_paged_kernel_us`` gauge
  (labels ``kernel`` / ``backend``).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

CACHE_ENV = "DLROVER_TPU_AUTOTUNE_CACHE"
_DEFAULT_CACHE = os.path.join(
    os.path.expanduser("~"), ".cache", "dlrover_tpu", "paged_autotune.json"
)
_DEFAULTS_FILE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "autotune_defaults.json"
)

#: in-process memo so the dispatcher's trace-time lookups are O(1)
_MEMO: Dict[str, Dict[str, Any]] = {}


def _cache_path() -> str:
    return os.getenv(CACHE_ENV, "").strip() or _DEFAULT_CACHE


def _device_kind() -> str:
    """Device bucket for cache keys: TPUs key by their real kind (tile
    economics differ per generation); everything else runs the kernels
    in interpret mode and shares one bucket."""
    from dlrover_tpu.ops.pallas_utils import use_interpret

    if use_interpret():
        return "interpret"
    return jax.devices()[0].device_kind.replace(" ", "-").lower()


def _pow2_bucket(n: int) -> int:
    b = 1
    while b < n:
        b *= 2
    return b


def shape_key(
    kernel: str,
    *,
    group: int,
    head_dim: int,
    block_size: int,
    max_blocks: int,
    dtype,
    window: int = 1,
    device_kind: Optional[str] = None,
) -> str:
    """Stable cache key.  ``max_blocks`` is pow2-bucketed (grid length
    only shifts the stream count, not the tile choice); everything that
    changes tile legality or arithmetic intensity keys exactly."""
    kind = device_kind if device_kind is not None else _device_kind()
    return "|".join(
        (
            kernel,
            f"g{group}",
            f"d{head_dim}",
            f"bs{block_size}",
            f"mb{_pow2_bucket(max_blocks)}",
            f"w{window}",
            np.dtype(dtype).name,
            kind,
        )
    )


def _load_json(path: str) -> Dict[str, Any]:
    try:
        with open(path, "r", encoding="utf-8") as f:
            loaded = json.load(f)
        return loaded if isinstance(loaded, dict) else {}
    except (OSError, ValueError):
        return {}


def _heuristic(
    kernel: str,
    *,
    group: int,
    head_dim: int,
    block_size: int,
    max_blocks: int,
    dtype,
    window: int = 1,
) -> Dict[str, Any]:
    """Untuned fallback.  Interpret mode: no row padding (padding is
    pure overhead when there is no sublane tile to fill) and one page
    per step.  Compiled TPU: tile-align the rows and stream the widest
    legal span up to 4 pages, amortizing grid overhead."""
    from dlrover_tpu.ops.pallas_utils import use_interpret
    from dlrover_tpu.ops.paged_kernels import sublane_tile

    rows = group * (window if kernel == "verify" else 1)
    if use_interpret():
        return {"q_rows": rows, "kv_span": 1}
    tile = sublane_tile(dtype)
    q_rows = ((rows + tile - 1) // tile) * tile
    span = 1
    for cand in (2, 4):
        if cand <= max_blocks and _span_is_legal(
            cand, block_size, max_blocks, dtype
        ):
            span = cand
    return {"q_rows": q_rows, "kv_span": span}


def _span_is_legal(
    span: int, block_size: int, max_blocks: int, dtype
) -> bool:
    """A span is legal iff the kv rows it streams per step survive
    ``round_block_to_tile`` unchanged — i.e. they already sit on a
    Mosaic tile boundary for this dtype."""
    from dlrover_tpu.accelerate.module_replace import round_block_to_tile

    total = max_blocks * block_size
    kv_rows = min(span * block_size, total)
    return round_block_to_tile(kv_rows, total, dtype) == kv_rows


def candidates(
    kernel: str,
    *,
    group: int,
    head_dim: int,
    block_size: int,
    max_blocks: int,
    dtype,
    window: int = 1,
) -> List[Dict[str, Any]]:
    """Legal (q_rows, kv_span) sweep for one shape, smallest first."""
    from dlrover_tpu.ops.paged_kernels import sublane_tile

    rows = group * (window if kernel == "verify" else 1)
    tile = sublane_tile(dtype)
    row_opts = sorted({rows, ((rows + tile - 1) // tile) * tile})
    span_opts = [
        s
        for s in (1, 2, 4, 8)
        if s <= max_blocks and _span_is_legal(s, block_size, max_blocks, dtype)
    ] or [1]
    return [
        {"q_rows": r, "kv_span": s} for r in row_opts for s in span_opts
    ]


def get_config(
    kernel: str,
    *,
    group: int,
    head_dim: int,
    block_size: int,
    max_blocks: int,
    dtype,
    window: int = 1,
) -> Dict[str, Any]:
    """Trace-time config lookup (never times anything): in-process memo
    -> user cache JSON -> checked-in defaults -> heuristic."""
    key = shape_key(
        kernel,
        group=group,
        head_dim=head_dim,
        block_size=block_size,
        max_blocks=max_blocks,
        dtype=dtype,
        window=window,
    )
    hit = _MEMO.get(key)
    if hit is not None:
        return hit
    cfg = _load_json(_cache_path()).get(key)
    if not isinstance(cfg, dict):
        cfg = _load_json(_DEFAULTS_FILE).get(key)
    if not isinstance(cfg, dict):
        cfg = _heuristic(
            kernel,
            group=group,
            head_dim=head_dim,
            block_size=block_size,
            max_blocks=max_blocks,
            dtype=dtype,
            window=window,
        )
    cfg = {"q_rows": int(cfg["q_rows"]), "kv_span": int(cfg["kv_span"])}
    _MEMO[key] = cfg
    return cfg


def clear_memo() -> None:
    """Drop the in-process lookup memo (tests; after cache writes)."""
    _MEMO.clear()


def _save_winner(key: str, config: Dict[str, Any], best_us: float) -> str:
    path = _cache_path()
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    table = _load_json(path)
    table[key] = dict(config, best_us=round(best_us, 3))
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(table, f, indent=2, sort_keys=True)
    os.replace(tmp, path)
    return path


def tune_kernel(
    kernel: str,
    run_fn: Callable[[Dict[str, Any]], Callable[[], Any]],
    cands: List[Dict[str, Any]],
    *,
    key: str,
    reps: int = 3,
    backend: str = "pallas",
    save: bool = True,
) -> Tuple[Dict[str, Any], List[Dict[str, Any]]]:
    """Time every candidate and persist + publish the winner.

    ``run_fn(config)`` returns a zero-arg callable that executes the
    kernel once, *blocking until the result is ready* (the callable is
    invoked once for warmup/compile before timing).  Candidates that
    fail to compile are skipped, not fatal.  Returns ``(best_config,
    report)`` where the report lists per-candidate microseconds.
    """
    from dlrover_tpu.observability.events import get_event_logger
    from dlrover_tpu.observability.metrics import get_registry

    start_wall = time.time()
    t_begin = time.perf_counter()
    report: List[Dict[str, Any]] = []
    best: Optional[Dict[str, Any]] = None
    best_us = float("inf")
    for config in cands:
        try:
            call = run_fn(config)
            call()  # warmup: compile + first run outside the clock
            elapsed_us = float("inf")
            for _ in range(max(1, reps)):
                t0 = time.perf_counter()
                call()
                elapsed_us = min(
                    elapsed_us, (time.perf_counter() - t0) * 1e6
                )
        except Exception as exc:  # illegal tile / OOM: skip, don't die
            report.append(dict(config, error=f"{type(exc).__name__}: {exc}"))
            continue
        report.append(dict(config, us=round(elapsed_us, 3)))
        if elapsed_us < best_us:
            best_us = elapsed_us
            best = config
    if best is None:
        raise RuntimeError(
            f"autotune[{kernel}]: no candidate ran (tried {len(cands)})"
        )
    if save:
        _save_winner(key, best, best_us)
        _MEMO[key] = dict(best)
    get_event_logger().complete(
        "kernel_autotune",
        start_wall,
        time.perf_counter() - t_begin,
        kernel=kernel,
        best_config=json.dumps(best, sort_keys=True),
        candidates=len(cands),
        best_us=round(best_us, 3),
    )
    get_registry().set_gauge(
        "dlrover_tpu_paged_kernel_us",
        best_us,
        labels={"kernel": kernel, "backend": backend},
    )
    return dict(best), report
