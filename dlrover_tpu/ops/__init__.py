from dlrover_tpu.ops.flash_attention import flash_attention  # noqa: F401
from dlrover_tpu.ops.fused import (  # noqa: F401
    fused_linear_cross_entropy,
    layer_norm,
    rms_norm,
)
