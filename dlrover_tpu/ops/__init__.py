from dlrover_tpu.ops.flash_attention import flash_attention  # noqa: F401
from dlrover_tpu.ops.fused import (  # noqa: F401
    fused_linear_cross_entropy,
    layer_norm,
    rms_norm,
)
from dlrover_tpu.ops.flash_attention import flash_attention_lse  # noqa: F401
from dlrover_tpu.ops.grouped_gemm import grouped_gemm  # noqa: F401
from dlrover_tpu.ops.quantization import (  # noqa: F401
    dequantize_blockwise,
    quantize_blockwise,
)
