"""Grouped GEMM for MoE experts.

Reference parity: ``atorch/atorch/modules/moe/grouped_gemm_moe.py``
(megablocks-style grouped matmul — tokens sorted by expert, one ragged
GEMM over contiguous expert groups instead of E separate matmuls or a
dense one-hot dispatch).

TPU form: ``jax.lax.ragged_dot`` is XLA's dedicated grouped-matmul op;
its TPU lowering tiles the ragged groups straight onto the MXU without
materializing per-expert capacity buffers — exactly what a
hand-written Pallas gmm kernel would do, with the compiler handling
tile-boundary crossing.  This module wraps it with the token
sort/unsort plumbing the MoE layer needs.

Measured on v5e (dim 1024, mlp 2816, 8 experts, top-2, 16k tokens,
bf16) vs the dense one-hot dispatch: forward 20.0 -> 14.5 ms (1.4x),
forward+backward 36.8 -> 21.7 ms (1.7x) — while also being dropless.
"""

from typing import Tuple

import jax
import jax.numpy as jnp


def grouped_gemm(
    lhs: jnp.ndarray,  # [T, K] tokens sorted by group
    rhs: jnp.ndarray,  # [G, K, N] one matrix per group
    group_sizes: jnp.ndarray,  # [G] int32, sum == T
) -> jnp.ndarray:
    """Rows ``offset[g] : offset[g]+group_sizes[g]`` of ``lhs`` are
    multiplied by ``rhs[g]``; returns [T, N]."""
    return jax.lax.ragged_dot(
        lhs, rhs.astype(lhs.dtype), group_sizes.astype(jnp.int32)
    )


def sort_tokens_by_expert(
    expert_ids: jnp.ndarray,  # [R] one expert id per token-replica
    num_experts: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(sort order [R], group_sizes [E]) for the grouped GEMM; the
    argsort is stable so replicas of one token keep their relative
    order inside an expert's group."""
    order = jnp.argsort(expert_ids, stable=True)
    group_sizes = jnp.bincount(expert_ids, length=num_experts)
    return order, group_sizes
