"""Fused normalization + fused linear-cross-entropy.

Reference parity: atorch ships a fused LayerNorm module
(``atorch/atorch/normalization/layernorm.py``) and fused losses
(``atorch/atorch/modules/transformer/losses.py``) as CUDA-side fusions.
The TPU forms:

* ``rms_norm`` — a Pallas forward kernel that computes the row rstd and
  the normalized output in one VMEM pass (one HBM read of ``x`` instead
  of the two XLA sometimes emits for the mean-of-squares + scale pair),
  with a ``custom_vjp`` whose backward reuses the saved rstd — no
  variance recompute.  The flagship llama family is RMSNorm, so that is
  the fused form; LayerNorm callers get the same treatment via
  ``layer_norm`` (plain XLA — its mean+var already fuse well and no
  model here is LayerNorm-hot).
* ``fused_linear_cross_entropy`` — the last-layer fusion that matters
  on TPU: next-token CE normally materializes fp32 logits ``[B*S, V]``
  *twice* (logits + log-softmax), ~0.5 GB per 4k-seq batch row at
  V=32k.  The fused form chunks the rows, computes
  ``chunk @ W -> logsumexp -> nll`` under ``jax.checkpoint`` inside a
  ``lax.scan``, so peak logits memory is ``chunk x V`` and the backward
  recomputes each chunk's logits while accumulating ``dW`` in fp32.
  Pure XLA (matmul-dominated — the MXU path — so a hand kernel would
  only get in the way of the compiler's own pipelining); exact same
  math as the dense loss.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128
_ROWS = 8  # row block: one sublane tile


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


# ------------------------------------------------------------ RMSNorm


def _rms_fwd_kernel(x_ref, w_ref, y_ref, rstd_ref, *, eps: float):
    x = x_ref[...].astype(jnp.float32)
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    rstd = lax.rsqrt(var + eps)
    y_ref[...] = (
        x * rstd * w_ref[...].astype(jnp.float32)
    ).astype(y_ref.dtype)
    rstd_ref[...] = rstd


def _rms_fwd_pallas(x2, w, eps):
    n, d = x2.shape
    grid = n // _ROWS
    y, rstd = pl.pallas_call(
        functools.partial(_rms_fwd_kernel, eps=eps),
        out_shape=(
            jax.ShapeDtypeStruct(x2.shape, x2.dtype),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((_ROWS, d), lambda i: (i, 0)),
            pl.BlockSpec((d,), lambda i: (0,)),
        ],
        out_specs=(
            pl.BlockSpec((_ROWS, d), lambda i: (i, 0)),
            pl.BlockSpec((_ROWS, 1), lambda i: (i, 0)),
        ),
        interpret=_use_interpret(),
    )(x2, w)
    return y, rstd


def _rms_plain(x, weight, eps):
    # weight multiply in fp32 with ONE final cast — the same rounding
    # as the Pallas kernel, so both paths produce identical values
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    rstd = lax.rsqrt(var + eps)
    return (
        (xf * rstd * weight.astype(jnp.float32)).astype(dtype),
        rstd,
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def rms_norm(x, weight, eps: float = 1e-5):
    """``x * rsqrt(mean(x^2) + eps) * weight`` over the last dim.

    Any leading shape; fused Pallas forward when the last dim is
    lane-aligned, plain XLA otherwise.  Numerics identical to the
    unfused form (fp32 statistics, output in ``x.dtype``).
    """
    return _rms_fwd(x, weight, eps)[0]


def _rms_fwd(x, weight, eps: float):
    d = x.shape[-1]
    lead = x.shape[:-1]
    n = 1
    for s in lead:
        n *= s
    if _use_interpret() or d % _LANES or n % _ROWS or n == 0:
        # off-TPU (or misaligned) the plain form is already one fused
        # XLA loop; the kernel itself is covered via interpret in tests
        y, rstd = _rms_plain(x, weight, eps)
        return y, (x, weight, rstd)
    x2 = x.reshape(n, d)
    y2, rstd = _rms_fwd_pallas(x2, weight, eps)
    return y2.reshape(*lead, d), (x, weight, rstd.reshape(*lead, 1))


def _rms_bwd(eps: float, res, g):
    x, weight, rstd = res
    d = x.shape[-1]
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    wf = weight.astype(jnp.float32)
    xhat = xf * rstd
    dxhat = gf * wf
    # d/dx of x*rsqrt(mean x^2 + eps): rstd * (dxhat - xhat * mean(dxhat*xhat))
    dot = jnp.sum(dxhat * xhat, axis=-1, keepdims=True) / d
    dx = (rstd * (dxhat - xhat * dot)).astype(x.dtype)
    dw = jnp.sum(
        (gf * xhat).reshape(-1, d), axis=0
    ).astype(weight.dtype)
    return dx, dw


rms_norm.defvjp(_rms_fwd, _rms_bwd)


def layer_norm(x, weight, bias, eps: float = 1e-5):
    """LayerNorm over the last dim (fp32 statistics).  XLA fuses the
    mean/var/scale chain on TPU already; kept for API parity with the
    reference's fused module."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
    y = (xf - mean) * lax.rsqrt(var + eps)
    return (y.astype(dtype) * weight.astype(dtype)) + bias.astype(dtype)


# ---------------------------------------- fused linear cross entropy


def _chunk_nll(h_c, t_c, m_c, w, dtype):
    """[C, D] rows -> (sum nll, sum mask) for one chunk; logits exist
    only inside this (rematerialized) scope."""
    logits = jnp.matmul(
        h_c, w.astype(dtype), preferred_element_type=jnp.float32
    )
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(
        logits, t_c[:, None], axis=-1
    ).squeeze(-1)
    nll = lse - picked
    return jnp.sum(nll * m_c), jnp.sum(m_c)


def fused_linear_cross_entropy(
    hidden: jnp.ndarray,
    w_vocab: jnp.ndarray,
    targets: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
    chunk_rows: int = 512,
) -> jnp.ndarray:
    """Mean next-token cross entropy of ``hidden @ w_vocab`` against
    ``targets`` without materializing the full logits tensor.

    hidden: [..., D] (bf16/fp32), w_vocab: [D, V], targets: [...] int,
    mask: optional [...] weights.  Rows are processed in
    ``chunk_rows``-sized chunks under ``jax.checkpoint`` inside a
    ``lax.scan`` — peak extra memory is one fp32 ``[chunk_rows, V]``
    block in forward AND backward (the backward recomputes each chunk's
    logits and accumulates ``dW`` chunk by chunk via the scan's
    cotangent sum).  Exact same math as dense CE (fp32 logits and
    reductions).
    """
    d = hidden.shape[-1]
    dtype = hidden.dtype
    h = hidden.reshape(-1, d)
    t = targets.reshape(-1)
    n = h.shape[0]
    m = (
        jnp.ones((n,), jnp.float32)
        if mask is None
        else mask.reshape(-1).astype(jnp.float32)
    )

    chunk = min(chunk_rows, n)
    n_pad = ((n + chunk - 1) // chunk) * chunk
    if n_pad != n:
        h = jnp.pad(h, ((0, n_pad - n), (0, 0)))
        t = jnp.pad(t, (0, n_pad - n))
        m = jnp.pad(m, (0, n_pad - n))  # padded rows carry zero weight
    n_chunks = n_pad // chunk

    body = jax.checkpoint(
        functools.partial(_chunk_nll, w=w_vocab, dtype=dtype)
    )

    def step(carry, xs):
        tot, cnt = carry
        h_c, t_c, m_c = xs
        s, c = body(h_c, t_c, m_c)
        return (tot + s, cnt + c), None

    (total, count), _ = lax.scan(
        step,
        (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
        (
            h.reshape(n_chunks, chunk, d),
            t.reshape(n_chunks, chunk),
            m.reshape(n_chunks, chunk),
        ),
    )
    return total / jnp.maximum(count, 1.0)
