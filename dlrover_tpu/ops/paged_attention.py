"""Paged (block-table) KV attention for continuous-batching decode.

Reference parity: vLLM's PagedAttention — the serving-side dual of the
flash kernels next door.  The KV cache is a pool of fixed-size blocks
(``[num_blocks, block_size, KV, head_dim]`` per layer); a sequence owns
a list of block ids (its *block table*) instead of a contiguous slab,
so admission/eviction churn never copies or fragments cache memory.

Two ops, both pure-jnp reference implementations that run on CPU CI:

- :func:`paged_decode_attention` — one query token per sequence
  (``[B, H, D]``) over each sequence's paged prefix; the decode-hot op.
- :func:`paged_prefill_attention` — a chunk of C query tokens for ONE
  sequence over its paged prefix (causal within the chunk); the
  chunked-prefill op.

Layout contract (Pallas-friendly, so a Mosaic kernel can swap in
without touching callers): ``head_dim`` is the minormost (lane) axis,
``block_size`` the sublane axis of each block — a block is a
``[block_size, KV, head_dim]`` contiguous tile, and a kernel grid over
(sequence, block-table entry) streams exactly one tile per step, the
same shape the flash kernels tile at 128-aligned boundaries.  The
gather here (``pool[tables]``) is the reference semantics of that
grid; on TPU the kernel would DMA blocks VMEM-resident instead of
materializing the gathered ``[B, T, KV, D]`` intermediate.

A third op serves the multi-token (speculative self-drafting) decode
path:

- :func:`paged_verify_attention` — K query tokens PER LANE (``[B, C,
  H, D]``) over each lane's paged prefix, causal within the window;
  the one-forward verification of a K-token draft.

Masking contract: key position ``t`` is visible iff ``t < seq_len``
(decode) / ``t <= query_pos`` (prefill/verify).  Block 0 is the NULL
block — schedulers point unallocated table entries and inactive lanes
at it; its contents are garbage by design and every read of it is
masked.

Sharing contract (prefix caching): a block is IMMUTABLE once all
``block_size`` positions are written, so several sequences' tables may
alias the same physical block id read-only — the gather is oblivious
to aliasing, and no copy-on-write is needed because writers only ever
touch a sequence's private tail blocks (``rl/kv_cache.py`` enforces
the ownership discipline).
"""

import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

NEG_INF = -1e30

#: Backend selector for the decode-hot ops (decode + verify; prefill
#: stays jnp).  ``auto`` picks the Pallas kernels whenever they can run
#: (compiled on TPU, interpret mode elsewhere); ``jnp`` is the
#: kill-switch that pins the original gather-based reference
#: byte-for-byte; ``pallas`` forces the kernels even if import fails
#: (loudly).
PAGED_KERNEL_ENV = "DLROVER_TPU_PAGED_KERNEL"

_VALID_BACKENDS = ("auto", "pallas", "jnp")


def paged_kernel_backend() -> str:
    """Resolve the active decode/verify backend: ``pallas`` or ``jnp``.

    ``auto`` picks the Pallas kernels where they compile to metal (a
    TPU host), and on other hosts only when interpret mode is
    explicitly forced (``DLROVER_TPU_PALLAS_INTERPRET=1`` — the
    run-the-real-kernel-slowly debug/CI switch); otherwise the jnp
    reference, which XLA fuses well enough on CPU that interpret mode
    would only burn CI wall-clock.  ``DLROVER_TPU_PAGED_KERNEL=pallas``
    forces the kernels anywhere (interpret off-TPU).

    Read at trace time: the scheduler's jitted decode step bakes the
    choice into its one compiled executable, so
    ``compile_counts()["decode"] == 1`` holds under either backend.
    """
    env = os.getenv(PAGED_KERNEL_ENV, "auto").strip().lower() or "auto"
    if env not in _VALID_BACKENDS:
        raise ValueError(
            f"{PAGED_KERNEL_ENV}={env!r}: expected one of {_VALID_BACKENDS}"
        )
    if env != "auto":
        return env
    on_tpu = jax.default_backend() == "tpu"
    if not on_tpu:
        from dlrover_tpu.ops.pallas_utils import INTERPRET_ENV, _TRUE

        if os.getenv(INTERPRET_ENV, "").strip().lower() not in _TRUE:
            return "jnp"
    try:
        from dlrover_tpu.ops import paged_kernels  # noqa: F401
    except Exception:  # pragma: no cover - pallas unavailable
        return "jnp"
    return "pallas"


def _gather_pool(pool: jnp.ndarray, tables: jnp.ndarray) -> jnp.ndarray:
    """``[num_blocks, bs, KV, D]`` gathered by ``[..., max_blocks]``
    tables -> ``[..., max_blocks * bs, KV, D]`` (the logical
    contiguous view of each sequence's paged cache)."""
    g = pool[tables]  # [..., MB, bs, KV, D]
    shape = g.shape[:-4] + (g.shape[-4] * g.shape[-3],) + g.shape[-2:]
    return g.reshape(shape)


def paged_decode_attention(
    q: jnp.ndarray,  # [B, H, D] one query token per sequence
    k_pool: jnp.ndarray,  # [num_blocks, block_size, KV, D]
    v_pool: jnp.ndarray,  # [num_blocks, block_size, KV, D]
    block_tables: jnp.ndarray,  # [B, max_blocks] int32 block ids
    seq_lens: jnp.ndarray,  # [B] int32: valid positions per sequence
    backend: Optional[str] = None,  # None -> DLROVER_TPU_PAGED_KERNEL
) -> jnp.ndarray:
    """Single-token GQA attention over each sequence's paged prefix.

    Returns ``[B, H, D]``.  fp32 logits/softmax accumulation (the MXU
    contract the dense kernels follow); masked lanes contribute
    exactly zero weight, so garbage in unallocated/null blocks can
    never leak into the output.  Lanes with ``seq_lens == 0`` return
    exact zeros.  Dispatches to the streamed Pallas kernel or this jnp
    reference per ``backend`` / :func:`paged_kernel_backend`.
    """
    if (backend or paged_kernel_backend()) == "pallas":
        from dlrover_tpu.ops.paged_kernels import paged_decode_kernel

        return paged_decode_kernel(q, k_pool, v_pool, block_tables, seq_lens)
    b, nh, d = q.shape
    nkv = k_pool.shape[2]
    group = nh // nkv
    k = _gather_pool(k_pool, block_tables)  # [B, T, KV, D]
    v = _gather_pool(v_pool, block_tables)
    t = k.shape[1]
    qg = q.reshape(b, nkv, group, d)
    logits = jnp.einsum(
        "bkgd,btkd->bkgt", qg, k, preferred_element_type=jnp.float32
    ) * (d**-0.5)
    valid = jnp.arange(t)[None] < seq_lens[:, None]  # [B, T]
    logits = jnp.where(valid[:, None, None], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    # Empty lanes (seq_lens == 0) have every key masked; softmax over
    # an all-NEG_INF row is uniform-over-garbage, so zero it outright.
    probs = jnp.where(seq_lens[:, None, None, None] > 0, probs, 0.0)
    out = jnp.einsum(
        "bkgt,btkd->bkgd",
        probs.astype(v.dtype),
        v,
        preferred_element_type=jnp.float32,
    ).astype(v.dtype)
    return out.reshape(b, nh, d)


def paged_prefill_attention(
    q: jnp.ndarray,  # [C, H, D] chunk of query tokens, one sequence
    k_pool: jnp.ndarray,  # [num_blocks, block_size, KV, D]
    v_pool: jnp.ndarray,  # [num_blocks, block_size, KV, D]
    block_table: jnp.ndarray,  # [max_blocks] int32: ONE sequence's table
    start_pos: jnp.ndarray,  # scalar int32: chunk's first position
) -> jnp.ndarray:
    """Chunked-prefill attention: query position ``start_pos + i``
    attends keys at positions ``<= start_pos + i`` (cached prefix +
    causal within the chunk).  The chunk's K/V must already be written
    into the pool.  Returns ``[C, H, D]``."""
    c, nh, d = q.shape
    nkv = k_pool.shape[2]
    group = nh // nkv
    k = _gather_pool(k_pool, block_table)  # [T, KV, D]
    v = _gather_pool(v_pool, block_table)
    t = k.shape[0]
    qg = q.reshape(c, nkv, group, d)
    logits = jnp.einsum(
        "ckgd,tkd->ckgt", qg, k, preferred_element_type=jnp.float32
    ) * (d**-0.5)
    q_pos = start_pos + jnp.arange(c)  # [C]
    visible = jnp.arange(t)[None] <= q_pos[:, None]  # [C, T]
    logits = jnp.where(visible[:, None, None], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum(
        "ckgt,tkd->ckgd",
        probs.astype(v.dtype),
        v,
        preferred_element_type=jnp.float32,
    ).astype(v.dtype)
    return out.reshape(c, nh, d)


def paged_verify_attention(
    q: jnp.ndarray,  # [B, C, H, D] a window of C query tokens per lane
    k_pool: jnp.ndarray,  # [num_blocks, block_size, KV, D]
    v_pool: jnp.ndarray,  # [num_blocks, block_size, KV, D]
    block_tables: jnp.ndarray,  # [B, max_blocks] int32 block ids
    positions: jnp.ndarray,  # [B] int32: lane's first window position
    backend: Optional[str] = None,  # None -> DLROVER_TPU_PAGED_KERNEL
) -> jnp.ndarray:
    """Batched-lane windowed attention: query ``i`` of lane ``b`` (at
    position ``positions[b] + i``) attends keys at positions
    ``<= positions[b] + i`` — the cached prefix plus causal within the
    window.  The window's own K/V must already sit in the pool (the
    draft loop wrote it); this op never writes.  Returns
    ``[B, C, H, D]``.  The decode-hot verify forward of speculative
    multi-token decode: one call scores a K-token draft for every
    lane.  Dispatches like :func:`paged_decode_attention`: the fused
    Pallas verify kernel shares one prefix pass across the K window
    positions; this jnp reference re-gathers the pool."""
    if (backend or paged_kernel_backend()) == "pallas":
        from dlrover_tpu.ops.paged_kernels import paged_verify_kernel

        return paged_verify_kernel(q, k_pool, v_pool, block_tables, positions)
    b, c, nh, d = q.shape
    nkv = k_pool.shape[2]
    group = nh // nkv
    k = _gather_pool(k_pool, block_tables)  # [B, T, KV, D]
    v = _gather_pool(v_pool, block_tables)
    t = k.shape[1]
    qg = q.reshape(b, c, nkv, group, d)
    logits = jnp.einsum(
        "bckgd,btkd->bckgt", qg, k,
        preferred_element_type=jnp.float32,
    ) * (d**-0.5)
    q_pos = positions[:, None] + jnp.arange(c)[None]  # [B, C]
    visible = (
        jnp.arange(t)[None, None] <= q_pos[:, :, None]
    )  # [B, C, T]
    logits = jnp.where(visible[:, :, None, None], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum(
        "bckgt,btkd->bckgd",
        probs.astype(v.dtype),
        v,
        preferred_element_type=jnp.float32,
    ).astype(v.dtype)
    return out.reshape(b, c, nh, d)


def write_block_kv(
    k_pool: jnp.ndarray,
    v_pool: jnp.ndarray,
    k_new: jnp.ndarray,  # [N, KV, D] one token's K per write
    v_new: jnp.ndarray,
    block_ids: jnp.ndarray,  # [N] int32 destination block per token
    offsets: jnp.ndarray,  # [N] int32 in-block slot per token
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Scatter N tokens' K/V into their (block, offset) cells.

    Callers route masked-out writes (inactive lanes, padded chunk
    tail) to the null block (id 0) — concurrent lanes may collide
    there, which is fine: null-block contents are never unmasked."""
    k_pool = k_pool.at[block_ids, offsets].set(k_new)
    v_pool = v_pool.at[block_ids, offsets].set(v_new)
    return k_pool, v_pool
