"""Pallas TPU flash attention (forward kernel + memory-efficient VJP).

Reference parity: the flash-attention injection layer of atorch
(``modules/transformer/layers.py:801`` ``FlashMHA``/FA2 wrappers) and
tfplus's TF flash-attention custom ops
(``tfplus/flash_attn/kernels/flash_attention_fwd_kernel.cc``).  Those
wrap Dao's CUDA kernels; on TPU the kernel itself is ours: an online-
softmax blockwise attention that never materializes the [S, S] score
matrix, tiled for the MXU (128-aligned blocks, fp32 accumulators in
VMEM scratch).

Layout contract: q, k, v are ``[B, S, H, D]`` (seq-major, the layout
the rest of the framework uses); GQA is handled by logical kv-head
broadcast.  The backward pass recomputes attention blockwise under
``jax.checkpoint`` via ``lax.scan`` — O(S) memory end to end, XLA fuses
the recompute; a hand-written bwd kernel can swap in later without API
change.

On non-TPU backends (CI's virtual CPU devices) the kernel runs in
Pallas interpret mode automatically.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _flash_fwd_kernel(
    q_ref,
    k_ref,
    v_ref,
    o_ref,
    m_scr,
    l_scr,
    acc_scr,
    *,
    sm_scale: float,
    causal: bool,
    block_q: int,
    block_k: int,
    seq_len: int,
):
    qi = pl.program_id(2)
    kj = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(kj == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # causal: a K block strictly above the diagonal is fully masked —
    # skip its matmuls entirely (~2x FLOPs saved on long sequences)
    if causal:
        visible = kj * block_k <= qi * block_q + block_q - 1
    else:
        visible = True

    @pl.when(visible)
    def _compute():
        q = q_ref[0, 0]  # [BQ, D]
        k = k_ref[0, 0]  # [BK, D]
        v = v_ref[0, 0]  # [BK, D]

        s = (
            jax.lax.dot_general(
                q,
                k,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * sm_scale
        )  # [BQ, BK]

        # bounds mask: the last K block is padded when seq_len is not a
        # multiple of block_k; padded columns MUST NOT feed the softmax
        # denominator, and padded V rows hold undefined data (possibly
        # NaN — 0 * NaN = NaN would poison the accumulator), so both
        # sides are masked.
        padded_k = seq_len % block_k != 0
        if padded_k:
            row_valid = (
                kj * block_k + lax.broadcasted_iota(jnp.int32, (block_k, 1), 0)
                < seq_len
            )
            v = jnp.where(row_valid, v, 0.0)
        if causal or padded_k:
            k_pos = kj * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            keep = jnp.ones((block_q, block_k), dtype=bool)
            if padded_k:
                keep &= k_pos < seq_len
            if causal:
                q_pos = qi * block_q + lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0
                )
                keep &= q_pos >= k_pos
            s = jnp.where(keep, s, NEG_INF)

        m_prev = m_scr[:, :1]  # [BQ, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)  # [BQ, BK]

        l_new = l_scr[:, :1] * alpha + jnp.sum(
            p, axis=-1, keepdims=True
        )
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype),
            v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(kj == nk - 1)
    def _finalize():
        denom = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0, 0] = (acc_scr[:] / denom).astype(o_ref.dtype)


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


@functools.partial(
    jax.jit, static_argnames=("causal", "sm_scale", "block_q", "block_k")
)
def _flash_fwd(
    q: jnp.ndarray,  # [B, H, S, D]
    k: jnp.ndarray,  # [B, KV, S, D]  (KV divides H: GQA)
    v: jnp.ndarray,
    causal: bool,
    sm_scale: float,
    block_q: int,
    block_k: int,
) -> jnp.ndarray:
    b, h, s, d = q.shape
    kv = k.shape[1]
    group = h // kv  # GQA: K/V blocks are shared by `group` q heads
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    grid = (b, h, pl.cdiv(s, block_q), pl.cdiv(s, block_k))

    kernel = functools.partial(
        _flash_fwd_kernel,
        sm_scale=sm_scale,
        causal=causal,
        block_q=block_q,
        block_k=block_k,
        seq_len=s,
    )
    # the kv index map folds the head group: no materialized repeat
    kv_spec = pl.BlockSpec(
        (1, 1, block_k, d),
        lambda b_, h_, i, j: (b_, h_ // group, j, 0),
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (1, 1, block_q, d), lambda b_, h_, i, j: (b_, h_, i, 0)
            ),
            kv_spec,
            kv_spec,
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block_q, d), lambda b_, h_, i, j: (b_, h_, i, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),  # running max
            pltpu.VMEM((block_q, 128), jnp.float32),  # running sum
            pltpu.VMEM((block_q, d), jnp.float32),  # output accum
        ],
        interpret=_use_interpret(),
    )(q, k, v)


def _blockwise_reference(q, k, v, causal: bool, sm_scale: float,
                         block_k: int = 512):
    """Differentiable blockwise attention (lax.scan over KV blocks with
    online softmax) — the VJP path; O(S*block) memory under remat.
    GQA handled by a grouped head dim (no KV materialization)."""
    b, h, s, d = q.shape
    kv = k.shape[1]
    g = h // kv
    qg = q.reshape(b, kv, g, s, d)
    nk = max(1, s // block_k)
    while s % nk != 0:
        nk -= 1
    bk = s // nk
    kb = jnp.moveaxis(k.reshape(b, kv, nk, bk, d), 2, 0)
    vb = jnp.moveaxis(v.reshape(b, kv, nk, bk, d), 2, 0)

    q_pos = jnp.arange(s)

    def body(carry, inputs):
        acc, m_prev, l_prev = carry
        kc, vc, j = inputs
        sblk = (
            jnp.einsum(
                "bhgqd,bhkd->bhgqk", qg, kc,
                preferred_element_type=jnp.float32,
            )
            * sm_scale
        )
        if causal:
            k_pos = j * bk + jnp.arange(bk)
            mask = q_pos[:, None] >= k_pos[None, :]
            sblk = jnp.where(mask[None, None, None], sblk, NEG_INF)
        m_cur = jnp.max(sblk, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(sblk - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.einsum(
            "bhgqk,bhkd->bhgqd", p.astype(vc.dtype), vc,
            preferred_element_type=jnp.float32,
        )
        return (acc, m_new, l_new), None

    acc0 = jnp.zeros((b, kv, g, s, d), jnp.float32)
    m0 = jnp.full((b, kv, g, s, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, kv, g, s, 1), jnp.float32)
    (acc, m, l), _ = lax.scan(
        jax.checkpoint(body), (acc0, m0, l0),
        (kb, vb, jnp.arange(nk)),
    )
    out = (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)
    return out.reshape(b, h, s, d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention_hsd(q, k, v, causal, sm_scale, block_q, block_k):
    return _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k)


def _fa_fwd(q, k, v, causal, sm_scale, block_q, block_k):
    out = _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k)
    return out, (q, k, v)


def _fa_bwd(causal, sm_scale, block_q, block_k, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _blockwise_reference(
            q_, k_, v_, causal, sm_scale
        ),
        q,
        k,
        v,
    )
    return vjp(g)


_flash_attention_hsd.defvjp(_fa_fwd, _fa_bwd)


def flash_attention(
    q: jnp.ndarray,  # [B, S, H, D]
    k: jnp.ndarray,  # [B, S, KV, D]
    v: jnp.ndarray,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
) -> jnp.ndarray:
    """Drop-in replacement for
    ``dlrover_tpu.models.llama.dot_product_attention`` (same [B,S,H,D]
    layout + GQA broadcast)."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    nh, nkv = q.shape[2], k.shape[2]
    if nh % nkv != 0:
        raise ValueError(f"heads {nh} not a multiple of kv {nkv}")
    # GQA stays logical: the kernel's kv index map folds the group
    # [B,S,H,D] -> [B,H,S,D]
    qt, kt, vt = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))
    out = _flash_attention_hsd(
        qt, kt, vt, causal, sm_scale, block_q, block_k
    )
    return jnp.swapaxes(out, 1, 2)
