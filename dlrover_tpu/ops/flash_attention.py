"""Pallas TPU flash attention (FA2: forward + backward kernels).

Reference parity: the flash-attention injection layer of atorch
(``modules/transformer/layers.py:801`` ``FlashMHA``/FA2 wrappers) and
tfplus's TF flash-attention custom ops
(``tfplus/flash_attn/kernels/flash_attention_fwd_kernel.cc:172``,
``flash_attention_bwd_kernel.cc:167``).  Those wrap Dao's CUDA kernels;
on TPU the kernels are ours: online-softmax blockwise attention that
never materializes the [S, S] score matrix, tiled for the MXU
(128-aligned blocks, fp32 accumulators in VMEM scratch).

FA2 recipe: the forward saves the per-row log-sum-exp (LSE) alongside
the output; the backward recomputes probabilities blockwise from
(q, k, lse) — ``p = exp(qk^T·scale − lse)`` — and accumulates
``dv = pᵀ·dO``, ``ds = p∘(dO·vᵀ − Δ)·scale`` (Δ = rowsum(dO∘O)),
``dk = dsᵀ·q``, ``dq = ds·k`` in two kernels: one gridded over KV
blocks (dk/dv), one over Q blocks (dq).  TPU's sequential grid makes
the accumulation race-free — no atomics, a VMEM scratch accumulates
across the innermost grid dimension.

Layout contract: q, k, v are ``[B, S, H, D]`` (seq-major, the layout
the rest of the framework uses); GQA is handled by logical kv-head
broadcast in the index maps (backward materializes per-q-head dk/dv,
then sums over the head group).

On non-TPU backends (CI's virtual CPU devices) the kernels run in
Pallas interpret mode automatically.
"""

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _flash_fwd_kernel(
    q_ref,
    k_ref,
    v_ref,
    o_ref,
    lse_ref,
    m_scr,
    l_scr,
    acc_scr,
    *,
    sm_scale: float,
    causal: bool,
    block_q: int,
    block_k: int,
    seq_len: int,
):
    qi = pl.program_id(2)
    kj = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(kj == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # causal: a K block strictly above the diagonal is fully masked —
    # skip its matmuls entirely (~2x FLOPs saved on long sequences)
    if causal:
        visible = kj * block_k <= qi * block_q + block_q - 1
    else:
        visible = True

    @pl.when(visible)
    def _compute():
        q = q_ref[0, 0]  # [BQ, D]
        k = k_ref[0, 0]  # [BK, D]
        v = v_ref[0, 0]  # [BK, D]

        s = (
            jax.lax.dot_general(
                q,
                k,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * sm_scale
        )  # [BQ, BK]

        # bounds mask: the last K block is padded when seq_len is not a
        # multiple of block_k; padded columns MUST NOT feed the softmax
        # denominator, and padded V rows hold undefined data (possibly
        # NaN — 0 * NaN = NaN would poison the accumulator), so both
        # sides are masked.
        padded_k = seq_len % block_k != 0
        if padded_k:
            row_valid = (
                kj * block_k + lax.broadcasted_iota(jnp.int32, (block_k, 1), 0)
                < seq_len
            )
            v = jnp.where(row_valid, v, 0.0)
        if causal or padded_k:
            k_pos = kj * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            keep = jnp.ones((block_q, block_k), dtype=bool)
            if padded_k:
                keep &= k_pos < seq_len
            if causal:
                q_pos = qi * block_q + lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0
                )
                keep &= q_pos >= k_pos
            s = jnp.where(keep, s, NEG_INF)

        m_prev = m_scr[:, :1]  # [BQ, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)  # [BQ, BK]

        l_new = l_scr[:, :1] * alpha + jnp.sum(
            p, axis=-1, keepdims=True
        )
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype),
            v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(kj == nk - 1)
    def _finalize():
        denom = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0, 0] = (acc_scr[:] / denom).astype(o_ref.dtype)
        # log-sum-exp residual for the FA2 backward: p = exp(s - lse);
        # [BQ, 1] — the trailing unit dim keeps Mosaic's block-shape
        # rule (last dim equal to the array dim) without the 128-lane
        # broadcast the stock kernel pays
        lse_ref[0, 0] = m_scr[:, :1] + jnp.log(denom)


def _use_interpret() -> bool:
    # Hoisted to ops/pallas_utils.py so the paged kernels share one
    # policy and one override env (DLROVER_TPU_PALLAS_INTERPRET).
    from dlrover_tpu.ops.pallas_utils import use_interpret

    return use_interpret()


@functools.partial(
    jax.jit, static_argnames=("causal", "sm_scale", "block_q", "block_k")
)
def _flash_fwd(
    q: jnp.ndarray,  # [B, H, S, D]
    k: jnp.ndarray,  # [B, KV, S, D]  (KV divides H: GQA)
    v: jnp.ndarray,
    causal: bool,
    sm_scale: float,
    block_q: int,
    block_k: int,
) -> jnp.ndarray:
    b, h, s, d = q.shape
    kv = k.shape[1]
    group = h // kv  # GQA: K/V blocks are shared by `group` q heads
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    grid = (b, h, pl.cdiv(s, block_q), pl.cdiv(s, block_k))

    kernel = functools.partial(
        _flash_fwd_kernel,
        sm_scale=sm_scale,
        causal=causal,
        block_q=block_q,
        block_k=block_k,
        seq_len=s,
    )
    # the kv index map folds the head group: no materialized repeat
    kv_spec = pl.BlockSpec(
        (1, 1, block_k, d),
        lambda b_, h_, i, j: (b_, h_ // group, j, 0),
    )
    return pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, s, 1), jnp.float32),  # lse
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (1, 1, block_q, d), lambda b_, h_, i, j: (b_, h_, i, 0)
            ),
            kv_spec,
            kv_spec,
        ],
        out_specs=(
            pl.BlockSpec(
                (1, 1, block_q, d), lambda b_, h_, i, j: (b_, h_, i, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_q, 1), lambda b_, h_, i, j: (b_, h_, i, 0)
            ),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),  # running max
            pltpu.VMEM((block_q, 128), jnp.float32),  # running sum
            pltpu.VMEM((block_q, d), jnp.float32),  # output accum
        ],
        interpret=_use_interpret(),
    )(q, k, v)


def _bwd_block_math(q, k, v, do, lse, delta, glse, keep, sm_scale):
    """Shared FA2 block algebra (fp32): returns (p, ds) for one
    [BQ, BK] tile.  ``lse``/``delta``/``glse`` are [BQ, 1]; ``keep`` is
    the combined causal/bounds mask or None.

    ``glse`` is the cotangent of the lse output (zero for the plain
    attention path): ∂lse/∂s_j = p_j, so it folds into ds as
    ``p∘(dp − Δ + glse)·scale`` — this is what makes the lse-returning
    variant (ring attention's inner kernel) differentiable."""
    s = (
        jax.lax.dot_general(
            q, k,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        * sm_scale
    )  # [BQ, BK]
    p = jnp.exp(s - lse)
    if keep is not None:
        p = jnp.where(keep, p, 0.0)
    dp = jax.lax.dot_general(
        do, v,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [BQ, BK]
    correction = dp - delta if glse is None else dp - delta + glse
    ds = p * correction * sm_scale
    if keep is not None:
        # p=0 alone is not enough: out-of-range rows load garbage
        # lse/delta (possibly NaN), and 0 * NaN = NaN
        ds = jnp.where(keep, ds, 0.0)
    return p, ds


def _bwd_masks(qi, kj, block_q, block_k, seq_len, causal):
    """The keep mask for a (qi, kj) tile, or None when nothing masks."""
    padded_q = seq_len % block_q != 0
    padded_k = seq_len % block_k != 0
    if not (causal or padded_q or padded_k):
        return None
    q_pos = qi * block_q + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    k_pos = kj * block_k + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    keep = jnp.ones((block_q, block_k), dtype=bool)
    if causal:
        keep &= q_pos >= k_pos
    if padded_q:
        # out-of-range q rows carry uninitialized lse/delta/do — a
        # stray p=inf there would poison the dk/dv accumulators
        keep &= q_pos < seq_len
    if padded_k:
        keep &= k_pos < seq_len
    return keep


def _flash_bwd_dkv_kernel(
    *refs, sm_scale, causal, block_q, block_k, seq_len, has_glse,
):
    if has_glse:
        (q_ref, do_ref, lse_ref, delta_ref, glse_ref, k_ref, v_ref,
         dk_ref, dv_ref, dk_scr, dv_scr) = refs
    else:
        (q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref,
         dk_ref, dv_ref, dk_scr, dv_scr) = refs
        glse_ref = None
    kj = pl.program_id(2)
    qi = pl.program_id(3)  # innermost: dk/dv accumulate across it
    nq = pl.num_programs(3)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    # causal: this K block sees no Q block strictly below the diagonal
    visible = (
        kj * block_k <= qi * block_q + block_q - 1 if causal else True
    )

    @pl.when(visible)
    def _compute():
        q = q_ref[0, 0]  # [BQ, D]
        do = do_ref[0, 0]  # [BQ, D]
        k = k_ref[0, 0]  # [BK, D]
        v = v_ref[0, 0]
        if seq_len % block_q != 0:
            # OOB q rows load garbage (NaN in interpret mode); the
            # p/ds masks zero their own entries, but dv = p^T·dO and
            # dk = ds^T·q contract over q rows — 0·NaN = NaN, so the
            # garbage operand rows must be zeroed too
            q_valid = (
                qi * block_q
                + lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)
                < seq_len
            )
            q = jnp.where(q_valid, q, 0)
            do = jnp.where(q_valid, do, 0)
        keep = _bwd_masks(qi, kj, block_q, block_k, seq_len, causal)
        p, ds = _bwd_block_math(
            q, k, v, do, lse_ref[0, 0], delta_ref[0, 0],
            glse_ref[0, 0] if glse_ref is not None else None,
            keep, sm_scale,
        )
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # p^T dO: [BK, D]
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # ds^T q: [BK, D]

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(
    *refs, sm_scale, causal, block_q, block_k, seq_len, has_glse,
):
    if has_glse:
        (q_ref, do_ref, lse_ref, delta_ref, glse_ref, k_ref, v_ref,
         dq_ref, dq_scr) = refs
    else:
        (q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref,
         dq_ref, dq_scr) = refs
        glse_ref = None
    qi = pl.program_id(2)
    kj = pl.program_id(3)  # innermost: dq accumulates across it
    nk = pl.num_programs(3)

    @pl.when(kj == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    visible = (
        kj * block_k <= qi * block_q + block_q - 1 if causal else True
    )

    @pl.when(visible)
    def _compute():
        q = q_ref[0, 0]
        do = do_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        if seq_len % block_k != 0:
            # dq = ds·k contracts over k rows: zero the OOB garbage
            # rows (ds already masks its own OOB columns)
            k_valid = (
                kj * block_k
                + lax.broadcasted_iota(jnp.int32, (block_k, 1), 0)
                < seq_len
            )
            k = jnp.where(k_valid, k, 0)
        keep = _bwd_masks(qi, kj, block_q, block_k, seq_len, causal)
        _, ds = _bwd_block_math(
            q, k, v, do, lse_ref[0, 0], delta_ref[0, 0],
            glse_ref[0, 0] if glse_ref is not None else None,
            keep, sm_scale,
        )
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # ds k: [BQ, D]

    @pl.when(kj == nk - 1)
    def _finalize():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("causal", "sm_scale", "block_q", "block_k")
)
def _flash_bwd(
    q, k, v, out, lse, g, g_lse, causal, sm_scale, block_q, block_k
):
    """FA2 backward: dq via one kernel (grid q-major), dk/dv via another
    (grid k-major); GQA dk/dv materialize per q-head then sum over the
    head group.  ``g_lse`` [B,H,S,1] is the lse-output cotangent (zeros
    for the plain path)."""
    b, h, s, d = q.shape
    kv = k.shape[1]
    group = h // kv
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    nq = pl.cdiv(s, block_q)
    nk = pl.cdiv(s, block_k)

    delta = jnp.sum(
        g.astype(jnp.float32) * out.astype(jnp.float32),
        axis=-1,
        keepdims=True,
    )  # [B, H, S, 1]
    has_glse = g_lse is not None
    glse_in = (
        (g_lse.astype(jnp.float32),) if has_glse else ()
    )

    qd_spec = lambda qpos: pl.BlockSpec(  # noqa: E731
        (1, 1, block_q, d),
        (lambda b_, h_, i, j: (b_, h_, i, 0))
        if qpos == "outer"
        else (lambda b_, h_, i, j: (b_, h_, j, 0)),
    )
    row_spec = lambda qpos: pl.BlockSpec(  # noqa: E731
        (1, 1, block_q, 1),
        (lambda b_, h_, i, j: (b_, h_, i, 0))
        if qpos == "outer"
        else (lambda b_, h_, i, j: (b_, h_, j, 0)),
    )
    kv_spec_for = lambda kpos: pl.BlockSpec(  # noqa: E731
        (1, 1, block_k, d),
        (lambda b_, h_, i, j: (b_, h_ // group, i, 0))
        if kpos == "outer"
        else (lambda b_, h_, i, j: (b_, h_ // group, j, 0)),
    )

    common = dict(
        sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, seq_len=s,
        has_glse=has_glse,
    )

    def _in_specs(qpos, kpos):
        """q/do/lse/delta [+glse] then k/v; glse only when present so
        the plain backward pays no extra buffer or VMEM load."""
        specs = [
            qd_spec(qpos),  # q
            qd_spec(qpos),  # do
            row_spec(qpos),  # lse
            row_spec(qpos),  # delta
        ]
        if has_glse:
            specs.append(row_spec(qpos))  # glse
        specs += [kv_spec_for(kpos), kv_spec_for(kpos)]  # k, v
        return specs

    # dk/dv: grid (b, h, kj, qi) — qi innermost accumulates in scratch
    dk_h, dv_h = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, **common),
        out_shape=(
            jax.ShapeDtypeStruct((b, h, s, d), jnp.float32),
            jax.ShapeDtypeStruct((b, h, s, d), jnp.float32),
        ),
        grid=(b, h, nk, nq),
        in_specs=_in_specs("inner", "outer"),
        out_specs=(
            pl.BlockSpec(
                (1, 1, block_k, d), lambda b_, h_, i, j: (b_, h_, i, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_k, d), lambda b_, h_, i, j: (b_, h_, i, 0)
            ),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=_use_interpret(),
    )(q, g, lse, delta, *glse_in, k, v)

    # GQA: fold per-q-head dk/dv back onto the kv heads
    if group > 1:
        dk = dk_h.reshape(b, kv, group, s, d).sum(axis=2)
        dv = dv_h.reshape(b, kv, group, s, d).sum(axis=2)
    else:
        dk, dv = dk_h, dv_h

    # dq: grid (b, h, qi, kj) — kj innermost accumulates in scratch
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, **common),
        out_shape=jax.ShapeDtypeStruct((b, h, s, d), jnp.float32),
        grid=(b, h, nq, nk),
        in_specs=_in_specs("outer", "inner"),
        out_specs=pl.BlockSpec(
            (1, 1, block_q, d), lambda b_, h_, i, j: (b_, h_, i, 0)
        ),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=_use_interpret(),
    )(q, g, lse, delta, *glse_in, k, v)

    return (
        dq.astype(q.dtype),
        dk.astype(k.dtype),
        dv.astype(v.dtype),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention_hsd(q, k, v, causal, sm_scale, block_q, block_k):
    out, _ = _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k)
    return out


def _fa_fwd(q, k, v, causal, sm_scale, block_q, block_k):
    out, lse = _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k)
    return out, (q, k, v, out, lse)


def _fa_bwd(causal, sm_scale, block_q, block_k, res, g):
    q, k, v, out, lse = res
    return _flash_bwd(
        q, k, v, out, lse, g, None, causal, sm_scale, block_q, block_k
    )


_flash_attention_hsd.defvjp(_fa_fwd, _fa_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention_lse_hsd(q, k, v, causal, sm_scale, block_q, block_k):
    return _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k)


def _fa_lse_fwd(q, k, v, causal, sm_scale, block_q, block_k):
    out, lse = _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k)
    return (out, lse), (q, k, v, out, lse)


def _fa_lse_bwd(causal, sm_scale, block_q, block_k, res, cts):
    q, k, v, out, lse = res
    g, g_lse = cts
    return _flash_bwd(
        q, k, v, out, lse, g, g_lse, causal, sm_scale, block_q, block_k
    )


_flash_attention_lse_hsd.defvjp(_fa_lse_fwd, _fa_lse_bwd)


def flash_attention_lse(
    q: jnp.ndarray,  # [B, S, H, D]
    k: jnp.ndarray,  # [B, S, KV, D]
    v: jnp.ndarray,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
):
    """Like :func:`flash_attention` but also returns the per-row
    log-sum-exp ``[B, S, H]`` — the residual that lets callers merge
    partial attention over KV blocks exactly (ring attention's inner
    kernel).  Differentiable in both outputs (the lse cotangent folds
    into ds inside the backward kernels)."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if block_q is None:
        block_q = _default_blocks(q.shape[1])[0]
    if block_k is None:
        block_k = _default_blocks(q.shape[1])[1]
    nh, nkv = q.shape[2], k.shape[2]
    if nh % nkv != 0:
        raise ValueError(f"heads {nh} not a multiple of kv {nkv}")
    qt, kt, vt = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))
    out, lse = _flash_attention_lse_hsd(
        qt, kt, vt, causal, sm_scale, block_q, block_k
    )
    # [B,H,S,D] -> [B,S,H,D]; lse [B,H,S,1] -> [B,S,H]
    return (
        jnp.swapaxes(out, 1, 2),
        jnp.swapaxes(lse[..., 0], 1, 2),
    )


def _default_blocks(seq_len: int) -> Tuple[int, int]:
    """(block_q, block_k), measured on v5e ([.,.,8,128] bf16):
    end-to-end on the llama-0.6b train step at seq 2048, asymmetric
    1024x512 beats 512x512 (0.5219 vs 0.5185 MFU) — a taller q tile
    halves the grid's q loop while the 512 k tile keeps the working
    set in VMEM; 512x256 loses badly (0.465).  Longer sequences keep
    the larger tiles to amortize grid overhead over the longer KV
    loop."""
    if seq_len >= 8192:
        return 1024, 1024
    return (1024, 512) if seq_len >= 2048 else (512, 512)


def flash_attention(
    q: jnp.ndarray,  # [B, S, H, D]
    k: jnp.ndarray,  # [B, S, KV, D]
    v: jnp.ndarray,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
) -> jnp.ndarray:
    """Drop-in replacement for
    ``dlrover_tpu.models.llama.dot_product_attention`` (same [B,S,H,D]
    layout + GQA broadcast).

    Default blocks are sequence-adaptive (512 short / 1024 long, see
    ``_default_blocks``); at [8,2048,8,128] bf16 the tuned kernel runs
    fwd+bwd 7.6x faster than naive 128x128 blocking and 4.4x faster
    than the dense XLA path, and stays functional to 32k tokens on one
    chip where dense attention cannot materialize the score matrix."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if block_q is None:
        block_q = _default_blocks(q.shape[1])[0]
    if block_k is None:
        block_k = _default_blocks(q.shape[1])[1]
    nh, nkv = q.shape[2], k.shape[2]
    if nh % nkv != 0:
        raise ValueError(f"heads {nh} not a multiple of kv {nkv}")
    # GQA stays logical: the kernel's kv index map folds the group
    # [B,S,H,D] -> [B,H,S,D]
    qt, kt, vt = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))
    out = _flash_attention_hsd(
        qt, kt, vt, causal, sm_scale, block_q, block_k
    )
    return jnp.swapaxes(out, 1, 2)
