"""Pallas blockwise int8 quantize/dequantize — low-bit optimizer states.

Reference parity: atorch's CUDA quantization kernels
(``atorch/atorch/ops/csrc/quantization/quantize.cu:150``,
``dequantize.cu:67``, ``quantization_optimizer.cu:686``) which store
Adam moments in 1-byte formats.  The TPU form is a Pallas kernel pair:
per-block absmax scaling to int8 (symmetric, matching the reference's
signed dynamic quantization), tiled (block, 128)-aligned for the VPU.

Used by ``dlrover_tpu.optimizers.low_bit`` to keep optimizer state in
1 byte/param (4x HBM saving vs fp32 moments).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# quantization block: one scale per BLOCK elements
BLOCK = 1024
_LANES = 128
_SUBLANES = BLOCK // _LANES
# Mosaic requires the scales output's second-minor block dim to be a
# multiple of 8 (or the whole array): handle 8 quant blocks per kernel
# invocation so the scales block is a legal (8, 1)
_GROUP = 8


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _quant_kernel(x_ref, q_ref, scale_ref, *, group: int):
    # x: [group * _SUBLANES, 128]; static unrolled loop per quant
    # block — no in-kernel reshapes, one scalar scale store per block
    for g in range(group):
        lo, hi = g * _SUBLANES, (g + 1) * _SUBLANES
        x = x_ref[lo:hi].astype(jnp.float32)
        absmax = jnp.max(jnp.abs(x))
        scale = jnp.maximum(absmax / 127.0, 1e-12)
        q_ref[lo:hi] = jnp.clip(
            jnp.round(x / scale), -127, 127
        ).astype(jnp.int8)
        scale_ref[g, 0] = scale


def _dequant_kernel(q_ref, scale_ref, x_ref, *, group: int):
    for g in range(group):
        lo, hi = g * _SUBLANES, (g + 1) * _SUBLANES
        x_ref[lo:hi] = (
            q_ref[lo:hi].astype(jnp.float32) * scale_ref[g, 0]
        )


def _group_for(n_blocks: int) -> int:
    """Scales block legality: second-minor block dim must be a
    multiple of 8 OR the whole array dim — small tensors use one
    whole-array invocation instead of paying 8-block padding."""
    return n_blocks if n_blocks < _GROUP else _GROUP


@jax.jit
def _quantize_2d(x):
    n_blocks = x.shape[0] // _SUBLANES
    group = _group_for(n_blocks)
    q, scales = pl.pallas_call(
        functools.partial(_quant_kernel, group=group),
        out_shape=(
            jax.ShapeDtypeStruct(x.shape, jnp.int8),
            jax.ShapeDtypeStruct((n_blocks, 1), jnp.float32),
        ),
        grid=(n_blocks // group,),
        in_specs=[
            pl.BlockSpec(
                (group * _SUBLANES, _LANES), lambda i: (i, 0)
            ),
        ],
        out_specs=(
            pl.BlockSpec(
                (group * _SUBLANES, _LANES), lambda i: (i, 0)
            ),
            pl.BlockSpec(
                (group, 1), lambda i: (i, 0),
                memory_space=pltpu.SMEM,
            ),
        ),
        interpret=_use_interpret(),
    )(x)
    return q, scales


@jax.jit
def _dequantize_2d(q, scales):
    n_blocks = q.shape[0] // _SUBLANES
    group = _group_for(n_blocks)
    return pl.pallas_call(
        functools.partial(_dequant_kernel, group=group),
        out_shape=jax.ShapeDtypeStruct(q.shape, jnp.float32),
        grid=(n_blocks // group,),
        in_specs=[
            pl.BlockSpec(
                (group * _SUBLANES, _LANES), lambda i: (i, 0)
            ),
            pl.BlockSpec(
                (group, 1), lambda i: (i, 0),
                memory_space=pltpu.SMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (group * _SUBLANES, _LANES), lambda i: (i, 0)
        ),
        interpret=_use_interpret(),
    )(q, scales)


def _fused_adam_kernel(
    g_ref, mu_q_ref, mu_s_ref, nu_q_ref, nu_s_ref, bc1_ref, bc2_ref,
    upd_ref, mu_q_out, mu_s_out, nu_q_out, nu_s_out,
    *, group: int, lr: float, b1: float, b2: float, eps: float,
):
    """One pass over a moment block: dequant -> Adam moment update ->
    update value -> requant.  Replaces 4 pallas_calls + XLA glue per
    leaf (reference fuses exactly this on CUDA:
    ``quantization_optimizer.cu:686``); int8 payloads are read and
    written ONCE and the f32 moments never touch HBM."""
    bc1 = bc1_ref[0, 0]
    bc2 = bc2_ref[0, 0]
    for i in range(group):
        lo, hi = i * _SUBLANES, (i + 1) * _SUBLANES
        g = g_ref[lo:hi].astype(jnp.float32)
        mu = mu_q_ref[lo:hi].astype(jnp.float32) * mu_s_ref[i, 0]
        # nu is stored as sqrt(nu) — see optimizers/low_bit.py for the
        # dynamic-range rationale
        nu_root = nu_q_ref[lo:hi].astype(jnp.float32) * nu_s_ref[i, 0]
        mu = b1 * mu + (1.0 - b1) * g
        nu = b2 * nu_root * nu_root + (1.0 - b2) * g * g
        upd_ref[lo:hi] = -lr * (mu / bc1) / (
            jnp.sqrt(nu / bc2) + eps
        )
        s_mu = jnp.maximum(jnp.max(jnp.abs(mu)) / 127.0, 1e-12)
        mu_q_out[lo:hi] = jnp.clip(
            jnp.round(mu / s_mu), -127, 127
        ).astype(jnp.int8)
        mu_s_out[i, 0] = s_mu
        nu_root_new = jnp.sqrt(nu)
        s_nu = jnp.maximum(
            jnp.max(jnp.abs(nu_root_new)) / 127.0, 1e-12
        )
        nu_q_out[lo:hi] = jnp.clip(
            jnp.round(nu_root_new / s_nu), -127, 127
        ).astype(jnp.int8)
        nu_s_out[i, 0] = s_nu


@functools.partial(
    jax.jit, static_argnames=("lr", "b1", "b2", "eps")
)
def _fused_adam_2d(g2, mu_q, mu_s, nu_q, nu_s, bc1, bc2,
                   *, lr, b1, b2, eps):
    n_blocks = g2.shape[0] // _SUBLANES
    group = _group_for(n_blocks)
    smem_scalar = pl.BlockSpec(
        (1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM
    )
    data_spec = pl.BlockSpec(
        (group * _SUBLANES, _LANES), lambda i: (i, 0)
    )
    scale_spec = pl.BlockSpec(
        (group, 1), lambda i: (i, 0), memory_space=pltpu.SMEM
    )
    return pl.pallas_call(
        functools.partial(
            _fused_adam_kernel,
            group=group, lr=lr, b1=b1, b2=b2, eps=eps,
        ),
        out_shape=(
            jax.ShapeDtypeStruct(g2.shape, jnp.float32),
            jax.ShapeDtypeStruct(g2.shape, jnp.int8),
            jax.ShapeDtypeStruct((n_blocks, 1), jnp.float32),
            jax.ShapeDtypeStruct(g2.shape, jnp.int8),
            jax.ShapeDtypeStruct((n_blocks, 1), jnp.float32),
        ),
        grid=(n_blocks // group,),
        in_specs=[
            data_spec,  # grads
            data_spec,  # mu int8
            scale_spec,  # mu scales
            data_spec,  # nu int8
            scale_spec,  # nu scales
            smem_scalar,  # bias correction 1
            smem_scalar,  # bias correction 2
        ],
        out_specs=(
            data_spec,   # update
            data_spec,   # new mu int8
            scale_spec,  # new mu scales
            data_spec,   # new nu int8
            scale_spec,  # new nu scales
        ),
        interpret=_use_interpret(),
    )(g2, mu_q, mu_s, nu_q, nu_s, bc1, bc2)


def fused_int8_adam_update(
    grad, mu_q, mu_scales, nu_q, nu_scales, meta,
    bc1, bc2, *, lr, b1, b2, eps,
):
    """Fused Adam step over int8 moments.

    ``meta`` is the ``(orig_shape, n)`` pair from
    :func:`quantize_blockwise`; ``bc1``/``bc2`` are the (traced)
    bias-correction scalars.  Returns ``(update, new_mu_q,
    new_mu_scales, new_nu_q, new_nu_scales)`` with the update shaped
    like ``grad``.  Pad-region lanes compute garbage updates that the
    final slice discards; their moment blocks quantize the padded
    zeros, exactly like the unfused path."""
    shape, n = meta
    if n == 0:
        return (
            jnp.zeros(shape, jnp.float32),
            mu_q, mu_scales, nu_q, nu_scales,
        )
    flat = grad.reshape(-1).astype(jnp.float32)
    flat, _ = _pad_to_blocks(flat)
    g2 = flat.reshape(-1, _LANES)
    bc1 = jnp.asarray(bc1, jnp.float32).reshape(1, 1)
    bc2 = jnp.asarray(bc2, jnp.float32).reshape(1, 1)
    upd2, mu_q2, mu_s2, nu_q2, nu_s2 = _fused_adam_2d(
        g2, mu_q, mu_scales, nu_q, nu_scales, bc1, bc2,
        lr=lr, b1=b1, b2=b2, eps=eps,
    )
    upd = upd2.reshape(-1)[:n].reshape(shape)
    return upd, mu_q2, mu_s2, nu_q2, nu_s2


def _pad_to_blocks(flat):
    n = flat.shape[0]
    padded = ((n + BLOCK - 1) // BLOCK) * BLOCK
    n_blocks = padded // BLOCK
    if n_blocks > _GROUP and n_blocks % _GROUP:
        # large tensors round their BLOCK count to a full kernel group
        n_blocks += _GROUP - (n_blocks % _GROUP)
        padded = n_blocks * BLOCK
    if padded != n:
        flat = jnp.pad(flat, (0, padded - n))
    return flat, n


def quantize_blockwise(x: jnp.ndarray):
    """Any-shape fp array -> (int8 payload [P/128,128], scales, meta)."""
    flat = x.reshape(-1).astype(jnp.float32)
    if flat.shape[0] == 0:  # zero-size leaf: nothing to quantize
        return (
            jnp.zeros((0, _LANES), jnp.int8),
            jnp.zeros((0, 1), jnp.float32),
            (x.shape, 0),
        )
    flat, n = _pad_to_blocks(flat)
    x2 = flat.reshape(-1, _LANES)
    q, scales = _quantize_2d(x2)
    return q, scales, (x.shape, n)


def dequantize_blockwise(q, scales, meta, dtype=jnp.float32):
    shape, n = meta
    if n == 0:
        return jnp.zeros(shape, dtype)
    out = _dequantize_2d(q, scales).reshape(-1)[:n]
    return out.reshape(shape).astype(dtype)
