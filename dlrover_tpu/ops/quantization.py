"""Pallas blockwise int8 quantize/dequantize — low-bit optimizer states.

Reference parity: atorch's CUDA quantization kernels
(``atorch/atorch/ops/csrc/quantization/quantize.cu:150``,
``dequantize.cu:67``, ``quantization_optimizer.cu:686``) which store
Adam moments in 1-byte formats.  The TPU form is a Pallas kernel pair:
per-block absmax scaling to int8 (symmetric, matching the reference's
signed dynamic quantization), tiled (block, 128)-aligned for the VPU.

Used by ``dlrover_tpu.optimizers.low_bit`` to keep optimizer state in
1 byte/param (4x HBM saving vs fp32 moments).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# quantization block: one scale per BLOCK elements
BLOCK = 1024
_LANES = 128
_SUBLANES = BLOCK // _LANES
# Mosaic requires the scales output's second-minor block dim to be a
# multiple of 8 (or the whole array): handle 8 quant blocks per kernel
# invocation so the scales block is a legal (8, 1)
_GROUP = 8


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _quant_kernel(x_ref, q_ref, scale_ref, *, group: int):
    # x: [group * _SUBLANES, 128]; static unrolled loop per quant
    # block — no in-kernel reshapes, one scalar scale store per block
    for g in range(group):
        lo, hi = g * _SUBLANES, (g + 1) * _SUBLANES
        x = x_ref[lo:hi].astype(jnp.float32)
        absmax = jnp.max(jnp.abs(x))
        scale = jnp.maximum(absmax / 127.0, 1e-12)
        q_ref[lo:hi] = jnp.clip(
            jnp.round(x / scale), -127, 127
        ).astype(jnp.int8)
        scale_ref[g, 0] = scale


def _dequant_kernel(q_ref, scale_ref, x_ref, *, group: int):
    for g in range(group):
        lo, hi = g * _SUBLANES, (g + 1) * _SUBLANES
        x_ref[lo:hi] = (
            q_ref[lo:hi].astype(jnp.float32) * scale_ref[g, 0]
        )


def _group_for(n_blocks: int) -> int:
    """Scales block legality: second-minor block dim must be a
    multiple of 8 OR the whole array dim — small tensors use one
    whole-array invocation instead of paying 8-block padding."""
    return n_blocks if n_blocks < _GROUP else _GROUP


@jax.jit
def _quantize_2d(x):
    n_blocks = x.shape[0] // _SUBLANES
    group = _group_for(n_blocks)
    q, scales = pl.pallas_call(
        functools.partial(_quant_kernel, group=group),
        out_shape=(
            jax.ShapeDtypeStruct(x.shape, jnp.int8),
            jax.ShapeDtypeStruct((n_blocks, 1), jnp.float32),
        ),
        grid=(n_blocks // group,),
        in_specs=[
            pl.BlockSpec(
                (group * _SUBLANES, _LANES), lambda i: (i, 0)
            ),
        ],
        out_specs=(
            pl.BlockSpec(
                (group * _SUBLANES, _LANES), lambda i: (i, 0)
            ),
            pl.BlockSpec(
                (group, 1), lambda i: (i, 0),
                memory_space=pltpu.SMEM,
            ),
        ),
        interpret=_use_interpret(),
    )(x)
    return q, scales


@jax.jit
def _dequantize_2d(q, scales):
    n_blocks = q.shape[0] // _SUBLANES
    group = _group_for(n_blocks)
    return pl.pallas_call(
        functools.partial(_dequant_kernel, group=group),
        out_shape=jax.ShapeDtypeStruct(q.shape, jnp.float32),
        grid=(n_blocks // group,),
        in_specs=[
            pl.BlockSpec(
                (group * _SUBLANES, _LANES), lambda i: (i, 0)
            ),
            pl.BlockSpec(
                (group, 1), lambda i: (i, 0),
                memory_space=pltpu.SMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (group * _SUBLANES, _LANES), lambda i: (i, 0)
        ),
        interpret=_use_interpret(),
    )(q, scales)


def _pad_to_blocks(flat):
    n = flat.shape[0]
    padded = ((n + BLOCK - 1) // BLOCK) * BLOCK
    n_blocks = padded // BLOCK
    if n_blocks > _GROUP and n_blocks % _GROUP:
        # large tensors round their BLOCK count to a full kernel group
        n_blocks += _GROUP - (n_blocks % _GROUP)
        padded = n_blocks * BLOCK
    if padded != n:
        flat = jnp.pad(flat, (0, padded - n))
    return flat, n


def quantize_blockwise(x: jnp.ndarray):
    """Any-shape fp array -> (int8 payload [P/128,128], scales, meta)."""
    flat = x.reshape(-1).astype(jnp.float32)
    if flat.shape[0] == 0:  # zero-size leaf: nothing to quantize
        return (
            jnp.zeros((0, _LANES), jnp.int8),
            jnp.zeros((0, 1), jnp.float32),
            (x.shape, 0),
        )
    flat, n = _pad_to_blocks(flat)
    x2 = flat.reshape(-1, _LANES)
    q, scales = _quantize_2d(x2)
    return q, scales, (x.shape, n)


def dequantize_blockwise(q, scales, meta, dtype=jnp.float32):
    shape, n = meta
    if n == 0:
        return jnp.zeros(shape, dtype)
    out = _dequantize_2d(q, scales).reshape(-1)[:n]
    return out.reshape(shape).astype(dtype)
