"""Shared plumbing for the repo's Pallas/Mosaic kernel families.

Both kernel families (``ops/flash_attention.py`` dense flash and
``ops/paged_kernels.py`` paged decode/verify) compile to Mosaic on TPU
and fall back to Pallas *interpret mode* everywhere else, so CPU CI
exercises the exact same kernel bodies the TPU runs — just slowly.
That policy used to live as a private ``_use_interpret`` helper inside
``flash_attention.py``; it is hoisted here so every kernel family
answers the question the same way and honors the same override.

Env contract (one env for all kernels):

- ``DLROVER_TPU_PALLAS_INTERPRET=1|true|on``  -> force interpret mode,
  even on a TPU host (useful for printf-debugging a kernel body).
- ``DLROVER_TPU_PALLAS_INTERPRET=0|false|off`` -> force compiled mode;
  on a non-TPU host Mosaic will refuse to lower and the call fails
  loudly — this is a "prove I am on metal" switch, not a fast path.
- unset -> interpret exactly when the default JAX backend is not TPU
  (the original ``flash_attention._use_interpret`` behavior, preserved
  byte-for-byte).
"""

from __future__ import annotations

import os

import jax

INTERPRET_ENV = "DLROVER_TPU_PALLAS_INTERPRET"

_TRUE = ("1", "true", "on", "yes")
_FALSE = ("0", "false", "off", "no")


def use_interpret() -> bool:
    """Should Pallas kernels run in interpret mode on this host?

    Read at trace time (the value is baked into each compiled
    executable), so flipping the env between jits takes effect on the
    next trace, not retroactively.
    """
    raw = os.getenv(INTERPRET_ENV, "").strip().lower()
    if raw in _TRUE:
        return True
    if raw in _FALSE:
        return False
    return jax.default_backend() != "tpu"
