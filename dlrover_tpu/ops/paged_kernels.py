"""Pallas/Mosaic kernels for paged attention (decode + K-step verify).

The jnp reference path in ``ops/paged_attention.py`` services one
decode token by *gathering* the sequence's entire paged prefix into a
dense ``[B, max_blocks*block_size, KV, D]`` tensor — O(context) HBM
traffic for O(1) new work.  The kernels here stream the K/V pool
block-by-block through the Pallas grid instead:

- the block table and sequence lengths ride in as **scalar-prefetch**
  operands (``pltpu.PrefetchScalarGridSpec``), so the BlockSpec index
  maps dereference ``tables[b, j]`` *before* each grid step and the
  pipeline fetches exactly one pool page per step — the gather never
  materializes;
- softmax runs **online** per lane (running ``(m, l, acc)`` in VMEM
  scratch, the flash-attention recipe from ``ops/flash_attention.py``)
  with fp32 logits and accumulation;
- lanes past ``seq_lens`` and null-block-0 reads contribute exactly
  zero weight: out-of-window columns are masked to ``NEG_INF`` *and*
  their probability rows are zeroed explicitly, so a fully-masked lane
  (``seq_lens == 0``) returns exact zeros rather than uniform weights
  over garbage;
- the per-lane **early exit** is in the index map: page indices are
  clamped to the lane's last valid block, so consecutive grid steps
  past a short sequence re-request the same page and the pipeline
  elides the copy — short lanes in a mixed batch don't pay the longest
  lane's traffic — while ``pl.when`` skips their FLOPs.

Layout contract (established in PR 13, unchanged): pools are
``[num_blocks, block_size, KV, head_dim]`` with ``head_dim`` minormost
and ``block_size`` on the sublane axis; block 0 is the null block and
is garbage by design.  One grid step fetches one whole page —
``[block_size, KV, head_dim]`` — and a static Python loop over the KV
heads runs each head's GQA row-block against its slice, so a single
page fetch serves every head.

Tunables per kernel (see ``ops/autotune.py``): ``q_rows`` (padded
query rows per KV head, a legal Mosaic sublane tile) and ``kv_span``
(pool pages streamed per grid step; the pool is passed ``kv_span``
times with staggered index maps, which is how a Pallas kernel widens
its KV block without regathering).

CPU CI runs these kernels in interpret mode
(``ops/pallas_utils.use_interpret``); on TPU the same bodies lower to
Mosaic unchanged.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dlrover_tpu.ops.pallas_utils import use_interpret

NEG_INF = -1e30


def sublane_tile(dtype) -> int:
    """Minimum legal Mosaic sublane tile for ``dtype`` (lane is 128)."""
    itemsize = np.dtype(dtype).itemsize
    if itemsize >= 4:
        return 8
    if itemsize == 2:
        return 16
    return 32


def _round_up(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


def _iota_rows(n: int) -> jnp.ndarray:
    return lax.broadcasted_iota(jnp.int32, (n, 1), 0)


def _iota_cols(n: int) -> jnp.ndarray:
    return lax.broadcasted_iota(jnp.int32, (1, n), 1)


def _online_update(m_scr, l_scr, acc_scr, rows, s_log, v, keep):
    """One online-softmax step for scratch rows ``rows`` (static slice).

    ``s_log`` is fp32 ``[R, bs]`` raw logits, ``keep`` a bool mask of
    the same shape, ``v`` fp32 ``[bs, D]`` with garbage rows already
    zeroed.  Probabilities are re-zeroed after the exp so a row with no
    visible keys accumulates ``l == 0`` (→ exact-zero output at
    finalize) instead of the uniform-over-garbage a plain softmax
    produces.
    """
    s_log = jnp.where(keep, s_log, NEG_INF)
    m_prev = m_scr[rows, :1]
    m_new = jnp.maximum(m_prev, jnp.max(s_log, axis=-1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.where(keep, jnp.exp(s_log - m_new), 0.0)
    l_new = l_scr[rows, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc_scr[rows] = acc_scr[rows] * alpha + lax.dot_general(
        p,
        v,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_scr[rows] = jnp.broadcast_to(m_new, m_scr[rows].shape)
    l_scr[rows] = jnp.broadcast_to(l_new, l_scr[rows].shape)


def _init_state(m_scr, l_scr, acc_scr):
    m_scr[...] = jnp.full(m_scr.shape, NEG_INF, dtype=m_scr.dtype)
    l_scr[...] = jnp.zeros(l_scr.shape, dtype=l_scr.dtype)
    acc_scr[...] = jnp.zeros(acc_scr.shape, dtype=acc_scr.dtype)


def _finalize(o_ref, m_scr, l_scr, acc_scr):
    denom = jnp.maximum(l_scr[:, :1], 1e-30)
    o_ref[0] = (acc_scr[...] / denom).astype(o_ref.dtype)


# ---------------------------------------------------------------------------
# decode: one query token per lane
# ---------------------------------------------------------------------------


def _decode_kernel(
    tables_ref,  # scalar prefetch [B, MB] — unused in body (index maps only)
    lens_ref,  # scalar prefetch [B]
    q_ref,  # [1, KV*GP, D]
    *rest,
    span: int,
    block_size: int,
    n_kv: int,
    gp: int,
    scale: float,
):
    k_refs = rest[:span]
    v_refs = rest[span : 2 * span]
    o_ref = rest[2 * span]
    m_scr, l_scr, acc_scr = rest[2 * span + 1 :]
    del tables_ref

    b = pl.program_id(0)
    j = pl.program_id(1)
    nj = pl.num_programs(1)
    seq_len = lens_ref[b]

    @pl.when(j == 0)
    def _init():
        _init_state(m_scr, l_scr, acc_scr)

    # Early exit: lanes whose prefix ended before this span of pages do
    # no work (their pages were index-clamped, so no fresh copy either).
    @pl.when(j * span * block_size < seq_len)
    def _compute():
        for s in range(span):
            start = (j * span + s) * block_size
            k_page = k_refs[s][0].astype(jnp.float32)  # [bs, KV, D]
            v_page = v_refs[s][0].astype(jnp.float32)
            col = start + _iota_cols(block_size)  # [1, bs]
            keep = col < seq_len  # [1, bs]
            # Zero garbage V rows: 0 * NaN would poison the accumulator.
            v_page = jnp.where(keep.T[:, :, None], v_page, 0.0)
            for h in range(n_kv):
                rows = slice(h * gp, (h + 1) * gp)
                s_log = (
                    lax.dot_general(
                        q_ref[0, rows].astype(jnp.float32),
                        k_page[:, h, :],
                        (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )
                    * scale
                )  # [GP, bs]
                _online_update(
                    m_scr,
                    l_scr,
                    acc_scr,
                    rows,
                    s_log,
                    v_page[:, h, :],
                    jnp.broadcast_to(keep, s_log.shape),
                )

    @pl.when(j == nj - 1)
    def _done():
        _finalize(o_ref, m_scr, l_scr, acc_scr)


def paged_decode_kernel(
    q: jnp.ndarray,  # [B, H, D]
    k_pool: jnp.ndarray,  # [N, bs, KV, D]
    v_pool: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, MB] int32
    seq_lens: jnp.ndarray,  # [B] int32
    *,
    config: Optional[Dict[str, Any]] = None,
) -> jnp.ndarray:
    """Streamed paged GQA decode attention. Drop-in for the jnp path."""
    from dlrover_tpu.ops import autotune

    batch, n_heads, head_dim = q.shape
    _, block_size, n_kv, _ = k_pool.shape
    group = n_heads // n_kv
    max_blocks = block_tables.shape[1]
    if config is None:
        config = autotune.get_config(
            "decode",
            group=group,
            head_dim=head_dim,
            block_size=block_size,
            max_blocks=max_blocks,
            dtype=q.dtype,
        )
    span = max(1, min(int(config.get("kv_span", 1)), max_blocks))
    gp = max(int(config.get("q_rows", group)), group)
    nj = -(-max_blocks // span)

    qg = q.reshape(batch, n_kv, group, head_dim)
    if gp > group:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, gp - group), (0, 0)))
    qg = qg.reshape(batch, n_kv * gp, head_dim)

    def _q_index(b, j, tables, lens):
        del j, tables, lens
        return (b, 0, 0)

    def _page_index(b, j, tables, lens, s=0):
        # Clamp to the lane's last valid block: grid steps past a short
        # sequence re-request the same page, and the pipeline elides
        # the copy (the per-lane early exit for traffic).
        last = jnp.maximum(lax.div(lens[b] + block_size - 1, block_size) - 1, 0)
        idx = jnp.minimum(j * span + s, jnp.minimum(last, max_blocks - 1))
        return (tables[b, idx], 0, 0, 0)

    kv_specs = [
        pl.BlockSpec(
            (1, block_size, n_kv, head_dim),
            functools.partial(_page_index, s=s),
        )
        for s in range(span)
    ]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(batch, nj),
        in_specs=[pl.BlockSpec((1, n_kv * gp, head_dim), _q_index)]
        + kv_specs
        + kv_specs,
        out_specs=pl.BlockSpec((1, n_kv * gp, head_dim), _q_index),
        scratch_shapes=[
            pltpu.VMEM((n_kv * gp, 128), jnp.float32),
            pltpu.VMEM((n_kv * gp, 128), jnp.float32),
            pltpu.VMEM((n_kv * gp, head_dim), jnp.float32),
        ],
    )

    out = pl.pallas_call(
        functools.partial(
            _decode_kernel,
            span=span,
            block_size=block_size,
            n_kv=n_kv,
            gp=gp,
            scale=head_dim**-0.5,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((batch, n_kv * gp, head_dim), q.dtype),
        interpret=use_interpret(),
    )(
        block_tables.astype(jnp.int32),
        seq_lens.astype(jnp.int32),
        qg,
        *([k_pool] * span),
        *([v_pool] * span),
    )

    out = out.reshape(batch, n_kv, gp, head_dim)[:, :, :group]
    return out.reshape(batch, n_heads, head_dim)


# ---------------------------------------------------------------------------
# verify: K speculative query positions per lane share one prefix pass
# ---------------------------------------------------------------------------


def _verify_kernel(
    tables_ref,
    pos_ref,  # scalar prefetch [B] — position of each lane's first query
    q_ref,  # [1, KV*WP, D]
    *rest,
    span: int,
    block_size: int,
    n_kv: int,
    group: int,
    window: int,
    wp: int,
    scale: float,
):
    k_refs = rest[:span]
    v_refs = rest[span : 2 * span]
    o_ref = rest[2 * span]
    m_scr, l_scr, acc_scr = rest[2 * span + 1 :]
    del tables_ref

    b = pl.program_id(0)
    j = pl.program_id(1)
    nj = pl.num_programs(1)
    pos = pos_ref[b]
    horizon = pos + window - 1  # last key any of the K queries may see

    @pl.when(j == 0)
    def _init():
        _init_state(m_scr, l_scr, acc_scr)

    @pl.when(j * span * block_size <= horizon)
    def _compute():
        # Row r of a head's WP-row block is query offset r // group
        # (rows r >= window*group are padding and fully masked).
        row = _iota_rows(wp)  # [WP, 1]
        q_pos = pos + row // group
        row_ok = row < window * group
        for s in range(span):
            start = (j * span + s) * block_size
            k_page = k_refs[s][0].astype(jnp.float32)
            v_page = v_refs[s][0].astype(jnp.float32)
            col = start + _iota_cols(block_size)  # [1, bs]
            # A key is garbage unless visible to at least the last query.
            v_page = jnp.where((col <= horizon).T[:, :, None], v_page, 0.0)
            keep = (col <= q_pos) & row_ok  # [WP, bs] causal window
            for h in range(n_kv):
                rows = slice(h * wp, (h + 1) * wp)
                s_log = (
                    lax.dot_general(
                        q_ref[0, rows].astype(jnp.float32),
                        k_page[:, h, :],
                        (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )
                    * scale
                )
                _online_update(
                    m_scr, l_scr, acc_scr, rows, s_log, v_page[:, h, :], keep
                )

    @pl.when(j == nj - 1)
    def _done():
        _finalize(o_ref, m_scr, l_scr, acc_scr)


def paged_verify_kernel(
    q: jnp.ndarray,  # [B, C, H, D] — C = draft window (K steps)
    k_pool: jnp.ndarray,
    v_pool: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, MB] int32
    positions: jnp.ndarray,  # [B] int32 — position of q[:, 0]
    *,
    config: Optional[Dict[str, Any]] = None,
) -> jnp.ndarray:
    """Fused K-step speculative verify: one paged-prefix pass serves all
    K query positions of a lane, window mask applied in-kernel."""
    from dlrover_tpu.ops import autotune

    batch, window, n_heads, head_dim = q.shape
    _, block_size, n_kv, _ = k_pool.shape
    group = n_heads // n_kv
    max_blocks = block_tables.shape[1]
    rows = window * group
    if config is None:
        config = autotune.get_config(
            "verify",
            group=group,
            head_dim=head_dim,
            block_size=block_size,
            max_blocks=max_blocks,
            dtype=q.dtype,
            window=window,
        )
    span = max(1, min(int(config.get("kv_span", 1)), max_blocks))
    wp = max(int(config.get("q_rows", rows)), rows)
    nj = -(-max_blocks // span)

    # [B, C, KV, G, D] -> [B, KV, C*G, D]: a head's K windows are
    # contiguous rows, padded to wp per head.
    qg = q.reshape(batch, window, n_kv, group, head_dim)
    qg = qg.transpose(0, 2, 1, 3, 4).reshape(batch, n_kv, rows, head_dim)
    if wp > rows:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, wp - rows), (0, 0)))
    qg = qg.reshape(batch, n_kv * wp, head_dim)

    def _q_index(b, j, tables, pos):
        del j, tables, pos
        return (b, 0, 0)

    def _page_index(b, j, tables, pos, s=0):
        last = jnp.maximum(
            lax.div(pos[b] + window - 1 + block_size, block_size) - 1, 0
        )
        idx = jnp.minimum(j * span + s, jnp.minimum(last, max_blocks - 1))
        return (tables[b, idx], 0, 0, 0)

    kv_specs = [
        pl.BlockSpec(
            (1, block_size, n_kv, head_dim),
            functools.partial(_page_index, s=s),
        )
        for s in range(span)
    ]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(batch, nj),
        in_specs=[pl.BlockSpec((1, n_kv * wp, head_dim), _q_index)]
        + kv_specs
        + kv_specs,
        out_specs=pl.BlockSpec((1, n_kv * wp, head_dim), _q_index),
        scratch_shapes=[
            pltpu.VMEM((n_kv * wp, 128), jnp.float32),
            pltpu.VMEM((n_kv * wp, 128), jnp.float32),
            pltpu.VMEM((n_kv * wp, head_dim), jnp.float32),
        ],
    )

    out = pl.pallas_call(
        functools.partial(
            _verify_kernel,
            span=span,
            block_size=block_size,
            n_kv=n_kv,
            group=group,
            window=window,
            wp=wp,
            scale=head_dim**-0.5,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((batch, n_kv * wp, head_dim), q.dtype),
        interpret=use_interpret(),
    )(
        block_tables.astype(jnp.int32),
        positions.astype(jnp.int32),
        qg,
        *([k_pool] * span),
        *([v_pool] * span),
    )

    out = out.reshape(batch, n_kv, wp, head_dim)[:, :, :rows]
    out = out.reshape(batch, n_kv, window, group, head_dim)
    return out.transpose(0, 2, 1, 3, 4).reshape(
        batch, window, n_heads, head_dim
    )
