"""Singleton agent→master client: every RPC the agent makes.

Reference parity: ``dlrover/python/elastic_agent/master_client.py:50``
(``MasterClient``) — one method per control-plane interaction:
rendezvous, data shards, metrics, failures, heartbeats, KV store.
Transport is the 2-RPC pickled-envelope channel
(``dlrover_tpu.common.comm.MasterChannel``).
"""

import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from dlrover_tpu.common import messages as msg
from dlrover_tpu.common.comm import MasterChannel
from dlrover_tpu.common.constants import NodeEnv, NodeType, RendezvousName
from dlrover_tpu.common.log import default_logger as logger


class MasterClient:
    """gRPC client to the job master; one instance per process."""

    _instance: Optional["MasterClient"] = None
    _lock = threading.Lock()

    def __init__(
        self,
        master_addr: str,
        node_id: int = 0,
        node_type: str = NodeType.WORKER,
        timeout: float = 15.0,
    ):
        self._addr = master_addr
        self._node_id = node_id
        self._node_type = node_type
        self._channel = MasterChannel(
            master_addr, node_id=node_id, node_type=node_type, timeout=timeout
        )

    # ------------------------------------------------------------ lifecycle
    @classmethod
    def singleton_instance(
        cls, master_addr: str = "", node_id: Optional[int] = None
    ) -> "MasterClient":
        with cls._lock:
            if cls._instance is None:
                addr = master_addr or os.getenv(NodeEnv.MASTER_ADDR, "")
                if not addr:
                    raise RuntimeError(
                        "no master address: pass master_addr or set "
                        f"${NodeEnv.MASTER_ADDR}"
                    )
                if node_id is None:
                    node_id = int(os.getenv(NodeEnv.NODE_RANK, "0"))
                cls._instance = cls(addr, node_id=node_id)
            return cls._instance

    @classmethod
    def reset(cls):
        with cls._lock:
            if cls._instance is not None:
                cls._instance.close()
            cls._instance = None

    @property
    def addr(self) -> str:
        return self._addr

    @property
    def node_id(self) -> int:
        return self._node_id

    def close(self):
        self._channel.close()

    # ----------------------------------------------------------- rendezvous
    def report_rdzv_params(
        self,
        min_nodes: int,
        max_nodes: int,
        waiting_timeout: int,
        node_unit: int = 1,
    ) -> bool:
        return self._channel.report(
            msg.RendezvousParams(
                min_nodes=min_nodes,
                max_nodes=max_nodes,
                waiting_timeout=waiting_timeout,
                node_unit=node_unit,
            )
        )

    def report_node_topology(self, node_rank: int, levels) -> bool:
        """Report this node's interconnect position (outermost level
        first) for topology-aware rank sorting."""
        return self._channel.report(
            msg.NodeTopology(node_rank=node_rank, levels=tuple(levels))
        )

    def join_rendezvous(
        self,
        node_rank: int,
        local_world_size: int,
        rdzv_name: str = RendezvousName.ELASTIC_TRAINING,
    ) -> int:
        state = self._channel.get(
            msg.JoinRendezvousRequest(
                node_rank=node_rank,
                local_world_size=local_world_size,
                rdzv_name=rdzv_name,
            )
        )
        return state.round if state else -1

    def get_comm_world(
        self, rdzv_name: str, node_rank: int
    ) -> Tuple[int, int, Dict[int, int]]:
        """Returns (round, group, {node_rank: local_world_size})."""
        world = self._channel.get(
            msg.CommWorldRequest(node_id=node_rank, rdzv_name=rdzv_name)
        )
        if world is None:
            return -1, 0, {}
        return world.round, world.group, world.world or {}

    def num_nodes_waiting(
        self, rdzv_name: str = RendezvousName.ELASTIC_TRAINING
    ) -> int:
        res = self._channel.get(msg.WaitingNodeNumRequest(rdzv_name=rdzv_name))
        return res.waiting_num if res else 0

    def check_fault_node(self) -> Tuple[List[int], str]:
        res = self._channel.get(msg.NetworkReadyRequest())
        if res is None:
            return [], ""
        return res.nodes or [], res.reason or ""

    def check_straggler(self) -> Tuple[List[int], str]:
        res = self._channel.get(msg.StragglerExistRequest())
        if res is None:
            return [], ""
        return res.nodes or [], res.reason or ""

    def report_network_status(
        self, node_rank: int, succeeded: bool, elapsed_time: float
    ) -> bool:
        return self._channel.report(
            msg.NetworkStatus(
                node_rank=node_rank,
                succeeded=succeeded,
                elapsed_time=elapsed_time,
            )
        )

    def sync_checkpoint(self, step: int) -> bool:
        return self._channel.report(
            msg.NodeCheckpointState(step=step)
        )

    def brain_query(self, kind: str = "speed", job: str = "default",
                    limit: int = 100, workload: str = ""):
        """Query the master's durable Brain datastore; returns the
        payload dict, or None when no datastore is configured.
        ``kind="measurements"`` + ``workload`` pulls calibration
        history — usable from a DIFFERENT job's master (multi-job
        Brain)."""
        res = self._channel.get(
            msg.BrainQueryRequest(
                kind=kind, job=job, limit=limit, workload=workload
            )
        )
        if res is None or not getattr(res, "available", False):
            return None
        return res.payload

    # ------------------------------------------------------------ KV store
    def kv_store_set(self, key: str, value: bytes) -> bool:
        return self._channel.report(msg.KeyValuePair(key=key, value=value))

    def kv_store_get(self, key: str) -> bytes:
        res = self._channel.get(msg.KeyValuePair(key=key))
        return res.value if res and res.value is not None else b""

    def kv_store_wait(
        self, key: str, timeout: float = 300.0, interval: float = 0.2
    ) -> bytes:
        """Poll the master KV store until ``key`` appears."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            value = self.kv_store_get(key)
            if value:
                return value
            time.sleep(interval)
        raise TimeoutError(f"key {key!r} not set within {timeout}s")

    # ---------------------------------------------------------- data shards
    def report_dataset_shard_params(
        self,
        dataset_name: str,
        dataset_size: int,
        batch_size: int = 0,
        num_epochs: int = 1,
        shuffle: bool = False,
        num_minibatches_per_shard: int = 2,
        storage_type: str = "table",
        task_type: str = msg.TaskType.TRAINING,
    ) -> bool:
        return self._channel.report(
            msg.DatasetShardParams(
                dataset_name=dataset_name,
                dataset_size=dataset_size,
                batch_size=batch_size,
                num_epochs=num_epochs,
                shuffle=shuffle,
                num_minibatches_per_shard=num_minibatches_per_shard,
                storage_type=storage_type,
                task_type=task_type,
            )
        )

    def get_task(self, dataset_name: str) -> msg.Task:
        task = self._channel.get(msg.TaskRequest(dataset_name=dataset_name))
        return task if task is not None else msg.Task(task_id=-1)

    def report_task_result(
        self, dataset_name: str, task_id: int, err_message: str = ""
    ) -> bool:
        return self._channel.report(
            msg.TaskResult(
                dataset_name=dataset_name,
                task_id=task_id,
                err_message=err_message,
            )
        )

    def get_shard_checkpoint(self, dataset_name: str):
        return self._channel.get(
            msg.ShardCheckpointRequest(dataset_name=dataset_name)
        )

    def report_shard_checkpoint(
        self, dataset_name: str, content: str
    ) -> bool:
        return self._channel.report(
            msg.ShardCheckpoint(dataset_name=dataset_name, content=content)
        )

    # -------------------------------------------------------------- metrics
    def report_global_step(
        self, step: int, timestamp: Optional[float] = None
    ) -> bool:
        return self._channel.report(
            msg.GlobalStep(step=step, timestamp=timestamp or time.time())
        )

    def report_resource_stats(
        self,
        cpu_percent: float,
        memory_mb: float,
        tpu_stats: Optional[list] = None,
    ) -> bool:
        return self._channel.report(
            msg.ResourceStats(
                cpu_percent=cpu_percent,
                memory_mb=memory_mb,
                tpu_stats=tpu_stats or [],
            )
        )

    def report_model_info(
        self,
        num_params: int,
        flops_per_step: float = 0.0,
        hidden_size: int = 0,
        num_layers: int = 0,
        seq_len: int = 0,
        extra=None,
    ) -> bool:
        return self._channel.report(
            msg.ModelInfo(
                num_params=num_params,
                flops_per_step=flops_per_step,
                hidden_size=hidden_size,
                num_layers=num_layers,
                seq_len=seq_len,
                extra=extra or {},
            )
        )

    def report_node_address(
        self, node_type: str, node_id: int, addr: str
    ) -> bool:
        return self._channel.report(
            msg.NodeAddress(node_type=node_type, node_id=node_id, addr=addr)
        )

    def report_heartbeat(self, timestamp: Optional[float] = None) -> bool:
        return self._channel.report(
            msg.HeartBeat(timestamp=timestamp or time.time())
        )

    def report_failure(
        self, error_data: str, restart_count: int = 0, level: str = "warning"
    ) -> bool:
        return self._channel.report(
            msg.NodeFailure(
                error_data=error_data,
                restart_count=restart_count,
                level=level,
            )
        )

    def report_succeeded(self) -> bool:
        return self._channel.report(msg.SucceededRequest())

    def report_timeline_events(self, events: list) -> bool:
        """Ship a batch of timeline records (``observability/events``
        JSONL schema) to the master's TimelineAggregator."""
        return self._channel.report(
            msg.TimelineEventsReport(events=list(events))
        )

    def get_goodput_ledger(
        self, job: str = "", limit: int = 0
    ) -> Optional[Tuple[Dict, list]]:
        """Fetch the master's merged goodput ledger (and the newest
        ``limit`` raw events); None when no aggregator is serving."""
        res = self._channel.get(
            msg.TimelineQueryRequest(job=job, limit=limit)
        )
        if res is None or not getattr(res, "available", False):
            return None
        return res.ledger, res.events

    # -------------------------------------------------------------- control
    def get_running_nodes(self) -> list:
        res = self._channel.get(msg.RunningNodesRequest())
        return res.nodes if res else []

    def get_training_status(self) -> str:
        res = self._channel.get(msg.TrainingStatusRequest())
        return res.status if res else ""

    def get_paral_config(self) -> msg.ParallelConfig:
        res = self._channel.get(msg.ParallelConfigRequest())
        return res if res is not None else msg.ParallelConfig()

    def report_paral_config(self, config: msg.ParallelConfig) -> bool:
        return self._channel.report(config)

    def need_to_restart_training(self) -> bool:
        res = self._channel.get(msg.CheckHardwareResetRequest())
        return bool(res and getattr(res, "restart", False))

    def get_elastic_run_config(self) -> Dict[str, str]:
        res = self._channel.get(msg.ElasticRunConfigRequest())
        return res.configs if res and res.configs else {}

    def report_diagnosis_data(
        self, data_cls: str, data_content: str, node_rank: int = -1
    ) -> bool:
        return self._channel.report(
            msg.DiagnosisReportData(
                data_cls=data_cls,
                data_content=data_content,
                node_rank=node_rank,
            )
        )
