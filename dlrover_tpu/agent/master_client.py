"""Singleton agent→master client: every RPC the agent makes.

Reference parity: ``dlrover/python/elastic_agent/master_client.py:50``
(``MasterClient``) — one method per control-plane interaction:
rendezvous, data shards, metrics, failures, heartbeats, KV store.
Transport is the 2-RPC pickled-envelope channel
(``dlrover_tpu.common.comm.MasterChannel``).
"""

import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from dlrover_tpu.common import messages as msg
from dlrover_tpu.common.comm import MasterChannel, wait_channel_ready
from dlrover_tpu.common.constants import NodeEnv, NodeType, RendezvousName
from dlrover_tpu.common.env import (
    control_batch_enabled,
    control_longpoll_enabled,
    master_failover_enabled,
)
from dlrover_tpu.common.fault_injection import maybe_crash
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.observability.events import get_event_logger
from dlrover_tpu.observability.metrics import record_dropped_reports

#: one long-poll RPC parks on the master at most this long; waits
#: longer than a chunk loop (each chunk is still ONE rpc, so a 5 min
#: wait costs 10 RPCs instead of 1500 at a 0.2 s poll)
LONGPOLL_CHUNK_S = float(
    os.getenv("DLROVER_TPU_CONTROL_LONGPOLL_CHUNK_S", "30")
)
#: grpc deadline margin over the server-side wait: the RPC must not be
#: deadline-killed while the master is still legitimately parked
_LONGPOLL_RPC_MARGIN_S = 10.0
#: a saturated master (parked-waiter cap hit) answers a long-poll
#: immediately instead of parking; pace re-issues so the fallback is
#: a 10 Hz poll, not a hot RPC spin
_LONGPOLL_SATURATED_BACKOFF_S = 0.1


def _pace_longpoll(chunk: float, rpc_elapsed: float):
    """Sleep briefly when a long-poll chunk came back empty far sooner
    than it should have (master degraded the wait to an immediate
    answer under load)."""
    if chunk > 0.2 and rpc_elapsed < 0.05:
        time.sleep(_LONGPOLL_SATURATED_BACKOFF_S)


def _longpoll_params(wait_timeout: float):
    """ONE definition of the chunk clamp + RPC deadline: returns
    ``(clamped_wait, rpc_timeout)`` — ``rpc_timeout`` None when not
    long-polling (the channel's default applies)."""
    if wait_timeout <= 0:
        return 0.0, None
    wait_timeout = min(wait_timeout, LONGPOLL_CHUNK_S)
    return wait_timeout, wait_timeout + _LONGPOLL_RPC_MARGIN_S


class MasterClient:
    """gRPC client to the job master; one instance per process."""

    _instance: Optional["MasterClient"] = None
    _lock = threading.Lock()

    def __init__(
        self,
        master_addr: str,
        node_id: int = 0,
        node_type: str = NodeType.WORKER,
        timeout: float = 15.0,
    ):
        self._addr = master_addr
        self._node_id = node_id
        self._node_type = node_type
        self._channel = MasterChannel(
            master_addr, node_id=node_id, node_type=node_type, timeout=timeout
        )
        # delta-protocol caches: last full response + its version, so
        # a ``NotModified`` answer resolves locally
        self._comm_world_cache: Dict[
            str, Tuple[int, Tuple[int, int, Dict[int, int]]]
        ] = {}
        self._running_nodes_cache: Optional[Tuple[int, list]] = None
        #: this client's OWN kv writes (newest-last), re-asserted on
        #: an incarnation change: a master ack races the write-behind
        #: journal flush, so a crash inside the linger window loses
        #: ACKED mutations — the agent must reattach AND re-assert,
        #: like DLRover agents re-registering with a recreated master
        #: pod.  Sets are last-writer-wins, so re-asserting a value
        #: that DID survive replay is a no-op.
        self._own_kv: Dict[str, bytes] = {}
        #: pending rendezvous joins (rdzv_name -> (rank, local_ws)),
        #: re-issued on reconnect while the round is still pending —
        #: an acked-but-unflushed join otherwise parks this node on a
        #: round the restarted master doesn't know it joined
        self._pending_join: Dict[str, Tuple[int, int]] = {}
        #: dataset registrations this client made, re-asserted on an
        #: incarnation change (idempotent server-side)
        self._own_datasets: Dict[str, msg.Message] = {}
        #: last JOB epoch this client acted under: re-assertion is
        #: only valid within one job generation (-1 = not learned yet)
        self._last_job_epoch = -1
        #: Brain node directive delivered on the last WaitingNodeNum
        #: response (action, reason, decision_id); consumed by the
        #: agent via :meth:`take_node_action`
        self._node_action: Optional[Tuple[str, str, int]] = None
        # epoch fencing: a StaleEpoch-triggered refresh means the job
        # generation (or master incarnation) changed — every versioned
        # cache is void (version counters restart with the new master)
        self._channel.on_epoch_change = self._on_epoch_change

    #: own-write re-assert cache bound: coordination keys are
    #: per-round and small; only the newest matter after a restart
    MAX_OWN_KV = 256

    #: re-assertion RPC budget: these calls fire from inside another
    #: call's recovery path — each opening its own full reconnect
    #: deadline would block the outer caller minutes past its own
    REASSERT_DEADLINE_S = 15.0

    def _on_epoch_change(self, job_epoch: int, incarnation: int):
        self._comm_world_cache.clear()
        self._running_nodes_cache = None
        prev_epoch, self._last_job_epoch = (
            self._last_job_epoch, job_epoch
        )
        if prev_epoch not in (-1, job_epoch):
            # the JOB generation changed (the old job was retired):
            # this client's session state belongs to the dead
            # generation — re-asserting it would inject the retired
            # job's KV keys / datasets / joins into the new one,
            # exactly what the epoch bump exists to fence off
            self._own_kv.clear()
            self._own_datasets.clear()
            self._pending_join.clear()
            logger.warning(
                "job epoch changed %s -> %s: session state dropped, "
                "nothing re-asserted", prev_epoch, job_epoch,
            )
            return
        if prev_epoch == -1 and incarnation <= 1:
            # first epoch learn, and the master never restarted: no
            # linger-window state was lost, so there is nothing to
            # re-assert — and if this client is a straggler of a
            # RETIRED generation (it never learned the old epoch, so
            # it can't tell), re-asserting would inject dead-job
            # state into the new one.  Caches stay: a later restart
            # of THIS generation's master re-asserts normally.
            return
        logger.info(
            "master epoch refreshed: job_epoch=%s incarnation=%s "
            "(delta caches dropped, %d own kv writes re-asserted)",
            job_epoch, incarnation, len(self._own_kv),
        )
        with self._channel.bounded_deadline(self.REASSERT_DEADLINE_S):
            for key, value in list(self._own_kv.items()):
                try:
                    self._channel.report(
                        msg.KeyValuePair(key=key, value=value)
                    )
                except ConnectionError as e:
                    logger.warning(
                        "kv re-assert of %r failed: %s", key, e
                    )
                    break
            for params in list(self._own_datasets.values()):
                try:
                    self._channel.report(params)
                except ConnectionError as e:
                    logger.warning(
                        "dataset re-assert failed: %s", e
                    )
                    break
            # a node parked between join and world-received re-asserts
            # its membership too (conditional: _pending_join is popped
            # the moment a world containing this node arrives, so
            # agents that finished rendezvous can never wipe a
            # completed world here)
            for rdzv_name in list(self._pending_join):
                self._ensure_rdzv_membership(rdzv_name)

    def _record_own_kv(self, key: str, value: bytes):
        self._own_kv.pop(key, None)  # re-insert newest-last
        self._own_kv[key] = value
        while len(self._own_kv) > self.MAX_OWN_KV:
            self._own_kv.pop(next(iter(self._own_kv)))

    def _ensure_rdzv_membership(
        self, rdzv_name: str, node_rank: Optional[int] = None
    ):
        """After a master restart mid-wait: re-join the pending round
        unless the completed world already contains this node (then
        the re-parked wait consumes it; a blind re-join would wipe a
        completed world and force a full re-rendezvous)."""
        join = self._pending_join.get(rdzv_name)
        if join is None:
            return
        if node_rank is None:
            node_rank = join[0]
        try:
            _rnd, _grp, world = self.get_comm_world(
                rdzv_name, node_rank
            )
            if world and node_rank in world:
                return
            self.join_rendezvous(
                join[0], join[1], rdzv_name=rdzv_name
            )
            logger.info(
                "re-joined %s rendezvous on the new master "
                "incarnation (node %s)", rdzv_name, node_rank,
            )
        except ConnectionError as e:
            logger.warning(
                "rendezvous re-join after reconnect failed "
                "(will retry on the next outage): %s", e,
            )

    def _survive_outage(self, deadline: float, what: str) -> bool:
        """Failover path of a parked long-poll: the master died
        mid-wait.  Block until the (restarted) master's channel is
        READY again — then refresh the fencing pair so the re-issued
        wait parks on the NEW incarnation.  False when the outage
        outlives ``deadline`` or failover is kill-switched (the caller
        re-raises)."""
        if not master_failover_enabled():
            return False
        remaining = deadline - time.time()
        if remaining <= 0:
            return False
        logger.warning(
            "master unreachable during %s; waiting up to %.0fs for "
            "it to come back", what, remaining,
        )
        with get_event_logger().span("control_wait", kind="reconnect"):
            while remaining > 0:
                if wait_channel_ready(
                    self._addr, timeout=min(remaining, 10.0)
                ):
                    try:
                        # bound the probe by what's left of the
                        # caller's wait deadline — an unbounded
                        # refresh would run its own full reconnect
                        # deadline on top of it
                        self._channel.refresh_epoch(
                            deadline_s=max(
                                deadline - time.time(), 1.0
                            )
                        )
                    except ConnectionError:
                        # it flapped; keep waiting out the deadline
                        remaining = deadline - time.time()
                        continue
                    return True
                remaining = deadline - time.time()
        return False

    # ------------------------------------------------------------ lifecycle
    @classmethod
    def singleton_instance(
        cls, master_addr: str = "", node_id: Optional[int] = None
    ) -> "MasterClient":
        with cls._lock:
            if cls._instance is None:
                addr = master_addr or os.getenv(NodeEnv.MASTER_ADDR, "")
                if not addr:
                    raise RuntimeError(
                        "no master address: pass master_addr or set "
                        f"${NodeEnv.MASTER_ADDR}"
                    )
                if node_id is None:
                    node_id = int(os.getenv(NodeEnv.NODE_RANK, "0"))
                cls._instance = cls(addr, node_id=node_id)
            return cls._instance

    @classmethod
    def reset(cls):
        with cls._lock:
            if cls._instance is not None:
                cls._instance.close()
            cls._instance = None

    @property
    def addr(self) -> str:
        return self._addr

    @property
    def node_id(self) -> int:
        return self._node_id

    @property
    def rpc_count(self) -> int:
        """RPCs issued on the wire by this client (attempts)."""
        return self._channel.rpc_count

    def close(self):
        self._channel.close()

    # ----------------------------------------------------------- rendezvous
    def report_rdzv_params(
        self,
        min_nodes: int,
        max_nodes: int,
        waiting_timeout: int,
        node_unit: int = 1,
    ) -> bool:
        return self._channel.report(
            msg.RendezvousParams(
                min_nodes=min_nodes,
                max_nodes=max_nodes,
                waiting_timeout=waiting_timeout,
                node_unit=node_unit,
            )
        )

    def report_node_topology(self, node_rank: int, levels) -> bool:
        """Report this node's interconnect position (outermost level
        first) for topology-aware rank sorting."""
        return self._channel.report(
            msg.NodeTopology(node_rank=node_rank, levels=tuple(levels))
        )

    def join_rendezvous(
        self,
        node_rank: int,
        local_world_size: int,
        rdzv_name: str = RendezvousName.ELASTIC_TRAINING,
    ) -> int:
        self._pending_join[rdzv_name] = (node_rank, local_world_size)
        state = self._channel.get(
            msg.JoinRendezvousRequest(
                node_rank=node_rank,
                local_world_size=local_world_size,
                rdzv_name=rdzv_name,
            )
        )
        return state.round if state else -1

    def get_comm_world(
        self, rdzv_name: str, node_rank: int
    ) -> Tuple[int, int, Dict[int, int]]:
        """Returns (round, group, {node_rank: local_world_size}).

        Delta protocol: the request carries the version of the cached
        copy; a ``NotModified`` answer resolves from the cache without
        the master re-shipping the world.
        """
        cached = self._comm_world_cache.get(rdzv_name)
        version = cached[0] if cached else -1
        world = self._channel.get(
            msg.CommWorldRequest(
                node_id=node_rank, rdzv_name=rdzv_name, version=version
            )
        )
        if isinstance(world, msg.NotModified) and cached:
            return cached[1]
        if world is None or isinstance(world, msg.NotModified):
            return -1, 0, {}
        result = (world.round, world.group, world.world or {})
        self._comm_world_cache[rdzv_name] = (
            getattr(world, "version", 0), result
        )
        return result

    def wait_comm_world(
        self,
        rdzv_name: str,
        node_rank: int,
        timeout: float,
        poll_interval: float = 0.3,
    ) -> Tuple[int, int, Dict[int, int]]:
        """Long-poll ``get_comm_world``: block until the master
        declares the world complete (or ``timeout`` elapses — an empty
        world is then returned).  Falls back to the get-every-
        ``poll_interval`` loop under
        ``DLROVER_TPU_CONTROL_LONGPOLL=0``."""
        deadline = time.time() + max(timeout, 0.0)
        longpoll = control_longpoll_enabled()
        # a master death can be absorbed BELOW this loop (the channel
        # retries inside its reconnect deadline and re-issues the
        # parked wait transparently) — watch the incarnation between
        # iterations so a lost-in-the-linger-window join is
        # re-asserted on whichever path survived the outage
        inc_seen = self._channel.master_incarnation
        with get_event_logger().span(
            "control_wait", kind="comm_world", rdzv=rdzv_name
        ):
            while True:
                if self._channel.master_incarnation != inc_seen:
                    inc_seen = self._channel.master_incarnation
                    self._ensure_rdzv_membership(
                        rdzv_name, node_rank
                    )
                remaining = deadline - time.time()
                if remaining <= 0:
                    return -1, 0, {}
                if longpoll:
                    chunk = min(remaining, LONGPOLL_CHUNK_S)
                    t0 = time.monotonic()
                    try:
                        world = self._channel.get(
                            msg.CommWorldRequest(
                                node_id=node_rank,
                                rdzv_name=rdzv_name,
                                wait_timeout=chunk,
                            ),
                            timeout=chunk + _LONGPOLL_RPC_MARGIN_S,
                        )
                    except ConnectionError:
                        # mid-wait master death: re-park on the new
                        # incarnation.  Replay usually restored this
                        # node's join; when the join ack died in the
                        # write-behind linger window, re-assert it.
                        if self._survive_outage(
                            deadline, "comm-world wait"
                        ):
                            self._ensure_rdzv_membership(
                                rdzv_name, node_rank
                            )
                            continue
                        raise
                    if world is not None and not isinstance(
                        world, msg.NotModified
                    ):
                        result = (
                            world.round, world.group, world.world or {}
                        )
                        if result[2]:
                            if node_rank in result[2]:
                                # joined world delivered: the pending
                                # join is consumed, later monitor
                                # waits must never re-join
                                self._pending_join.pop(
                                    rdzv_name, None
                                )
                            self._comm_world_cache[rdzv_name] = (
                                getattr(world, "version", 0), result
                            )
                            return result
                    _pace_longpoll(chunk, time.monotonic() - t0)
                else:
                    rnd, group, world_map = self.get_comm_world(
                        rdzv_name, node_rank
                    )
                    if world_map:
                        if node_rank in world_map:
                            self._pending_join.pop(rdzv_name, None)
                        return rnd, group, world_map
                    time.sleep(poll_interval)

    def num_nodes_waiting(
        self,
        rdzv_name: str = RendezvousName.ELASTIC_TRAINING,
        wait_timeout: float = 0.0,
        last_num: int = -1,
    ) -> int:
        """Current waiting count; with ``wait_timeout`` > 0 the master
        long-polls until the count differs from ``last_num``."""
        wait_timeout, timeout = _longpoll_params(wait_timeout)
        res = self._channel.get(
            msg.WaitingNodeNumRequest(
                rdzv_name=rdzv_name,
                wait_timeout=wait_timeout,
                last_num=last_num,
            ),
            timeout=timeout,
        )
        if res is None:
            return 0
        # Brain directive piggyback (getattr: an old master's pickle
        # has no such fields); stashed for the agent's monitor loop
        action = getattr(res, "action", "")
        if action:
            self._node_action = (
                action,
                getattr(res, "action_reason", ""),
                int(getattr(res, "action_id", 0) or 0),
            )
        return res.waiting_num

    def take_node_action(self) -> Optional[Tuple[str, str, int]]:
        """Consume the Brain directive the last waiting-num poll
        delivered (``(action, reason, decision_id)`` or None)."""
        action, self._node_action = self._node_action, None
        return action

    def check_fault_node(self) -> Tuple[List[int], str]:
        res = self._channel.get(msg.NetworkReadyRequest())
        if res is None:
            return [], ""
        return res.nodes or [], res.reason or ""

    def check_straggler(self) -> Tuple[List[int], str]:
        res = self._channel.get(msg.StragglerExistRequest())
        if res is None:
            return [], ""
        return res.nodes or [], res.reason or ""

    def report_network_status(
        self, node_rank: int, succeeded: bool, elapsed_time: float
    ) -> bool:
        return self._channel.report(
            msg.NetworkStatus(
                node_rank=node_rank,
                succeeded=succeeded,
                elapsed_time=elapsed_time,
            )
        )

    def sync_checkpoint(self, step: int) -> bool:
        return self._channel.report(
            msg.NodeCheckpointState(step=step)
        )

    def brain_query(self, kind: str = "speed", job: str = "default",
                    limit: int = 100, workload: str = ""):
        """Query the master's durable Brain datastore; returns the
        payload dict, or None when no datastore is configured.
        ``kind="measurements"`` + ``workload`` pulls calibration
        history — usable from a DIFFERENT job's master (multi-job
        Brain)."""
        res = self._channel.get(
            msg.BrainQueryRequest(
                kind=kind, job=job, limit=limit, workload=workload
            )
        )
        if res is None or not getattr(res, "available", False):
            return None
        return res.payload

    # ------------------------------------------------------------ KV store
    def kv_store_set(self, key: str, value: bytes) -> bool:
        self._record_own_kv(key, value)
        return self._channel.report(msg.KeyValuePair(key=key, value=value))

    def kv_store_get(self, key: str) -> bytes:
        res = self._channel.get(msg.KeyValuePair(key=key))
        return res.value if res and res.value is not None else b""

    def kv_store_wait(
        self,
        key: str,
        timeout: float = 300.0,
        interval: float = 0.2,
        longpoll: Optional[bool] = None,
    ) -> bytes:
        """Block until ``key`` appears in the master KV store.

        Long-poll (default): each RPC parks on the master's KV
        condition up to ``LONGPOLL_CHUNK_S`` — an idle 5 min wait costs
        ~10 RPCs.  ``DLROVER_TPU_CONTROL_LONGPOLL=0`` (or
        ``longpoll=False``) restores the get-every-``interval`` polling
        loop as the bench reference.
        """
        if longpoll is None:
            longpoll = control_longpoll_enabled()
        deadline = time.time() + timeout
        with get_event_logger().span("control_wait", kind="kv", key=key):
            while time.time() < deadline:
                if longpoll:
                    chunk = min(
                        deadline - time.time(), LONGPOLL_CHUNK_S
                    )
                    t0 = time.monotonic()
                    try:
                        res = self._channel.get(
                            msg.KVWaitRequest(
                                key=key, wait_timeout=chunk
                            ),
                            timeout=chunk + _LONGPOLL_RPC_MARGIN_S,
                        )
                    except ConnectionError:
                        # mid-wait master death: re-park on the new
                        # incarnation (journal replay restored the KV
                        # contents, so a pre-crash set still answers)
                        if self._survive_outage(deadline, "kv wait"):
                            continue
                        raise
                    value = (
                        res.value
                        if res and res.value is not None
                        else b""
                    )
                    if value:
                        return value
                    _pace_longpoll(chunk, time.monotonic() - t0)
                else:
                    value = self.kv_store_get(key)
                    if value:
                        return value
                    time.sleep(interval)
        raise TimeoutError(f"key {key!r} not set within {timeout}s")

    # ---------------------------------------------------------- data shards
    def report_dataset_shard_params(
        self,
        dataset_name: str,
        dataset_size: int,
        batch_size: int = 0,
        num_epochs: int = 1,
        shuffle: bool = False,
        num_minibatches_per_shard: int = 2,
        storage_type: str = "table",
        task_type: str = msg.TaskType.TRAINING,
    ) -> bool:
        params = msg.DatasetShardParams(
            dataset_name=dataset_name,
            dataset_size=dataset_size,
            batch_size=batch_size,
            num_epochs=num_epochs,
            shuffle=shuffle,
            num_minibatches_per_shard=num_minibatches_per_shard,
            storage_type=storage_type,
            task_type=task_type,
        )
        # re-asserted on an incarnation change (new_dataset is a no-op
        # when the registration survived journal replay): a dataset
        # the restarted master doesn't know reads as "exhausted" to
        # every fetch_shard and silently ends the epoch
        self._own_datasets[dataset_name] = params
        return self._channel.report(params)

    def get_task(
        self, dataset_name: str, wait_timeout: float = 0.0
    ) -> msg.Task:
        """Next shard task; ``wait_timeout`` > 0 long-polls through
        WAIT answers (the master parks until a task is dispatchable).

        A mid-wait master death re-parks on the new incarnation
        (failover mode): an empty answer here would read as "dataset
        exhausted" to ``fetch_shard`` and silently end the epoch."""
        wait_timeout, timeout = _longpoll_params(wait_timeout)
        deadline = time.time() + max(wait_timeout, 5.0)
        while True:
            try:
                task = self._channel.get(
                    msg.TaskRequest(
                        dataset_name=dataset_name,
                        wait_timeout=wait_timeout,
                    ),
                    timeout=timeout,
                )
                break
            except ConnectionError:
                if self._survive_outage(deadline, "task wait"):
                    continue
                raise
        return task if task is not None else msg.Task(task_id=-1)

    def report_task_result(
        self, dataset_name: str, task_id: int, err_message: str = ""
    ) -> bool:
        return self._channel.report(
            msg.TaskResult(
                dataset_name=dataset_name,
                task_id=task_id,
                err_message=err_message,
            )
        )

    def get_shard_checkpoint(self, dataset_name: str):
        return self._channel.get(
            msg.ShardCheckpointRequest(dataset_name=dataset_name)
        )

    def report_shard_checkpoint(
        self, dataset_name: str, content: str
    ) -> bool:
        return self._channel.report(
            msg.ShardCheckpoint(dataset_name=dataset_name, content=content)
        )

    # -------------------------------------------------------------- metrics
    def report_global_step(
        self, step: int, timestamp: Optional[float] = None
    ) -> bool:
        return self._channel.report(
            msg.GlobalStep(step=step, timestamp=timestamp or time.time())
        )

    def report_resource_stats(
        self,
        cpu_percent: float,
        memory_mb: float,
        tpu_stats: Optional[list] = None,
    ) -> bool:
        return self._channel.report(
            msg.ResourceStats(
                cpu_percent=cpu_percent,
                memory_mb=memory_mb,
                tpu_stats=tpu_stats or [],
            )
        )

    def report_model_info(
        self,
        num_params: int,
        flops_per_step: float = 0.0,
        hidden_size: int = 0,
        num_layers: int = 0,
        seq_len: int = 0,
        extra=None,
    ) -> bool:
        return self._channel.report(
            msg.ModelInfo(
                num_params=num_params,
                flops_per_step=flops_per_step,
                hidden_size=hidden_size,
                num_layers=num_layers,
                seq_len=seq_len,
                extra=extra or {},
            )
        )

    def report_node_address(
        self, node_type: str, node_id: int, addr: str
    ) -> bool:
        return self._channel.report(
            msg.NodeAddress(node_type=node_type, node_id=node_id, addr=addr)
        )

    def report_heartbeat(self, timestamp: Optional[float] = None) -> bool:
        return self._channel.report(
            msg.HeartBeat(timestamp=timestamp or time.time())
        )

    def report_failure(
        self, error_data: str, restart_count: int = 0, level: str = "warning"
    ) -> bool:
        return self._channel.report(
            msg.NodeFailure(
                error_data=error_data,
                restart_count=restart_count,
                level=level,
            )
        )

    def report_succeeded(self) -> bool:
        return self._channel.report(msg.SucceededRequest())

    def report_profile(
        self,
        node_rank: int,
        kind: str = "capture",
        reason: str = "",
        capture_id: int = 0,
        summary: Optional[Dict] = None,
        artifact: str = "",
    ) -> bool:
        """Ship one deep-capture result (parsed profile summary +
        artifact path) to the master's CaptureCoordinator."""
        return self._channel.report(
            msg.ProfileReport(
                node_rank=node_rank,
                kind=kind,
                reason=reason,
                capture_id=capture_id,
                summary=summary or {},
                artifact=artifact,
            )
        )

    def report_timeline_events(self, events: list) -> bool:
        """Ship a batch of timeline records (``observability/events``
        JSONL schema) to the master's TimelineAggregator."""
        return self._channel.report(
            msg.TimelineEventsReport(events=list(events))
        )

    def get_job_status(
        self, job: str = "", conclusions: int = 16
    ) -> Optional[Dict]:
        """Fetch the master observatory's derived snapshot (per-node
        health, goodput ledger, newest diagnosis conclusions); None
        when the observatory is off (``DLROVER_TPU_OBSERVATORY=0``)
        or the master predates it."""
        res = self._channel.get(
            msg.JobStatusRequest(job=job, conclusions=conclusions)
        )
        if res is None or not getattr(res, "available", False):
            return None
        return res.status

    def get_goodput_ledger(
        self, job: str = "", limit: int = 0
    ) -> Optional[Tuple[Dict, list]]:
        """Fetch the master's merged goodput ledger (and the newest
        ``limit`` raw events); None when no aggregator is serving."""
        res = self._channel.get(
            msg.TimelineQueryRequest(job=job, limit=limit)
        )
        if res is None or not getattr(res, "available", False):
            return None
        return res.ledger, res.events

    # -------------------------------------------------------------- control
    def get_running_nodes(self) -> list:
        """Running node list; versioned — an unchanged master answers
        ``NotModified`` and the cached copy is returned."""
        cached = self._running_nodes_cache
        version = cached[0] if cached else -1
        res = self._channel.get(msg.RunningNodesRequest(version=version))
        if isinstance(res, msg.NotModified) and cached:
            return cached[1]
        if res is None or isinstance(res, msg.NotModified):
            return []
        nodes = res.nodes or []
        self._running_nodes_cache = (getattr(res, "version", 0), nodes)
        return nodes

    def get_training_status(self, wait_timeout: float = 0.0) -> str:
        """Training-loop status; ``wait_timeout`` > 0 long-polls until
        training starts (or the timeout elapses)."""
        wait_timeout, timeout = _longpoll_params(wait_timeout)
        res = self._channel.get(
            msg.TrainingStatusRequest(wait_timeout=wait_timeout),
            timeout=timeout,
        )
        return res.status if res else ""

    def get_paral_config(self) -> msg.ParallelConfig:
        res = self._channel.get(msg.ParallelConfigRequest())
        return res if res is not None else msg.ParallelConfig()

    def report_paral_config(self, config: msg.ParallelConfig) -> bool:
        return self._channel.report(config)

    def need_to_restart_training(self) -> bool:
        res = self._channel.get(msg.CheckHardwareResetRequest())
        return bool(res and getattr(res, "restart", False))

    def get_elastic_run_config(self) -> Dict[str, str]:
        res = self._channel.get(msg.ElasticRunConfigRequest())
        return res.configs if res and res.configs else {}

    def report_diagnosis_data(
        self, data_cls: str, data_content: str, node_rank: int = -1
    ) -> bool:
        return self._channel.report(
            msg.DiagnosisReportData(
                data_cls=data_cls,
                data_content=data_content,
                node_rank=node_rank,
            )
        )


class ReportBuffer:
    """Client-side coalescer for fire-and-forget reports.

    Heartbeats, speed/metric samples, node events, and timeline
    batches accumulate here and ship as ONE ``BatchedReport`` envelope
    when either threshold trips — ``max_items`` (flushed inline by the
    adder) or ``max_age_s`` (flushed by a daemon thread).  Item order
    is preserved end to end: flushes are serialized, and a
    transport-failed batch is re-queued at the FRONT so nothing is
    reordered or lost across a master hiccup or an agent restart
    (``flush`` runs on shutdown and before every rendezvous).

    The buffer is BOUNDED (``max_pending``): reports are advisory
    telemetry, so when a master outage outlives the buffer the OLDEST
    items are dropped (counted on
    ``dlrover_tpu_control_dropped_reports`` + a warning) — a long
    outage must degrade observability, never OOM the agent.

    ``DLROVER_TPU_CONTROL_BATCH=0`` degenerates ``add`` to the old
    one-RPC-per-report path.
    """

    def __init__(
        self,
        client: MasterClient,
        max_items: int = 64,
        max_age_s: float = 1.0,
        auto_flush: bool = True,
        max_pending: int = 4096,
    ):
        self._client = client
        self._max_items = max_items
        self._max_age_s = max_age_s
        self._max_pending = max(max_pending, 1)
        #: lifetime tally of overflow-dropped reports
        self.dropped = 0
        self._lock = threading.Lock()
        #: serializes flushes: two concurrent flushes could otherwise
        #: ship their batches out of order
        self._flush_lock = threading.Lock()
        self._items: List[msg.Message] = []
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if auto_flush:
            self._thread = threading.Thread(
                target=self._loop, name="report-buffer", daemon=True
            )
            self._thread.start()

    @property
    def pending(self) -> int:
        with self._lock:
            return len(self._items)

    def _trim_locked(self):
        """Caller holds the lock: enforce the bound by dropping the
        OLDEST items (the newest telemetry is the useful telemetry
        when the master comes back)."""
        overflow = len(self._items) - self._max_pending
        if overflow <= 0:
            return
        del self._items[:overflow]
        self.dropped += overflow
        record_dropped_reports(overflow)
        logger.warning(
            "report buffer overflow: dropped %d oldest reports "
            "(%d total dropped) — master unreachable too long?",
            overflow, self.dropped,
        )

    def add(self, message: msg.Message) -> bool:
        """Queue one report (or send it straight through when batching
        is disabled).  Returns the delivery ack for the direct path;
        for a buffered enqueue it returns True unconditionally — the
        buffer owns delivery from here (a transport-failed inline
        flush re-queues the batch, so the report is still owed, not
        lost or rejected)."""
        if not control_batch_enabled():
            return self._client._channel.report(message)
        with self._lock:
            self._items.append(message)
            self._trim_locked()
            full = len(self._items) >= self._max_items
        if full:
            self.flush()
        return True

    def flush(self) -> bool:
        """Ship everything pending as one ``BatchedReport``.  A
        transport failure re-queues the batch at the front (no loss
        below the ``max_pending`` bound, no reorder); a master-side
        handler failure is dropped with a warning — exactly what the
        old per-report path did with its False ack."""
        with self._flush_lock:
            with self._lock:
                items, self._items = self._items, []
            if not items:
                return True
            # chaos hook: agent death between drain and send loses
            # the batch with the process, like any crash would
            maybe_crash("mid_report_flush")
            try:
                ok = self._client._channel.report(
                    msg.BatchedReport(items=items)
                )
            except ConnectionError as e:
                logger.warning(
                    "report batch of %d undeliverable (%s); re-queued",
                    len(items), e,
                )
                with self._lock:
                    self._items[0:0] = items
                    self._trim_locked()
                return False
            if not ok:
                logger.warning(
                    "master rejected a report batch of %d items; "
                    "dropping it", len(items),
                )
            return ok

    def _loop(self):
        while not self._stopped.wait(self._max_age_s):
            try:
                self.flush()
            except Exception as e:  # noqa: BLE001 - reporter must survive
                logger.warning("report buffer flush failed: %s", e)

    def close(self):
        """Stop the age flusher and drain (agent shutdown — buffered
        reports must survive the process)."""
        self._stopped.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        try:
            self.flush()
        except Exception as e:  # noqa: BLE001
            logger.warning("report buffer final flush failed: %s", e)
