"""Pre-fork worker zygote: restart workers without re-paying imports.

Restart-to-first-step latency IS goodput loss under preemption, and on
a 1-core TPU-VM the dominant fixed cost of a fresh worker is the
Python/jax import chain (~3-4 s) that a restart repays on every
incarnation.  The reference stack restarts workers through torchelastic
``subprocess`` spawn and eats that cost each time
(``dlrover/python/elastic_agent/torch/training.py:582`` restart path);
here the agent instead keeps a **zygote** process alive — started once,
with the heavy modules pre-imported but NO jax backend initialized —
and forks each worker incarnation from it.  A fork inherits the warm
``sys.modules``, so a restarted worker is compute-ready in the time it
takes to initialize the backend and re-join the coordinator.

Safety rules baked in:

- the zygote NEVER touches ``jax.devices()``/arrays — a live backend
  (TPU client, threadpools) does not survive ``fork``; import-only is
  fork-safe.
- the zygote is single-threaded (reaping is polled between socket
  requests, no SIGCHLD handler, no reaper thread), so a forked child
  cannot inherit a lock held by a background thread.
- env vars that jax captures at import time (``JAX_PLATFORMS``,
  compilation-cache settings) are re-applied to ``jax.config`` in the
  child when the spawn env disagrees with the zygote's import-time
  value.

The agent talks to the zygote over a length-prefixed pickled unix
socket (the repo's standard local IPC frame, ``common/multi_process``);
``ZygotePool`` exposes Popen-shaped handles so the agent's monitor loop
is oblivious to how a worker was spawned, and falls back to plain
``subprocess`` spawn whenever the zygote is unavailable.
"""

import argparse
import os
import signal
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence, Tuple

from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.multi_process import (
    _recv_msg,
    _send_msg,
    _socket_path,
)

# modules worth pre-importing: the jax stack plus this framework's
# worker-side entry surface (all read env at call time, not import time)
DEFAULT_PRELOAD = (
    "jax",
    "jax.numpy",
    "optax",
    "dlrover_tpu.trainer.elastic",
)

# jax reads these env vars once at import; a forked child whose spawn
# env differs must push the new value into jax.config explicitly
_JAX_ENV_CONFIG = {
    "JAX_PLATFORMS": "jax_platforms",
    "JAX_COMPILATION_CACHE_DIR": "jax_compilation_cache_dir",
    "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": (
        "jax_persistent_cache_min_compile_time_secs"
    ),
}


def _exit_code(status: int) -> int:
    """waitpid status -> Popen-style returncode (negative signal)."""
    if os.WIFSIGNALED(status):
        return -os.WTERMSIG(status)
    if os.WIFEXITED(status):
        return os.WEXITSTATUS(status)
    return 1


def exit_record_dir(sock_path: str) -> str:
    return sock_path + ".exits"


def _record_exit(exit_dir: str, pid: int, code: int):
    """Atomically record a child's own exit code: the fallback truth
    source when the zygote (and its waitpid bookkeeping) is gone.  A
    signal-killed child writes nothing — absence means abnormal."""
    try:
        tmp = os.path.join(exit_dir, f".{pid}.tmp")
        with open(tmp, "w") as f:
            f.write(str(code))
        os.rename(tmp, os.path.join(exit_dir, str(pid)))
    except OSError:
        pass


def read_exit_record(exit_dir: str, pid: int) -> Optional[int]:
    try:
        with open(os.path.join(exit_dir, str(pid))) as f:
            return int(f.read().strip())
    except (OSError, ValueError):
        return None


def _fixup_jax_config(spawn_env: Dict[str, str]):
    """Align jax.config with the CHILD's env for import-time-captured
    settings (no-op when jax is not preloaded)."""
    jax = sys.modules.get("jax")
    if jax is None:
        return
    for env_key, cfg_key in _JAX_ENV_CONFIG.items():
        if env_key not in spawn_env:
            continue
        value: object = spawn_env[env_key]
        if cfg_key == "jax_persistent_cache_min_compile_time_secs":
            try:
                value = float(value)  # config is numeric
            except ValueError:
                continue
        try:
            jax.config.update(cfg_key, value)
        except Exception as e:  # noqa: BLE001 - best effort
            print(
                f"zygote: jax.config.update({cfg_key}) failed: {e}",
                file=sys.stderr,
                flush=True,
            )


def _run_child(argv: Sequence[str], env: Dict[str, str]) -> int:
    """Become the worker: runs in the forked child, never returns to
    the server loop (caller os._exit()s with the return value)."""
    import runpy

    os.environ.clear()
    os.environ.update(env)
    _fixup_jax_config(env)
    # the zygote ignores nothing special, but inherited dispositions
    # must not leak into trainers that install their own handlers
    signal.signal(signal.SIGTERM, signal.SIG_DFL)
    signal.signal(signal.SIGINT, signal.SIG_DFL)
    if argv and argv[0] == "-m":
        sys.argv = list(argv[1:])
        target, mode = argv[1], "module"
    else:
        sys.argv = list(argv)
        target, mode = argv[0], "path"
    try:
        if mode == "module":
            runpy.run_module(
                target, run_name="__main__", alter_sys=True
            )
        else:
            runpy.run_path(target, run_name="__main__")
        return 0
    except SystemExit as e:
        code = e.code
        if code is None:
            return 0
        return code if isinstance(code, int) else 1
    except BaseException:  # noqa: BLE001 - worker crash surface
        import traceback

        traceback.print_exc()
        return 1


class ZygoteServer:
    """Single-threaded fork server (run via ``python -m
    dlrover_tpu.agent.zygote``)."""

    def __init__(self, sock_name: str, preload: Sequence[str]):
        self._path = _socket_path(sock_name)
        if os.path.exists(self._path):
            os.unlink(self._path)
        self._listener = socket.socket(
            socket.AF_UNIX, socket.SOCK_STREAM
        )
        self._listener.bind(self._path)
        self._listener.listen(4)
        self._listener.settimeout(0.2)
        self._exit_codes: Dict[int, int] = {}
        self._live: set = set()
        self._conn: Optional[socket.socket] = None
        # children record their OWN exit code here (exit_record_dir):
        # if the zygote dies, the agent can still distinguish a clean
        # worker completion from a crash instead of failing the rank
        self._exit_dir = exit_record_dir(self._path)
        os.makedirs(self._exit_dir, exist_ok=True)
        for stale in os.listdir(self._exit_dir):
            try:
                os.unlink(os.path.join(self._exit_dir, stale))
            except OSError:
                pass
        self._preload(preload)

    def _preload(self, modules: Sequence[str]):
        import importlib

        t0 = time.time()
        for mod in modules:
            try:
                importlib.import_module(mod)
            except Exception as e:  # noqa: BLE001
                print(
                    f"zygote: preload {mod} failed: {e}",
                    file=sys.stderr,
                    flush=True,
                )
        jax = sys.modules.get("jax")
        if jax is not None:
            # a live backend would not survive fork — refuse to serve.
            # The check reads a private attribute; if a jax upgrade
            # moves it the guard must DEGRADE LOUDLY, not silently
            # vanish (ADVICE-r4)
            bridge = getattr(
                getattr(jax, "_src", None), "xla_bridge", None
            )
            backends = getattr(bridge, "_backends", None)
            if bridge is None or backends is None:
                print(
                    "zygote: WARNING jax._src.xla_bridge._backends "
                    "not found — cannot verify no backend was "
                    "initialized by preload modules; forked workers "
                    "may inherit a broken backend",
                    file=sys.stderr,
                    flush=True,
                )
            elif backends:
                raise RuntimeError(
                    "zygote preload initialized a jax backend; "
                    "remove the offending preload module"
                )
        print(
            f"zygote: ready ({len(modules)} modules in "
            f"{time.time() - t0:.1f}s)",
            file=sys.stderr,
            flush=True,
        )

    def _reap(self):
        while True:
            try:
                pid, status = os.waitpid(-1, os.WNOHANG)
            except ChildProcessError:
                return
            if pid == 0:
                return
            self._live.discard(pid)
            self._exit_codes[pid] = _exit_code(status)

    def _spawn(self, argv: Sequence[str], env: Dict[str, str]) -> int:
        pid = os.fork()
        if pid != 0:
            # the kernel recycles pids: stale exit state recorded for a
            # PREVIOUS child under this pid would make poll report the
            # old exit code for the live worker
            self._exit_codes.pop(pid, None)
            try:
                os.unlink(os.path.join(self._exit_dir, str(pid)))
            except OSError:
                pass
        if pid == 0:
            code = 1
            try:
                # drop BOTH server fds: a worker holding the accepted
                # agent connection would keep it from seeing EOF after
                # a zygote crash (poll RPCs would block to timeout)
                self._listener.close()
                if self._conn is not None:
                    self._conn.close()
                code = _run_child(argv, env)
            finally:
                code = code if isinstance(code, int) else 1
                _record_exit(self._exit_dir, os.getpid(), code)
                os._exit(code)
        self._live.add(pid)
        return pid

    def _handle(self, req) -> Tuple:
        cmd = req.get("cmd")
        if cmd == "spawn":
            # the entrypoint always starts with a python executable;
            # the fork IS the interpreter, so drop it
            argv = list(req["argv"])
            if argv and os.path.basename(argv[0]).startswith("python"):
                argv = argv[1:]
            return ("ok", self._spawn(argv, req["env"]))
        if cmd == "poll":
            self._reap()
            return ("ok", self._exit_codes.get(req["pid"]))
        if cmd == "ping":
            return ("ok", os.getpid())
        if cmd == "shutdown":
            return ("bye", None)
        return ("err", f"unknown cmd {cmd!r}")

    def serve_forever(self):
        try:
            while True:
                self._reap()
                if self._conn is None:
                    try:
                        self._conn, _ = self._listener.accept()
                        self._conn.settimeout(0.2)
                    except socket.timeout:
                        continue
                try:
                    req = _recv_msg(self._conn)
                except socket.timeout:
                    continue
                except (ConnectionError, EOFError, OSError):
                    self._conn.close()
                    self._conn = None
                    continue
                resp = self._handle(req)
                try:
                    _send_msg(self._conn, resp)
                except OSError:
                    self._conn.close()
                    self._conn = None
                if resp[0] == "bye":
                    return
        finally:
            if self._conn is not None:
                self._conn.close()
            self._listener.close()
            try:
                os.unlink(self._path)
            except OSError:
                pass


class ZygoteHandle:
    """Popen-shaped handle for a zygote-forked worker."""

    def __init__(self, pid: int, pool: "ZygotePool"):
        self.pid = pid
        self._pool = pool
        self.returncode: Optional[int] = None

    def poll(self) -> Optional[int]:
        if self.returncode is not None:
            return self.returncode
        try:
            self.returncode = self._pool._rpc(
                {"cmd": "poll", "pid": self.pid}
            )
        except (ConnectionError, OSError):
            # zygote gone: its children were reparented to init and
            # keep running.  The child's own exit record is consulted
            # FIRST: after a clean exit the kernel may recycle the pid
            # for an unrelated process, and a liveness probe alone
            # would then report the dead rank as running forever
            # (ADVICE-r4).  A signal death writes no record; only then
            # does the probe decide alive vs ORPHAN_EXIT.
            recorded = read_exit_record(
                self._pool.exit_dir, self.pid
            )
            if recorded is not None:
                self.returncode = recorded
            else:
                try:
                    os.kill(self.pid, 0)
                except ProcessLookupError:
                    self.returncode = ZygotePool.ORPHAN_EXIT
        return self.returncode

    def wait(self, timeout: Optional[float] = None) -> int:
        deadline = None if timeout is None else time.time() + timeout
        while True:
            rc = self.poll()
            if rc is not None:
                return rc
            if deadline is not None and time.time() > deadline:
                raise subprocess.TimeoutExpired(
                    f"zygote-worker-{self.pid}", timeout
                )
            time.sleep(0.05)

    def send_signal(self, sig: int):
        if self.poll() is None:
            try:
                os.kill(self.pid, sig)
            except ProcessLookupError:
                pass

    def terminate(self):
        self.send_signal(signal.SIGTERM)

    def kill(self):
        self.send_signal(signal.SIGKILL)


class ZygotePool:
    """Agent-side client; spawns workers through the fork server.

    ``spawn`` transparently falls back to ``subprocess.Popen`` when the
    zygote is missing or broken — worker startup must never fail
    because the LATENCY optimization did.
    """

    # sentinel returncode when the zygote died and took the exit
    # status with it (nonzero -> the agent treats the worker as failed)
    ORPHAN_EXIT = -257

    def __init__(
        self,
        name: str = "zygote",
        preload: Sequence[str] = DEFAULT_PRELOAD,
        start_timeout: float = 120.0,
    ):
        self._sock_name = name
        self._preload = tuple(preload)
        self._start_timeout = start_timeout
        self._proc: Optional[subprocess.Popen] = None
        self._sock: Optional[socket.socket] = None

    @property
    def exit_dir(self) -> str:
        return exit_record_dir(_socket_path(self._sock_name))

    # ----------------------------------------------------------- server
    def start(
        self, env: Optional[Dict[str, str]] = None, wait: bool = False
    ) -> bool:
        """Launch the fork server with the agent's worker base env.

        Non-blocking by default: preload takes seconds and the FIRST
        worker launch shouldn't wait on it — ``spawn`` quietly falls
        back to plain Popen until the zygote answers.  ``wait=True``
        blocks until ready (tests)."""
        env = dict(env or os.environ)
        # the server must import dlrover_tpu regardless of how the
        # caller made it importable (sys.path edits don't inherit)
        import dlrover_tpu

        pkg_root = os.path.dirname(
            os.path.dirname(os.path.abspath(dlrover_tpu.__file__))
        )
        parts = env.get("PYTHONPATH", "").split(os.pathsep)
        if pkg_root not in parts:
            env["PYTHONPATH"] = os.pathsep.join(
                p for p in [pkg_root, *parts] if p
            )
        self._proc = subprocess.Popen(  # noqa: S603
            [
                sys.executable,
                "-m",
                "dlrover_tpu.agent.zygote",
                "--socket",
                self._sock_name,
                "--preload",
                ",".join(self._preload),
            ],
            env=env,
        )
        if not wait:
            return True
        deadline = time.time() + self._start_timeout
        while time.time() < deadline:
            if self._proc.poll() is not None:
                logger.warning(
                    "zygote exited %s during startup",
                    self._proc.returncode,
                )
                return False
            try:
                if self._rpc({"cmd": "ping"}):
                    return True
            except (ConnectionError, OSError):
                time.sleep(0.2)
        logger.warning("zygote did not come up; using plain spawn")
        self.close()
        return False

    def _connect(self) -> socket.socket:
        if self._sock is None:
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            s.settimeout(10.0)
            s.connect(_socket_path(self._sock_name))
            self._sock = s
        return self._sock

    def _rpc(self, req):
        try:
            sock = self._connect()
            _send_msg(sock, req)
            status, result = _recv_msg(sock)
        except (ConnectionError, OSError, socket.timeout):
            if self._sock is not None:
                self._sock.close()
                self._sock = None
            raise ConnectionError("zygote unreachable")
        if status == "err":
            raise RuntimeError(result)
        return result

    @property
    def alive(self) -> bool:
        return self._proc is not None and self._proc.poll() is None

    # ----------------------------------------------------------- spawn
    def spawn(self, argv: List[str], env: Dict[str, str]):
        """Fork a worker (zygote) or Popen it (fallback); returns a
        Popen-shaped handle either way."""
        if self.alive:
            try:
                pid = self._rpc(
                    {"cmd": "spawn", "argv": argv, "env": env}
                )
                return ZygoteHandle(pid, self)
            except ConnectionError:
                # normal during the preload window right after start()
                logger.info("zygote not ready; plain spawn")
            except RuntimeError as e:
                logger.warning(
                    "zygote spawn failed (%s); plain spawn", e
                )
        return subprocess.Popen(argv, env=env)  # noqa: S603

    def close(self):
        if self._sock is not None:
            try:
                _send_msg(self._sock, {"cmd": "shutdown"})
                _recv_msg(self._sock)
            except (ConnectionError, OSError, socket.timeout, EOFError):
                pass
            self._sock.close()
            self._sock = None
        if self._proc is not None:
            if self._proc.poll() is None:
                self._proc.terminate()
                try:
                    self._proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    self._proc.kill()
                    self._proc.wait()
            self._proc = None


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="dlrover-tpu-zygote")
    parser.add_argument("--socket", required=True)
    parser.add_argument(
        "--preload", default=",".join(DEFAULT_PRELOAD)
    )
    args = parser.parse_args(argv)
    preload = [m for m in args.preload.split(",") if m]
    server = ZygoteServer(args.socket, preload)
    server.serve_forever()
    return 0


if __name__ == "__main__":
    sys.exit(main())
