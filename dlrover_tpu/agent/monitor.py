"""Agent-side monitors: node resources, heartbeat, training progress.

Reference parity: ``dlrover/python/elastic_agent/monitor/resource.py:86``
(``ResourceMonitor``: psutil CPU/mem + per-accelerator stats reported to
the master) and ``monitor/training.py:77`` (``TorchTrainingMonitor``:
global step read from a file the training process writes).  On TPU the
per-chip stats come from the training process itself (it owns the
libtpu runtime); the agent aggregates host-level stats.
"""

import json
import os
import threading
import time
from typing import List, Optional

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.common.log import default_logger as logger

try:
    import psutil
except ImportError:  # pragma: no cover
    psutil = None


def get_process_cpu_percent() -> float:
    if psutil is None:
        return 0.0
    return psutil.cpu_percent(interval=None)


def get_used_memory_mb() -> int:
    if psutil is None:
        return 0
    return int(psutil.virtual_memory().used / 1024 / 1024)


class PeriodicReporter:
    """Daemon-thread loop calling ``_tick`` every ``interval`` seconds;
    master connectivity errors are logged, never fatal."""

    name = "periodic-reporter"

    def __init__(
        self, client: Optional[MasterClient] = None, interval: float = 15.0
    ):
        self._client = client or MasterClient.singleton_instance()
        self._interval = interval
        self._thread: Optional[threading.Thread] = None
        self._stopped = threading.Event()

    def _tick(self):
        raise NotImplementedError

    def _loop(self):
        while not self._stopped.wait(self._interval):
            try:
                self._tick()
            except ConnectionError as e:
                logger.warning("%s report failed: %s", self.name, e)

    def start(self):
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name=self.name, daemon=True
        )
        self._thread.start()

    def stop(self):
        self._stopped.set()


class ResourceMonitor(PeriodicReporter):
    """Periodically reports host CPU/memory (+ optional chip stats file)
    to the master; feeds the autoscaler / resource optimizer."""

    name = "resource-monitor"

    def __init__(
        self,
        client: Optional[MasterClient] = None,
        interval: float = 15.0,
        chip_stats_file: str = "",
    ):
        super().__init__(client, interval)
        self._chip_stats_file = chip_stats_file or os.getenv(
            "DLROVER_TPU_CHIP_STATS_FILE", ""
        )

    def _read_chip_stats(self) -> List[dict]:
        """Chip stats dropped by the training process (device memory in
        use, duty cycle) — the TPU runtime is only visible there."""
        if not self._chip_stats_file or not os.path.exists(
            self._chip_stats_file
        ):
            return []
        try:
            with open(self._chip_stats_file) as f:
                data = json.load(f)
            return data if isinstance(data, list) else [data]
        except (OSError, ValueError):
            return []

    def _tick(self):
        self._client.report_resource_stats(
            cpu_percent=get_process_cpu_percent(),
            memory_mb=get_used_memory_mb(),
            tpu_stats=self._read_chip_stats(),
        )


class HeartbeatReporter(PeriodicReporter):
    """Agent heartbeat so the master can detect dead nodes
    (reference ``dist_job_manager.py:340`` heartbeat monitor)."""

    name = "heartbeat"

    def _tick(self):
        self._client.report_heartbeat(time.time())


class TrainingMonitor(PeriodicReporter):
    """Reports the training global step to the master's SpeedMonitor by
    watching the step file the trainer writes (reference
    ``TorchTrainingMonitor`` ``monitor/training.py:77``)."""

    name = "training-monitor"

    def __init__(
        self,
        step_file: str,
        client: Optional[MasterClient] = None,
        interval: float = 15.0,
    ):
        super().__init__(client, interval)
        self._step_file = step_file
        self._last_step = -1

    def _tick(self):
        if not os.path.exists(self._step_file):
            return
        try:
            with open(self._step_file) as f:
                data = json.load(f)
            step = int(data.get("step", -1))
            ts = float(data.get("timestamp", time.time()))
        except (OSError, ValueError):
            return
        if step > self._last_step:
            # report first: a ConnectionError must not advance
            # _last_step or the step would never be re-reported
            self._client.report_global_step(step, ts)
            self._last_step = step
