"""Agent-side monitors: node resources, heartbeat, training progress.

Reference parity: ``dlrover/python/elastic_agent/monitor/resource.py:86``
(``ResourceMonitor``: psutil CPU/mem + per-accelerator stats reported to
the master) and ``monitor/training.py:77`` (``TorchTrainingMonitor``:
global step read from a file the training process writes).  On TPU the
per-chip stats come from the training process itself (it owns the
libtpu runtime); the agent aggregates host-level stats.
"""

import json
import os
import threading
import time
from typing import List, Optional

from dlrover_tpu.agent.master_client import MasterClient, ReportBuffer
from dlrover_tpu.common import messages as msg
from dlrover_tpu.common.log import default_logger as logger

try:
    import psutil
except ImportError:  # pragma: no cover
    psutil = None


def get_process_cpu_percent() -> float:
    if psutil is None:
        return 0.0
    return psutil.cpu_percent(interval=None)


def get_used_memory_mb() -> int:
    if psutil is None:
        return 0
    return int(psutil.virtual_memory().used / 1024 / 1024)


class PeriodicReporter:
    """Daemon-thread loop calling ``_tick`` every ``interval`` seconds;
    master connectivity errors are logged, never fatal.

    With a shared ``ReportBuffer`` the tick's message coalesces into
    the node's next ``BatchedReport`` envelope instead of paying its
    own RPC — heartbeats, resource stats, step samples, and timeline
    batches from one node ride together.
    """

    name = "periodic-reporter"

    def __init__(
        self,
        client: Optional[MasterClient] = None,
        interval: float = 15.0,
        buffer: Optional[ReportBuffer] = None,
    ):
        self._client = client or MasterClient.singleton_instance()
        self._interval = interval
        self._buffer = buffer
        self._thread: Optional[threading.Thread] = None
        self._stopped = threading.Event()

    def _submit(self, message: msg.Message) -> bool:
        """One report message: buffered when a ReportBuffer is wired,
        a direct RPC otherwise."""
        if self._buffer is not None:
            return self._buffer.add(message)
        return self._client._channel.report(message)

    def _tick(self):
        raise NotImplementedError

    def _loop(self):
        while not self._stopped.wait(self._interval):
            try:
                self._tick()
            except ConnectionError as e:
                logger.warning("%s report failed: %s", self.name, e)

    def start(self):
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name=self.name, daemon=True
        )
        self._thread.start()

    def stop(self):
        self._stopped.set()


class ResourceMonitor(PeriodicReporter):
    """Periodically reports host CPU/memory (+ optional chip stats file)
    to the master; feeds the autoscaler / resource optimizer."""

    name = "resource-monitor"

    def __init__(
        self,
        client: Optional[MasterClient] = None,
        interval: float = 15.0,
        chip_stats_file: str = "",
        buffer: Optional[ReportBuffer] = None,
    ):
        super().__init__(client, interval, buffer=buffer)
        self._chip_stats_file = chip_stats_file or os.getenv(
            "DLROVER_TPU_CHIP_STATS_FILE", ""
        )

    def _read_chip_stats(self) -> List[dict]:
        """Chip stats dropped by the training process (device memory in
        use, duty cycle) — the TPU runtime is only visible there."""
        if not self._chip_stats_file or not os.path.exists(
            self._chip_stats_file
        ):
            return []
        try:
            with open(self._chip_stats_file) as f:
                data = json.load(f)
            return data if isinstance(data, list) else [data]
        except (OSError, ValueError):
            return []

    def _tick(self):
        self._submit(
            msg.ResourceStats(
                cpu_percent=get_process_cpu_percent(),
                memory_mb=get_used_memory_mb(),
                tpu_stats=self._read_chip_stats(),
            )
        )


class HeartbeatReporter(PeriodicReporter):
    """Agent heartbeat so the master can detect dead nodes
    (reference ``dist_job_manager.py:340`` heartbeat monitor)."""

    name = "heartbeat"

    def _tick(self):
        self._submit(msg.HeartBeat(timestamp=time.time()))


class TrainingMonitor(PeriodicReporter):
    """Reports the training global step to the master's SpeedMonitor by
    watching the step file the trainer writes (reference
    ``TorchTrainingMonitor`` ``monitor/training.py:77``)."""

    name = "training-monitor"

    def __init__(
        self,
        step_file: str,
        client: Optional[MasterClient] = None,
        interval: float = 15.0,
        buffer: Optional[ReportBuffer] = None,
    ):
        super().__init__(client, interval, buffer=buffer)
        self._step_file = step_file
        self._last_step = -1

    def _tick(self):
        if not os.path.exists(self._step_file):
            return
        try:
            with open(self._step_file) as f:
                data = json.load(f)
            step = int(data.get("step", -1))
            ts = float(data.get("timestamp", time.time()))
        except (OSError, ValueError):
            return
        if step > self._last_step:
            # report first: a ConnectionError must not advance
            # _last_step or the step would never be re-reported (the
            # buffered path re-queues undeliverable batches instead)
            self._submit(msg.GlobalStep(step=step, timestamp=ts))
            self._last_step = step


class TimelineReporter(PeriodicReporter):
    """Tails the node-local event timeline (the JSONL every process on
    this node appends to — see ``observability/events.py``) and ships
    the delta to the master's TimelineAggregator each tick.

    Only whole lines past the last shipped offset are consumed, so a
    write caught mid-line is picked up next tick; a truncated file
    (fresh run reusing the path) resets the offset.
    """

    name = "timeline-reporter"

    def __init__(
        self,
        events_file: str,
        client: Optional[MasterClient] = None,
        interval: float = 5.0,
        max_batch: int = 1000,
        buffer: Optional[ReportBuffer] = None,
    ):
        super().__init__(client, interval, buffer=buffer)
        self._events_file = events_file
        self._offset = 0
        #: inode of the file instance ``_offset`` was measured in —
        #: how a size-based rotation is told apart from ordinary
        #: growth (the recreated file can regrow PAST the old offset
        #: between ticks, so size alone cannot detect it)
        self._ino: Optional[int] = None
        self._max_batch = max_batch

    def _read_delta(self):
        """New complete JSONL records past the shipped offset, each
        paired with the file offset consuming it advances to."""
        try:
            st = os.stat(self._events_file)
        except OSError:
            return []
        size = st.st_size
        if self._ino is None:
            self._ino = st.st_ino
        elif st.st_ino != self._ino:
            # the path points at a NEW file: a size rotation
            # (EventLogger moved ours to `.1`) or a fresh run
            # recreating the path.  On rotation the unshipped tail
            # lives in the backup — drain it first or up to one
            # reporter interval of spans (including E records the
            # master's open-span bookkeeping needs) silently
            # vanishes from the ledger.
            tail = self._read_rotated_tail(expect_ino=self._ino)
            self._ino = st.st_ino
            self._offset = 0
            if tail:
                return tail
        elif size < self._offset:
            self._offset = 0  # truncated in place
        if size == self._offset:
            return []
        try:
            with open(self._events_file, "rb") as f:
                f.seek(self._offset)
                chunk = f.read(size - self._offset)
        except OSError:
            return []
        cut = chunk.rfind(b"\n")
        if cut < 0:
            return []  # only a partial line so far
        out = []  # (record, end_offset)
        pos = self._offset
        for line in chunk[: cut + 1].splitlines(keepends=True):
            pos += len(line)
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and "name" in rec:
                out.append((rec, pos))
        # torn/blank trailing lines must still be consumed
        if out:
            out[-1] = (out[-1][0], self._offset + cut + 1)
        else:
            self._offset += cut + 1
        return out

    def _read_rotated_tail(self, expect_ino: int):
        """Whole-line records past the shipped offset in the rotated
        backup (``<events_file>.1``), with end offsets pinned to 0 so
        delivering them leaves the offset at the START of the new
        live file.  The backup must BE the file instance the offset
        was measured in (``expect_ino``) — a stale backup from an
        older run, or the middle file of a double rotation, would
        ship garbage from a misaligned offset.  Empty when absent,
        foreign, or fully shipped already."""
        backup = self._events_file + ".1"
        try:
            st = os.stat(backup)
            if st.st_ino != expect_ino or st.st_size <= self._offset:
                return []
            with open(backup, "rb") as f:
                f.seek(self._offset)
                chunk = f.read(st.st_size - self._offset)
        except OSError:
            return []
        out = []
        for line in chunk.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and "name" in rec:
                out.append((rec, 0))
        return out

    def _tick(self):
        delta = self._read_delta()
        # the offset advances PER DELIVERED BATCH: a ConnectionError
        # mid-loop re-ships only the undelivered tail next tick (no
        # duplicates for batches the master already accepted, no loss
        # for the ones it didn't).  On the BUFFERED path "delivered"
        # means handed to the ReportBuffer, which owns delivery from
        # there (front re-queue on transport failure, drained on
        # close) — the timeline batch then coalesces with heartbeats
        # and metric samples into one envelope.
        for i in range(0, len(delta), self._max_batch):
            batch = delta[i:i + self._max_batch]
            events = [rec for rec, _ in batch]
            if self._buffer is not None:
                # add() is the direct-send ack under
                # DLROVER_TPU_CONTROL_BATCH=0 and True for a buffered
                # enqueue — either way it IS the delivery verdict
                ok = self._buffer.add(
                    msg.TimelineEventsReport(events=events)
                )
            else:
                ok = self._client.report_timeline_events(events)
            if not ok:
                # master refused (no aggregator / old master): drop
                # with a trace rather than re-shipping forever
                logger.warning(
                    "master rejected a timeline batch of %d events; "
                    "dropping it", len(batch),
                )
            self._offset = batch[-1][1]

    def flush(self):
        """One synchronous drain (agent shutdown / tests)."""
        try:
            self._tick()
            if self._buffer is not None:
                self._buffer.flush()
        except ConnectionError as e:
            logger.warning("timeline flush failed: %s", e)
