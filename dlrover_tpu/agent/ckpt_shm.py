"""Shared-memory checkpoint shard handling, used on both sides of the
agent/training-process boundary.

Reference parity: ``dlrover/python/elastic_agent/torch/ckpt_saver.py:
175-345`` (``SharedMemoryHandler``: tensors are memcpy'd into a pinned
shm buffer, metadata lives in a ``SharedDict``).  TPU twist: leaves are
JAX arrays; each training process snapshots its *addressable shards*
(``jax.device_get`` of fully-replicated or per-host-sharded arrays) so a
multi-host GSPMD checkpoint is the union of per-process shard files.

Layout of one shard:
- shm segment ``dlrover_tpu_shm_ckpt_{name}_{rank}``: concatenated raw
  array bytes.
- SharedDict ``ckpt_meta_{name}_{rank}``: {"step", "specs":
  [(keypath, dtype, shape, offset, nbytes)], "total_bytes", "valid"}.

File format of a persisted shard (``*.drckpt``): 8-byte little-endian
header length + pickled meta + raw bytes (same offsets as shm), so the
agent persists with a single pass over the shm buffer.
"""

import pickle
import struct
import time as _time
from typing import Dict, List, Optional, Tuple

import numpy as np

from dlrover_tpu.common import parallel_io
from dlrover_tpu.common.fault_injection import maybe_crash
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.multi_process import (
    SharedDict,
    SharedLock,
    SharedMemory,
)

SHM_PREFIX = "dlrover_tpu_ckpt"
_HDR = struct.Struct("<Q")
#: generation side-segment payload: published step + 1 (0 = none)
_GEN = struct.Struct("<q")


def _flatten_keyed(tree) -> List[Tuple[str, object]]:
    """Flatten a pytree to (keypath, leaf) pairs in a deterministic
    order, launching every device->host transfer async up front so the
    copies pipeline instead of serializing.  Leaves stay un-materialized
    (device arrays) — the caller drains each one straight into its final
    destination, so at most ONE leaf-sized host buffer is live at a time
    instead of a full extra copy of the state."""
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    for _, leaf in flat:
        if hasattr(leaf, "copy_to_host_async"):
            try:
                leaf.copy_to_host_async()
            except Exception:  # noqa: BLE001 - deleted/donated buffer
                pass
    return [(jax.tree_util.keystr(path), leaf) for path, leaf in flat]


def restore_to_target(target, arrays: Dict[str, np.ndarray],
                      to_device: bool = True, copy_host: bool = False):
    """Map {keypath: array} back onto the structure of ``target``.

    When ``to_device`` and a target leaf is a committed ``jax.Array``,
    the restored value is transferred with ``jax.device_put`` onto that
    leaf's sharding in ONE batched call (transfers overlap; safe to feed
    zero-copy shm views — the call blocks until buffers are on device).
    ``copy_host=True`` additionally copies values that stay on host
    (required when ``arrays`` holds zero-copy shm views: the next
    snapshot would otherwise mutate the restored state in place).
    """
    import jax

    flat, treedef = jax.tree_util.tree_flatten_with_path(target)
    leaves = []
    shardings = []
    for path, leaf in flat:
        key = jax.tree_util.keystr(path)
        if key not in arrays:
            raise KeyError(f"checkpoint missing leaf {key}")
        value = arrays[key]
        if hasattr(leaf, "dtype") and value.dtype != leaf.dtype:
            value = value.astype(leaf.dtype)
        sharding = (
            leaf.sharding
            if to_device and isinstance(leaf, jax.Array)
            else None
        )
        if sharding is None and copy_host and isinstance(value, np.ndarray):
            value = np.array(value, copy=True)
        leaves.append(value)
        shardings.append(sharding)
    if any(s is not None for s in shardings):
        put = jax.device_put(
            [v for v, s in zip(leaves, shardings) if s is not None],
            [s for s in shardings if s is not None],
        )
        jax.block_until_ready(put)
        it = iter(put)
        leaves = [
            next(it) if s is not None else v
            for v, s in zip(leaves, shardings)
        ]
    return jax.tree_util.tree_unflatten(treedef, leaves)


class SharedMemoryHandler:
    """One checkpoint shard in shared memory (one per training process).

    The training-process side writes (``save_state``); the agent-side
    saver reads (``read_raw``/``load_state``).  Both sides synchronize
    through the companion ``SharedLock`` owned by the agent.
    """

    def __init__(self, rank: int, name: str = "default",
                 host: bool = False):
        # host=True on the agent side (creates the meta dict service)
        self._rank = rank
        self._name = name
        self._shm_name = f"{SHM_PREFIX}_{name}_{rank}"
        self._shm: Optional[SharedMemory] = None
        self._gen_name = f"{SHM_PREFIX}_gen_{name}_{rank}"
        self._gen: Optional[SharedMemory] = None
        self.meta = SharedDict(f"ckpt_meta_{name}_{rank}", create=host)

    # -- writer (training process) ----------------------------------------
    NUM_SLOTS = 2  # double-buffer: previous snapshot survives a crash
    _ALIGN = 4096

    def save_state(self, step: int, tree, layouts=None) -> int:
        """Snapshot a pytree into shm; returns total bytes written.

        ``layouts`` ({keypath: LeafLayout dict}, see
        ``trainer/checkpoint/reshard.py``) is the per-leaf
        global-layout header: the leaf's global shape plus this
        shard's index slice.  It rides the slot meta and every
        persisted ``.drckpt`` header, making the shard readable by
        ANY world size (resharded restore).  None keeps the legacy
        world-locked format.

        Single-pass drain: specs are computed from leaf metadata (no
        transfer), then each leaf is materialized and copied into its
        shm slot one at a time — peak extra host memory is one leaf,
        not a full second copy of the state.

        Double-buffered: consecutive saves alternate between two
        regions of the segment, and the top-level meta keeps pointing
        at the previous (complete) snapshot until the new one is fully
        written.  A crash mid-write therefore never destroys the last
        restorable state — the failure mode behind torn multi-rank
        checkpoints (one rank at step N+1, a killed peer at N) becomes
        recoverable: step N is still present in the survivor's other
        slot."""
        pairs = _flatten_keyed(tree)
        specs = []
        offset = 0
        for key, leaf in pairs:
            # hasattr guards, NOT getattr defaults: a getattr default
            # argument is evaluated eagerly, and np.asarray(leaf) on a
            # jax array blocks on the D2H transfer and pins the host
            # copy — for every leaf at once
            if hasattr(leaf, "dtype") and hasattr(leaf, "shape"):
                dtype = np.dtype(leaf.dtype)
                shape = tuple(leaf.shape)
            else:
                arr = np.asarray(leaf)
                dtype, shape = arr.dtype, arr.shape
            nbytes = int(dtype.itemsize * int(np.prod(shape or (1,))))
            specs.append((key, str(dtype), shape, offset, nbytes))
            offset += nbytes
        total = offset

        meta_all = self.meta.get_all()
        stride = int(meta_all.get("stride", 0))
        slots = dict(meta_all.get("slots", {}))
        last = int(meta_all.get("last_slot", self.NUM_SLOTS - 1))
        if total > stride:
            # state grew past the region stride: the segment will be
            # unlinked and recreated zero-filled, so EVERY old snapshot
            # dies — invalidate the meta BEFORE touching the segment
            # (a crash between recreate and meta write must not present
            # the zeroed buffer as the old step-N checkpoint)
            stride = -(-total // self._ALIGN) * self._ALIGN
            slots = {}
            self.mark_invalid()
        slot = (last + 1) % self.NUM_SLOTS
        base = slot * stride

        # before touching the region: repoint the restorable snapshot
        # at the OTHER slot (or mark nothing-restorable when it holds
        # no complete state) so a crash mid-write stays recoverable
        slots[str(slot)] = {"valid": False}
        other = slots.get(str((slot + 1) % self.NUM_SLOTS))
        header = {"slots": slots, "stride": stride, "last_slot": last}
        if other and other.get("valid"):
            repoint = dict(
                header,
                step=other["step"],
                specs=other["specs"],
                total_bytes=other["total_bytes"],
                base=other["base"],
                valid=True,
            )
            # explicit None beats key-absence: SharedDict.update
            # merges, so a stale top-level layouts entry from an
            # earlier save would otherwise describe the wrong specs
            repoint["layouts"] = other.get("layouts")
            self.meta.update(repoint)
        else:
            self.meta.update(dict(header, valid=False))

        self._ensure_shm(self.NUM_SLOTS * stride)
        self._drain_leaves(pairs, specs, base)

        # torn-publish chaos hook: a kill landing here leaves the new
        # slot fully written but the meta still pointing at the OTHER
        # valid slot — readers keep serving the previous generation
        maybe_crash("mid_weight_publish")

        slot_meta = {
            "step": step,
            "specs": specs,
            "total_bytes": total,
            "base": base,
            "valid": True,
        }
        slot_meta["layouts"] = dict(layouts) if layouts else None
        slots[str(slot)] = slot_meta
        self.meta.update(
            dict(
                slot_meta,
                slots=slots,
                stride=stride,
                last_slot=slot,
            )
        )
        return total

    def _drain_leaves(self, pairs, specs, base: int):
        """Two-stage leaf pipeline into shm.

        Stage A (pool thread): materialize leaf k+1's host copy
        (``np.asarray`` lands the async D2H transfer launched in
        ``_flatten_keyed``).  Stage B (this thread): chunk-parallel
        memcpy of leaf k into its shm slot.  The stages overlap, so
        the drain's wall time is max(D2H, shm memcpy) per leaf instead
        of their sum; leaves above the chunk threshold additionally
        split across the pool inside ``parallel_memcpy``.  Peak extra
        host memory stays at two leaves (the one copying + the one
        materializing).  With ``DLROVER_TPU_CKPT_COPY_WORKERS=1`` both
        stages run inline on this thread — the exact serial pre-change
        path, byte for byte.
        """
        buf = self._shm.buf
        pipelined = parallel_io.copy_workers() > 1
        items = list(zip(pairs, specs))
        pending = (
            parallel_io.submit(np.asarray, items[0][0][1])
            if pipelined and items
            else None
        )
        for i, ((_key, leaf), (_, dts, shape, off, _nb)) in enumerate(
            items
        ):
            if pending is not None:
                arr = pending.result()
                pending = (
                    parallel_io.submit(np.asarray, items[i + 1][0][1])
                    if i + 1 < len(items)
                    else None
                )
            else:
                arr = np.asarray(leaf)
            dst = np.ndarray(shape, dtype=np.dtype(dts), buffer=buf,
                             offset=base + off)
            if arr.dtype == dst.dtype and arr.flags.c_contiguous:
                parallel_io.parallel_memcpy(dst, arr)
            else:  # exotic leaf (cast or strided): plain copy
                np.copyto(dst, arr)

    def mark_invalid(self):
        self.meta.update({"valid": False, "slots": {}})

    # -- generation side-segment (flywheel weight publish) ----------------
    # One little-endian int64 in its own tiny shm segment holding the
    # last PUBLISHED step + 1 (0 = nothing published).  Readers poll it
    # with a single shared-memory load — no SharedDict RPC — so a
    # replica can skip all adopt work when the generation hasn't moved.
    # The writer bumps it only AFTER ``save_state`` returns (meta flipped
    # valid), so a torn publish never advances the generation.

    def _attach_gen(self, create: bool = False) -> Optional[SharedMemory]:
        if self._gen is None:
            try:
                self._gen = SharedMemory(
                    self._gen_name, create=create, size=_GEN.size
                )
            except FileNotFoundError:
                return None
            except FileExistsError:
                # a restarted publisher re-attaches the live segment
                self._gen = SharedMemory(self._gen_name, create=False)
        return self._gen

    def publish_generation(self, step: int):
        """Stamp ``step`` as the published generation (writer side;
        call after a successful ``save_state``)."""
        seg = self._attach_gen(create=True)
        _GEN.pack_into(seg.buf, 0, int(step) + 1)

    def peek_generation(self) -> int:
        """Last published generation, or -1 when the writer has never
        published (segment absent / zero).  One atomic-width load —
        safe to call every scheduler iteration."""
        seg = self._attach_gen(create=False)
        if seg is None:
            return -1
        return int(_GEN.unpack_from(seg.buf, 0)[0]) - 1

    def steps_available(self):
        """Steps restorable from this segment, newest first (the active
        snapshot plus the surviving previous slot)."""
        meta = self.meta.get_all()
        steps = set()
        if meta.get("valid"):
            steps.add(int(meta.get("step", -1)))
        for slot_meta in meta.get("slots", {}).values():
            if slot_meta.get("valid"):
                steps.add(int(slot_meta.get("step", -1)))
        return sorted((s for s in steps if s >= 0), reverse=True)

    def _resolve_slot(self, meta: Dict, step: Optional[int]):
        """Slot meta holding ``step`` (None = newest valid) or None."""
        if step is None or (
            meta.get("valid") and meta.get("step") == step
        ):
            return meta if meta.get("valid") else None
        for slot_meta in meta.get("slots", {}).values():
            if slot_meta.get("valid") and slot_meta.get("step") == step:
                return slot_meta
        return None

    def preallocate(self, nbytes: int):
        """Create the segment and fault in its pages ahead of the first
        snapshot (the first save otherwise pays segment creation + page
        allocation on the hot path — observed ~80 s for 3 GB vs ~0.5 s
        warm; reference pre-attaches shm at engine init,
        ``ckpt_saver.py:210``)."""
        if self.get_step() >= 0 and self.attach(min_size=nbytes):
            # a valid snapshot survives in the segment (e.g. this is a
            # relaunched process): its pages are already faulted in and
            # zeroing them would destroy the restorable state
            logger.info(
                "rank %s: shm already holds a valid step-%s snapshot; "
                "skipping preallocation", self._rank, self.get_step(),
            )
            return
        start = _time.time()
        # the segment is about to be (re)created and zero-filled: stale
        # meta saying valid=True over a fresh all-zero buffer would let
        # a restore present zeros as a real step-N checkpoint (also
        # covers a crash mid-zeroing)
        self.mark_invalid()
        stride = -(-nbytes // self._ALIGN) * self._ALIGN
        self.meta.update({"stride": stride})
        self._ensure_shm(self.NUM_SLOTS * stride)
        view = np.ndarray((self._shm.size,), dtype=np.uint8,
                          buffer=self._shm.buf)
        # touch every page (tmpfs allocates lazily); first-touch
        # faulting serializes on one core (measured 0.17 vs 7.7 GB/s
        # resident), so the fill is chunked ACROSS the worker pool
        parallel_io.parallel_fill(view, 0)
        logger.info(
            "rank %s: preallocated %.1f MB shm in %.2fs "
            "(%.2f GB/s, workers=%s)",
            self._rank, self._shm.size / 1e6, _time.time() - start,
            parallel_io.throughput_gbps(
                self._shm.size, _time.time() - start
            ),
            parallel_io.copy_workers(),
        )

    def _ensure_shm(self, size: int):
        if self._shm is None or self._shm.size < size:
            if self._shm is not None:
                self._shm.close()
            # the wrapper's create=True implements the full segment
            # lifecycle policy this path needs: ATTACH an existing
            # adequately-sized segment (a relaunched process's
            # predecessor may hold the only crash-survivable snapshot
            # — it must never be zeroed), and only on genuine growth
            # unlink-then-recreate (callers already invalidated the
            # meta, so the old snapshots are dead either way).
            # Behavior pinned by test_parallel_io.TestEnsureShmGrowth.
            self._shm = SharedMemory(
                self._shm_name, create=True, size=max(size, 1)
            )

    # -- reader (agent or restarted training process) ----------------------
    def attach(self, min_size: int = 0) -> bool:
        """Attach to the segment; re-attach when the writer grew and
        recreated it (a stale mapping would silently truncate reads)."""
        if self._shm is not None and self._shm.size < min_size:
            self._shm.close()
            self._shm = None
        if self._shm is not None:
            return True
        try:
            self._shm = SharedMemory(self._shm_name)
        except FileNotFoundError:
            return False
        if min_size and self._shm.size < min_size:
            # segment exists but is the old, smaller generation
            self._shm.close()
            self._shm = None
            return False
        # a fresh attach (restarted process) minor-faults every page
        # on first read; WILLNEED lets the kernel populate the PTEs
        # ahead of the restore's sequential pass instead of one fault
        # per 4 KiB inside it (VERDICT-r3 weak #4: the first-touch
        # read ran at 0.086 GB/s vs 4.4 resident)
        try:
            import mmap as _mmap

            self._shm._mmap.madvise(_mmap.MADV_WILLNEED)
        except (AttributeError, OSError, ValueError):
            pass  # private CPython detail; purely advisory
        return True

    def get_step(self) -> int:
        meta = self.meta.get_all()
        if not meta.get("valid"):
            return -1
        return meta.get("step", -1)

    def slot_layouts(self, step: Optional[int] = None):
        """The global-layout header of the slot holding ``step``
        (None = newest valid), or None when the slot predates layout
        headers / does not exist."""
        slot = self._resolve_slot(self.meta.get_all(), step)
        if slot is None:
            return None
        return slot.get("layouts") or None

    def slot_shapes(self, step: Optional[int] = None):
        """{keypath: local shape} of the slot holding ``step``, read
        from the meta specs alone — no shm attach, no leaf views."""
        slot = self._resolve_slot(self.meta.get_all(), step)
        if slot is None:
            return None
        return {
            key: tuple(int(d) for d in shape)
            for key, _dt, shape, _off, _nb in slot["specs"]
        }

    def load_state(
        self, copy: bool = True, step: Optional[int] = None
    ) -> Tuple[int, Dict[str, np.ndarray]]:
        """Rebuild {keypath: ndarray} from shm.

        ``copy=True`` returns standalone arrays (ONE bulk memcpy; shm
        may be overwritten afterwards).  Cost note: the copy's wall
        time is dominated by FIRST-TOUCH page faults of the fresh
        private buffer, not memcpy (measured 0.17 GB/s faulting vs
        7.7 GB/s resident in the build container) — which is why
        ``copy=False`` zero-copy views are the restore hot path (feed
        them straight to ``jax.device_put`` and drop them before the
        slot is reused, two snapshots later).

        ``step`` selects a specific restorable step (either slot);
        None = the newest complete snapshot."""
        meta = self.meta.get_all()
        slot = self._resolve_slot(meta, step)
        if slot is None:
            return -1, {}
        base = int(slot.get("base", 0))
        total = slot.get("total_bytes", 0)
        if not self.attach(min_size=base + total):
            return -1, {}
        arrays = {}
        buf = self._shm.buf
        if copy:
            # ONE bulk memcpy of the used region into a private buffer,
            # then slice views onto it.  The copy is chunk-parallel:
            # its wall time is dominated by FIRST-TOUCH faults of the
            # fresh private pages, which serialize per-core — N workers
            # fault N page ranges concurrently.
            private = np.empty(total, dtype=np.uint8)
            parallel_io.parallel_memcpy(
                private,
                np.ndarray((total,), dtype=np.uint8, buffer=buf,
                           offset=base),
            )
            buf = private.data
            base = 0
        for key, dtype, shape, off, nbytes in slot["specs"]:
            arrays[key] = np.ndarray(
                tuple(shape), dtype=np.dtype(dtype), buffer=buf,
                offset=base + off,
            )
        return slot.get("step", -1), arrays

    def dump_to_file(
        self, path: str, storage, step: Optional[int] = None
    ) -> Optional[int]:
        """Persist header+raw shm bytes to ``path`` (agent side).
        ``step`` selects which slot to persist (None = newest).
        Returns the raw bytes written, or None on failure."""
        meta = self.meta.get_all()
        slot = self._resolve_slot(meta, step)
        if slot is None:
            logger.warning(
                "no valid shm checkpoint for rank %s (step=%s)",
                self._rank, step,
            )
            return None
        base = int(slot.get("base", 0))
        total = slot["total_bytes"]
        if not self.attach(min_size=base + total):
            logger.warning("shm segment missing for rank %s", self._rank)
            return None
        file_meta = {"step": slot["step"], "specs": slot["specs"]}
        if slot.get("layouts"):
            # the device-count-agnostic header: with per-leaf global
            # layouts in the file, ANY world size can reassemble any
            # leaf from whichever shards cover its new slices
            file_meta["layouts"] = slot["layouts"]
        header = pickle.dumps(file_meta)
        # stream header + BOUNDED zero-copy slices of the shm buffer:
        # the agent never materializes a second shard-sized object,
        # and backends that buffer per-chunk (multipart uploads) see
        # chunk-sized pieces instead of one multi-GB write
        view = memoryview(self._shm.buf)[base : base + total]
        try:
            def _chunks():
                yield _HDR.pack(len(header))
                yield header
                for off, n in parallel_io.chunked_iter(total):
                    yield view[off : off + n]

            storage.write_chunks(_chunks(), path)
        finally:
            view.release()
        return int(total)

    def unlink_name(self):
        """Remove the segment's /dev/shm name WITHOUT closing the
        mapping (POSIX: safe while mapped; the memory dies when the
        last process unmaps).  For teardown paths that must leave live
        buffer views untouched."""
        try:
            if self._shm is not None:
                self._shm.unlink()
            else:
                shm = SharedMemory(self._shm_name)
                shm.unlink()
                shm.close()  # drop the just-created mapping
        except FileNotFoundError:
            pass
        except Exception as e:  # noqa: BLE001
            logger.warning("unlink of %s failed: %s", self._shm_name, e)

    def close(self, unlink: bool = False):
        if self._shm is not None:
            self._shm.close()
            if unlink:
                self._shm.unlink()
            self._shm = None
        if self._gen is not None:
            self._gen.close()
            if unlink:
                try:
                    self._gen.unlink()
                except FileNotFoundError:
                    pass
            self._gen = None
        self.meta.close()


class TruncatedShardError(ValueError):
    """The shard file ended before the raw section was complete."""


def stream_shard_leaves(path: str, storage=None):
    """Generator over a persisted ``*.drckpt`` shard, leaf by leaf.

    Yields ``("meta", step, specs, layouts)`` first (``layouts`` is
    the per-leaf global-layout header dict, or None for old-format
    files), then ``("leaf", key, ndarray)`` for each leaf THE MOMENT
    its bytes land, in file (offset) order.  All leaf views share ONE preallocated private
    buffer (the ``read_shard_file`` memory discipline) — peak memory
    is the shard size.  The leaf-granular stream is what lets a
    restore consumer pipeline ``device_put`` against the tail of the
    read (trainer/checkpoint restart prefetch) instead of waiting on
    a whole-shard barrier.

    Raises :class:`TruncatedShardError` on a short file; propagates
    the backend's own errors on absence.
    """
    if storage is not None:
        f = storage.open_read(path)
    else:
        f = open(path, "rb")
    with f:
        hdr = f.read(_HDR.size)
        if not hdr or len(hdr) < _HDR.size:
            raise TruncatedShardError(f"no header in {path}")
        (hdr_len,) = _HDR.unpack(hdr)
        meta = pickle.loads(f.read(hdr_len))
        specs = meta["specs"]
        total = max(
            (int(off) + int(nbytes) for _k, _d, _s, off, nbytes in specs),
            default=0,
        )
        yield "meta", meta.get("step", -1), specs, meta.get("layouts")
        raw = np.empty(total, dtype=np.uint8)
        mv = memoryview(raw)
        filled = 0
        chunk = parallel_io.chunk_nbytes()

        def _fill_to(limit: int):
            nonlocal filled
            while filled < limit:
                want = min(chunk, limit - filled)
                if hasattr(f, "readinto"):
                    got = f.readinto(mv[filled : filled + want])
                else:  # buffered remote reader without readinto
                    data = f.read(want)
                    got = len(data)
                    if got:
                        mv[filled : filled + got] = data
                if not got:
                    raise TruncatedShardError(
                        f"truncated shard file {path} "
                        f"({filled} of {total} raw bytes)"
                    )
                filled += got

        # specs are written in increasing-offset order (save_state);
        # sort defensively so a reordered header can't yield a leaf
        # whose bytes haven't landed
        for key, dtype, shape, off, nbytes in sorted(
            specs, key=lambda s: int(s[3])
        ):
            _fill_to(int(off) + int(nbytes))
            yield "leaf", key, np.ndarray(
                tuple(shape), dtype=np.dtype(dtype), buffer=raw,
                offset=int(off),
            )


def read_shard_file(path: str, storage=None) -> Tuple[int, Dict[str, np.ndarray]]:
    """Load a persisted ``*.drckpt`` shard.

    Streams the raw section straight into ONE preallocated private
    buffer in bounded chunks and hands out zero-copy leaf views onto
    it — peak memory is the shard size, not the former raw-bytes
    object + a ``.copy()`` per leaf (2× shard RAM).
    """
    try:
        step, arrays = -1, {}
        for item in stream_shard_leaves(path, storage):
            if item[0] == "meta":
                step = item[1]
            else:
                arrays[item[1]] = item[2]
        return step, arrays
    except TruncatedShardError as e:
        logger.warning("%s", e)
        return -1, {}
    except (FileNotFoundError, IsADirectoryError):
        if storage is not None:
            # genuine absence maps to "no checkpoint", matching the
            # old storage.read()->b"" semantics; transient IO errors
            # still raise.  A bare LOCAL path keeps raising on
            # absence (pre-change behavior): callers like the orbax
            # merge list-then-read and must fail loudly if a shard
            # vanishes mid-merge, not export a partial checkpoint.
            return -1, {}
        raise


def shard_lock(rank: int, name: str = "default", create: bool = False) -> SharedLock:
    return SharedLock(f"ckpt_{name}_{rank}", create=create)
