"""The per-node elastic agent: rendezvous, worker lifecycle, failover.

Reference parity: ``dlrover/python/elastic_agent/torch/training.py`` —
``ElasticLaunchConfig:118``, ``MasterRendezvousHandler:181``,
``ElasticTrainingAgent:364`` (``_invoke_run:582`` monitor loop,
``_initialize_workers:547``, restart-on-membership-change ``:716``),
``launch_agent:776`` and the node-check agent ``:906``.

TPU-native redesign: instead of torchelastic's C10d store handing out
MASTER_ADDR/MASTER_PORT, the rank-0 agent publishes a
``jax.distributed`` coordinator address through the master KV store and
each training process calls ``jax.distributed.initialize`` with the
world assembled by the master's rendezvous (SURVEY.md §2.9).  Because
JAX cannot change process count in-place, every re-mesh fully restarts
the training processes — the same behavior the reference exhibits on
membership change (``training.py:646-648``); a persistent XLA
compilation cache keeps the restart cheap.
"""

import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from dlrover_tpu.agent.ckpt_saver import AsyncCheckpointSaver
from dlrover_tpu.agent.master_client import MasterClient, ReportBuffer
from dlrover_tpu.common.constants import (
    AgentExitCode,
    NodeEnv,
    RendezvousConstant,
    RendezvousName,
    TrainingExceptionLevel,
)
from dlrover_tpu.common.env import (
    control_longpoll_enabled,
    env_float,
    get_free_port,
    preempt_drain_grace_s,
    reshard_enabled,
)
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.observability.events import get_event_logger


@dataclass
class ElasticLaunchConfig:
    """Launch flags (reference ``ElasticLaunchConfig`` ``training.py:118``)."""

    min_nodes: int = 1
    max_nodes: int = 1
    nproc_per_node: int = 1
    rdzv_timeout: int = RendezvousConstant.MAX_WAIT_SECS
    # master-side window rule: how long after the last join an
    # under-max round waits before completing with what it has.
    # <0 = rdzv_timeout (the historical coupling).  The preemption
    # harness shortens THIS without shrinking the join wait: a lone
    # survivor must re-mesh in seconds, while a joining node may
    # legitimately wait minutes for peers.
    rdzv_waiting_timeout: float = -1.0
    node_unit: int = 1
    network_check: bool = False
    comm_perf_test: bool = False
    max_restarts: int = 3
    monitor_interval: float = 5.0
    # SIGTERM -> SIGKILL grace when stopping workers.  A worker blocked
    # in a collective (the COMMON failure posture: every survivor of a
    # peer crash is stalled in an allreduce/barrier) cannot run Python
    # signal handlers, so it always eats the full grace period —
    # recovery latency is dominated by this knob.
    stop_timeout: float = 15.0
    # grace used instead of stop_timeout when restarting after a
    # WORKER FAILURE: the group is already broken (survivors are wedged
    # in a collective against a dead peer, and the agent has already
    # flushed the shm checkpoint itself), so a long SIGTERM grace buys
    # nothing but recovery latency
    failure_stop_timeout: float = 1.0
    # fork restarted workers from a pre-imported zygote process
    # (agent/zygote.py): removes the ~3-4s Python/jax import chain
    # from every restart's critical path
    prefork: bool = False
    node_rank: int = field(
        default_factory=lambda: int(os.getenv(NodeEnv.NODE_RANK, "0"))
    )
    # extra env vars injected into every training process
    envs: Dict[str, str] = field(default_factory=dict)
    # persistent XLA compilation cache keeps post-restart warmup cheap
    compile_cache_dir: str = ""
    # overlapped restart critical path in the workers (restore byte
    # prefetch + background AOT compile, trainer/restart_path.py);
    # False exports DLROVER_TPU_RESTART_OVERLAP=0 so every worker runs
    # the serial restore->compile order
    restart_overlap: bool = True
    # watch the GCE metadata maintenance-event endpoint: on TPU-VMs
    # preemption fires there ~60s before any SIGTERM (agent/preemption.py)
    watch_preemption: bool = True

    def auto_configure_params(self):
        """Fill nproc from local device count when unset (reference
        ``auto_configure_params`` ``training.py:155``)."""
        if self.nproc_per_node <= 0:
            self.nproc_per_node = 1
        if self.max_nodes < self.min_nodes:
            self.max_nodes = self.min_nodes


class WorkerState:
    INIT = "INIT"
    HEALTHY = "HEALTHY"
    FAILED = "FAILED"
    SUCCEEDED = "SUCCEEDED"


@dataclass
class RunResult:
    state: str = WorkerState.INIT
    failed_ranks: List[int] = field(default_factory=list)
    return_codes: Dict[int, int] = field(default_factory=dict)


class MasterRendezvousHandler:
    """Master-backed rendezvous (reference ``training.py:181``).

    ``next_rendezvous`` joins the master round, polls until the master
    declares the world complete, and returns
    ``(round, rank, world_size, world)`` where ``world`` maps
    node_rank -> local_world_size for every participating node.
    """

    def __init__(
        self,
        client: MasterClient,
        node_rank: int,
        local_world_size: int,
        rdzv_name: str = RendezvousName.ELASTIC_TRAINING,
        timeout: float = RendezvousConstant.MAX_WAIT_SECS,
        poll_interval: float = 0.3,
    ):
        self._client = client
        self._node_rank = node_rank
        self._local_world_size = local_world_size
        self._rdzv_name = rdzv_name
        self._timeout = timeout
        self._poll = poll_interval

    def next_rendezvous(self):
        # topology hint (e.g. "superpod0/pod1/slice2") enables
        # topology-aware rank sorting on the master; absent = no-op
        topo = os.getenv("DLROVER_TPU_TOPOLOGY", "")
        if topo:
            try:
                self._client.report_node_topology(
                    self._node_rank, tuple(topo.split("/"))
                )
            except Exception as e:  # noqa: BLE001
                logger.warning("topology report failed: %s", e)
        rdzv_round = self._client.join_rendezvous(
            self._node_rank, self._local_world_size, self._rdzv_name
        )
        logger.info(
            "node %d joined %s rendezvous round %d",
            self._node_rank,
            self._rdzv_name,
            rdzv_round,
        )
        # long-poll: the RPC parks on the master's rendezvous condition
        # and returns the moment the round completes — one RPC per
        # ~30 s chunk instead of one every 0.3 s.  wait_comm_world
        # falls back to the exact old get/sleep loop under
        # DLROVER_TPU_CONTROL_LONGPOLL=0.
        rnd, group, world = self._client.wait_comm_world(
            self._rdzv_name,
            self._node_rank,
            timeout=self._timeout,
            poll_interval=self._poll,
        )
        if world:
            if self._node_rank not in world:
                raise NodeExcludedError(
                    f"node {self._node_rank} excluded from round {rnd}"
                )
            return rnd, group, world
        raise TimeoutError(
            f"rendezvous {self._rdzv_name!r} timed out after {self._timeout}s"
        )


class NodeExcludedError(RuntimeError):
    """The master left this node out of the comm world (fault/straggler)."""


class ElasticTrainingAgent:
    """Spawns and supervises the node's training processes.

    The monitor loop (reference ``_invoke_run`` ``training.py:582``):

    - any proc FAILED  -> report to master, flush shm ckpt, restart
    - all procs done   -> SUCCEEDED, exit
    - master says new nodes waiting -> flush shm ckpt, restart (re-mesh)
    """

    def __init__(
        self,
        config: ElasticLaunchConfig,
        entrypoint: Sequence[str],
        client: Optional[MasterClient] = None,
        start_ckpt_saver: bool = True,
    ):
        self._config = config
        self._entrypoint = list(entrypoint)
        self._client = client or MasterClient.singleton_instance()
        self._node_rank = config.node_rank
        self._procs: List[subprocess.Popen] = []
        self._restart_count = 0
        self._remaining_restarts = config.max_restarts
        self._start_ckpt_saver = start_ckpt_saver
        self._coordinator_port = get_free_port()
        self._stopped = False
        self._zygote = None  # ZygotePool when config.prefork
        #: the node received a preemption notice / SIGTERM: it must
        #: drain + flush, NOT restart into the next rendezvous (the
        #: hardware is going away; the master has fenced it)
        self._preempted = False
        #: the master excluded this node from the comm world
        self._excluded = False
        #: world size of the previous completed round (exported to
        #: workers as DLROVER_TPU_PREV_WORLD so the trainer can
        #: re-solve its parallelism strategy on a world change)
        self._last_world_size = 0
        #: last waiting-node count seen by the monitor pacing long-poll
        self._last_waiting = 0
        #: shared coalescing buffer for fire-and-forget reports
        #: (timeline batches, heartbeats, metric samples); flushed
        #: before every rendezvous and drained on shutdown
        self._report_buffer: Optional[ReportBuffer] = None
        #: capture ids already executed — a failover-re-armed
        #: directive for an in-flight capture must not double-fire
        #: (two SIGUSR2 bursts + duplicate Brain rows)
        self._seen_capture_ids: List[int] = []

    # ------------------------------------------------------------- workers
    def _rendezvous(self):
        if self._report_buffer is not None:
            # nothing buffered may straddle a restart: the world (and
            # possibly this process) changes on the other side
            self._report_buffer.flush()
        handler = MasterRendezvousHandler(
            self._client,
            self._node_rank,
            self._config.nproc_per_node,
            timeout=self._config.rdzv_timeout,
        )
        # chaos hook: an agent SIGKILLed here has joined nothing yet —
        # the master's window rule must simply proceed without it
        from dlrover_tpu.common.fault_injection import maybe_crash

        maybe_crash("mid_rendezvous")
        with get_event_logger().span(
            "rendezvous", inc=self._restart_count
        ):
            rnd, _group, world = handler.next_rendezvous()
        return rnd, world

    def _assign_worker_ranks(self, world: Dict[int, int]):
        """Global process ranks from the node world, in the MASTER's
        order (reference ``_assign_worker_ranks`` ``training.py:486``).
        The master emits the world topology-sorted (interconnect
        neighbors adjacent); dict insertion order survives the pickled
        transport, so the received order IS the rank order."""
        sorted_nodes = list(world)
        world_size = sum(world.values())
        rank_offset = 0
        for nr in sorted_nodes:
            if nr == self._node_rank:
                break
            rank_offset += world[nr]
        num_processes = world_size
        process_ids = list(
            range(rank_offset, rank_offset + world[self._node_rank])
        )
        node_index = sorted_nodes.index(self._node_rank)
        return world_size, num_processes, process_ids, node_index

    def _publish_coordinator(self, rdzv_round: int, is_first_node: bool):
        """Rank-0 node publishes the jax.distributed coordinator address
        via the master KV store; everyone else waits for it.

        This replaces the reference's ``MasterKVStore`` MASTER_ADDR /
        MASTER_PORT exchange (``master_kv_store.py``, ``training.py:252``).
        """
        key = f"jax_coordinator/{rdzv_round}"
        if is_first_node:
            host = os.getenv(
                "DLROVER_TPU_HOST_IP", socket.gethostbyname(socket.gethostname())
            )
            addr = f"{host}:{self._coordinator_port}"
            self._client.kv_store_set(key, addr.encode())
            return addr
        return self._client.kv_store_wait(
            key, timeout=self._config.rdzv_timeout
        ).decode()

    def _worker_env(
        self,
        rdzv_round: int,
        coordinator: str,
        world_size: int,
        process_rank: int,
        local_rank: int,
    ) -> Dict[str, str]:
        env = dict(os.environ)
        env.update(self._config.envs)
        env.update(
            {
                NodeEnv.MASTER_ADDR: self._client.addr,
                NodeEnv.NODE_RANK: str(self._node_rank),
                NodeEnv.PROCESS_RANK: str(process_rank),
                NodeEnv.PROCESS_COUNT: str(world_size),
                NodeEnv.LOCAL_RANK: str(local_rank),
                NodeEnv.LOCAL_PROCESS_COUNT: str(
                    self._config.nproc_per_node
                ),
                NodeEnv.COORDINATOR_ADDR: coordinator,
                "DLROVER_TPU_RDZV_ROUND": str(rdzv_round),
                "DLROVER_TPU_RESTART_COUNT": str(self._restart_count),
                # the previous round's world size: a relaunched
                # trainer compares it against the new world to decide
                # whether its pinned parallelism strategy must be
                # re-solved (accelerate/solver.resolve_for_world)
                "DLROVER_TPU_PREV_WORLD": str(self._last_world_size),
            }
        )
        if self._config.compile_cache_dir:
            env.setdefault(
                "JAX_COMPILATION_CACHE_DIR", self._config.compile_cache_dir
            )
        if not self._config.restart_overlap:
            env["DLROVER_TPU_RESTART_OVERLAP"] = "0"
        # deep-capture rendezvous point: agent and workers must agree
        # where stack dumps and profile artifacts land — the NODE-
        # scoped dir (base from DLROVER_TPU_CAPTURE_DIR / the events
        # file, namespaced by node rank so a shared artifact volume
        # never mixes two nodes' captures).  Explicit assignment: the
        # worker must see the node-scoped path, not the inherited base.
        from dlrover_tpu.common.env import profile_enabled

        if profile_enabled():
            cdir = self._capture_dir()
            if cdir:
                env["DLROVER_TPU_CAPTURE_DIR"] = cdir
        return env

    def _clear_armed_markers(self):
        """Drop the previous worker generation's ``armed_<pid>``
        markers BEFORE spawning the next one: a recycled pid matching
        a stale marker would let a capture SIGUSR2 a worker that
        never installed the handler (default disposition: death)."""
        import glob as _glob

        cdir = self._capture_dir()
        if not cdir:
            return
        from dlrover_tpu.trainer.capture import ARMED_FILE_PREFIX

        for path in _glob.glob(
            os.path.join(cdir, f"{ARMED_FILE_PREFIX}*")
        ):
            try:
                os.unlink(path)
            except OSError:
                pass

    def _initialize_workers(self) -> bool:
        """One rendezvous round + process spawn. Returns False when the
        master excluded this node."""
        if self._config.network_check:
            self._run_network_check()
        self._clear_armed_markers()
        try:
            rdzv_round, world = self._rendezvous()
        except NodeExcludedError as e:
            # a scheduling verdict, not a crash: surface it as its
            # own failure level + a distinct agent exit code so the
            # controller does not reschedule the node into this job
            logger.error("%s", e)
            self._excluded = True
            self._try_report_failure(
                str(e), TrainingExceptionLevel.NODE_EXCLUDED
            )
            return False
        except (TimeoutError, ConnectionError) as e:
            logger.error("rendezvous failed: %s", e)
            self._try_report_failure(
                f"rendezvous: {e}", TrainingExceptionLevel.RDZV_ERROR
            )
            return False
        (
            world_size,
            _num,
            process_ids,
            node_index,
        ) = self._assign_worker_ranks(world)
        try:
            coordinator = self._publish_coordinator(
                rdzv_round, node_index == 0
            )
        except (TimeoutError, ConnectionError) as e:
            logger.error("coordinator exchange failed: %s", e)
            self._try_report_failure(
                f"coordinator exchange: {e}",
                TrainingExceptionLevel.RDZV_ERROR,
            )
            return False
        logger.info(
            "round %d: world_size=%d coordinator=%s local ranks=%s",
            rdzv_round,
            world_size,
            coordinator,
            process_ids,
        )
        self._procs = []
        for local_rank, process_rank in enumerate(process_ids):
            env = self._worker_env(
                rdzv_round, coordinator, world_size, process_rank, local_rank
            )
            if self._zygote is not None:
                proc = self._zygote.spawn(self._entrypoint, env)
            else:
                proc = subprocess.Popen(  # noqa: S603
                    self._entrypoint, env=env
                )
            self._procs.append(proc)
        self._last_world_size = world_size
        return True

    # ------------------------------------------------------------- monitor
    def _monitor_workers(self) -> RunResult:
        result = RunResult(state=WorkerState.HEALTHY)
        codes: Dict[int, int] = {}
        running = 0
        for local_rank, proc in enumerate(self._procs):
            rc = proc.poll()
            if rc is None:
                running += 1
            else:
                codes[local_rank] = rc
                if rc != 0:
                    result.failed_ranks.append(local_rank)
        result.return_codes = codes
        if result.failed_ranks:
            result.state = WorkerState.FAILED
        elif running == 0:
            result.state = WorkerState.SUCCEEDED
        return result

    def _pace_monitor(self):
        """One monitor-interval pause.  Under long-poll the pause IS
        the waiting-count RPC parked on the master — the same one RPC
        per tick as the old sleep+poll pair, but a membership change
        wakes the loop INSTANTLY instead of at the next tick.  The
        legacy plain sleep survives the kill-switch."""
        interval = self._config.monitor_interval
        if not control_longpoll_enabled():
            time.sleep(interval)
            return
        try:
            self._last_waiting = self._client.num_nodes_waiting(
                wait_timeout=interval, last_num=self._last_waiting
            )
        except ConnectionError:
            # unreachable master must read as "no membership change"
            # (the old polling path returned False here) — a stale
            # nonzero count would fire a restart storm every tick for
            # the whole outage
            self._last_waiting = 0
            time.sleep(interval)

    def _membership_changed(self) -> bool:
        if control_longpoll_enabled():
            # _pace_monitor just fetched it — no second RPC
            waiting = self._last_waiting
        else:
            try:
                waiting = self._client.num_nodes_waiting()
            except ConnectionError:
                return False
        node_unit = max(self._config.node_unit, 1)
        return waiting > 0 and waiting % node_unit == 0

    def _stop_workers(self, timeout: Optional[float] = None):
        if timeout is None:
            timeout = self._config.stop_timeout
        for proc in self._procs:
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        deadline = time.time() + timeout
        for proc in self._procs:
            remaining = max(deadline - time.time(), 0.1)
            try:
                proc.wait(timeout=remaining)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
        self._procs = []

    def _save_ckpt_to_storage(self, reason: str):
        """Flush the latest shm checkpoint snapshot before killing
        workers (reference ``_save_ckpt_to_storage`` ``training.py:670``)."""
        saver = AsyncCheckpointSaver.get_ckpt_saver()
        if saver is not None:
            try:
                saver.save_shm_to_storage(reason=reason)
            except Exception as e:  # noqa: BLE001
                logger.warning("breakpoint ckpt flush failed: %s", e)

    def _drain_worker_snapshots(self, reason: str):
        """Graceful drain: ask every live worker (SIGUSR1 →
        ``trainer/drain.py``) to snapshot at each step boundary, then
        wait — bounded by ``DLROVER_TPU_PREEMPT_DRAIN_GRACE_S`` — for
        a FRESH common step to land in shm, so the flush that follows
        persists the step the world just completed instead of the
        last periodic snapshot.  Workers wedged in a collective
        simply cannot advance; the grace expires and the flush uses
        the newest complete snapshot, exactly today's behavior.
        No-op under ``DLROVER_TPU_RESHARD=0``."""
        if not reshard_enabled():
            return
        live = [p for p in self._procs if p.poll() is None]
        if not live:
            return
        from dlrover_tpu.trainer.drain import DRAIN_SIGNAL

        saver = AsyncCheckpointSaver.get_ckpt_saver()
        before = saver.max_common_step() if saver is not None else -1
        for proc in live:
            try:
                proc.send_signal(DRAIN_SIGNAL)
            except (ProcessLookupError, OSError):
                pass
        grace = preempt_drain_grace_s()
        logger.info(
            "drain requested of %d workers (%s); waiting up to "
            "%.1fs for a fresh snapshot (current common step %s)",
            len(live), reason, grace, before,
        )
        if saver is None:
            # no agent-side saver (tests / exotic embeddings): give
            # the workers one bounded beat to run their drain saves
            time.sleep(min(grace, 1.0))
            return
        deadline = time.time() + grace
        while time.time() < deadline:
            common = saver.max_common_step()
            if common > before >= 0 or (before < 0 <= common):
                logger.info(
                    "drain snapshot landed at step %s", common
                )
                return
            if all(p.poll() is not None for p in live):
                return  # nothing left to wait on
            time.sleep(0.1)
        logger.warning(
            "drain grace expired (%.1fs); flushing the newest "
            "complete snapshot (step %s)", grace,
            saver.max_common_step(),
        )

    def _restart_workers(
        self, reason: str, consume_budget: bool = True
    ) -> bool:
        """Restart the local worker set.  Failure restarts consume the
        budget; elastic re-mesh restarts (membership change) do not —
        a healthy job that scales N times must not die on the N+1th
        node join (torchelastic decrements only on failures)."""
        if consume_budget:
            if self._remaining_restarts <= 0:
                logger.error("restart budget exhausted (%s)", reason)
                return False
            self._remaining_restarts -= 1
        self._restart_count += 1
        logger.info(
            "restarting workers (%s); %d restarts left",
            reason,
            self._remaining_restarts,
        )
        # the span's inc is the NEW incarnation this restart produces,
        # correlating it with the relaunched workers' step/compile
        # spans; the nested rendezvous span carves its own share out
        # of the restart loss in the ledger
        with get_event_logger().span(
            "restart", reason=reason, inc=self._restart_count
        ):
            if not consume_budget:
                # elastic re-mesh: the workers are still coupled and
                # stepping — drain them so the flush below persists a
                # FRESH step for the new world to reshard from (a
                # failure restart skips this: the group is broken and
                # nothing can advance)
                self._drain_worker_snapshots(reason)
            self._save_ckpt_to_storage(reason)
            # failure restarts: the group is broken and the shm
            # snapshot is already flushed — survivors wedged in
            # collectives would eat the full stop grace for nothing
            self._stop_workers(
                timeout=self._config.failure_stop_timeout
                if consume_budget
                else None
            )
            return self._initialize_workers()

    def _report_failure(self, result: RunResult):
        self._try_report_failure(
            str(result.return_codes), TrainingExceptionLevel.PROCESS_ERROR
        )

    def _try_report_failure(self, error_data: str, level: str):
        try:
            self._client.report_failure(
                error_data=error_data,
                restart_count=self._restart_count,
                level=level,
            )
        except ConnectionError as e:
            logger.warning("failed reporting failure to master: %s", e)

    def _run_network_check(self):
        """Pre-flight node health check round (reference
        ``run_network_check`` ``training.py:1154``)."""
        with tempfile.NamedTemporaryFile(
            prefix="node_check_", suffix=".txt", delete=False
        ) as f:
            result_file = f.name
        env = dict(os.environ)
        env["DLROVER_TPU_NODE_CHECK_RESULT_FILE"] = result_file
        handler = MasterRendezvousHandler(
            self._client,
            self._node_rank,
            self._config.nproc_per_node,
            rdzv_name=RendezvousName.NETWORK_CHECK,
            timeout=self._config.rdzv_timeout,
        )
        try:
            handler.next_rendezvous()
        except (TimeoutError, NodeExcludedError) as e:
            logger.warning("network-check rendezvous failed: %s", e)
            return
        proc = subprocess.Popen(  # noqa: S603
            [sys.executable, "-m", "dlrover_tpu.agent.node_check"], env=env
        )
        try:
            rc = proc.wait(timeout=300)
        except subprocess.TimeoutExpired:
            # a wedged chip must not hang the agent: kill the payload
            # and report the node unhealthy
            proc.kill()
            proc.wait()
            rc = -1
        elapsed = -1.0
        if rc == 0:
            try:
                with open(result_file) as f:
                    elapsed = float(f.read().strip())
            except (OSError, ValueError):
                pass
        os.unlink(result_file)
        self._client.report_network_status(
            self._node_rank, succeeded=(rc == 0), elapsed_time=elapsed
        )
        if rc != 0:
            raise RuntimeError(
                f"node {self._node_rank} failed the health check"
            )

    # ----------------------------------------------------------------- run
    def run(self) -> int:
        """Agent main loop. Returns a process exit code."""
        factory_queue = None
        preemption_watcher = None
        timeline_reporter = None
        self._report_buffer = ReportBuffer(self._client)
        events = get_event_logger()
        if events.enabled:
            from dlrover_tpu.agent.monitor import TimelineReporter

            timeline_reporter = TimelineReporter(
                events.path,
                client=self._client,
                buffer=self._report_buffer,
                # ship cadence bounds how fast the master's health
                # derivations (and therefore the Brain) can see a
                # signal; chaos/bench harnesses tighten it
                interval=env_float(
                    "DLROVER_TPU_TIMELINE_REPORT_S", 5.0
                ),
            )
            timeline_reporter.start()
        if self._start_ckpt_saver:
            factory_queue = AsyncCheckpointSaver.start_async_saving_ckpt()
        if reshard_enabled():
            # graceful-drain SIGTERM: supersede the bare ckpt_saver
            # flush hook with drain → flush → fence → exit, so a pod
            # kill leaves survivors a FRESH reshardable checkpoint
            # and an already-fenced master.  DLROVER_TPU_RESHARD=0
            # keeps today's flush-only hook exactly.
            try:
                signal.signal(signal.SIGTERM, self._on_sigterm)
            except ValueError:
                logger.warning(
                    "not on main thread: graceful SIGTERM drain not "
                    "installed"
                )
        if self._config.watch_preemption:
            from dlrover_tpu.agent.preemption import PreemptionWatcher

            preemption_watcher = PreemptionWatcher()
            preemption_watcher.on_preemption(self._on_preemption)
            preemption_watcher.start()
        if self._config.prefork:
            from dlrover_tpu.agent.zygote import ZygotePool

            pool = ZygotePool(
                name=f"zygote_{self._node_rank}_{os.getpid()}"
            )
            env = dict(os.environ)
            env.update(self._config.envs)
            if self._config.compile_cache_dir:
                env.setdefault(
                    "JAX_COMPILATION_CACHE_DIR",
                    self._config.compile_cache_dir,
                )
            if pool.start(env=env):
                self._zygote = pool
        try:
            return self._invoke_run()
        finally:
            self._stopped = True
            if preemption_watcher is not None:
                preemption_watcher.stop()
            self._stop_workers()
            if timeline_reporter is not None:
                timeline_reporter.stop()
                timeline_reporter.flush()  # the final partial batch
            if self._report_buffer is not None:
                # flush-on-shutdown: buffered heartbeats/metrics/
                # timeline batches must survive the agent
                self._report_buffer.close()
                self._report_buffer = None
            if self._zygote is not None:
                self._zygote.close()
                self._zygote = None
            if factory_queue is not None:
                factory_queue.close()
                AsyncCheckpointSaver.reset()

    def _on_preemption(self, event: str):
        """Maintenance event: drain the workers to a fresh snapshot,
        flush it to storage, and fence this node at the master BEFORE
        the hardware goes away (the SIGTERM path may never run).  The
        ``node_preempted`` report makes the master fence the node out
        of the next round immediately, so survivors observe the
        membership change within one monitor interval instead of
        waiting for this node's heartbeat to go stale."""
        self._preempted = True
        with get_event_logger().span("preemption_drain", event=event):
            self._drain_worker_snapshots(f"preemption:{event}")
            self._save_ckpt_to_storage(f"preemption:{event}")
            self._try_report_failure(
                f"maintenance event {event}",
                TrainingExceptionLevel.NODE_PREEMPTED
                if reshard_enabled()
                else TrainingExceptionLevel.NODE_ERROR,
            )

    def _on_sigterm(self, signum, frame):  # pragma: no cover - signal
        """Pod kill: drain → flush → fence, then die with the
        preemption exit code.  Runs on the main thread (signal
        contract); every step is bounded so the pod's termination
        grace is respected."""
        logger.warning("SIGTERM: graceful drain before exit")
        self._on_preemption(f"SIGTERM:{signum}")
        self._stop_workers(
            timeout=self._config.failure_stop_timeout
        )
        raise SystemExit(AgentExitCode.NODE_PREEMPTED)

    def _exit_code(self, default: int = AgentExitCode.ERROR) -> int:
        if self._excluded:
            return AgentExitCode.NODE_EXCLUDED
        if self._preempted:
            return AgentExitCode.NODE_PREEMPTED
        return default

    def _take_brain_directive(self):
        """A master directive delivered on the monitor-pacing poll.
        ``capture`` executes here (background — the monitor loop keeps
        supervising); ``drain`` is returned to the loop.  Ignored (and
        logged) when the respective machinery is kill-switched — the
        master's execution deadline then falls back to fencing this
        node without our cooperation."""
        directive = self._client.take_node_action()
        if directive is None:
            return None
        action, reason, decision_id = directive
        if action == "capture":
            self._start_capture(reason, decision_id)
            return None
        if action != "drain":
            logger.warning(
                "ignoring unknown brain directive %r (decision %s)",
                action, decision_id,
            )
            return None
        if not reshard_enabled():
            logger.warning(
                "brain drain directive ignored: DLROVER_TPU_RESHARD=0"
            )
            return None
        return directive

    # ------------------------------------------------------ deep capture
    def _capture_dir(self) -> str:
        """This NODE's capture artifact dir: the resolved base
        (``DLROVER_TPU_CAPTURE_DIR`` / events-dir default) namespaced
        by node rank, so agents sharing one pinned artifact volume
        can never collect each other's worker profiles as their own.
        "" when no base is resolvable."""
        from dlrover_tpu.common.env import capture_dir

        base = capture_dir()
        if not base:
            return ""
        return os.path.join(base, f"node_{self._node_rank}")

    def _start_capture(self, reason: str, capture_id: int):
        """A master ``capture`` directive: run the deep capture on a
        background thread — the monitor loop must keep supervising
        workers while the trace window and the artifact wait run.
        A re-delivered id (failover re-armed the directive while the
        first execution was still in flight) is dropped — one
        capture, one SIGUSR2 burst, one Brain row."""
        from dlrover_tpu.common.env import profile_enabled

        if not profile_enabled():
            logger.warning(
                "capture directive ignored: DLROVER_TPU_PROFILE=0"
            )
            return
        if capture_id in self._seen_capture_ids:
            logger.info(
                "capture %s already executed; ignoring re-delivery",
                capture_id,
            )
            return
        self._seen_capture_ids.append(capture_id)
        del self._seen_capture_ids[:-64]
        threading.Thread(
            target=self._execute_capture,
            args=(reason, capture_id),
            name="deep-capture",
            daemon=True,
        ).start()

    @staticmethod
    def _capture_dir_state(cdir: str) -> Dict[str, tuple]:
        """``{path: (mtime, size)}`` of the artifact files currently
        in the capture dir — the freshness baseline.  New-or-changed
        against this snapshot beats comparing mtimes to
        ``time.time()``: the two clocks need not agree (sandboxed
        filesystems), and a stale artifact from an older capture must
        not be re-shipped either way."""
        import glob as _glob

        state = {}
        for pattern in ("profile_*.json", "stacks_*.txt"):
            for path in _glob.glob(os.path.join(cdir, pattern)):
                try:
                    st = os.stat(path)
                    state[path] = (st.st_mtime, st.st_size)
                except OSError:
                    continue
        return state

    @classmethod
    def _collect_capture_profiles(
        cls, cdir: str, before: Dict[str, tuple]
    ) -> List[dict]:
        """Worker profile JSONs that appeared (or changed) since the
        ``before`` snapshot (the attribution worker drops them
        atomically)."""
        import glob as _glob
        import json as _json

        out = []
        for path in sorted(
            _glob.glob(os.path.join(cdir, "profile_*.json"))
        ):
            try:
                st = os.stat(path)
                if before.get(path) == (st.st_mtime, st.st_size):
                    continue  # a stale artifact of an older capture
                with open(path) as f:
                    out.append(_json.load(f))
            except (OSError, ValueError):
                continue
        return out

    @classmethod
    def _collect_capture_stacks(
        cls, cdir: str, before: Dict[str, tuple],
        tail_chars: int = 4000,
    ) -> Dict[str, str]:
        """Stack-dump tails that appeared (or grew) since the
        ``before`` snapshot (faulthandler appends one all-thread dump
        per signal) — the xpu_timer hang-dump parity: for a rank
        wedged in a collective this is the whole artifact.  Only the
        file TAIL is read: the dump file grows one append per capture
        over the job's life (cooldown-bounded), and the newest dump
        is the one this capture wants."""
        import glob as _glob

        out = {}
        for path in sorted(
            _glob.glob(os.path.join(cdir, "stacks_*.txt"))
        ):
            try:
                st = os.stat(path)
                if before.get(path) == (st.st_mtime, st.st_size):
                    continue
                with open(path, "rb") as f:
                    if st.st_size > 4 * tail_chars:
                        f.seek(-4 * tail_chars, os.SEEK_END)
                    text = f.read().decode(errors="replace")
            except OSError:
                continue
            if text.strip():
                out[os.path.basename(path)] = text[-tail_chars:]
        return out

    @staticmethod
    def _sweep_capture_dir(cdir: str, keep: int = 16):
        """Bound the captures dir: keep only the newest ``keep``
        capture/profile JSON artifacts (a chronically slow rank
        triggers one capture per cooldown forever; the repo's growth
        bounds apply here like everywhere else — the stacks files are
        already cooldown-bounded appends read tail-only)."""
        import glob as _glob

        files = []
        for pattern in ("capture_*.json", "profile_*.json"):
            for path in _glob.glob(os.path.join(cdir, pattern)):
                try:
                    files.append((os.path.getmtime(path), path))
                except OSError:
                    continue
        files.sort(reverse=True)
        for _mtime, path in files[keep:]:
            try:
                os.unlink(path)
            except OSError:
                pass

    def _execute_capture(self, reason: str, capture_id: int) -> dict:
        """The cooperative half of a deep capture: signal every live
        worker (SIGUSR2 → faulthandler all-thread dump + an N-step
        ``jax.profiler`` window via ``trainer/capture.py``), wait —
        bounded — for the worker profile artifacts, assemble ONE
        combined artifact under the events dir, and report the parsed
        summary to the master's Brain ``profiles`` table.  A hung
        worker never writes a profile; its stack dump is the
        evidence and the wait simply times out."""
        import json as _json
        import tempfile

        from dlrover_tpu.common.env import capture_timeout_s
        from dlrover_tpu.trainer.capture import CAPTURE_SIGNAL

        cdir = self._capture_dir() or tempfile.mkdtemp(
            prefix="dlrover_capture_"
        )
        try:
            os.makedirs(cdir, exist_ok=True)
        except OSError as e:
            logger.warning("capture dir unavailable: %s", e)
            return {}
        get_event_logger().instant(
            "capture",
            node_rank=self._node_rank,
            reason=reason,
            capture_id=capture_id,
        )
        from dlrover_tpu.trainer.capture import ARMED_FILE_PREFIX

        t0 = time.time()
        before = self._capture_dir_state(cdir)
        # only signal workers that ARMED the handler (they drop a
        # marker at install): the default SIGUSR2 disposition
        # TERMINATES a process, so signalling an arbitrary
        # entrypoint that never installed it would kill the exact
        # node this diagnostic wanted to observe
        live = []
        skipped = 0
        for p in self._procs:
            if p.poll() is not None:
                continue
            pid = getattr(p, "pid", None)
            if pid is not None and os.path.exists(
                os.path.join(cdir, f"{ARMED_FILE_PREFIX}{pid}")
            ):
                live.append(p)
            else:
                skipped += 1
        if skipped:
            logger.warning(
                "capture %s: %d workers never armed the capture "
                "handler; not signalling them (stacks unavailable)",
                capture_id, skipped,
            )
        for proc in live:
            try:
                proc.send_signal(CAPTURE_SIGNAL)
            except (ProcessLookupError, OSError):
                pass
        logger.info(
            "capture %s: signalled %d workers (%s)",
            capture_id, len(live), reason,
        )
        deadline = time.time() + capture_timeout_s()
        profiles: List[dict] = []
        while time.time() < deadline:
            profiles = self._collect_capture_profiles(cdir, before)
            if live and len(profiles) >= len(live):
                break
            if not live:
                break  # nothing will ever answer
            time.sleep(0.2)
        stacks = self._collect_capture_stacks(cdir, before)
        summary = {
            "reason": reason,
            "capture_id": capture_id,
            "node": self._node_rank,
            "workers_signalled": len(live),
            "workers_unarmed": skipped,
            "profiles_collected": len(profiles),
            "stack_dumps": len(stacks),
            "profiles": [
                {
                    k: p.get(k)
                    for k in (
                        "pid", "step", "steps", "step_time_s",
                        "shares", "tflops", "mfu", "truncated",
                    )
                }
                for p in profiles
            ],
            # the op-level evidence: top-10 ops, category shares and
            # GEMM clusters from the first (usually only) worker
            "profile_summary": (
                profiles[0].get("summary") if profiles else None
            ),
        }
        artifact = os.path.join(
            cdir,
            f"capture_{self._node_rank}_{capture_id}.json",
        )
        try:
            tmp = artifact + ".tmp"
            with open(tmp, "w") as f:
                _json.dump(
                    dict(summary, stacks=stacks, t=t0), f
                )
            os.replace(tmp, artifact)
        except OSError as e:
            logger.warning("capture artifact write failed: %s", e)
            artifact = ""
        self._sweep_capture_dir(cdir)
        try:
            self._client.report_profile(
                node_rank=self._node_rank,
                reason=reason,
                capture_id=capture_id,
                summary=summary,
                artifact=artifact,
            )
        except ConnectionError as e:
            logger.warning("capture report failed: %s", e)
        return summary

    def _execute_brain_drain(self, reason: str, decision_id: int) -> int:
        """The cooperative half of a Brain drain_replace/shrink: the
        PR-9 graceful-drain protocol (snapshot-every-step → flush →
        ``node_preempted`` report, which fences this node at the
        master) and exit with the preemption code so the controller
        reschedules the pod instead of counting a crash."""
        logger.warning(
            "brain directive: graceful drain and exit "
            "(decision %s: %s)", decision_id, reason,
        )
        self._on_preemption(f"brain:{reason}")
        self._stop_workers(timeout=self._config.failure_stop_timeout)
        return AgentExitCode.NODE_PREEMPTED

    def _invoke_run(self) -> int:
        if not self._initialize_workers():
            return self._exit_code()
        while True:
            self._pace_monitor()
            directive = self._take_brain_directive()
            result = self._monitor_workers()
            if result.state == WorkerState.SUCCEEDED:
                # a completed job outranks a drain directive: there is
                # nothing left to drain and the success must be
                # reported as one
                logger.info("all workers finished successfully")
                try:
                    self._client.report_succeeded()
                except ConnectionError:
                    pass
                return 0
            if directive is not None:
                _action, reason, decision_id = directive
                return self._execute_brain_drain(reason, decision_id)
            if result.state == WorkerState.FAILED:
                if self._preempted:
                    # the hardware is going away and the drain +
                    # flush + fence already happened — restarting
                    # into a rendezvous the master fenced us out of
                    # would only delay the pod's death
                    logger.info(
                        "workers gone after preemption drain; "
                        "exiting without restart"
                    )
                    return AgentExitCode.NODE_PREEMPTED
                logger.error(
                    "worker failure: local ranks %s codes %s",
                    result.failed_ranks,
                    result.return_codes,
                )
                self._report_failure(result)
                if not self._restart_workers("worker failure"):
                    return self._exit_code()
                continue
            # HEALTHY: elastic re-mesh when new nodes wait at the master
            if self._membership_changed():
                if not self._restart_workers(
                    "membership change", consume_budget=False
                ):
                    return self._exit_code()


def launch_agent(
    config: ElasticLaunchConfig,
    entrypoint: Sequence[str],
    master_addr: str = "",
) -> int:
    """Build the client + agent and run (reference ``launch_agent``
    ``training.py:776``)."""
    config.auto_configure_params()
    client = MasterClient.singleton_instance(master_addr)
    waiting_timeout = (
        config.rdzv_waiting_timeout
        if config.rdzv_waiting_timeout >= 0
        else config.rdzv_timeout
    )
    client.report_rdzv_params(
        config.min_nodes,
        config.max_nodes,
        waiting_timeout,
        config.node_unit,
    )
    agent = ElasticTrainingAgent(config, entrypoint, client=client)
    return agent.run()
