"""Node health-check payload: chip enumeration + matmul + collective.

Reference parity: ``dlrover/trainer/torch/node_check/nvidia_gpu.py:24-56``
(matmul + 16M-element allreduce timed rounds) and the agent entries
``node_health_check`` / ``comm_perf_check``
(``elastic_agent/torch/training.py:1115,1134``).  The TPU twist
(SURVEY.md §7 step 3): a "node" is a TPU-VM worker, and the payload is
chip enumeration plus a small ICI allreduce/matmul run under ``pmap``
across the node's local devices.

The payload runs in a throwaway subprocess so a wedged chip cannot hang
the agent; elapsed time goes back to the master's
``NetworkCheckRendezvousManager`` which shuffles pair groups across two
rounds to isolate the straggler / fault node.
"""

import functools
import os
import time

from dlrover_tpu.common.log import default_logger as logger

# Matches the reference's payload scale (matmul K x K, 16M-element
# allreduce) but sized to finish in ~1s on one TPU chip.
_MATMUL_DIM = 1024
_MATMUL_ROUNDS = 3
_ALLREDUCE_ELEMS = 1 << 24


def mock_error() -> bool:
    """Fault injection switch (reference ``node_check/utils.py:49``)."""
    return os.getenv("DLROVER_TPU_MOCK_NODE_ERROR", "") == "1"


def run_health_check() -> float:
    """Run the compute+collective payload on all local devices.

    Returns elapsed seconds; raises on failure (bad chip, injected
    fault).  Imports jax lazily so the agent process itself never
    touches the accelerator runtime.
    """
    if mock_error():
        raise RuntimeError("injected node-check failure")

    import jax
    import jax.numpy as jnp

    devices = jax.local_devices()
    if not devices:
        raise RuntimeError("no local accelerator devices visible")
    n = len(devices)
    logger.info("node check: %d local devices (%s)", n, devices[0].platform)

    start = time.time()

    # Per-chip matmul (MXU) + ICI allreduce across local chips.
    @functools.partial(jax.pmap, axis_name="i")
    def _payload(v):
        y = v
        for _ in range(_MATMUL_ROUNDS):
            y = jnp.tanh(y @ v)
        s = jax.lax.psum(jnp.sum(y), axis_name="i")
        return y, s

    key = jax.random.PRNGKey(0)
    x = jax.random.normal(
        key, (n, _MATMUL_DIM, _MATMUL_DIM), dtype=jnp.bfloat16
    )
    out = _payload(x)
    jax.block_until_ready(out)

    # Bandwidth probe: 16M-element (64MB fp32) allreduce, reference
    # ``bm_allreduce`` (node_check/utils.py:88).
    big = jnp.ones((n, _ALLREDUCE_ELEMS // n), dtype=jnp.float32)
    r = jax.pmap(
        lambda v: jax.lax.psum(v, axis_name="i"), axis_name="i"
    )(big)
    jax.block_until_ready(r)

    elapsed = time.time() - start
    logger.info("node check passed in %.3fs", elapsed)
    return elapsed


def main() -> int:
    """Subprocess entry: ``python -m dlrover_tpu.agent.node_check``."""
    try:
        elapsed = run_health_check()
    except Exception as e:  # noqa: BLE001
        logger.error("node check failed: %s", e)
        return 1
    # Elapsed time goes to the parent via a result file; the agent
    # forwards it to the master (report_network_status).
    out = os.getenv("DLROVER_TPU_NODE_CHECK_RESULT_FILE", "")
    if out:
        with open(out, "w") as f:
            f.write(f"{elapsed:.6f}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
