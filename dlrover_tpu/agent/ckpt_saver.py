"""Agent-side asynchronous checkpoint saver.

Reference parity: ``dlrover/python/elastic_agent/torch/ckpt_saver.py:345``
(AsyncCheckpointSaver + CommonDirCheckpointSaver): lives in the *agent*
process so it survives training-process crashes; drains save events from
a SharedQueue, persists shm shards to storage with a two-phase stage-dir
commit, and flushes the last shm snapshot on SIGTERM or worker failure.

Commit protocol (reference ``:774+``):
1. write every shard to ``<dir>/._dlrover_ckpt_stage/checkpoint-<step>/``
2. write a per-node done file ``done_<node_rank>``
3. the committing node (node_rank 0) waits for all done files, then
   atomically moves the stage dir to ``<dir>/checkpoint-<step>`` and
   rewrites ``latest_checkpointed_iteration.txt``.

On GCS-Fuse/NFS the stage dir is on the shared filesystem, so multi-host
commits need no extra RPC.
"""

import os
import queue
import signal
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from dlrover_tpu.common.constants import CheckpointConstant
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.multi_process import SharedQueue
from dlrover_tpu.common.storage import get_checkpoint_storage
from dlrover_tpu.agent.ckpt_shm import SharedMemoryHandler, shard_lock

FACTORY_QUEUE = "ckpt_factory"
EVENT_QUEUE = "ckpt_event"


@dataclass
class SaverConfig:
    """Sent by the training process to tell the agent which saver to
    build (reference: the "factory" SharedQueue protocol)."""

    checkpoint_dir: str = ""
    local_shard_num: int = 1
    global_shard_num: int = 1
    node_rank: int = 0
    name: str = "default"


@dataclass
class CheckpointEvent:
    """A save/update request from the training process."""

    event_type: str = "save"  # save | update
    step: int = 0
    checkpoint_dir: str = ""


class AsyncCheckpointSaver:
    """One instance per agent; persists every local shard."""

    _instance: Optional["AsyncCheckpointSaver"] = None
    _factory_thread: Optional[threading.Thread] = None

    def __init__(self, config: SaverConfig, storage=None):
        self.config = config
        self._storage = storage or get_checkpoint_storage(
            path=config.checkpoint_dir
        )
        self._shm_handlers: List[SharedMemoryHandler] = []
        self._locks = []
        for local_rank in range(config.local_shard_num):
            self._shm_handlers.append(
                SharedMemoryHandler(
                    self._global_rank(local_rank),
                    name=config.name,
                    host=True,
                )
            )
            self._locks.append(
                shard_lock(
                    self._global_rank(local_rank),
                    name=config.name,
                    create=True,
                )
            )
        self._event_queue = SharedQueue(
            f"{EVENT_QUEUE}_{config.name}", create=True
        )
        self._stopped = False
        self._persist_thread: Optional[threading.Thread] = None
        self._latest_persisted_step = -1

    def _global_rank(self, local_rank: int) -> int:
        return (
            self.config.node_rank * self.config.local_shard_num
            + local_rank
        )

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        self._persist_thread = threading.Thread(
            target=self._event_loop, name="ckpt-saver", daemon=True
        )
        self._persist_thread.start()

    def stop(self):
        self._stopped = True

    def close(self, unlink: bool = False):
        self.stop()
        # join the event loop BEFORE closing shm: a persist in flight
        # holds memoryview slices of the segments (dump_to_file), and
        # closing under it raises BufferError "exported pointers exist"
        t = self._persist_thread
        if t is not None and t.is_alive():
            t.join(timeout=60)
            if t.is_alive():
                # the handles must stay open (the stuck persist holds
                # buffer views), but named POSIX shm is NOT reclaimed
                # at process exit — unlink the names now (safe while
                # mapped) so the multi-GB segments die with the last
                # process instead of squatting in /dev/shm until reboot
                logger.error(
                    "ckpt saver event loop still busy after 60s; "
                    "leaving handles open%s",
                    ", unlinking shm names" if unlink else "",
                )
                if unlink:
                    for handler in self._shm_handlers:
                        handler.unlink_name()
                return
        for handler in self._shm_handlers:
            handler.close(unlink=unlink)
        for lock in self._locks:
            lock.close()
        self._event_queue.close()

    def _event_loop(self):
        logger.info(
            "async ckpt saver running for %s (local shards: %s)",
            self.config.checkpoint_dir,
            self.config.local_shard_num,
        )
        while not self._stopped:
            try:
                event: CheckpointEvent = self._event_queue.get(
                    timeout=1.0
                )
            except queue.Empty:
                continue
            except Exception as e:  # noqa: BLE001
                logger.warning("ckpt event queue error: %s", e)
                time.sleep(0.5)
                continue
            try:
                self.save_step_checkpoint(
                    event.step, event.checkpoint_dir
                )
            except Exception as e:  # noqa: BLE001
                logger.error(
                    "persist of step %s failed: %s", event.step, e
                )

    # -- persist -----------------------------------------------------------
    def _stage_dir(self, root: str, step: int) -> str:
        return os.path.join(
            root,
            CheckpointConstant.STAGE_DIR,
            f"{CheckpointConstant.CKPT_DIR_PREFIX}{step}",
        )

    def _final_dir(self, root: str, step: int) -> str:
        return os.path.join(
            root, f"{CheckpointConstant.CKPT_DIR_PREFIX}{step}"
        )

    def save_step_checkpoint(self, step: int, root: Optional[str] = None,
                             commit_timeout: Optional[float] = None):
        """Persist all local shm shards of ``step`` and commit.

        A shard whose shm snapshot is at a different step makes the
        whole save fail — persisting a mixed-step checkpoint would
        silently corrupt a later restore.  ``commit_timeout`` bounds
        the node-0 done-file wait (None = SAVE_TIMEOUT): emergency
        flushes pass a small bound because under preemption the PEER
        node may never write its done file — a 600 s poll there would
        wedge the survivor's restart path behind a commit that cannot
        happen."""
        from dlrover_tpu.observability.events import anchored_now

        t0_mono = time.monotonic()
        t0_wall = anchored_now(t0_mono)
        root = root or self.config.checkpoint_dir
        stage = self._stage_dir(root, step)
        self._storage.safe_makedirs(stage)
        ok = True
        persisted_bytes = 0
        io_seconds = 0.0  # pure dump time: lock waits excluded
        for local_rank, handler in enumerate(self._shm_handlers):
            global_rank = self._global_rank(local_rank)
            lock = self._locks[local_rank]
            acquired = lock.acquire(timeout=60)
            if not acquired:
                # a trainer mid-snapshot holds the lock; persisting
                # without it could write a torn buffer
                logger.error(
                    "shard %s: lock not acquired; aborting this save",
                    global_rank,
                )
                ok = False
                continue
            try:
                if step not in handler.steps_available():
                    logger.error(
                        "shm shard %s holds steps %s, wanted %s; "
                        "aborting this save",
                        global_rank, handler.steps_available(), step,
                    )
                    ok = False
                    continue
                path = os.path.join(
                    stage, f"shard_{global_rank}.drckpt"
                )
                t_io = time.monotonic()
                nbytes = handler.dump_to_file(
                    path, self._storage, step=step
                )
                if nbytes is None:
                    ok = False
                else:
                    persisted_bytes += nbytes
                    io_seconds += time.monotonic() - t_io
            finally:
                lock.release()
        if not ok:
            logger.error("step %s: some shards failed to persist", step)
            return False
        # persist-side data-plane visibility: the streamed
        # shm->storage write as a checkpoint_save span (async in the
        # agent, so overlapping train steps still charge the step in
        # the ledger) plus throughput gauges.  Span duration is full
        # wall (ledger input); throughput_gbps is computed from PURE
        # dump time so a trainer holding a shard lock for 50 s cannot
        # make a healthy storage write look like a bandwidth
        # regression.
        from dlrover_tpu.common.parallel_io import throughput_gbps
        from dlrover_tpu.observability.events import get_event_logger
        from dlrover_tpu.observability.metrics import record_ckpt_io

        persist_dur = time.monotonic() - t0_mono
        get_event_logger().complete(
            "checkpoint_save",
            t0_wall,
            persist_dur,
            step=step,
            bytes=persisted_bytes,
            throughput_gbps=throughput_gbps(
                persisted_bytes, io_seconds
            ),
            stage="persist",
        )
        record_ckpt_io("persist", persisted_bytes, io_seconds)
        self._write_done_file(stage)
        if self.config.node_rank == 0:
            committed = self.commit_checkpoint(
                step, root,
                timeout=(
                    commit_timeout
                    if commit_timeout is not None
                    else CheckpointConstant.SAVE_TIMEOUT
                ),
            )
            if committed:
                self._latest_persisted_step = step
            return committed
        self._latest_persisted_step = step
        return True

    def _write_done_file(self, stage: str):
        self._storage.write(
            str(self.config.local_shard_num),
            os.path.join(stage, f"done_{self.config.node_rank}"),
        )

    def commit_checkpoint(self, step: int, root: str,
                          timeout: float = CheckpointConstant.SAVE_TIMEOUT) -> bool:
        """Node-rank-0: wait for all nodes' done files, then atomically
        publish the stage dir and update the tracker file."""
        stage = self._stage_dir(root, step)
        node_num = max(
            1,
            self.config.global_shard_num
            // max(self.config.local_shard_num, 1),
        )
        deadline = time.time() + timeout
        while time.time() < deadline:
            done = [
                f
                for f in self._storage.listdir(stage)
                if f.startswith("done_")
            ]
            if len(done) >= node_num:
                final = self._final_dir(root, step)
                for f in done:
                    self._storage.safe_remove(os.path.join(stage, f))
                # re-saving an existing step replaces it: safe_move
                # no-ops when the destination exists, which would
                # silently discard the fresh shards
                if self._storage.exists(final):
                    self._storage.safe_rmtree(final)
                self._storage.safe_move(stage, final)
                self._storage.write(
                    str(step),
                    os.path.join(
                        root, CheckpointConstant.TRACKER_FILE
                    ),
                )
                logger.info("checkpoint step %s committed -> %s",
                            step, final)
                return True
            time.sleep(0.2)
        logger.error("commit of step %s timed out", step)
        return False

    def max_common_step(self) -> int:
        """Newest step present in EVERY local shard's shm (what an
        emergency flush would persist), or -1.  The agent's graceful
        drain polls this to learn when the workers' drain-mode
        snapshots have landed."""
        step_sets = [
            set(h.steps_available()) for h in self._shm_handlers
        ]
        if not step_sets or not all(step_sets):
            return -1
        common = set.intersection(*step_sets)
        return max(common) if common else -1

    def save_shm_to_storage(self, reason: str = ""):
        """Emergency flush: persist whatever valid snapshot sits in shm
        (called on SIGTERM / worker failure; reference ``:473-495``).

        Picks the NEWEST step available in every local shard's shm —
        with double-buffered slots a kill that tore the shards (one at
        N+1, one at N) still flushes a complete step N instead of
        aborting on the mismatch."""
        # chaos hook: a kill pinned here dies with the emergency flush
        # half done — the shm snapshot (crash-survivable segment) and
        # the storage tier's atomic rename must both tolerate it
        from dlrover_tpu.common.fault_injection import maybe_crash

        maybe_crash("mid_checkpoint_persist")
        step_sets = [set(h.steps_available()) for h in self._shm_handlers]
        if not step_sets or not all(step_sets):
            logger.info("no shm checkpoint to flush (%s)", reason)
            return False
        common = set.intersection(*step_sets)
        if not common:
            logger.error(
                "no step common to all %d shards (%s); nothing flushed",
                len(step_sets), [sorted(s) for s in step_sets],
            )
            return False
        step = max(common)
        if step <= self._latest_persisted_step:
            logger.info(
                "shm step %s already persisted; skip flush", step
            )
            return True
        logger.info(
            "emergency-flushing shm checkpoint step %s (%s)",
            step, reason,
        )
        from dlrover_tpu.common.env import env_float

        # bounded commit: under preemption the peer node may never
        # write its done file; the shards themselves are persisted
        # either way, and a restart must not stall behind the poll
        return self.save_step_checkpoint(
            step,
            commit_timeout=env_float(
                "DLROVER_TPU_EMERGENCY_COMMIT_TIMEOUT_S", 20.0
            ),
        )

    #: whether the atexit fallback flush is armed (non-main-thread
    #: embedders that could not install the SIGTERM hook)
    _atexit_registered = False

    @classmethod
    def register_signal_handlers(cls):
        """Install the SIGTERM flush hook.  Must run on the MAIN thread
        (``signal.signal`` raises ValueError elsewhere) — the factory
        thread therefore never calls this; the agent does, once, before
        starting the factory."""

        def _on_term(signum, frame):  # pragma: no cover - signal path
            saver = cls._instance
            if saver is not None:
                saver.save_shm_to_storage(reason=f"signal {signum}")
            raise SystemExit(128 + signum)

        signal.signal(signal.SIGTERM, _on_term)

    @classmethod
    def _atexit_flush(cls):
        """Fallback crash-snapshot flush for embedders that could not
        install the SIGTERM hook: runs at interpreter shutdown, so a
        clean SystemExit (including the one a SIGTERM's default
        handler does NOT produce, but an embedder's catch-and-exit
        does) still lands the last shm snapshot in storage."""
        saver = cls._instance
        if saver is not None and not saver._stopped:
            try:
                saver.save_shm_to_storage(reason="atexit fallback")
            except Exception as e:  # noqa: BLE001 - shutdown path
                logger.warning("atexit ckpt flush failed: %s", e)

    @classmethod
    def register_atexit_fallback(cls):
        """Arm the atexit fallback flush + warning metric.  Called
        when ``register_signal_handlers`` failed (not on the main
        thread): embedded/test callers still get the crash snapshot
        on any orderly interpreter exit, and the metric flags that
        TRUE kill-signal coverage is missing."""
        import atexit

        if cls._atexit_registered:
            return
        cls._atexit_registered = True
        atexit.register(cls._atexit_flush)
        try:
            from dlrover_tpu.observability.metrics import get_registry

            get_registry().inc_counter(
                "dlrover_tpu_ckpt_sigterm_fallback"
            )
        except Exception:  # noqa: BLE001 - metrics never break startup
            pass

    # -- factory (class-level) ---------------------------------------------
    @classmethod
    def start_async_saving_ckpt(cls, install_signal_handlers: bool = True):
        """Run the factory thread: training processes push SaverConfig
        onto the factory queue; the agent builds the saver lazily
        (reference ``:411-434``)."""
        if install_signal_handlers:
            try:
                cls.register_signal_handlers()
            except ValueError:
                # embedded/test caller off the main thread: a SIGTERM
                # will not flush, but an orderly interpreter exit
                # still can — arm the atexit fallback instead of
                # silently dropping crash-snapshot coverage
                logger.warning(
                    "not on main thread: SIGTERM flush hook not "
                    "installed; registering atexit fallback flush"
                )
                cls.register_atexit_fallback()
        factory_queue = SharedQueue(FACTORY_QUEUE, create=True)

        def _factory_loop():
            while True:
                try:
                    config: SaverConfig = factory_queue.get(timeout=2)
                except queue.Empty:
                    continue
                except Exception:  # queue closed
                    return
                if cls._instance is not None:
                    logger.info("ckpt saver already exists; skip")
                    continue
                saver = cls(config)
                saver.start()
                cls._instance = saver
                logger.info("ckpt saver created from factory event")

        cls._factory_thread = threading.Thread(
            target=_factory_loop, name="ckpt-factory", daemon=True
        )
        cls._factory_thread.start()
        return factory_queue

    @classmethod
    def get_ckpt_saver(cls) -> Optional["AsyncCheckpointSaver"]:
        return cls._instance

    @classmethod
    def reset(cls):
        if cls._instance is not None:
            cls._instance.close()
            cls._instance = None


def find_latest_checkpoint(root: str, storage=None) -> Optional[str]:
    """Resolve the newest committed checkpoint dir via the tracker."""
    storage = storage or get_checkpoint_storage(path=root)
    tracker = os.path.join(root, CheckpointConstant.TRACKER_FILE)
    content = storage.read(tracker)
    if content:
        step = content.strip()
        path = os.path.join(
            root, f"{CheckpointConstant.CKPT_DIR_PREFIX}{step}"
        )
        if storage.exists(path):
            return path
    # fall back to scanning
    candidates = []
    for entry in storage.listdir(root):
        if entry.startswith(CheckpointConstant.CKPT_DIR_PREFIX):
            try:
                candidates.append(
                    int(entry[len(CheckpointConstant.CKPT_DIR_PREFIX):])
                )
            except ValueError:
                continue
    if not candidates:
        return None
    return os.path.join(
        root,
        f"{CheckpointConstant.CKPT_DIR_PREFIX}{max(candidates)}",
    )
