"""Cross-node checkpoint replicas: peer shm backup + recovery.

Reference parity: ``dlrover/trainer/torch/flash_checkpoint/replica.py``
(``CkptReplicaManger:28,73``: backup shm shards to peer ranks via
allgather ``:116``, ``gather:193`` restores a relaunched node's shard
from its peer).  The reference rides NCCL/gloo; agents here exchange
shard bytes host-to-host over a tiny length-prefixed TCP protocol
(DCN path — device HBM is never involved), so a node that comes back
with empty shm can pull its last snapshot from its backup peer faster
than any storage read.

Protocol (one request per connection):
  ``GET <rank>\n``              -> ``<8-byte len><payload>`` (len 0 = miss)
  ``PUT <rank> <len>\n<bytes>`` -> ``OK\n``
"""

import socket
import threading
from typing import Callable, Dict, Optional

from dlrover_tpu.common.env import get_free_port
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.netio import (
    LEN as _LEN,
    recv_exact as _recv_exact,
    recv_line as _recv_line,
)


class ReplicaService:
    """Per-agent replica store + TCP server."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0):
        self._store: Dict[int, bytes] = {}
        self._lock = threading.Lock()
        self._port = port or get_free_port()
        self._host = host
        self._srv: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None
        self._stopped = threading.Event()

    @property
    def port(self) -> int:
        return self._port

    # -------------------------------------------------------- local API
    def put_local(self, rank: int, payload: bytes):
        with self._lock:
            self._store[rank] = payload

    def get_local(self, rank: int) -> Optional[bytes]:
        with self._lock:
            return self._store.get(rank)

    # ----------------------------------------------------------- server
    def start(self):
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((self._host, self._port))
        self._srv.listen(8)
        self._srv.settimeout(0.5)
        self._thread = threading.Thread(
            target=self._serve, name="replica-service", daemon=True
        )
        self._thread.start()

    def stop(self):
        self._stopped.set()
        if self._srv is not None:
            self._srv.close()

    def _serve(self):
        while not self._stopped.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                self._handle(conn)
            except (ConnectionError, OSError) as e:
                logger.warning("replica request failed: %s", e)
            finally:
                conn.close()

    def _handle(self, conn: socket.socket):
        line = _recv_line(conn)
        parts = line.split()
        if parts[0] == "GET":
            payload = self.get_local(int(parts[1])) or b""
            conn.sendall(_LEN.pack(len(payload)))
            if payload:
                conn.sendall(payload)
        elif parts[0] == "PUT":
            rank, size = int(parts[1]), int(parts[2])
            payload = _recv_exact(conn, size)
            self.put_local(rank, payload)
            conn.sendall(b"OK\n")


def push_replica(addr: str, rank: int, payload: bytes,
                 timeout: float = 60.0) -> bool:
    host, _, port = addr.rpartition(":")
    try:
        with socket.create_connection(
            (host, int(port)), timeout=timeout
        ) as conn:
            conn.sendall(f"PUT {rank} {len(payload)}\n".encode())
            conn.sendall(payload)
            return _recv_line(conn) == "OK"
    except (OSError, ConnectionError) as e:
        logger.warning("replica push to %s failed: %s", addr, e)
        return False


def fetch_replica(addr: str, rank: int,
                  timeout: float = 60.0) -> Optional[bytes]:
    host, _, port = addr.rpartition(":")
    try:
        with socket.create_connection(
            (host, int(port)), timeout=timeout
        ) as conn:
            conn.sendall(f"GET {rank}\n".encode())
            size = _LEN.unpack(_recv_exact(conn, _LEN.size))[0]
            if size == 0:
                return None
            return _recv_exact(conn, size)
    except (OSError, ConnectionError) as e:
        logger.warning("replica fetch from %s failed: %s", addr, e)
        return None


class ReplicaManager:
    """Backs up this node's shard to ``(node_rank + k) % n`` peers.

    ``peer_addrs`` maps node_rank -> "host:port" of each agent's
    ReplicaService (agents register these through the master's
    NodeAddress registry).
    """

    def __init__(
        self,
        node_rank: int,
        service: ReplicaService,
        peer_addrs_fn: Callable[[], Dict[int, str]],
        backup_count: int = 1,
    ):
        self._node_rank = node_rank
        self._service = service
        self._peer_addrs_fn = peer_addrs_fn
        self._backup_count = backup_count

    def backup(self, payload: bytes) -> int:
        """Push this node's shard to its backup peers; returns how many
        replicas landed."""
        peers = self._peer_addrs_fn()
        n = len(peers)
        if n <= 1:
            return 0
        ok = 0
        for k in range(1, self._backup_count + 1):
            target = (self._node_rank + k) % n
            if target == self._node_rank:
                continue
            addr = peers.get(target)
            if addr and push_replica(addr, self._node_rank, payload):
                ok += 1
        return ok

    def restore(self) -> Optional[bytes]:
        """A relaunched node pulls its shard from whichever peer holds
        the replica (reference ``gather:193``)."""
        local = self._service.get_local(self._node_rank)
        if local is not None:
            return local
        peers = self._peer_addrs_fn()
        n = len(peers)
        # replicas were pushed to (rank + k): ask those peers
        for k in range(1, max(n, 2)):
            holder = (self._node_rank + k) % n
            if holder == self._node_rank:
                continue
            addr = peers.get(holder)
            if not addr:
                continue
            payload = fetch_replica(addr, self._node_rank)
            if payload is not None:
                logger.info(
                    "restored shard %d from peer %d (%d bytes)",
                    self._node_rank, holder, len(payload),
                )
                return payload
        return None
