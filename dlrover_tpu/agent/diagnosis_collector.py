"""Agent-side diagnosis data collectors.

Reference parity: ``dlrover/python/elastic_agent/datacollector/*``
(cuda-log / log / metrics collectors, ~130 LoC skeletons feeding the
master's DiagnosisManager) and the diagnosis agent of
``elastic_agent/monitor/diagnosis.py:112``.  The TPU forms:

* :class:`TrainingLogCollector` — incrementally tails the training
  process's log file and ships only NEW error-class lines (XLA/HBM
  OOM, RESOURCE_EXHAUSTED, tracebacks, NaN reports) to the master,
  where the inference chain (``master/diagnosis.py``) pattern-matches
  them into recovery verdicts.  There is no CUDA-log analog on TPU —
  the XLA error text IS the chip-side log.
* :class:`ChipMetricsCollector` — forwards the chip-stats JSON the
  training process drops (device HBM in use, duty cycle; the agent
  cannot open the TPU runtime itself) as CHIP_METRICS diagnosis data.

Both run on the agent's :class:`PeriodicReporter` daemon-thread loop
and survive master connectivity blips.
"""

import json
import os
import re
from typing import List, Optional

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.agent.monitor import PeriodicReporter
from dlrover_tpu.common.log import default_logger as logger

# lines worth shipping to the master's inference chain; everything
# else stays on the node (the reference ships whole logs to Brain —
# on TPU slices that volume would ride DCN for no diagnostic value)
_ERROR_PATTERN = re.compile(
    r"(RESOURCE_EXHAUSTED|OOM|out of memory|Traceback|"
    r"FAILED_PRECONDITION|DEADLINE_EXCEEDED|UNAVAILABLE|"
    r"NaN|non-finite|loss spike|halted|XlaRuntimeError)",
    re.IGNORECASE,
)
_MAX_LINES_PER_TICK = 50
_MAX_LINE_CHARS = 500


class TrainingLogCollector(PeriodicReporter):
    """Tail ``log_file`` from the last read offset; report error-class
    lines as TRAINING_LOG diagnosis data."""

    name = "training-log-collector"

    def __init__(
        self,
        log_file: str,
        client: Optional[MasterClient] = None,
        interval: float = 30.0,
        node_rank: int = -1,
    ):
        super().__init__(client, interval)
        self._log_file = log_file
        self._offset = 0
        self._node_rank = node_rank

    def _read_new_lines(self) -> List[str]:
        if not self._log_file or not os.path.exists(self._log_file):
            return []
        try:
            size = os.path.getsize(self._log_file)
            if size < self._offset:  # rotated/truncated: restart
                self._offset = 0
            with open(
                self._log_file, "r", errors="replace"
            ) as f:
                f.seek(self._offset)
                chunk = f.read()
                self._offset = f.tell()
        except OSError:
            return []
        return chunk.splitlines()

    def _tick(self):
        hits = [
            line[:_MAX_LINE_CHARS]
            for line in self._read_new_lines()
            if _ERROR_PATTERN.search(line)
        ][:_MAX_LINES_PER_TICK]
        if not hits:
            return
        from dlrover_tpu.master.diagnosis import DiagnosisDataType

        self._client.report_diagnosis_data(
            DiagnosisDataType.TRAINING_LOG,
            "\n".join(hits),
            node_rank=self._node_rank,
        )
        logger.info(
            "shipped %d error log lines for diagnosis", len(hits)
        )


class ChipMetricsCollector(PeriodicReporter):
    """Forward the training process's chip-stats drop file as
    CHIP_METRICS diagnosis data (device HBM bytes in use, duty cycle —
    the inference chain's straggler/OOM evidence)."""

    name = "chip-metrics-collector"

    def __init__(
        self,
        chip_stats_file: str = "",
        client: Optional[MasterClient] = None,
        interval: float = 60.0,
        node_rank: int = -1,
    ):
        super().__init__(client, interval)
        self._chip_stats_file = chip_stats_file or os.getenv(
            "DLROVER_TPU_CHIP_STATS_FILE", ""
        )
        self._node_rank = node_rank
        self._last_mtime = 0.0

    def _tick(self):
        f = self._chip_stats_file
        if not f or not os.path.exists(f):
            return
        try:
            mtime = os.path.getmtime(f)
            if mtime <= self._last_mtime:  # nothing new
                return
            with open(f) as fh:
                data = json.load(fh)
        except (OSError, ValueError):
            return
        self._last_mtime = mtime
        from dlrover_tpu.master.diagnosis import DiagnosisDataType

        self._client.report_diagnosis_data(
            DiagnosisDataType.CHIP_METRICS,
            json.dumps(data),
            node_rank=self._node_rank,
        )
