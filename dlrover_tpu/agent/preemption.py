"""TPU-VM preemption / maintenance-event watcher.

Reference parity: on GPU clusters the primary failure signal is the
k8s pod kill (SIGTERM → ``ckpt_saver`` flush, ``training.py``
restart); on TPU-VMs the PRIMARY signal is the GCE metadata server's
maintenance-event — it fires ~60s before the host is migrated or the
preemptible VM is terminated, long before any SIGTERM arrives
(SURVEY.md §7 "hard parts": the agent must subscribe to both).

``PreemptionWatcher`` plain-polls the instance metadata endpoint
every ``poll_interval`` seconds (well inside the ~60s preemption
lead; the metadata ``wait_for_change`` long-poll would shave the
interval but complicates the injectable-fetcher seam) and invokes
the registered callbacks once per event:
the agent wires these to (1) flush the latest shm checkpoint slot to
storage and (2) report the imminent failure to the master so the
rendezvous can fence the node before the hardware goes away.

The metadata fetcher is injectable (tests and non-GCE environments
never touch the network).
"""

import os
import threading
from typing import Callable, List, Optional

from dlrover_tpu.common.log import default_logger as logger

_METADATA_BASE = (
    "http://metadata.google.internal/computeMetadata/v1/instance/"
)


def _metadata_base() -> str:
    """Metadata server base URL; overridable so fault-injection
    harnesses (bench_goodput) can stand in a fake endpoint and drive
    the REAL watcher->flush->restart path."""
    return os.getenv("DLROVER_TPU_METADATA_BASE", _METADATA_BASE)
# Hosted-VM migration/termination and spot/preemptible termination
# are surfaced on DIFFERENT endpoints (maintenance-event says
# NONE/MIGRATE.../TERMINATE...; preempted says TRUE/FALSE) — a
# spot preemption never appears on maintenance-event, so both must
# be polled.
_METADATA_PATHS = ("maintenance-event", "preempted")
_NONE_EVENT = "NONE"


def _fetch_metadata(path: str, timeout: float) -> Optional[str]:
    import urllib.request

    req = urllib.request.Request(
        _metadata_base() + path,
        headers={"Metadata-Flavor": "Google"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.read().decode().strip()
    except OSError:
        return None


def _default_fetcher(timeout: float = 5.0) -> Optional[str]:
    """Poll maintenance-event then preempted; return the first
    non-idle value, an idle value when both endpoints answered idle,
    or None when the metadata server is unreachable (not on GCE)."""
    idle_seen: Optional[str] = None
    for path in _METADATA_PATHS:
        value = _fetch_metadata(path, timeout)
        if value is None:
            continue
        if value.upper() in (_NONE_EVENT, "FALSE", ""):
            idle_seen = value
            continue
        return "PREEMPTED" if path == "preempted" else value
    return idle_seen


class PreemptionWatcher:
    """Fire callbacks exactly once per maintenance event.

    Events (GCE contract): ``NONE`` (idle), ``MIGRATE_ON_HOST_MAINTENANCE``,
    ``TERMINATE_ON_HOST_MAINTENANCE``; preemptible VMs surface
    ``TRUE``/``FALSE`` on the preempted endpoint — any non-idle value
    is treated as "hardware goes away soon"."""

    def __init__(
        self,
        fetcher: Optional[Callable[[], Optional[str]]] = None,
        poll_interval: Optional[float] = None,
    ):
        self._fetch = fetcher or _default_fetcher
        if poll_interval is None:
            # well inside the ~60s preemption lead; harnesses shrink
            # it so graceful-path recovery is measurable at CI scale
            raw = os.getenv("DLROVER_TPU_PREEMPTION_POLL", "5.0")
            try:
                poll_interval = float(raw)
            except ValueError:
                logger.warning(
                    "ignoring malformed DLROVER_TPU_PREEMPTION_POLL"
                    "=%r", raw,
                )
                poll_interval = 5.0
        self._interval = poll_interval
        self._callbacks: List[Callable[[str], None]] = []
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_event = _NONE_EVENT
        self.unavailable = False  # metadata server unreachable

    def on_preemption(self, callback: Callable[[str], None]):
        """Register ``callback(event_str)``; called from the watcher
        thread once per distinct non-idle event."""
        self._callbacks.append(callback)

    def _is_idle(self, value: Optional[str]) -> bool:
        return value is None or value.upper() in (_NONE_EVENT, "FALSE", "")

    def check_once(self) -> Optional[str]:
        """One poll; fires callbacks on a NEW non-idle event and
        returns it (None otherwise)."""
        value = self._fetch()
        if value is None:
            if not self.unavailable:
                self.unavailable = True
                logger.info(
                    "metadata server unreachable; preemption watcher "
                    "idle (not on GCE)"
                )
            return None
        self.unavailable = False
        if self._is_idle(value):
            self._last_event = _NONE_EVENT
            return None
        if value == self._last_event:
            return None  # already reported this event
        self._last_event = value
        logger.warning("maintenance event: %s — flushing state", value)
        from dlrover_tpu.observability.events import get_event_logger

        get_event_logger().instant("preemption_signal", event=value)
        for cb in self._callbacks:
            try:
                cb(value)
            except Exception as e:  # noqa: BLE001
                logger.error("preemption callback failed: %s", e)
        return value

    def _loop(self):
        backoff = self._interval
        while not self._stopped.wait(backoff):
            self.check_once()
            # when not on GCE, poll rarely — the endpoint won't appear
            backoff = 300.0 if self.unavailable else self._interval

    def start(self):
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="preemption-watcher", daemon=True
        )
        self._thread.start()

    def stop(self):
        self._stopped.set()
