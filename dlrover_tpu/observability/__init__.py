from dlrover_tpu.observability.events import (  # noqa: F401
    EventLogger,
    TimelineAggregator,
    compute_ledger,
    export_chrome_trace,
    get_event_logger,
    read_events,
)
from dlrover_tpu.observability.metrics import (  # noqa: F401
    MetricsExporter,
    MetricsRegistry,
)
from dlrover_tpu.observability.health import HealthEngine  # noqa: F401
from dlrover_tpu.observability.profiler import AProfiler  # noqa: F401
from dlrover_tpu.observability.status_server import (  # noqa: F401
    StatusServer,
)
from dlrover_tpu.observability.hlo_census import (  # noqa: F401
    census_report,
    gemm_census,
)
