from dlrover_tpu.observability.metrics import (  # noqa: F401
    MetricsExporter,
    MetricsRegistry,
)
from dlrover_tpu.observability.profiler import AProfiler  # noqa: F401
