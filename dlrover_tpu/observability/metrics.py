"""Training metrics registry + native Prometheus exporter control.

Reference parity: xpu_timer's bvar/Prometheus export
(``atorch/dev/xpu_timer``, port 28888+rank).  Training processes write
counters/gauges through ``MetricsRegistry`` (atomic file rewrite);
the C++ daemon (``native/metrics_exporter/exporter.cc``) serves them
as Prometheus text on 28888+rank.
"""

import re
import os
import subprocess
import tempfile
import threading
import time
from typing import Dict, List, Optional, Tuple

from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.native_build import needs_rebuild, write_stamp

BASE_PORT = 28888  # xpu_timer's port convention


def log_bounds(base: float, growth: float, count: int) -> Tuple[float, ...]:
    """Geometric (log-spaced) histogram bucket upper bounds:
    ``base * growth**i`` for ``i in range(count)``.  Log buckets give
    constant RELATIVE resolution — the right shape for latencies and
    sizes, whose interesting range spans decades."""
    return tuple(base * growth ** i for i in range(count))


#: default latency buckets: 100 µs .. ~210 s, ×2 per bucket (22
#: buckets + the implicit +Inf).  A control-plane RPC lands in the
#: low-millisecond buckets when healthy and walks up the ladder as the
#: master saturates — exactly the drift the p99 gauges key on.
LATENCY_BOUNDS = log_bounds(1e-4, 2.0, 22)
#: default size buckets: 64 B .. ~1 GB, ×4 per bucket (13 buckets +
#: +Inf) — request/response payloads and flush batches.
SIZE_BOUNDS = log_bounds(64.0, 4.0, 13)


class Histogram:
    """One log-bucketed histogram series: cumulative bucket counts +
    sum + count, rendered in the classic Prometheus text format
    (``<name>_bucket{le=...}`` / ``<name>_sum`` / ``<name>_count``).
    NOT thread-safe on its own — the owning registry's lock guards
    every observe/render."""

    __slots__ = ("bounds", "counts", "sum", "count")

    def __init__(self, bounds: Tuple[float, ...] = LATENCY_BOUNDS):
        self.bounds = tuple(sorted(bounds))
        # one count per finite bound + the +Inf overflow bucket;
        # NON-cumulative internally (one increment per observe),
        # accumulated at render time
        self.counts = [0] * (len(self.bounds) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float):
        value = float(value)
        self.sum += value
        self.count += 1
        # linear scan: bounds are ~20 entries and the loop is cheaper
        # than bisect's call overhead at that size
        for i, bound in enumerate(self.bounds):
            if value <= bound:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def quantile(self, q: float) -> float:
        """Upper-bound estimate of the ``q`` quantile (0..1) from the
        bucket counts: the smallest bucket bound whose cumulative
        count reaches ``q * count``.  Observations past the last
        finite bound report that bound — an under-estimate, loudly
        conservative rather than invented."""
        if self.count <= 0:
            return 0.0
        target = q * self.count
        cum = 0
        for i, bound in enumerate(self.bounds):
            cum += self.counts[i]
            if cum >= target:
                return bound
        return self.bounds[-1] if self.bounds else 0.0

    @staticmethod
    def _fmt_le(bound: float) -> str:
        return f"{bound:.9g}"

    def render_lines(
        self, name: str, inner_labels: str, stamp: str = ""
    ) -> List[str]:
        """The exposition lines for this series.  ``inner_labels`` is
        the pre-rendered ``k="v"`` list (may be empty); ``le`` is
        appended last so the caller's label escaping is reused."""
        lines = []
        cum = 0
        for i, bound in enumerate(self.bounds):
            cum += self.counts[i]
            le = f'le="{self._fmt_le(bound)}"'
            inner = f"{inner_labels},{le}" if inner_labels else le
            lines.append(f"{name}_bucket{{{inner}}} {cum}{stamp}")
        le = 'le="+Inf"'
        inner = f"{inner_labels},{le}" if inner_labels else le
        lines.append(f"{name}_bucket{{{inner}}} {self.count}{stamp}")
        suffix = f"{{{inner_labels}}}" if inner_labels else ""
        lines.append(f"{name}_sum{suffix} {self.sum:.9g}{stamp}")
        lines.append(f"{name}_count{suffix} {self.count}{stamp}")
        return lines

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_SRC = os.path.join(
    _REPO_ROOT, "native", "metrics_exporter", "exporter.cc"
)
_BIN_DIR = os.path.join(_REPO_ROOT, "native", "metrics_exporter", "build")
_BIN = os.path.join(_BIN_DIR, "metrics_exporter")


class MetricsRegistry:
    """Process-local metric store flushed to the exporter file."""

    def __init__(self, path: str = "", flush_interval: float = 5.0,
                 rank: Optional[int] = None):
        """``rank``: when set, every metric carries a ``rank`` label —
        the per-rank series the reference's per-rank bvar exporters
        provide (aggregation then happens in PromQL, not here)."""
        self._path = path or os.path.join(
            tempfile.gettempdir(),
            f"dlrover_tpu_metrics_{os.getpid()}.prom",
        )
        self._metrics: Dict[str, float] = {}
        #: (name, rendered-inner-labels) -> Histogram — kept separate
        #: from the scalar map because one logical series renders as
        #: many exposition lines
        self._histograms: Dict[Tuple[str, str], Histogram] = {}
        self._lock = threading.Lock()
        self._flush_interval = flush_interval
        self._last_flush = 0.0
        self._rank = rank

    @property
    def path(self) -> str:
        return self._path

    @staticmethod
    def _escape_label(value) -> str:
        """Prometheus text-format label escaping (backslash, quote,
        newline) — an unescaped quote in a value would corrupt the
        whole exposition line."""
        return (
            str(value)
            .replace("\\", "\\\\")
            .replace('"', '\\"')
            .replace("\n", "\\n")
        )

    _NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")

    def _inner_labels(self, labels: Optional[Dict] = None) -> str:
        """The rendered ``k="v"`` label list (no braces; "" when no
        labels survive the merge)."""
        merged = dict(labels or {})
        if self._rank is not None:
            merged.setdefault("rank", self._rank)
        if not merged:
            return ""
        return ",".join(
            f'{self._NAME_RE.sub("_", str(k))}='
            f'"{self._escape_label(v)}"'
            for k, v in sorted(merged.items())
        )

    def _key(self, name: str, labels: Optional[Dict] = None) -> str:
        name = self._NAME_RE.sub("_", name)
        inner = self._inner_labels(labels)
        if not inner:
            return name
        return f"{name}{{{inner}}}"

    def set_gauge(self, name: str, value: float, labels=None):
        with self._lock:
            self._metrics[self._key(name, labels)] = float(value)
        self._maybe_flush()

    def inc_counter(self, name: str, value: float = 1.0, labels=None):
        key = self._key(name, labels)
        with self._lock:
            self._metrics[key] = self._metrics.get(key, 0.0) + value
        self._maybe_flush()

    def observe_duration(self, name: str, seconds: float, labels=None):
        """Simple duration tracking: _sum/_count pair."""
        self.inc_counter(name + "_seconds_sum", seconds, labels)
        self.inc_counter(name + "_count", 1.0, labels)

    def observe_histogram(self, name: str, value: float, labels=None,
                          bounds: Optional[Tuple[float, ...]] = None):
        """Record one observation into a log-bucketed histogram
        series (created on first observe; ``bounds`` only applies
        then — a series' bucket layout is immutable).  Rendered as
        classic Prometheus ``_bucket``/``_sum``/``_count`` lines by
        ``render_text()``/``flush()``."""
        name = self._NAME_RE.sub("_", name)
        with self._lock:
            key = (name, self._inner_labels(labels))
            hist = self._histograms.get(key)
            if hist is None:
                hist = self._histograms[key] = Histogram(
                    bounds if bounds is not None else LATENCY_BOUNDS
                )
            hist.observe(value)
        self._maybe_flush()

    def histogram(self, name: str, labels=None) -> Optional[Histogram]:
        """The live ``Histogram`` for a series (None before its first
        observe) — quantile reads for the self-telemetry snapshot and
        the fleet bench.  The returned object is shared; treat it as
        read-only."""
        with self._lock:
            return self._histograms.get(
                (self._NAME_RE.sub("_", name),
                 self._inner_labels(labels))
            )

    def histogram_series(self, name: str) -> Dict[str, Histogram]:
        """Every label-set of one histogram name, keyed by the
        rendered inner-label string (reader for per-kind sweeps)."""
        name = self._NAME_RE.sub("_", name)
        with self._lock:
            return {
                inner: hist
                for (n, inner), hist in self._histograms.items()
                if n == name
            }

    def retire_series(self, labels: Dict) -> int:
        """Drop every series — scalar gauges/counters AND histogram
        series — carrying ALL of the given label pairs, and return
        how many were dropped.  A dead or drained serving replica's
        ``dlrover_tpu_serving_*{replica=...}`` gauges would otherwise
        keep their last values on ``/metrics`` forever, reading as a
        live-but-frozen replica; retiring the series makes the death
        visible as absence."""
        pairs = {
            f'{self._NAME_RE.sub("_", str(k))}='
            f'"{self._escape_label(v)}"'
            for k, v in labels.items()
        }
        if not pairs:
            return 0
        dropped = 0
        with self._lock:
            for key in list(self._metrics):
                if "{" not in key:
                    continue
                inner = key[key.index("{") + 1:key.rindex("}")]
                if pairs <= set(inner.split(",")):
                    del self._metrics[key]
                    dropped += 1
            for hkey in list(self._histograms):
                if pairs <= set(hkey[1].split(",")):
                    del self._histograms[hkey]
                    dropped += 1
        self._maybe_flush()
        return dropped

    def _histogram_lines(self, stamp: str = "") -> list:
        """Caller holds the lock."""
        lines = []
        for (name, inner) in sorted(self._histograms):
            lines.extend(
                self._histograms[(name, inner)].render_lines(
                    name, inner, stamp
                )
            )
        return lines

    def render_text(self) -> str:
        """The current metrics as Prometheus exposition text for the
        master's plain-HTTP ``/metrics`` endpoint.  NO trailing
        timestamp: the classic text format demands int64
        *milliseconds* there, and the seconds-float stamp ``flush``
        writes (which the C++ exporter strips before serving, using
        it only for staleness eviction) would make a real Prometheus
        scrape land every sample at ~epoch — served samples must
        carry the scrape time instead."""
        with self._lock:
            lines = [
                f"{k} {v:.9g}"
                for k, v in sorted(self._metrics.items())
            ]
            lines.extend(self._histogram_lines())
        return "\n".join(lines) + "\n"

    def _maybe_flush(self):
        now = time.time()
        if now - self._last_flush >= self._flush_interval:
            self.flush()

    def flush(self):
        with self._lock:
            now = time.time()
            # trailing unix timestamp (Prometheus text format allows
            # it) is what lets the exporter evict STALE series — a
            # crashed writer's last file would otherwise be served as
            # live forever
            lines = [
                f"{k} {v:.9g} {now:.3f}"
                for k, v in sorted(self._metrics.items())
            ]
            lines.extend(self._histogram_lines(f" {now:.3f}"))
            self._last_flush = now
        tmp = self._path + ".tmp"
        try:
            with open(tmp, "w") as f:
                f.write("\n".join(lines) + "\n")
            os.replace(tmp, self._path)
        except OSError as e:
            logger.warning("metrics flush failed: %s", e)


_default_registry: Optional[MetricsRegistry] = None
_default_registry_lock = threading.Lock()


def get_registry() -> MetricsRegistry:
    """Process-wide default registry (the Trainer installs its own as
    the default when it starts, so library counters land in the same
    exporter file)."""
    global _default_registry
    with _default_registry_lock:
        if _default_registry is None:
            _default_registry = MetricsRegistry()
        return _default_registry


def set_default_registry(registry: MetricsRegistry):
    global _default_registry
    with _default_registry_lock:
        _default_registry = registry


def record_ckpt_io(kind: str, nbytes: int, seconds: float):
    """Export one checkpoint data-plane measurement as gauges
    (``dlrover_tpu_ckpt_io_gbps{kind=...}`` / ``_bytes{kind=...}``).
    ``kind``: drain | restore | persist | prealloc.  Never raises —
    metrics must not break a save."""
    try:
        reg = get_registry()
        gbps = nbytes / 1e9 / max(seconds, 1e-9)
        reg.set_gauge(
            "dlrover_tpu_ckpt_io_gbps", gbps, labels={"kind": kind}
        )
        reg.set_gauge(
            "dlrover_tpu_ckpt_io_bytes",
            float(nbytes),
            labels={"kind": kind},
        )
    except Exception as e:  # noqa: BLE001
        logger.warning("ckpt io metric export failed: %s", e)


def record_input_io(stage: str, nbytes: int, seconds: float):
    """Export one input data-plane measurement as gauges
    (``dlrover_tpu_input_gbps{stage=...}`` / ``_bytes{stage=...}``).
    ``stage``: ``host_fetch`` (what the consumer waited on) |
    ``read_batch`` (the loader producer pool's raw fetch bandwidth —
    distinct so stacking ``host_prefetch`` over an already-pipelined
    loader doesn't fold two measurements into one series) | ``h2d``.
    Never raises — metrics must not break the input pipeline."""
    try:
        reg = get_registry()
        gbps = nbytes / 1e9 / max(seconds, 1e-9)
        reg.set_gauge(
            "dlrover_tpu_input_gbps", gbps, labels={"stage": stage}
        )
        reg.set_gauge(
            "dlrover_tpu_input_bytes",
            float(nbytes),
            labels={"stage": stage},
        )
    except Exception as e:  # noqa: BLE001
        logger.warning("input io metric export failed: %s", e)


def record_serving(
    replica: str,
    tokens_per_s=None,
    queue_depth=None,
    kv_blocks_used=None,
    p99_latency_s=None,
    kv_utilization=None,
    preemptions=None,
    prefix_hit_rate=None,
    accepted_tokens_per_step=None,
):
    """Export one serving-plane snapshot as gauges
    (``dlrover_tpu_serving_*{replica=...}``): generation throughput,
    dispatch/admission queue depth, paged-KV pool occupancy, the
    dispatcher-side end-to-end p99, plus the incremental-allocation
    vitals — filled-cache utilization, cumulative preemptions, the
    shared-block prefix hit rate and the multi-token decode
    accept-per-window mean — the numbers the serving pane in
    ``scripts/top.py`` and ``bench_serving.py`` key on.  ``None``
    fields are skipped (replicas know their pool, only the dispatcher
    knows fleet latency).  Never raises — metrics must not break the
    serving loop."""
    try:
        reg = get_registry()
        labels = {"replica": replica}
        if tokens_per_s is not None:
            reg.set_gauge(
                "dlrover_tpu_serving_tokens_per_s",
                float(tokens_per_s),
                labels=labels,
            )
        if queue_depth is not None:
            reg.set_gauge(
                "dlrover_tpu_serving_queue_depth",
                float(queue_depth),
                labels=labels,
            )
        if kv_blocks_used is not None:
            reg.set_gauge(
                "dlrover_tpu_serving_kv_blocks_used",
                float(kv_blocks_used),
                labels=labels,
            )
        if p99_latency_s is not None:
            reg.set_gauge(
                "dlrover_tpu_serving_p99_latency",
                float(p99_latency_s),
                labels=labels,
            )
        if kv_utilization is not None:
            reg.set_gauge(
                "dlrover_tpu_serving_kv_utilization",
                float(kv_utilization),
                labels=labels,
            )
        if preemptions is not None:
            reg.set_gauge(
                "dlrover_tpu_serving_preemptions",
                float(preemptions),
                labels=labels,
            )
        if prefix_hit_rate is not None:
            reg.set_gauge(
                "dlrover_tpu_serving_prefix_hit_rate",
                float(prefix_hit_rate),
                labels=labels,
            )
        if accepted_tokens_per_step is not None:
            reg.set_gauge(
                "dlrover_tpu_serving_accepted_tokens_per_step",
                float(accepted_tokens_per_step),
                labels=labels,
            )
    except Exception as e:  # noqa: BLE001
        logger.warning("serving metric export failed: %s", e)


def record_serving_latency(
    replica: str,
    ttft_s=None,
    tbt_p99_s=None,
    e2e_s=None,
    queue_wait_s=None,
):
    """Observe one completed request's SLO latencies into the
    per-replica log-bucketed histograms
    (``dlrover_tpu_serving_{ttft,tbt,e2e,queue_wait}_seconds``),
    rendered as classic ``_bucket``/``_sum``/``_count`` exposition —
    the quantile source for ``/status`` and the SLO-straggler
    derivation.  ``tbt_p99_s`` observations are the request-level
    per-token-gap p99 (one sample per request, not per token — the
    series is a distribution over requests).  Inert when
    ``DLROVER_TPU_SERVE_OBS=0`` (no series created).  Never raises."""
    from dlrover_tpu.common.env import serve_obs_enabled

    if not serve_obs_enabled():
        return
    try:
        reg = get_registry()
        labels = {"replica": replica}
        if ttft_s is not None:
            reg.observe_histogram(
                "dlrover_tpu_serving_ttft_seconds",
                float(ttft_s), labels=labels,
            )
        if tbt_p99_s is not None:
            reg.observe_histogram(
                "dlrover_tpu_serving_tbt_seconds",
                float(tbt_p99_s), labels=labels,
            )
        if e2e_s is not None:
            reg.observe_histogram(
                "dlrover_tpu_serving_e2e_seconds",
                float(e2e_s), labels=labels,
            )
        if queue_wait_s is not None:
            reg.observe_histogram(
                "dlrover_tpu_serving_queue_wait_seconds",
                float(queue_wait_s), labels=labels,
            )
    except Exception as e:  # noqa: BLE001
        logger.warning("serving latency export failed: %s", e)


def record_offload_io(nbytes: int, seconds: float, buffered: bool):
    """Export one host-offload chunk-stream measurement as gauges
    (``dlrover_tpu_offload_gbps{buffered=...}`` / ``_bytes``): the
    optimizer-state host<->device traffic of one streamed update.
    ``buffered`` distinguishes the rolling double-buffered DMA window
    from the serial (kill-switched) stream so a regression in the
    overlap shows up as a ratio between the two series.  Never raises
    — metrics must not break a train step."""
    try:
        reg = get_registry()
        gbps = nbytes / 1e9 / max(seconds, 1e-9)
        labels = {"buffered": "1" if buffered else "0"}
        reg.set_gauge(
            "dlrover_tpu_offload_gbps", gbps, labels=labels
        )
        reg.set_gauge(
            "dlrover_tpu_offload_bytes", float(nbytes), labels=labels
        )
    except Exception as e:  # noqa: BLE001
        logger.warning("offload io metric export failed: %s", e)


def record_reshard_io(from_world: int, to_world: int, nbytes: int,
                      seconds: float):
    """Export one elastic-reshard restore measurement as gauges
    (``dlrover_tpu_reshard_gbps`` / ``_bytes``, labeled with the world
    transition) plus a ``dlrover_tpu_reshard_total`` counter: the
    overlap-range bytes that reassembled this rank's new slices from a
    different-world checkpoint.  Never raises — metrics must not break
    a restore."""
    try:
        reg = get_registry()
        labels = {
            "from_world": str(int(from_world)),
            "to_world": str(int(to_world)),
        }
        reg.set_gauge(
            "dlrover_tpu_reshard_gbps",
            nbytes / 1e9 / max(seconds, 1e-9),
            labels=labels,
        )
        reg.set_gauge(
            "dlrover_tpu_reshard_bytes", float(nbytes), labels=labels
        )
        reg.inc_counter("dlrover_tpu_reshard_total")
    except Exception as e:  # noqa: BLE001
        logger.warning("reshard metric export failed: %s", e)


def record_datastore_flush(rows: int, seconds: float):
    """One write-behind flush batch landed: its commit latency feeds
    the ``dlrover_tpu_datastore_flush_seconds`` histogram and the
    batch size the ``dlrover_tpu_datastore_flush_rows`` histogram —
    the tail of this distribution is the journal's durability lag
    under load.  Gated by ``DLROVER_TPU_SELF_OBS=0`` (the pre-self-obs
    metric surface must stay exact).  Never raises — telemetry must
    not break a flush."""
    from dlrover_tpu.common.env import self_obs_enabled

    try:
        if not self_obs_enabled():
            return
        reg = get_registry()
        reg.observe_histogram(
            "dlrover_tpu_datastore_flush_seconds", seconds
        )
        reg.observe_histogram(
            "dlrover_tpu_datastore_flush_rows", float(rows),
            bounds=SIZE_BOUNDS,
        )
    except Exception as e:  # noqa: BLE001
        logger.warning("datastore flush metric export failed: %s", e)


def record_dropped_reports(n: int = 1):
    """Count fire-and-forget reports dropped by the client-side
    ``ReportBuffer`` overflow cap during a master outage
    (``dlrover_tpu_control_dropped_reports``).  A nonzero rate means
    the outage outlived the buffer — telemetry from that window is
    gone (training state is unaffected; reports are advisory).  Never
    raises."""
    try:
        get_registry().inc_counter(
            "dlrover_tpu_control_dropped_reports", float(n)
        )
    except Exception as e:  # noqa: BLE001
        logger.warning("dropped-report metric export failed: %s", e)


#: windowed meter behind ``dlrover_tpu_control_rps``: the master's
#: servicer calls ``record_control_rpc`` per RPC; the rate gauge is
#: recomputed at most once per window so the metric itself cannot
#: become control-plane load
_CONTROL_RPS_WINDOW_S = 5.0
_control_rpc_lock = threading.Lock()
_control_rpc_window_start = 0.0
_control_rpc_window_count = 0


def record_control_rpc(n: int = 1):
    """Count one (or ``n``) master control-plane RPCs; exports the
    windowed rate as ``dlrover_tpu_control_rps`` and the lifetime tally
    as ``dlrover_tpu_control_rpc_total``.  Never raises."""
    global _control_rpc_window_start, _control_rpc_window_count
    try:
        reg = get_registry()
        reg.inc_counter("dlrover_tpu_control_rpc_total", float(n))
        now = time.monotonic()
        with _control_rpc_lock:
            if not _control_rpc_window_start:
                _control_rpc_window_start = now
            _control_rpc_window_count += n
            elapsed = now - _control_rpc_window_start
            if elapsed < _CONTROL_RPS_WINDOW_S:
                return
            rps = _control_rpc_window_count / elapsed
            _control_rpc_window_start = now
            _control_rpc_window_count = 0
        reg.set_gauge("dlrover_tpu_control_rps", rps)
    except Exception as e:  # noqa: BLE001
        logger.warning("control rpc metric export failed: %s", e)


class MetricsExporter:
    """Builds (once) and supervises the native exporter daemon.

    ``extra_files``: additional per-rank metric files to merge into
    this exporter's exposition (node-level aggregation: rank 0 serves
    every local rank).  ``stale_secs``: series whose trailing flush
    timestamp is older than this are evicted (0 = never)."""

    def __init__(self, registry: MetricsRegistry, rank: int = 0,
                 port: Optional[int] = None,
                 extra_files: Optional[list] = None,
                 stale_secs: float = 600.0):
        self._registry = registry
        self._port = port if port is not None else BASE_PORT + rank
        self._extra_files = list(extra_files or [])
        self._stale_secs = stale_secs
        self._proc: Optional[subprocess.Popen] = None

    @property
    def port(self) -> int:
        return self._port

    @staticmethod
    def build() -> str:
        os.makedirs(_BIN_DIR, exist_ok=True)
        if needs_rebuild(_BIN, _SRC):
            cmd = ["g++", "-O2", "-std=c++17", "-o", _BIN, _SRC]
            logger.info("building metrics exporter: %s", " ".join(cmd))
            subprocess.run(cmd, check=True, capture_output=True)
            write_stamp(_BIN, _SRC)
        return _BIN

    def start(self):
        binary = self.build()
        self._registry.flush()
        self._proc = subprocess.Popen(  # noqa: S603
            [
                binary,
                str(self._port),
                str(self._stale_secs),
                self._registry.path,
                *self._extra_files,
            ],
            stderr=subprocess.DEVNULL,
        )
        logger.info(
            "metrics exporter on :%d (%d files)",
            self._port, 1 + len(self._extra_files),
        )

    def stop(self):
        if self._proc is not None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self._proc.kill()
            self._proc = None
