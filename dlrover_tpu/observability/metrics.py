"""Training metrics registry + native Prometheus exporter control.

Reference parity: xpu_timer's bvar/Prometheus export
(``atorch/dev/xpu_timer``, port 28888+rank).  Training processes write
counters/gauges through ``MetricsRegistry`` (atomic file rewrite);
the C++ daemon (``native/metrics_exporter/exporter.cc``) serves them
as Prometheus text on 28888+rank.
"""

import re
import os
import subprocess
import tempfile
import threading
import time
from typing import Dict, Optional

from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.native_build import needs_rebuild, write_stamp

BASE_PORT = 28888  # xpu_timer's port convention

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_SRC = os.path.join(
    _REPO_ROOT, "native", "metrics_exporter", "exporter.cc"
)
_BIN_DIR = os.path.join(_REPO_ROOT, "native", "metrics_exporter", "build")
_BIN = os.path.join(_BIN_DIR, "metrics_exporter")


class MetricsRegistry:
    """Process-local metric store flushed to the exporter file."""

    def __init__(self, path: str = "", flush_interval: float = 5.0,
                 rank: Optional[int] = None):
        """``rank``: when set, every metric carries a ``rank`` label —
        the per-rank series the reference's per-rank bvar exporters
        provide (aggregation then happens in PromQL, not here)."""
        self._path = path or os.path.join(
            tempfile.gettempdir(),
            f"dlrover_tpu_metrics_{os.getpid()}.prom",
        )
        self._metrics: Dict[str, float] = {}
        self._lock = threading.Lock()
        self._flush_interval = flush_interval
        self._last_flush = 0.0
        self._rank = rank

    @property
    def path(self) -> str:
        return self._path

    @staticmethod
    def _escape_label(value) -> str:
        """Prometheus text-format label escaping (backslash, quote,
        newline) — an unescaped quote in a value would corrupt the
        whole exposition line."""
        return (
            str(value)
            .replace("\\", "\\\\")
            .replace('"', '\\"')
            .replace("\n", "\\n")
        )

    _NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")

    def _key(self, name: str, labels: Optional[Dict] = None) -> str:
        name = self._NAME_RE.sub("_", name)
        merged = dict(labels or {})
        if self._rank is not None:
            merged.setdefault("rank", self._rank)
        if not merged:
            return name
        inner = ",".join(
            f'{self._NAME_RE.sub("_", str(k))}='
            f'"{self._escape_label(v)}"'
            for k, v in sorted(merged.items())
        )
        return f"{name}{{{inner}}}"

    def set_gauge(self, name: str, value: float, labels=None):
        with self._lock:
            self._metrics[self._key(name, labels)] = float(value)
        self._maybe_flush()

    def inc_counter(self, name: str, value: float = 1.0, labels=None):
        key = self._key(name, labels)
        with self._lock:
            self._metrics[key] = self._metrics.get(key, 0.0) + value
        self._maybe_flush()

    def observe_duration(self, name: str, seconds: float, labels=None):
        """Simple duration tracking: _sum/_count pair."""
        self.inc_counter(name + "_seconds_sum", seconds, labels)
        self.inc_counter(name + "_count", 1.0, labels)

    def render_text(self) -> str:
        """The current metrics as Prometheus exposition text for the
        master's plain-HTTP ``/metrics`` endpoint.  NO trailing
        timestamp: the classic text format demands int64
        *milliseconds* there, and the seconds-float stamp ``flush``
        writes (which the C++ exporter strips before serving, using
        it only for staleness eviction) would make a real Prometheus
        scrape land every sample at ~epoch — served samples must
        carry the scrape time instead."""
        with self._lock:
            lines = [
                f"{k} {v:.9g}"
                for k, v in sorted(self._metrics.items())
            ]
        return "\n".join(lines) + "\n"

    def _maybe_flush(self):
        now = time.time()
        if now - self._last_flush >= self._flush_interval:
            self.flush()

    def flush(self):
        with self._lock:
            now = time.time()
            # trailing unix timestamp (Prometheus text format allows
            # it) is what lets the exporter evict STALE series — a
            # crashed writer's last file would otherwise be served as
            # live forever
            lines = [
                f"{k} {v:.9g} {now:.3f}"
                for k, v in sorted(self._metrics.items())
            ]
            self._last_flush = now
        tmp = self._path + ".tmp"
        try:
            with open(tmp, "w") as f:
                f.write("\n".join(lines) + "\n")
            os.replace(tmp, self._path)
        except OSError as e:
            logger.warning("metrics flush failed: %s", e)


_default_registry: Optional[MetricsRegistry] = None
_default_registry_lock = threading.Lock()


def get_registry() -> MetricsRegistry:
    """Process-wide default registry (the Trainer installs its own as
    the default when it starts, so library counters land in the same
    exporter file)."""
    global _default_registry
    with _default_registry_lock:
        if _default_registry is None:
            _default_registry = MetricsRegistry()
        return _default_registry


def set_default_registry(registry: MetricsRegistry):
    global _default_registry
    with _default_registry_lock:
        _default_registry = registry


def record_ckpt_io(kind: str, nbytes: int, seconds: float):
    """Export one checkpoint data-plane measurement as gauges
    (``dlrover_tpu_ckpt_io_gbps{kind=...}`` / ``_bytes{kind=...}``).
    ``kind``: drain | restore | persist | prealloc.  Never raises —
    metrics must not break a save."""
    try:
        reg = get_registry()
        gbps = nbytes / 1e9 / max(seconds, 1e-9)
        reg.set_gauge(
            "dlrover_tpu_ckpt_io_gbps", gbps, labels={"kind": kind}
        )
        reg.set_gauge(
            "dlrover_tpu_ckpt_io_bytes",
            float(nbytes),
            labels={"kind": kind},
        )
    except Exception as e:  # noqa: BLE001
        logger.warning("ckpt io metric export failed: %s", e)


def record_input_io(stage: str, nbytes: int, seconds: float):
    """Export one input data-plane measurement as gauges
    (``dlrover_tpu_input_gbps{stage=...}`` / ``_bytes{stage=...}``).
    ``stage``: ``host_fetch`` (what the consumer waited on) |
    ``read_batch`` (the loader producer pool's raw fetch bandwidth —
    distinct so stacking ``host_prefetch`` over an already-pipelined
    loader doesn't fold two measurements into one series) | ``h2d``.
    Never raises — metrics must not break the input pipeline."""
    try:
        reg = get_registry()
        gbps = nbytes / 1e9 / max(seconds, 1e-9)
        reg.set_gauge(
            "dlrover_tpu_input_gbps", gbps, labels={"stage": stage}
        )
        reg.set_gauge(
            "dlrover_tpu_input_bytes",
            float(nbytes),
            labels={"stage": stage},
        )
    except Exception as e:  # noqa: BLE001
        logger.warning("input io metric export failed: %s", e)


def record_offload_io(nbytes: int, seconds: float, buffered: bool):
    """Export one host-offload chunk-stream measurement as gauges
    (``dlrover_tpu_offload_gbps{buffered=...}`` / ``_bytes``): the
    optimizer-state host<->device traffic of one streamed update.
    ``buffered`` distinguishes the rolling double-buffered DMA window
    from the serial (kill-switched) stream so a regression in the
    overlap shows up as a ratio between the two series.  Never raises
    — metrics must not break a train step."""
    try:
        reg = get_registry()
        gbps = nbytes / 1e9 / max(seconds, 1e-9)
        labels = {"buffered": "1" if buffered else "0"}
        reg.set_gauge(
            "dlrover_tpu_offload_gbps", gbps, labels=labels
        )
        reg.set_gauge(
            "dlrover_tpu_offload_bytes", float(nbytes), labels=labels
        )
    except Exception as e:  # noqa: BLE001
        logger.warning("offload io metric export failed: %s", e)


def record_reshard_io(from_world: int, to_world: int, nbytes: int,
                      seconds: float):
    """Export one elastic-reshard restore measurement as gauges
    (``dlrover_tpu_reshard_gbps`` / ``_bytes``, labeled with the world
    transition) plus a ``dlrover_tpu_reshard_total`` counter: the
    overlap-range bytes that reassembled this rank's new slices from a
    different-world checkpoint.  Never raises — metrics must not break
    a restore."""
    try:
        reg = get_registry()
        labels = {
            "from_world": str(int(from_world)),
            "to_world": str(int(to_world)),
        }
        reg.set_gauge(
            "dlrover_tpu_reshard_gbps",
            nbytes / 1e9 / max(seconds, 1e-9),
            labels=labels,
        )
        reg.set_gauge(
            "dlrover_tpu_reshard_bytes", float(nbytes), labels=labels
        )
        reg.inc_counter("dlrover_tpu_reshard_total")
    except Exception as e:  # noqa: BLE001
        logger.warning("reshard metric export failed: %s", e)


def record_dropped_reports(n: int = 1):
    """Count fire-and-forget reports dropped by the client-side
    ``ReportBuffer`` overflow cap during a master outage
    (``dlrover_tpu_control_dropped_reports``).  A nonzero rate means
    the outage outlived the buffer — telemetry from that window is
    gone (training state is unaffected; reports are advisory).  Never
    raises."""
    try:
        get_registry().inc_counter(
            "dlrover_tpu_control_dropped_reports", float(n)
        )
    except Exception as e:  # noqa: BLE001
        logger.warning("dropped-report metric export failed: %s", e)


#: windowed meter behind ``dlrover_tpu_control_rps``: the master's
#: servicer calls ``record_control_rpc`` per RPC; the rate gauge is
#: recomputed at most once per window so the metric itself cannot
#: become control-plane load
_CONTROL_RPS_WINDOW_S = 5.0
_control_rpc_lock = threading.Lock()
_control_rpc_window_start = 0.0
_control_rpc_window_count = 0


def record_control_rpc(n: int = 1):
    """Count one (or ``n``) master control-plane RPCs; exports the
    windowed rate as ``dlrover_tpu_control_rps`` and the lifetime tally
    as ``dlrover_tpu_control_rpc_total``.  Never raises."""
    global _control_rpc_window_start, _control_rpc_window_count
    try:
        reg = get_registry()
        reg.inc_counter("dlrover_tpu_control_rpc_total", float(n))
        now = time.monotonic()
        with _control_rpc_lock:
            if not _control_rpc_window_start:
                _control_rpc_window_start = now
            _control_rpc_window_count += n
            elapsed = now - _control_rpc_window_start
            if elapsed < _CONTROL_RPS_WINDOW_S:
                return
            rps = _control_rpc_window_count / elapsed
            _control_rpc_window_start = now
            _control_rpc_window_count = 0
        reg.set_gauge("dlrover_tpu_control_rps", rps)
    except Exception as e:  # noqa: BLE001
        logger.warning("control rpc metric export failed: %s", e)


class MetricsExporter:
    """Builds (once) and supervises the native exporter daemon.

    ``extra_files``: additional per-rank metric files to merge into
    this exporter's exposition (node-level aggregation: rank 0 serves
    every local rank).  ``stale_secs``: series whose trailing flush
    timestamp is older than this are evicted (0 = never)."""

    def __init__(self, registry: MetricsRegistry, rank: int = 0,
                 port: Optional[int] = None,
                 extra_files: Optional[list] = None,
                 stale_secs: float = 600.0):
        self._registry = registry
        self._port = port if port is not None else BASE_PORT + rank
        self._extra_files = list(extra_files or [])
        self._stale_secs = stale_secs
        self._proc: Optional[subprocess.Popen] = None

    @property
    def port(self) -> int:
        return self._port

    @staticmethod
    def build() -> str:
        os.makedirs(_BIN_DIR, exist_ok=True)
        if needs_rebuild(_BIN, _SRC):
            cmd = ["g++", "-O2", "-std=c++17", "-o", _BIN, _SRC]
            logger.info("building metrics exporter: %s", " ".join(cmd))
            subprocess.run(cmd, check=True, capture_output=True)
            write_stamp(_BIN, _SRC)
        return _BIN

    def start(self):
        binary = self.build()
        self._registry.flush()
        self._proc = subprocess.Popen(  # noqa: S603
            [
                binary,
                str(self._port),
                str(self._stale_secs),
                self._registry.path,
                *self._extra_files,
            ],
            stderr=subprocess.DEVNULL,
        )
        logger.info(
            "metrics exporter on :%d (%d files)",
            self._port, 1 + len(self._extra_files),
        )

    def stop(self):
        if self._proc is not None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self._proc.kill()
            self._proc = None
