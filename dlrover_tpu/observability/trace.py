"""Runtime per-op timing: capture + parse jax.profiler chrome traces.

Reference parity: the xpu_timer's core is MEASURED per-kernel and
per-collective time on the running job
(``atorch/dev/xpu_timer/xpu_timer/nvidia/hook.cc:111`` intercepts
kernel launches; ``common/manager.h:201`` clusters GEMMs), plus the
offline trace analyser ``atorch/atorch/utils/parse_trace_json.py``
(chrome trace -> per-op aggregation).  The TPU design needs no
LD_PRELOAD hook: XLA already stamps every HLO op's device time into
the ``jax.profiler`` trace (``*.trace.json.gz``, chrome format) with
its HLO category, FLOPs, bytes accessed, and shape — this module turns
that into the same actionable report: time share by category, GEMM
clusters by shape with achieved TFLOP/s, collective time, step time.

Use ``capture_op_profile(step_fn, args)`` on a live job/bench, or
``parse_trace(path)`` on a recorded trace directory.
"""

import glob
import gzip
import json
import os
import re
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from dlrover_tpu.common.log import default_logger as logger

# hlo_category values seen on TPU: "loop fusion", "fusion",
# "convolution", "data formatting", "copy", "all-reduce", ...
_COLLECTIVE_RE = re.compile(
    r"all-reduce|all-gather|reduce-scatter|all-to-all|"
    r"collective|permute|send|recv",
    re.IGNORECASE,
)
# the MXU ops: TPU lowers dots to convolutions, so both count
_GEMM_RE = re.compile(r"convolution|dot|matmul", re.IGNORECASE)

# control-flow CONTAINERS whose duration spans their body ops (a scan
# layer-loop "while" holds ~50% of wall time and every op inside it is
# also emitted individually) — counting them would double-book
_CONTAINER_CATEGORIES = frozenset(
    {"while", "conditional", "call", "control-flow"}
)


@dataclass
class OpAggregate:
    key: str
    category: str
    time_us: float = 0.0
    count: int = 0
    flops: float = 0.0
    bytes_accessed: float = 0.0
    example: str = ""  # one representative op name
    source: str = ""

    @property
    def tflops_per_sec(self) -> float:
        return (
            self.flops / (self.time_us * 1e6)
            if self.time_us > 0
            else 0.0
        )


@dataclass
class TraceReport:
    """Parsed per-op device-time report for one trace."""

    total_device_us: float = 0.0
    step_count: int = 0
    mean_step_us: float = 0.0
    by_category: Dict[str, float] = field(default_factory=dict)
    gemm_clusters: List[OpAggregate] = field(default_factory=list)
    collectives: List[OpAggregate] = field(default_factory=list)
    top_ops: List[OpAggregate] = field(default_factory=list)
    device: str = ""
    #: the trace file was torn (a capture interrupted by preemption /
    #: a SIGKILLed writer): the report is the parsed PREFIX, flagged
    #: so consumers can tell a clean short trace from a truncated one
    truncated: bool = False
    # device time carried by ops OUTSIDE any step (module) window —
    # host-transfer artifacts of the capture harness (state readbacks
    # etc.).  VERDICT-r4 weak #2: counting these inflated the census
    # ~6x past the measured step time; they are now excluded from
    # total/shares and surfaced here so the exclusion is auditable.
    outside_step_us: float = 0.0

    def summary(self, top_k: int = 10) -> dict:
        """JSON-ready digest (bench extras / exporter payload)."""
        total = self.total_device_us or 1.0

        def row(a: OpAggregate) -> dict:
            return {
                "key": a.key,
                "time_us": round(a.time_us, 1),
                "share": round(a.time_us / total, 4),
                "count": a.count,
                "tflops_per_sec": round(a.tflops_per_sec, 2),
                "example": a.example,
                "source": a.source,
            }

        return {
            "total_device_us": round(self.total_device_us, 1),
            "truncated": self.truncated,
            "steps": self.step_count,
            "mean_step_us": round(self.mean_step_us, 1),
            "outside_step_us": round(self.outside_step_us, 1),
            "category_share": {
                k: round(v / total, 4)
                for k, v in sorted(
                    self.by_category.items(),
                    key=lambda kv: -kv[1],
                )
            },
            "gemm_clusters": [
                row(a) for a in self.gemm_clusters[:top_k]
            ],
            "collectives": [
                row(a) for a in self.collectives[:top_k]
            ],
            "top_ops": [row(a) for a in self.top_ops[:top_k]],
        }

    def export_to_registry(self, registry, top_k: int = 5):
        """Mirror the report onto a MetricsRegistry: category shares
        and the top GEMM clusters' achieved TFLOP/s as gauges the C++
        exporter serves (xpu_timer's Prometheus surface)."""
        total = self.total_device_us or 1.0
        for cat, us in self.by_category.items():
            name = re.sub(r"[^a-zA-Z0-9]+", "_", cat).strip("_")
            registry.set_gauge(f"optime_share_{name}", us / total)
        for i, a in enumerate(self.gemm_clusters[:top_k]):
            registry.set_gauge(
                f"gemm_cluster_{i}_tflops", a.tflops_per_sec
            )
            registry.set_gauge(
                f"gemm_cluster_{i}_share", a.time_us / total
            )
        if self.mean_step_us:
            registry.set_gauge(
                "traced_step_time_us", self.mean_step_us
            )


def _find_trace_file(path: str) -> str:
    """Accept a trace file, a profile dir, or a jax.profiler log dir
    (searches for the newest ``*.trace.json.gz``)."""
    if os.path.isfile(path):
        return path
    candidates = sorted(
        glob.glob(
            os.path.join(path, "**", "*.trace.json*"), recursive=True
        )
    )
    if not candidates:
        raise FileNotFoundError(f"no chrome trace under {path}")
    return candidates[-1]


def _read_raw(trace_file: str) -> Tuple[bytes, bool]:
    """Raw (decompressed) trace bytes, tolerating a TORN gzip stream:
    a capture interrupted by preemption leaves the file without its
    end-of-stream marker — ``zlib.decompressobj`` recovers the
    decodable prefix instead of raising.  Returns
    ``(bytes, truncated)``."""
    with open(trace_file, "rb") as f:
        data = f.read()
    if not trace_file.endswith(".gz"):
        return data, False
    try:
        return gzip.decompress(data), False
    except (EOFError, OSError, zlib.error):
        pass
    d = zlib.decompressobj(47)  # gzip or zlib header, autodetected
    try:
        out = d.decompress(data)
        out += d.flush()
    except zlib.error:
        out = b""
    return out, True


def _recover_events_prefix(text: str) -> List[dict]:
    """Best-effort parse of a truncated chrome-trace JSON: walk the
    ``traceEvents`` array object-by-object with ``raw_decode`` and
    keep everything before the tear.  Handles both the wrapped
    (``{"traceEvents": [...]``) and the bare-array formats."""
    decoder = json.JSONDecoder()
    start = 0
    key = text.find('"traceEvents"')
    if key >= 0:
        start = text.find("[", key)
    else:
        start = text.find("[")
    if start < 0:
        return []
    out: List[dict] = []
    i = start + 1
    n = len(text)
    while i < n:
        while i < n and text[i] in " \t\r\n,":
            i += 1
        if i >= n or text[i] != "{":
            break
        try:
            obj, end = decoder.raw_decode(text, i)
        except ValueError:
            break  # the torn tail: everything before it is kept
        if isinstance(obj, dict):
            out.append(obj)
        i = end
    return out


def _load_events(trace_file: str) -> Tuple[List[dict], bool]:
    """``(events, truncated)``.  A torn/partially-written trace (the
    writer was preempted mid-dump) yields the parsed PREFIX with
    ``truncated=True`` instead of raising — a capture that survives a
    preemption is still evidence."""
    raw, truncated = _read_raw(trace_file)
    text = raw.decode("utf-8", errors="replace")
    if not truncated:
        try:
            parsed = json.loads(text)
            if isinstance(parsed, list):  # bare-array chrome format
                return parsed, False
            return parsed.get("traceEvents", []), False
        except ValueError:
            truncated = True
    events = _recover_events_prefix(text)
    if truncated:
        logger.warning(
            "trace %s is truncated; parsed %d-event prefix",
            trace_file, len(events),
        )
    return events, truncated


def _shape_key(args: dict, name: str) -> str:
    shape = args.get("shape_with_layout", "")
    # strip tiling/memory annotations: cluster by logical shape
    shape = re.sub(r"\{[^}]*\}", "", shape)
    if shape:
        return shape
    return re.sub(r"\.\d+$", "", name)  # dot.42 -> dot


def parse_trace(path: str, device_prefix: str = "/device:") -> TraceReport:
    """Chrome trace -> :class:`TraceReport`.

    Aggregates X (complete) events on device-process "XLA Ops" tracks;
    steps come from the "XLA Modules" track.  Works on any backend
    that emits device tracks (TPU does; CPU traces carry only host
    events and yield an empty report rather than an error).
    """
    trace_file = _find_trace_file(path)
    events, truncated = _load_events(trace_file)
    pids: Dict[int, str] = {}
    tids: Dict[Tuple[int, int], str] = {}
    for e in events:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            pids[e["pid"]] = e.get("args", {}).get("name", "")
        elif e.get("name") == "thread_name":
            tids[(e["pid"], e.get("tid"))] = e.get("args", {}).get(
                "name", ""
            )

    report = TraceReport(truncated=truncated)
    ops: Dict[str, OpAggregate] = {}
    step_durs: List[float] = []
    # pass 1: step windows from the "XLA Modules" track — each module
    # execution span is one step of a jitted program.  Ops outside
    # every window are capture-harness artifacts (host readbacks of
    # state between steps), not training work (VERDICT-r4 weak #2)
    windows: List[Tuple[float, float]] = []
    for e in events:
        if e.get("ph") != "X":
            continue
        if not pids.get(e.get("pid"), "").startswith(device_prefix):
            continue
        tname = tids.get((e.get("pid"), e.get("tid")), "")
        if tname.startswith("XLA Modules"):
            ts = float(e.get("ts", 0.0))
            dur = float(e.get("dur", 0.0))
            step_durs.append(dur)
            windows.append((ts, ts + dur))
    windows.sort()
    # merge overlaps: multi-device traces interleave module spans
    # (device A's long step may cover device B's short one), and a
    # bisect against raw spans would misclassify ops inside an
    # earlier, longer window as outside-step
    merged: List[Tuple[float, float]] = []
    for lo, hi in windows:
        if merged and lo <= merged[-1][1]:
            merged[-1] = (merged[-1][0], max(merged[-1][1], hi))
        else:
            merged.append((lo, hi))

    def in_step(ts: float) -> bool:
        if not merged:
            return True  # no module track (CPU): keep everything
        import bisect

        i = bisect.bisect_right(merged, (ts, float("inf"))) - 1
        return i >= 0 and ts < merged[i][1]

    for e in events:
        if e.get("ph") != "X":
            continue
        pname = pids.get(e.get("pid"), "")
        if not pname.startswith(device_prefix):
            continue
        report.device = report.device or pname
        tname = tids.get((e.get("pid"), e.get("tid")), "")
        dur = float(e.get("dur", 0.0))
        if not tname.startswith("XLA Ops"):
            continue
        args = e.get("args", {}) or {}
        name = e.get("name", "?")
        category = args.get("hlo_category", "") or "uncategorized"
        if category in _CONTAINER_CATEGORIES:
            continue  # body ops are emitted individually
        if not in_step(float(e.get("ts", 0.0))):
            report.outside_step_us += dur
            continue
        report.total_device_us += dur
        report.by_category[category] = (
            report.by_category.get(category, 0.0) + dur
        )
        key = f"{category}|{_shape_key(args, name)}"
        agg = ops.get(key)
        if agg is None:
            agg = ops[key] = OpAggregate(
                key=_shape_key(args, name),
                category=category,
                example=name,
                source=args.get("source", ""),
            )
        agg.time_us += dur
        agg.count += 1
        try:
            agg.flops += float(args.get("model_flops", 0) or 0)
        except (TypeError, ValueError):
            pass
        try:
            agg.bytes_accessed += float(
                args.get("raw_bytes_accessed", 0) or 0
            )
        except (TypeError, ValueError):
            pass

    by_time = sorted(ops.values(), key=lambda a: -a.time_us)
    report.top_ops = by_time
    report.gemm_clusters = [
        a
        for a in by_time
        if _GEMM_RE.search(a.category)
        or _GEMM_RE.search(a.example)
    ]
    report.collectives = [
        a
        for a in by_time
        if _COLLECTIVE_RE.search(a.category)
        or _COLLECTIVE_RE.search(a.example)
    ]
    report.step_count = len(step_durs)
    if step_durs:
        report.mean_step_us = sum(step_durs) / len(step_durs)
    if not report.total_device_us:
        logger.warning(
            "trace %s has no device op events (CPU backend?)",
            trace_file,
        )
    return report


def capture_op_profile(
    step_fn,
    *args,
    steps: int = 3,
    trace_dir: Optional[str] = None,
    warmup: int = 1,
) -> TraceReport:
    """Run ``step_fn(*args)`` ``steps`` times under the profiler and
    parse the result.  The carry convention matches train steps:
    when ``step_fn`` returns a tuple whose first element has the same
    structure as ``args[0]``, it is threaded through."""
    import tempfile

    import jax

    d = trace_dir or tempfile.mkdtemp(prefix="dlrover_optrace_")
    carry = args

    def one(carry):
        out = step_fn(*carry)
        if isinstance(out, tuple) and len(carry) > 1:
            return (out[0],) + tuple(carry[1:])
        return carry

    for _ in range(warmup):
        carry = one(carry)
    jax.block_until_ready(carry)
    with jax.profiler.trace(d):
        for _ in range(steps):
            carry = one(carry)
        jax.block_until_ready(carry)
    return parse_trace(d)
