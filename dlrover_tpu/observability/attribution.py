"""Live device-time attribution: from *who* is slow to *why*.

The observatory (PR 8) can name a slow or hung rank, but nothing in
the live job says what the device was doing — compute vs collective
vs copy vs host — which until now was only visible in offline bench
runs (``bench_mfu``'s op-trace leg).  This module is the bridge: the
trainer periodically captures a short ``jax.profiler`` trace around
one step (``DLROVER_TPU_PROFILE_EVERY_N_STEPS``; default off ⇒ zero
overhead), a background thread runs the existing ``trace.py`` parser,
folds the HLO categories into five stable buckets —

- **compute**   (fusions, convolutions/dots — the MXU doing work)
- **collective** (all-reduce / all-gather / reduce-scatter / permute —
  waiting on peers; a straggler with a LOW collective share is the
  slow one, its peers show HIGH shares)
- **copy**      (copy / copy-start / copy-done / data formatting —
  the host-offload DMA and reshard traffic)
- **infeed**    (infeed / outfeed / host transfers — input pipeline)
- **idle**      (step wall time no device op covers)

— and emits ONE ``step_profile`` span whose labels carry the shares,
the achieved TFLOP/s, and this node's MFU (FLOPs from the jitted
step's ``cost_analysis`` when available, trace-summed op FLOPs as the
fallback; peak FLOPs from the per-device-kind table in
``profiler.py``).  The span rides the ordinary timeline path (agent
``TimelineReporter`` → master ``TimelineAggregator``), so the
``HealthEngine`` grows per-node rolling attribution for free and the
straggler/data-stall diagnosis conclusions can cite the dominant
category: a straggler at 40% copy share is an offload problem, not a
bad host.

Everything is behind ``DLROVER_TPU_PROFILE=0`` (no spans, no gauges)
and parsing never runs on the training thread.
"""

import json
import os
import queue
import re
import shutil
import threading
from typing import Callable, Dict, Optional

from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.observability import trace as trace_mod
from dlrover_tpu.observability.trace import (
    _COLLECTIVE_RE,
    TraceReport,
)

#: the closed bucket vocabulary — gauge labels, span labels and the
#: top.py "why" column all key on these five names
CATEGORIES = ("compute", "collective", "copy", "infeed", "idle")

_COPY_RE = re.compile(r"copy|data formatting|transpose", re.IGNORECASE)
_INFEED_RE = re.compile(r"infeed|outfeed|host", re.IGNORECASE)


def bucket_category(hlo_category: str) -> str:
    """Fold one HLO category string into the 4 busy buckets."""
    if _COLLECTIVE_RE.search(hlo_category):
        return "collective"
    if _COPY_RE.search(hlo_category):
        return "copy"
    if _INFEED_RE.search(hlo_category):
        return "infeed"
    return "compute"


def bucket_shares(report: TraceReport) -> Dict[str, float]:
    """Per-bucket share of the traced step WALL time (all five sum to
    ~1).  Idle is the step-window time no device op covers; when the
    trace has no module (step) track — CPU backends — idle is 0 and
    the busy buckets are normalized over device time alone."""
    shares = {c: 0.0 for c in CATEGORIES}
    busy_us = report.total_device_us
    if busy_us <= 0:
        return shares
    by_bucket: Dict[str, float] = {}
    for cat, us in report.by_category.items():
        bucket = bucket_category(cat)
        by_bucket[bucket] = by_bucket.get(bucket, 0.0) + us
    window_us = report.mean_step_us * max(report.step_count, 1)
    if window_us > busy_us:
        idle = (window_us - busy_us) / window_us
        scale = (1.0 - idle) / busy_us
    else:
        # no step windows (or ops overlap past the window — async
        # streams): normalize over device time, idle unknown ⇒ 0
        idle = 0.0
        scale = 1.0 / busy_us
    for bucket, us in by_bucket.items():
        shares[bucket] = round(us * scale, 4)
    shares["idle"] = round(idle, 4)
    return shares


def trace_flops_per_step(report: TraceReport) -> float:
    """Fallback FLOPs source: the trace's per-op ``model_flops``
    summed over the window, per step (0 on CPU traces, which carry no
    device ops)."""
    total = sum(a.flops for a in report.top_ops)
    return total / max(report.step_count, 1)


def dominant_category(shares: Dict[str, float]) -> Optional[tuple]:
    """``(name, share)`` of the biggest bucket, None when empty."""
    busy = [(c, shares.get(c, 0.0)) for c in CATEGORIES]
    busy = [t for t in busy if t[1] > 0]
    if not busy:
        return None
    return max(busy, key=lambda t: t[1])


class AttributionWorker:
    """Single background thread parsing captured traces off the
    training thread: the trainer hands it ``(trace_dir, step, ...)``
    and keeps stepping; the worker parses, emits the ``step_profile``
    span, and (for deep captures) writes the artifact JSON where the
    agent collects it.  The queue is bounded — a wedged parse drops
    the OLDEST pending capture rather than growing without bound."""

    MAX_PENDING = 4

    def __init__(self, flops_fn: Optional[Callable[[], float]] = None):
        #: lazily-evaluated cost-analysis FLOPs (cached after the
        #: first call; any failure caches 0 and the trace fallback
        #: carries the number)
        self._flops_fn = flops_fn
        self._flops_cache: Optional[float] = None
        self._queue: "queue.Queue[Optional[dict]]" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        #: newest processed summary (tests / bench introspection)
        self.last_profile: Optional[dict] = None

    def _ensure_thread(self):
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._thread = threading.Thread(
                target=self._loop,
                name="attribution-profiler",
                daemon=True,
            )
            self._thread.start()

    def submit(
        self,
        trace_dir: str,
        step: int,
        start_wall: float,
        duration_s: float,
        steps: int = 1,
        mode: str = "profile",
        reason: str = "",
        artifact_dir: str = "",
    ):
        """Queue one captured window for background processing."""
        job = {
            "trace_dir": trace_dir,
            "step": int(step),
            "start_wall": float(start_wall),
            "duration_s": float(duration_s),
            "steps": max(int(steps), 1),
            "mode": mode,
            "reason": reason,
            "artifact_dir": artifact_dir,
        }
        while self._queue.qsize() >= self.MAX_PENDING:
            try:
                stale = self._queue.get_nowait()
                if stale is not None:
                    shutil.rmtree(
                        stale["trace_dir"], ignore_errors=True
                    )
                    logger.warning(
                        "attribution worker backlogged; dropped the "
                        "capture at step %s", stale.get("step"),
                    )
            except queue.Empty:
                break
        self._queue.put(job)
        self._ensure_thread()

    def close(self, timeout: float = 10.0):
        """Drain pending captures (train end / tests)."""
        thread = self._thread
        if thread is None or not thread.is_alive():
            return
        self._queue.put(None)
        thread.join(timeout=timeout)

    # ------------------------------------------------------------ worker
    def _flops_per_step(self, report: TraceReport):
        """``(flops, global_scope)``: cost-analysis FLOPs count the
        whole jitted computation (GLOBAL device scope), the
        trace-summed fallback only this process's device tracks
        (LOCAL scope) — the MFU denominator must match or multi-host
        numbers are off by the process count."""
        if self._flops_cache is None:
            flops = 0.0
            if self._flops_fn is not None:
                try:
                    flops = float(self._flops_fn() or 0.0)
                except Exception as e:  # noqa: BLE001 - fall back to trace
                    logger.warning(
                        "cost-analysis FLOPs unavailable (%s); using "
                        "trace-summed op FLOPs", e,
                    )
            self._flops_cache = flops
        if self._flops_cache > 0:
            return self._flops_cache, True
        return trace_flops_per_step(report), False

    def _loop(self):
        while True:
            job = self._queue.get()
            if job is None:
                return
            try:
                self._process(job)
            except Exception as e:  # noqa: BLE001 - observability only
                logger.warning("attribution processing failed: %s", e)
                shutil.rmtree(job["trace_dir"], ignore_errors=True)

    def _process(self, job: dict):
        from dlrover_tpu.observability.events import get_event_logger
        from dlrover_tpu.observability.profiler import (
            device_peak_flops,
        )

        try:
            # via the module attr so test monkeypatching of
            # trace.parse_trace reaches this thread too
            report = trace_mod.parse_trace(job["trace_dir"])
        finally:
            shutil.rmtree(job["trace_dir"], ignore_errors=True)
        shares = bucket_shares(report)
        flops_per_step, global_flops = self._flops_per_step(report)
        step_s = job["duration_s"] / job["steps"]
        if report.step_count and report.mean_step_us > 0:
            # the trace's own step timing is tighter than the wall
            # window (which includes trace start/stop overhead)
            step_s = report.mean_step_us / 1e6
        tflops = (
            flops_per_step / step_s / 1e12 if step_s > 0 else 0.0
        )
        # the MFU denominator matches the numerator's scope: the
        # jitted step's cost analysis counts the GLOBAL computation
        # (peak = per-chip × all devices, the same peak_total ratio
        # bench_mfu reports; per-node variation then comes from this
        # node's measured step time), while trace-summed FLOPs only
        # cover this PROCESS's device tracks (peak = local devices)
        try:
            import jax

            n_devices = max(
                jax.device_count()
                if global_flops
                else jax.local_device_count(),
                1,
            )
        except Exception:  # noqa: BLE001 - no backend
            n_devices = 1
        peak = device_peak_flops() * n_devices
        mfu = (
            flops_per_step / step_s / peak
            if step_s > 0 and peak > 0
            else 0.0
        )
        profile = {
            "step": job["step"],
            "steps": job["steps"],
            "mode": job["mode"],
            "step_time_s": round(step_s, 6),
            "shares": shares,
            "tflops": round(tflops, 3),
            "mfu": round(mfu, 4),
            "flops_per_step": flops_per_step,
            "truncated": report.truncated,
            "summary": report.summary(top_k=10),
        }
        self.last_profile = profile
        get_event_logger().complete(
            "step_profile",
            job["start_wall"],
            job["duration_s"],
            step=job["step"],
            share_compute=shares["compute"],
            share_collective=shares["collective"],
            share_copy=shares["copy"],
            share_infeed=shares["infeed"],
            share_idle=shares["idle"],
            tflops=round(tflops, 3),
            mfu=round(mfu, 4),
            steps=job["steps"],
            mode=job["mode"],
            truncated=report.truncated,
        )
        if job["mode"] == "capture" and job["artifact_dir"]:
            self._write_capture_artifact(job, profile)

    def _write_capture_artifact(self, job: dict, profile: dict):
        """Deep capture: drop this worker's parsed profile where the
        agent's capture executor collects it (atomic rename so the
        collector never reads a torn file)."""
        try:
            os.makedirs(job["artifact_dir"], exist_ok=True)
            path = os.path.join(
                job["artifact_dir"],
                f"profile_{os.getpid()}_{job['step']}.json",
            )
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(
                    dict(profile, pid=os.getpid(),
                         reason=job["reason"]),
                    f,
                )
            os.replace(tmp, path)
            logger.info("capture profile written to %s", path)
        except OSError as e:
            logger.warning("capture artifact write failed: %s", e)
