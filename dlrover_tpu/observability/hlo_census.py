"""GEMM census over compiled HLO — the xpu_timer shape-clustering,
compile-time edition.

Reference parity: xpu_timer's core trick is clustering CUDA GEMM
launches by (b, m, n, k) and exporting per-cluster counts/latency
(``atorch/dev/xpu_timer/xpu_timer/common/manager.h``,
``nvidia/hook.cc``).  There is no symbol-interposition seam on TPU —
but the SAME census is available *before the program ever runs*: every
matmul is a ``dot`` in the compiled HLO with explicit operand shapes.
This module parses them out of ``compiled.as_text()`` and aggregates
by contraction shape, so the "where do my FLOPs go" table the
reference computes from hooked kernel launches comes from one compile
here — plus MXU-alignment warnings (a dimension not a multiple of the
128-lane width wastes systolic-array cycles) that a runtime hook
cannot give.
"""

import re
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

# every HLO value definition, e.g.
#   %a.1 = f32[64,128]{1,0} parameter(0)
_DEF_RE = re.compile(
    r"%(?P<name>[\w.\-]+)\s*=\s*(?P<dtype>[a-z0-9]+)"
    r"\[(?P<shape>[0-9,]*)\]"
)
# a dot instruction; operand shapes are NOT inline in compiled HLO —
# they resolve through the definition table, e.g.
#   ROOT %dot_general.1 = f32[64,256]{1,0} dot(%a.1, %b.1),
#       lhs_contracting_dims={1}, rhs_contracting_dims={0}, ...
_DOT_RE = re.compile(
    r"%(?P<out>[\w.\-]+)\s*=\s*(?P<odtype>[a-z0-9]+)"
    r"\[(?P<oshape>[0-9,]*)\][^\n]*?\bdot\("
    r"\s*%(?P<lhs>[\w.\-]+)\s*,\s*%(?P<rhs>[\w.\-]+)\s*\)"
    r"[^\n]*?lhs_contracting_dims=\{(?P<lc>[0-9,]*)\}",
)
# the StableHLO form (``jax.jit(f).lower(...)``): types inline, one
# regex, identical on every backend (TPU's COMPILED hlo rewrites dots
# into layout-annotated convolutions — the lowered module is the
# stable census surface), e.g.
#   %2 = stablehlo.dot_general %0, %1, batching_dims = [0] x [0],
#     contracting_dims = [2] x [1] :
#     (tensor<4x32x64xbf16>, tensor<4x64x16xbf16>) -> tensor<4x32x16xbf16>
_STABLEHLO_DOT_RE = re.compile(
    r"stablehlo\.dot_general\b[^:\n]*?"
    r"contracting_dims\s*=\s*\[(?P<lc>[0-9, ]*)\]\s*x\s*\[[0-9, ]*\]"
    r"[^:\n]*:\s*\(tensor<(?P<l>[0-9a-zA-Z_x]+)>\s*,\s*"
    r"tensor<(?P<r>[0-9a-zA-Z_x]+)>\)\s*->\s*"
    r"tensor<(?P<o>[0-9a-zA-Z_x]+)>",
)
_MXU_LANES = 128


def _mlir_shape(s: str) -> Tuple[Tuple[int, ...], str]:
    """'4x32x64xbf16' -> ((4, 32, 64), 'bf16')."""
    parts = s.split("x")
    dims = []
    for p in parts:
        if p.isdigit():
            dims.append(int(p))
        else:
            return tuple(dims), p
    return tuple(dims), parts[-1]


def _dims(s: str) -> Tuple[int, ...]:
    return tuple(int(x) for x in s.split(",")) if s else ()


@dataclass
class GemmCluster:
    """All dots sharing one (batch, m, n, k) contraction shape."""

    batch: int
    m: int
    n: int
    k: int
    dtype: str
    count: int = 0
    # dims not divisible by the 128-wide MXU lanes
    misaligned_dims: Tuple[str, ...] = ()

    @property
    def flops(self) -> float:
        """Total MACs x2 across the cluster."""
        return 2.0 * self.batch * self.m * self.n * self.k * self.count

    def describe(self) -> str:
        tag = (
            f" [MISALIGNED {','.join(self.misaligned_dims)}]"
            if self.misaligned_dims
            else ""
        )
        return (
            f"{self.dtype} b={self.batch} m={self.m} n={self.n} "
            f"k={self.k} x{self.count} -> {self.flops / 1e9:.2f} "
            f"GFLOP{tag}"
        )


def _add(clusters: Dict, batch, mm, nn, k, dtype):
    key = (batch, mm, nn, k, dtype)
    if key not in clusters:
        misaligned = tuple(
            name
            for name, v in (("m", mm), ("n", nn), ("k", k))
            if v % _MXU_LANES and v > _MXU_LANES
        )
        clusters[key] = GemmCluster(
            batch=batch, m=mm, n=nn, k=k, dtype=dtype,
            misaligned_dims=misaligned,
        )
    clusters[key].count += 1


def _add_dot(
    clusters: Dict,
    lshape: Tuple[int, ...],
    oshape: Tuple[int, ...],
    lc: Tuple[int, ...],
    dtype: str,
):
    """Shared (m, n, k, batch) derivation for both HLO dialects."""
    if not lshape or not lc:
        return
    k = 1
    for d in lc:
        if d < len(lshape):
            k *= lshape[d]
    batch = 1
    # batch dims = everything in the output beyond (m, n)
    if len(oshape) > 2:
        for d in oshape[:-2]:
            batch *= d
    mm = oshape[-2] if len(oshape) >= 2 else 1
    nn = oshape[-1] if len(oshape) >= 1 else 1
    _add(clusters, batch, mm, nn, k, dtype)


def gemm_census(module) -> List[GemmCluster]:
    """Parse every dot/dot_general out of an HLO or StableHLO module
    and cluster by contraction shape, largest total FLOPs first.

    Accepts text or anything with ``as_text()``.  Prefer
    ``jax.jit(f).lower(args)`` (StableHLO — identical on every
    backend; TPU's post-layout HLO rewrites dots beyond recognition);
    CPU/GPU ``.compile()`` output parses too."""
    text = module if isinstance(module, str) else module.as_text()
    clusters: Dict[Tuple, GemmCluster] = {}

    # StableHLO form (types inline)
    for m in _STABLEHLO_DOT_RE.finditer(text):
        lshape, _ = _mlir_shape(m.group("l"))
        oshape, dtype = _mlir_shape(m.group("o"))
        lc = tuple(
            int(x) for x in m.group("lc").replace(" ", "").split(",")
            if x
        )
        _add_dot(clusters, lshape, oshape, lc, dtype)

    if not clusters:
        # compiled-HLO form: operand shapes resolve through the
        # definition table
        shapes: Dict[str, Tuple[int, ...]] = {}
        for m in _DEF_RE.finditer(text):
            shapes[m.group("name")] = _dims(m.group("shape"))
        for m in _DOT_RE.finditer(text):
            _add_dot(
                clusters,
                shapes.get(m.group("lhs"), ()),
                _dims(m.group("oshape")),
                _dims(m.group("lc")),
                m.group("odtype"),
            )
    return sorted(
        clusters.values(), key=lambda c: c.flops, reverse=True
    )


def census_report(hlo_text_or_compiled, top: int = 10) -> str:
    """Human-readable top-N GEMM table + totals."""
    clusters = gemm_census(hlo_text_or_compiled)
    total = sum(c.flops for c in clusters)
    lines = [
        f"GEMM census: {sum(c.count for c in clusters)} dots, "
        f"{len(clusters)} shape clusters, "
        f"{total / 1e12:.3f} TFLOP total"
    ]
    for c in clusters[:top]:
        share = 100.0 * c.flops / total if total else 0.0
        lines.append(f"  {share:5.1f}%  {c.describe()}")
    return "\n".join(lines)
