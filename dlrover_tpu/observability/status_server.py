"""Plain-HTTP observatory endpoints on the master: ``/metrics`` +
``/status``.

The native C++ exporter (``observability/metrics.py``
``MetricsExporter``) serves per-RANK metrics on 28888+rank for the
training processes; the MASTER had no scrape surface at all — its
gauges (goodput ledger, node health, straggler scores, control-plane
rate) only existed in the registry file.  This server is the master's
own surface, deliberately dependency-free (``http.server`` from the
standard library, threaded, daemonized):

- ``GET /metrics`` — Prometheus text exposition of the master
  registry (health gauges refreshed on demand so a scrape never
  reads values staler than the snapshot it could have computed);
- ``GET /status``  — the full observatory snapshot as JSON (the same
  payload the ``JobStatusRequest`` RPC returns; ``scripts/top.py``
  can read either);
- anything else — 404.

Off by default: the master only starts it when ``--status_port`` is
given AND the observatory kill-switch is on.
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from dlrover_tpu.common.log import default_logger as logger


class StatusServer:
    """Threaded HTTP server wrapping a metrics registry + a status
    snapshot callable."""

    def __init__(
        self,
        port: int,
        registry=None,
        snapshot_fn: Optional[Callable[[], dict]] = None,
        health_engine=None,
        telemetry=None,
        serving_refresh=None,
        host: str = "0.0.0.0",
    ):
        self._port = port
        self._host = host
        self._registry = registry
        self._snapshot_fn = snapshot_fn
        self._health = health_engine
        #: the master's self-telemetry collector (None = self-obs
        #: off): its sweep gauges refresh at scrape time like the
        #: health engine's
        self._telemetry = telemetry
        #: zero-arg serving-plane refresh hook (None = no co-located
        #: serving engine or DLROVER_TPU_SERVE_OBS=0): lets a scrape
        #: pull the replica gauges/health current before rendering
        self._serving_refresh = serving_refresh
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        """The BOUND port (resolves a requested port of 0)."""
        if self._httpd is not None:
            return self._httpd.server_address[1]
        return self._port

    def _build_handler(self):
        server = self

        class _Handler(BaseHTTPRequestHandler):
            # one handler class per server instance so the closure
            # carries the registry/snapshot without globals
            def log_message(self, fmt, *args):  # noqa: N802
                pass  # scrapes must not spam the master's stdout

            def _send(self, code: int, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                try:
                    if path == "/metrics":
                        if server._health is not None:
                            # scrape-time freshness: the throttled
                            # report-path refresh may be seconds old
                            server._health.refresh_gauges()
                        if server._telemetry is not None:
                            server._telemetry.refresh_gauges()
                        if server._serving_refresh is not None:
                            server._serving_refresh()
                        text = (
                            server._registry.render_text()
                            if server._registry is not None
                            else ""
                        )
                        self._send(
                            200,
                            text.encode(),
                            "text/plain; version=0.0.4",
                        )
                    elif path == "/status":
                        snap = (
                            server._snapshot_fn()
                            if server._snapshot_fn is not None
                            else {}
                        )
                        self._send(
                            200,
                            json.dumps(snap, default=str).encode(),
                            "application/json",
                        )
                    else:
                        self._send(404, b"not found\n", "text/plain")
                except Exception as e:  # noqa: BLE001 - a bad scrape must not kill the thread
                    try:
                        self._send(
                            500, f"{e}\n".encode(), "text/plain"
                        )
                    except OSError:
                        pass

        return _Handler

    def start(self):
        if self._httpd is not None:
            return
        self._httpd = ThreadingHTTPServer(
            (self._host, self._port), self._build_handler()
        )
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="status-server",
            daemon=True,
        )
        self._thread.start()
        logger.info(
            "observatory status server on :%d (/metrics, /status)",
            self.port,
        )

    def stop(self):
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
