"""Unified job-event timeline: structured spans + goodput attribution.

The reference's headline metric is goodput (69% -> 95% under faults),
but a single ratio cannot say WHERE the lost wall clock went —
rendezvous, recompile, checkpoint stalls, restarts.  This module is
the repo-wide answer:

- every process (master, agent, trainer, launcher) appends structured
  begin/end span and instant events to one JSONL file — one
  ``os.write`` per line on an ``O_APPEND`` fd, so concurrent writers
  never interleave; each record carries BOTH clocks (``wall`` for
  cross-process merging, ``mono`` for drift-free durations) plus the
  job/node/rank/incarnation labels that correlate a restart's spans
  across worker generations;
- :func:`compute_ledger` partitions a merged timeline's wall clock
  into phases by priority sweep — the **goodput ledger**: phase losses
  sum EXACTLY to ``wall − useful`` (the invariant the tests assert),
  so ``1 − goodput`` is fully attributed, never hand-waved;
- :func:`export_chrome_trace` renders the same timeline as a
  Perfetto-loadable chrome trace (one track per node/rank);
- :class:`TimelineAggregator` is the master-side sink: per-node event
  batches arrive over the report RPC (``common/messages.py``
  ``TimelineEventsReport``), merge into the sqlite Brain datastore,
  and serve the live ledger through a get RPC and as gauges on the
  ``MetricsRegistry`` the native Prometheus exporter reads.

Phase names are a CLOSED set (``PHASES`` + ``INSTANT_EVENTS``);
``scripts/check_event_schema.py`` lints every emit site against it so
a typo'd phase can never silently drop out of the ledger.
"""

import io
import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

from dlrover_tpu.common.log import default_logger as logger

EVENTS_FILE_ENV = "DLROVER_TPU_EVENTS_FILE"

# One (wall, mono) anchor per process: every record's ``wall`` is
# derived from ``mono`` against this pair, so the two clocks carry a
# constant offset within a writer.  Sampling both clocks per event
# would let the offset jitter by microseconds between records, and
# span ends reconstructed as ``begin.wall + mono_delta`` could then
# land before a nested child's end.
_WALL_EPOCH = time.time()
_MONO_EPOCH = time.monotonic()


def anchored_now(mono: Optional[float] = None) -> float:
    """Wall-clock "now" on the same ``(wall, mono)`` anchor the
    emitted records use.  Callers that report a span after the fact
    (``complete()``) must sample its start through this — passing the
    ``time.monotonic()`` they already took, if any — so X-records stay
    on one clock with B/E records even across an NTP step."""
    if mono is None:
        mono = time.monotonic()
    return _WALL_EPOCH + (mono - _MONO_EPOCH)

#: Span phases, HIGHEST attribution priority first.  When spans
#: overlap, each instant of wall clock is charged to the
#: highest-priority covering phase.  ``step`` is the only USEFUL
#: phase; ``data_stall`` outranks it because a step span measured
#: step_done-to-step_done covers the between-step input wait — a
#: named 10s pipeline stall must surface as loss, not as useful time.
#: Everything below ``step`` loses to it on overlap: an ASYNC
#: checkpoint drain or a preemption flush running while steps
#: complete charges the step (training progressed, nothing was
#: lost), and a rendezvous nested inside a restart charges
#: rendezvous.
PHASE_DATA_STALL = "data_stall"
PHASE_STEP = "step"
PHASE_PREEMPTION_DRAIN = "preemption_drain"
PHASE_CHECKPOINT_RESTORE = "checkpoint_restore"
# restart-critical-path legs (trainer/restart_path.py): the restore
# byte stream, the background AOT compile, the device-world wait and
# the staged-bytes -> device finish.  They outrank their serial
# cousins' parent (restart_path) but rank BELOW checkpoint_restore /
# compile so a serial-path span that covers the same instant keeps
# its attribution.
PHASE_RESTORE_PREFETCH = "restore_prefetch"
# elastic-reshard data leg (trainer/checkpoint/reshard.py): the
# overlap-range reads that reassemble this rank's NEW slices from a
# checkpoint written by a DIFFERENT world size.  Ranks with the other
# restore legs: below checkpoint_restore (a covering serial-restore
# span keeps its attribution) and beside restore_prefetch (the leg it
# replaces when the world changed).
PHASE_RESHARD = "reshard"
PHASE_FINISH_RESTORE = "finish_restore"
PHASE_COMPILE = "compile"
PHASE_AOT_COMPILE = "aot_compile"
PHASE_RENDEZVOUS = "rendezvous"
PHASE_RENDEZVOUS_WAIT = "rendezvous_wait"
PHASE_CHECKPOINT_SAVE = "checkpoint_save"
# host-offload optimizer-state chunk stream (optimizers/host_offload):
# the D2H/H2D traffic of one streamed update.  Ranks BELOW step on
# purpose — the stream is designed to overlap the backward, so an
# instant covered by both charges the step (nothing was lost); a
# standalone offload_copy (the exposed tail) surfaces as its own loss
PHASE_OFFLOAD_COPY = "offload_copy"
# parent span covering one whole overlapped (or fallen-back serial)
# restart critical path; the child legs above carve their shares out
PHASE_RESTART_PATH = "restart_path"
PHASE_RESTART = "restart"
# live attribution profiler (observability/attribution.py): one
# traced-window span per continuous-leg capture, whose labels carry
# the per-category device-time shares + achieved TFLOP/s + MFU the
# HealthEngine derives per-node gauges from.  Ranks BELOW step on
# purpose: the window covers real train steps, which keep their
# ledger attribution; only standalone profiler overhead (trace
# start/stop outside a step span) surfaces as its own bucket.
PHASE_STEP_PROFILE = "step_profile"
# the inference plane (rl/scheduler.py + the multi-replica serving
# workers): one ``serve_step`` span per scheduler iteration, with
# ``prefill`` (prompt-chunk) and ``decode`` (token-step) legs inside
# it.  Serving processes run no train steps, so these never contend
# with ``step`` for attribution; they rank just below step_profile so
# a trainer-co-located rollout keeps its training attribution.
PHASE_SERVE_STEP = "serve_step"
PHASE_PREFILL = "prefill"
PHASE_DECODE = "decode"
# incremental-allocation serving (ISSUE 15): one ``preempt`` span per
# pool-pressure eviction (the victim's blocks return to the pool and
# the request requeues with its generated tail), one ``verify`` span
# per fused multi-token decode window (K drafted tokens scored by one
# batched verify forward).  Same attribution rank as the serving
# spans above.
PHASE_PREEMPT = "preempt"
PHASE_VERIFY = "verify"
# per-request lifecycle tracing (ISSUE 16): every served request gets
# a ``serve_request`` parent span covering submit→completion, with
# ``queue_wait`` (dispatcher submit → scheduler admission, measured
# from the wall-clock anchor that rides the shm request ring),
# ``admit`` (the admission bookkeeping itself) and — after a
# pool-pressure eviction — ``resume`` (re-admission of the preempted
# tail) children.  The children rank above the parent so a request's
# time attributes to the specific lifecycle stage, not the envelope.
PHASE_QUEUE_WAIT = "queue_wait"
PHASE_ADMIT = "admit"
PHASE_RESUME = "resume"
PHASE_SERVE_REQUEST = "serve_request"
# disaggregated prefill/decode (ISSUE 17): one ``kv_ship`` span per
# prefill-worker handoff — the staged block regions' copy into the
# ship arena, sized and timed like the checkpoint data-plane spans
# (the shm transfer IS the disaggregation tax; a throughput
# regression here shows up as decode-side TTFT, so it must be
# attributable from the timeline alone).
PHASE_KV_SHIP = "kv_ship"
# client-side control-plane wait (a long-poll RPC parked on the
# master, or the legacy polling loop it replaces).  LOWEST priority:
# these waits are almost always nested inside rendezvous/restart
# spans, which keep the attribution; a standalone control_wait still
# surfaces as its own loss bucket instead of vanishing into
# unattributed time.
PHASE_CONTROL_WAIT = "control_wait"

# one paged-kernel autotune sweep (ops/autotune.py): the tuner timed
# every legal (q-block, kv-block) candidate for one shape key and
# persisted the winner — the span is the audit record of WHY the
# cached config is what it is
PHASE_KERNEL_AUTOTUNE = "kernel_autotune"

# the flywheel's train->serve weight hop (rl/flywheel.py): one
# in-place publish of the policy (+ drafter) into the double-buffered
# shm snapshot segment — the span's duration IS the trainer stall the
# zero-copy path is supposed to bound
PHASE_WEIGHT_PUBLISH = "weight_publish"

# one rollout round of the RLHF flywheel: prompts submitted, every
# trajectory streamed back, the round's staleness verdicts settled
PHASE_ROLLOUT_ROUND = "rollout_round"

# one completed rollout crossing the serve->train boundary as a ready
# training sample (the shm trajectory stream's unit of account)
PHASE_TRAJECTORY = "trajectory"

PHASES: Tuple[str, ...] = (
    PHASE_DATA_STALL,
    PHASE_STEP,
    PHASE_PREEMPTION_DRAIN,
    PHASE_CHECKPOINT_RESTORE,
    PHASE_RESTORE_PREFETCH,
    PHASE_RESHARD,
    PHASE_FINISH_RESTORE,
    PHASE_COMPILE,
    PHASE_AOT_COMPILE,
    PHASE_RENDEZVOUS,
    PHASE_RENDEZVOUS_WAIT,
    PHASE_CHECKPOINT_SAVE,
    PHASE_OFFLOAD_COPY,
    PHASE_RESTART_PATH,
    PHASE_RESTART,
    PHASE_STEP_PROFILE,
    PHASE_SERVE_STEP,
    PHASE_PREFILL,
    PHASE_DECODE,
    PHASE_PREEMPT,
    PHASE_VERIFY,
    PHASE_QUEUE_WAIT,
    PHASE_ADMIT,
    PHASE_RESUME,
    PHASE_SERVE_REQUEST,
    PHASE_KV_SHIP,
    PHASE_CONTROL_WAIT,
    PHASE_KERNEL_AUTOTUNE,
    PHASE_WEIGHT_PUBLISH,
    PHASE_ROLLOUT_ROUND,
    PHASE_TRAJECTORY,
)

#: Phases that count as useful training time in the ledger.
USEFUL_PHASES = frozenset({PHASE_STEP})

#: Wall clock covered by no span at all (monitor-detection gaps,
#: wedged-in-collective survivors, scheduler noise).  Kept as its own
#: ledger bucket so the losses still sum exactly to ``wall − useful``.
UNATTRIBUTED = "unattributed"

#: Point events (``ph: "i"``) — markers, not ledger input.
#: ``fault_injected`` marks a chaos-harness fault (a plan-driven
#: SIGKILL or an RPC drop/delay/dup at the channel boundary) so an
#: injected fault and the recovery it provokes share one trace;
#: ``master_restart`` marks a master incarnation replaying its
#: journal+snapshot back to serving state.
#: ``diagnosis`` marks one fresh inference-chain conclusion (the
#: observatory's DiagnosisManager): the problem, the recovery action
#: and the node it names — the trace shows the verdict next to the
#: evidence that produced it.
#: ``scale_decision`` / ``scale_execute`` bracket one Brain planned
#: action (``master/auto_scaler.BrainAutoScaler``): the decision as it
#: was made (rule, direction, world transition) and its execution
#: outcome (done / fallback-fenced / abandoned) — a chaos trace shows
#: the autonomy loop's verdicts next to the drains and re-meshes they
#: caused, and a failover-resumed action keeps the SAME decision id.
INSTANT_EVENTS = frozenset(
    {
        "preemption_signal",
        "job_start",
        "job_end",
        "worker_kill",
        "fault_injected",
        "master_restart",
        "diagnosis",
        "scale_decision",
        "scale_execute",
        "capture",
        # the master's own overload deriver fired: sustained p99 /
        # queue-near-bound / journal-lag / pool-saturation streak
        # (observability/health.py MasterHealth)
        "master_overload",
        # the serving observatory fired (observability/health.py
        # ServingHealthEngine): a replica's derived verdict changed
        # (serving_health) or a per-replica SLO signal breached its
        # threshold for ``sustain`` consecutive derivations
        # (slo_breach)
        "serving_health",
        "slo_breach",
    }
)

#: Labels an ``instant()`` emit site must pass explicitly; enforced by
#: ``scripts/check_event_schema.py`` like ``REQUIRED_SPAN_LABELS``.
#: ``fault_injected`` without kind+target would be an unattributable
#: blip in a chaos trace — exactly the record that must be precise.
REQUIRED_INSTANT_LABELS: Dict[str, Tuple[str, ...]] = {
    "fault_injected": ("kind", "target"),
    "master_restart": ("incarnation",),
    # an anonymous conclusion is useless to the operator reading the
    # trace AND to scripts/top.py's conclusions pane
    "diagnosis": ("problem", "action", "node_rank"),
    # a scale record without the rule that fired and the world
    # transition it planned is unauditable — "drain_replace node 2,
    # straggler 3.9x, 3→2" is the whole story of a Brain action
    # ``plane`` names WHICH side of the train/serve boundary the
    # action moved capacity on ("train" for the classic Brain loop,
    # "serve" for flywheel device lending) — without it a lend and a
    # straggler drain-replace read as the same world transition
    "scale_decision": ("action", "reason", "from_world", "to_world",
                       "plane"),
    "scale_execute": ("action", "reason", "from_world", "to_world",
                      "plane"),
    # one deep capture fired at a node (the agent's xpu_timer
    # hang-dump analog): the trace must show WHICH node was captured
    # and WHY (hang / straggler / operator request), next to the
    # diagnosis conclusion that triggered it
    "capture": ("node_rank", "reason"),
    # an overload verdict without WHICH signal breached and by how
    # much is unactionable — "journal_lag 8200 rows vs 5000" tells
    # the operator to grow the flusher, "pool_saturated 0.97 vs 0.9"
    # to raise DLROVER_TPU_MASTER_WORKERS
    "master_overload": ("reason", "value", "threshold"),
    # a serving verdict without the replica it names and the reason it
    # fired is exactly the "a node is slow" blip the observatory
    # exists to replace with "this is why"
    "serving_health": ("replica", "verdict", "reason"),
    "slo_breach": ("replica", "reason", "value", "threshold"),
}

#: Labels an emit SITE must pass explicitly (beyond the automatic
#: job/node/rank/inc/pid identity labels); enforced by
#: ``scripts/check_event_schema.py``.
REQUIRED_SPAN_LABELS: Dict[str, Tuple[str, ...]] = {
    PHASE_STEP: ("step",),
    # input-pipeline stalls carry the stage that stalled
    # (host_fetch — producing the host batch — vs h2d — staging it
    # onto devices) so a slow storage read and a saturated transfer
    # link stay distinguishable in the ledger
    PHASE_DATA_STALL: ("stage",),
    # checkpoint data-plane spans carry their size and measured
    # bandwidth so throughput regressions surface in the ledger and
    # in bench_goodput's loss breakdown, not only in wall time
    PHASE_CHECKPOINT_SAVE: ("step", "bytes", "throughput_gbps"),
    PHASE_CHECKPOINT_RESTORE: ("step", "bytes", "throughput_gbps"),
    # host-offload chunk-stream spans carry the streamed bytes, the
    # measured wire throughput and whether the rolling double-buffered
    # window was active (vs the serial kill-switched stream) so DMA
    # pipeline regressions are attributable from the timeline alone
    PHASE_OFFLOAD_COPY: ("bytes", "throughput_gbps", "buffered"),
    # a reshard span without the world transition and the moved bytes
    # is uninterpretable: "8→4, 3.1 GB at 1.2 GB/s" is the whole story
    # of an elastic restore, and MTTR regressions key on it
    PHASE_RESHARD: ("from_world", "to_world", "bytes",
                    "throughput_gbps"),
    PHASE_RESTART: ("reason",),
    PHASE_PREEMPTION_DRAIN: ("event",),
    # the live attribution payload: a step_profile span without the
    # category shares + achieved TFLOP/s + MFU is just a blip — the
    # labels ARE the signal the HealthEngine's per-node gauges and the
    # "why" column in top.py are built from
    PHASE_STEP_PROFILE: (
        "step",
        "share_compute",
        "share_collective",
        "share_copy",
        "share_infeed",
        "share_idle",
        "tflops",
        "mfu",
    ),
    # which control-plane wait parked (kv | comm_world | task |
    # status) so rendezvous-bootstrap waits and shard starvation stay
    # distinguishable in the ledger
    PHASE_CONTROL_WAIT: ("kind",),
    # the serving loop's per-iteration record: prompt tokens
    # prefilled + tokens sampled + the iteration's token throughput —
    # without them a serve_step is an unactionable blip, with them
    # the trace alone answers "why did tokens/s dip" (prefill-heavy
    # interval vs starved slots)
    PHASE_SERVE_STEP: ("tokens", "new_tokens", "throughput_tps"),
    # a prefill leg without its chunk size can't distinguish a long
    # prompt's chunks from a trivial one (sites may additionally
    # carry ``prefix_hit_blocks`` — prompt blocks served from the
    # shared-block index instead of prefilled)
    PHASE_PREFILL: ("tokens",),
    # a decode leg's sampled-token count IS its progress record
    PHASE_DECODE: ("new_tokens",),
    # a preemption without its cost (blocks returned to the pool) and
    # its waste (tokens the victim must re-prefill) is just a blip —
    # the two numbers ARE the incremental-admission tradeoff
    PHASE_PREEMPT: ("blocks_freed", "tokens_generated"),
    # the speculative window's scoreboard: drafted vs accepted is the
    # whole story of a multi-token decode step (accept rate == the
    # dispatch amortization actually achieved)
    PHASE_VERIFY: ("drafted", "accepted"),
    # the request's whole life in one record: identity, where it ran,
    # its size, and the SLO numbers (TTFT, per-token-gap p99) plus the
    # efficiency story (preemptions suffered, prompt blocks served
    # from the prefix cache) — the serve_request span alone must
    # answer "was THIS request slow, and why".  The fleet layer
    # (ISSUE 17) adds the routing story: HOW the dispatcher picked
    # the replica (least_outstanding / affinity / ship — "local" for
    # in-process schedulers) and WHICH SLO lane the request rode —
    # without them an affinity miss and a lane-starved batch request
    # are indistinguishable blips
    PHASE_SERVE_REQUEST: (
        "req_id",
        "replica",
        "prompt_tokens",
        "gen_tokens",
        "ttft_s",
        "tbt_p99_s",
        "preempts",
        "prefix_hit_blocks",
        "route",
        "slo_class",
    ),
    # the disaggregation handoff, sized and timed like the
    # checkpoint/offload data-plane spans: staged blocks, moved
    # bytes, achieved shm throughput
    PHASE_KV_SHIP: ("blocks", "bytes", "throughput_gbps"),
    PHASE_QUEUE_WAIT: ("req_id",),
    PHASE_ADMIT: ("req_id",),
    # a resume without the restored tail size can't distinguish a
    # cheap re-admission from re-prefilling hundreds of tokens
    PHASE_RESUME: ("req_id", "resume_tokens"),
    # an autotune event without the shape's winner and the sweep size
    # is unauditable: which kernel, what config won, out of how many
    # legal candidates, at what best time — the four numbers let a
    # later regression be traced to "the cache picked THIS because"
    PHASE_KERNEL_AUTOTUNE: (
        "kernel",
        "best_config",
        "candidates",
        "best_us",
    ),
    # a publish without its generation, its moved bytes and the stall
    # it charged the trainer is unauditable — stall_s vs the step time
    # IS the flywheel's acceptance criterion
    PHASE_WEIGHT_PUBLISH: ("generation", "bytes", "stall_s"),
    # the round's scoreboard: how many trajectories came back and how
    # many the staleness policy refused — together they are the
    # on-policy/off-policy budget actually spent
    PHASE_ROLLOUT_ROUND: ("round", "trajectories",
                          "staleness_dropped"),
    # identity + provenance of one streamed sample: which request,
    # which policy generation sampled it, how many tokens it carries
    PHASE_TRAJECTORY: ("req_id", "generation", "tokens"),
}


class EventLogger:
    """Append structured events to a JSONL timeline file.

    Disabled (every call a cheap no-op) when no path is configured —
    library code can instrument unconditionally.  One ``os.write`` per
    line on an ``O_APPEND`` descriptor keeps concurrent writers from
    ever interleaving bytes (POSIX atomic append).
    """

    def __init__(
        self,
        path: str = "",
        job: str = "",
        node: Optional[int] = None,
        rank: Optional[int] = None,
        incarnation: Optional[int] = None,
    ):
        self._path = path or os.getenv(EVENTS_FILE_ENV, "")
        self._job = job or os.getenv("DLROVER_TPU_JOB_NAME", "default")
        self._node = (
            node
            if node is not None
            else int(os.getenv("DLROVER_TPU_NODE_RANK", "0") or 0)
        )
        # -1 = not a training process (agent / launcher / master)
        self._rank = (
            rank
            if rank is not None
            else int(os.getenv("DLROVER_TPU_PROCESS_RANK", "-1") or -1)
        )
        self._inc = (
            incarnation
            if incarnation is not None
            else int(os.getenv("DLROVER_TPU_RESTART_COUNT", "0") or 0)
        )
        self._fd: Optional[int] = None
        self._lock = threading.Lock()
        self._sid = 0
        # per-(thread, phase) open-span stack for begin/end pairing
        self._open: Dict[Tuple[int, str], List[dict]] = {}
        #: emits since the last rotation check (the size stat is not
        #: paid per line)
        self._emits_since_check = 0

    #: how many emitted lines between size checks for rotation
    ROTATE_CHECK_EVERY = 128

    def _maybe_rotate_locked(self):
        """Size-based rotation of the JSONL file (caller holds the
        lock, fd is open).  One ``.1`` backup is kept; the agent's
        ``TimelineReporter`` treats the recreated (smaller) file as a
        truncation and restarts its tail offset at 0.  Multi-writer
        safe: a writer whose fd no longer matches the path (someone
        else already rotated) just follows to the new file instead of
        rotating the fresh file away."""
        from dlrover_tpu.common.env import (
            events_max_bytes,
            observatory_enabled,
        )

        if not observatory_enabled():
            return  # kill-switch: unbounded growth, exactly as before
        max_bytes = events_max_bytes()
        if max_bytes <= 0:
            return
        try:
            st_fd = os.fstat(self._fd)
            try:
                st_path = os.stat(self._path)
            except FileNotFoundError:
                st_path = None
            if st_path is None or st_path.st_ino != st_fd.st_ino:
                # rotated (or unlinked) under us: reopen on next emit
                os.close(self._fd)
                self._fd = None
                return
            if st_path.st_size < max_bytes:
                return
            os.close(self._fd)
            self._fd = None
            os.replace(self._path, self._path + ".1")
            logger.info(
                "rotated events file %s (%d bytes > %d)",
                self._path, st_path.st_size, max_bytes,
            )
        except OSError as e:
            logger.warning("events rotation failed: %s", e)

    @property
    def enabled(self) -> bool:
        return bool(self._path)

    @property
    def path(self) -> str:
        return self._path

    # ------------------------------------------------------------- emit
    def _record(self, name: str, ph: str, **labels) -> dict:
        mono = time.monotonic()
        rec = {
            "name": name,
            "ph": ph,
            "wall": _WALL_EPOCH + (mono - _MONO_EPOCH),
            "mono": mono,
            "job": self._job,
            "node": self._node,
            "rank": self._rank,
            "inc": labels.pop("inc", self._inc),
            "pid": os.getpid(),
        }
        if labels:
            rec["labels"] = {k: v for k, v in labels.items()}
        return rec

    def emit(self, record: dict):
        """Write one record as one atomic appended JSONL line."""
        if not self._path:
            return
        try:
            line = (
                json.dumps(record, separators=(",", ":"), default=str)
                + "\n"
            )
        except (TypeError, ValueError):
            return
        with self._lock:
            try:
                if self._fd is None:
                    parent = os.path.dirname(
                        os.path.abspath(self._path)
                    )
                    os.makedirs(parent, exist_ok=True)
                    self._fd = os.open(
                        self._path,
                        os.O_WRONLY | os.O_APPEND | os.O_CREAT,
                        0o644,
                    )
                os.write(self._fd, line.encode())
                self._emits_since_check += 1
                if (
                    self._emits_since_check
                    >= self.ROTATE_CHECK_EVERY
                ):
                    self._emits_since_check = 0
                    self._maybe_rotate_locked()
            except OSError as e:
                logger.warning("event emit failed: %s", e)

    def begin(self, phase: str, **labels) -> int:
        """Open a span; returns the span id ``end`` pairs on."""
        if not self._path:
            return -1
        with self._lock:
            self._sid += 1
            sid = self._sid
        rec = self._record(phase, "B", **labels)
        rec["sid"] = sid
        key = (threading.get_ident(), phase)
        self._open.setdefault(key, []).append(rec)
        self.emit(rec)
        return sid

    def end(self, phase: str, sid: int = -1, **labels):
        if not self._path:
            return
        rec = self._record(phase, "E", **labels)
        key = (threading.get_ident(), phase)
        stack = self._open.get(key)
        if sid < 0 and stack:
            sid = stack[-1].get("sid", -1)
        if stack:
            stack.pop()
        rec["sid"] = sid
        self.emit(rec)

    def complete(
        self, phase: str, start_wall: float, duration_s: float, **labels
    ):
        """One finished span, emitted after the fact (``ph: "X"``)."""
        if not self._path:
            return
        rec = self._record(phase, "X", **labels)
        rec["wall"] = float(start_wall)
        rec["dur"] = max(float(duration_s), 0.0)
        self.emit(rec)

    def instant(self, name: str, **labels):
        if not self._path:
            return
        self.emit(self._record(name, "i", **labels))

    @contextmanager
    def span(self, phase: str, **labels):
        """``with events.span("rendezvous"): ...`` — ends on exit,
        even on exception (the failed attempt's time is still loss)."""
        sid = self.begin(phase, **labels)
        try:
            yield sid
        finally:
            self.end(phase, sid=sid)

    def close(self):
        with self._lock:
            if self._fd is not None:
                try:
                    os.close(self._fd)
                except OSError:
                    pass
                self._fd = None


_default_logger: Optional[EventLogger] = None
_default_logger_lock = threading.Lock()


def get_event_logger() -> EventLogger:
    """Process-wide logger configured from the environment
    (``DLROVER_TPU_EVENTS_FILE`` etc.); disabled no-op when unset."""
    global _default_logger
    with _default_logger_lock:
        if _default_logger is None:
            _default_logger = EventLogger()
        return _default_logger


def set_default_event_logger(event_logger: Optional[EventLogger]):
    """Install (or with ``None`` reset) the process default — tests
    and harnesses that flip the env mid-process need this."""
    global _default_logger
    with _default_logger_lock:
        _default_logger = event_logger


# --------------------------------------------------------------------------
# timeline reading / merging
# --------------------------------------------------------------------------


def read_events(path: str) -> List[dict]:
    """Parse a JSONL timeline file; skips torn/partial lines (a
    SIGKILLed writer's final line may be incomplete)."""
    if not os.path.exists(path):
        return []
    out = []
    with io.open(path, "r", errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict) and "name" in rec:
                out.append(rec)
    return out


def pair_spans(events: List[dict]) -> List[dict]:
    """Turn raw events into closed intervals on the WALL clock.

    ``X`` records map directly; ``B``/``E`` pairs match by
    ``(pid, sid)`` (falling back to a per-``(pid, name)`` LIFO stack
    for sid-less writers), and the duration comes from the MONOTONIC
    clock — a wall-clock step (NTP) cannot corrupt a span length, only
    shift its anchor.  A ``B`` whose writer died before ``E`` closes at
    the writer's last observed monotonic instant, so a killed worker's
    half-open span still lands in the ledger instead of vanishing.
    """
    intervals: List[dict] = []
    # writers are identified by (node, pid), never bare pid: in a
    # master-side MERGED stream, containers on different hosts reuse
    # the same pids (and per-process sid counters all start at 1) — a
    # bare-pid key would close node0's B with node1's E and subtract
    # monotonic clocks from different hosts
    open_by_sid: Dict[Tuple, dict] = {}
    open_stacks: Dict[Tuple, List[dict]] = {}
    last_mono: Dict[Tuple, float] = {}
    for e in sorted(events, key=lambda e: e.get("mono", 0.0)):
        ph = e.get("ph")
        pid = (e.get("node", 0), e.get("pid", 0))
        mono = float(e.get("mono", 0.0))
        last_mono[pid] = max(last_mono.get(pid, mono), mono)
        if ph == "X":
            start = float(e.get("wall", 0.0))
            dur = max(float(e.get("dur", 0.0)), 0.0)
            intervals.append(
                {
                    "phase": e.get("name", ""),
                    "start": start,
                    "end": start + dur,
                    **_identity(e),
                }
            )
        elif ph == "B":
            sid = e.get("sid", -1)
            if sid >= 0:
                open_by_sid[(pid, sid)] = e
            open_stacks.setdefault(
                (pid, e.get("name", "")), []
            ).append(e)
        elif ph == "E":
            b = open_by_sid.pop((pid, e.get("sid", -1)), None)
            stack = open_stacks.get((pid, e.get("name", "")))
            if b is None and stack:
                b = stack.pop()
            elif b is not None and stack and b in stack:
                stack.remove(b)
            if b is None:
                continue  # E without B: writer restarted mid-span
            dur = max(mono - float(b.get("mono", mono)), 0.0)
            start = float(b.get("wall", 0.0))
            labels = dict(b.get("labels") or {})
            labels.update(e.get("labels") or {})
            iv = {
                "phase": b.get("name", ""),
                "start": start,
                "end": start + dur,
                **_identity(b),
            }
            if labels:
                iv["labels"] = labels
            intervals.append(iv)
    # close writer-died spans at the writer's last seen instant
    leftovers = list(open_by_sid.values())
    seen = {id(b) for b in leftovers}
    for stack in open_stacks.values():
        leftovers.extend(b for b in stack if id(b) not in seen)
    for b in leftovers:
        pid = (b.get("node", 0), b.get("pid", 0))
        dur = max(
            last_mono.get(pid, 0.0) - float(b.get("mono", 0.0)), 0.0
        )
        start = float(b.get("wall", 0.0))
        intervals.append(
            {
                "phase": b.get("name", ""),
                "start": start,
                "end": start + dur,
                "truncated": True,
                **_identity(b),
            }
        )
    intervals.sort(key=lambda iv: (iv["start"], iv["end"]))
    return intervals


def _identity(e: dict) -> dict:
    out = {
        "job": e.get("job", ""),
        "node": e.get("node", 0),
        "rank": e.get("rank", -1),
        "inc": e.get("inc", 0),
        "pid": e.get("pid", 0),
    }
    if e.get("labels"):
        out["labels"] = e["labels"]
    return out


def compute_ledger(
    events: List[dict],
    window: Optional[Tuple[float, float]] = None,
) -> dict:
    """Partition wall clock into phases — the goodput ledger.

    Sweep-line over all span intervals: every elementary segment of
    the window is charged to the highest-priority covering phase
    (``PHASES`` order), or to ``unattributed`` when nothing covers it.
    Because the partition is exact,

        ``sum(loss_breakdown.values()) == wall_s − useful_s``

    holds to float precision — losses can never silently leak.
    """
    intervals = pair_spans(events)
    if window is None:
        if not intervals:
            return {
                "wall_s": 0.0,
                "useful_s": 0.0,
                "goodput": 0.0,
                "loss_breakdown": {},
                "spans": 0,
                "incarnations": [],
            }
        window = (
            min(iv["start"] for iv in intervals),
            max(iv["end"] for iv in intervals),
        )
    w0, w1 = float(window[0]), float(window[1])
    # priority index: declared phases first, then undeclared span names
    # (still attributable, ranked after every declared phase), then
    # the unattributed bucket
    order: List[str] = list(PHASES)
    for iv in intervals:
        if iv["phase"] not in order:
            order.append(iv["phase"])
    order.append(UNATTRIBUTED)
    idx = {p: i for i, p in enumerate(order)}
    unattr_idx = idx[UNATTRIBUTED]

    # boundary sweep with per-phase active counters
    bounds: List[Tuple[float, int, int]] = []  # (t, 0=end/1=start, phase)
    for iv in intervals:
        lo = max(iv["start"], w0)
        hi = min(iv["end"], w1)
        if hi <= lo:
            continue
        p = idx[iv["phase"]]
        bounds.append((lo, 1, p))
        bounds.append((hi, 0, p))
    bounds.sort(key=lambda b: (b[0], b[1]))
    active = [0] * len(order)
    acc = [0.0] * len(order)
    prev_t = w0
    covered = 0
    for t, kind, p in bounds:
        if t > prev_t:
            seg = t - prev_t
            if covered:
                winner = next(
                    i for i, n in enumerate(active) if n > 0
                )
            else:
                winner = unattr_idx
            acc[winner] += seg
            prev_t = t
        if kind == 1:
            active[p] += 1
            covered += 1
        else:
            active[p] -= 1
            covered -= 1
    if w1 > prev_t:
        acc[unattr_idx] += w1 - prev_t

    useful = sum(
        acc[idx[p]] for p in USEFUL_PHASES if p in idx
    )
    wall = max(w1 - w0, 0.0)
    loss = {
        order[i]: round(acc[i], 6)
        for i in range(len(order))
        if order[i] not in USEFUL_PHASES and acc[i] > 0.0
    }
    # the bucket is always present: "no unattributed time" is a
    # statement, not an omission
    loss.setdefault(UNATTRIBUTED, 0.0)
    return {
        "wall_s": round(wall, 6),
        "useful_s": round(useful, 6),
        "goodput": round(useful / wall, 6) if wall > 0 else 0.0,
        "loss_breakdown": loss,
        "spans": len(intervals),
        "incarnations": sorted(
            {iv.get("inc", 0) for iv in intervals}
        ),
    }


def export_chrome_trace(events: List[dict], path: str) -> dict:
    """Write the timeline as a chrome-trace JSON Perfetto loads
    directly: one process track per node, one thread per rank (the
    agent's rank ``-1`` renders as its own "agent" track).  Returns
    the trace dict."""
    intervals = pair_spans(events)
    t0 = min(
        (iv["start"] for iv in intervals), default=0.0
    )
    trace_events: List[dict] = []
    seen_tracks = set()
    for iv in intervals:
        pid = int(iv.get("node", 0))
        rank = int(iv.get("rank", -1))
        tid = rank + 1  # agent (-1) -> tid 0, rank r -> r+1
        if (pid, None) not in seen_tracks:
            seen_tracks.add((pid, None))
            trace_events.append(
                {
                    "name": "process_name",
                    "ph": "M",
                    "pid": pid,
                    "args": {"name": f"node{pid}"},
                }
            )
        if (pid, tid) not in seen_tracks:
            seen_tracks.add((pid, tid))
            tname = "agent" if rank < 0 else f"rank{rank}"
            trace_events.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": pid,
                    "tid": tid,
                    "args": {"name": tname},
                }
            )
        args = dict(iv.get("labels") or {})
        args["inc"] = iv.get("inc", 0)
        trace_events.append(
            {
                "name": iv["phase"],
                "ph": "X",
                "ts": round((iv["start"] - t0) * 1e6, 1),
                "dur": round((iv["end"] - iv["start"]) * 1e6, 1),
                "pid": pid,
                "tid": tid,
                "args": args,
            }
        )
    for e in events:
        if e.get("ph") != "i":
            continue
        trace_events.append(
            {
                "name": e.get("name", ""),
                "ph": "i",
                "s": "g",
                "ts": round((float(e.get("wall", t0)) - t0) * 1e6, 1),
                "pid": int(e.get("node", 0)),
                "tid": int(e.get("rank", -1)) + 1,
                "args": dict(e.get("labels") or {}),
            }
        )
    trace = {"traceEvents": trace_events, "displayTimeUnit": "ms"}
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(trace, f)
    os.replace(tmp, path)
    return trace


# --------------------------------------------------------------------------
# master-side aggregation
# --------------------------------------------------------------------------


class TimelineAggregator:
    """Master-side sink merging per-node event streams.

    Batches arrive through the report RPC (``TimelineEventsReport``;
    the agent's ``TimelineReporter`` tails the node-local JSONL and
    ships deltas).  The merged stream is durable when a Brain
    datastore is wired (``timeline_events`` table) and the live ledger
    is served three ways: the ``TimelineQueryRequest`` get-RPC,
    :class:`MetricsRegistry` gauges (native Prometheus exporter), and
    the chrome-trace export.
    """

    MAX_EVENTS = 200_000  # in-memory ring bound (control-plane rates)
    #: gauge refresh cadence: the ledger sweep is O(ring log ring),
    #: so it must not run on every node's report RPC
    GAUGE_REFRESH_S = 5.0
    #: Brain timeline_events retention sweep cadence (age/row-cap;
    #: the sweep itself lives in the datastore)
    RETENTION_SWEEP_S = 300.0

    def __init__(
        self, job: str = "", registry=None, datastore=None,
        health=None,
    ):
        """``health``: an ``observability.health.HealthEngine`` — the
        observatory's streaming tap; every accepted batch is forwarded
        so per-node derivations update at report rate (None = no
        observatory, today's behavior)."""
        self._job = job or os.getenv(
            "DLROVER_TPU_JOB_NAME", "default"
        )
        self._registry = registry
        self._datastore = datastore
        self._health = health
        self._events: List[dict] = []
        self._lock = threading.Lock()
        self._last_gauge_refresh = 0.0
        self._last_retention_sweep = time.monotonic()

    @property
    def job(self) -> str:
        return self._job

    def add_events(self, node_id: int, events: List[dict]) -> int:
        """Merge one node's batch; returns the count accepted."""
        accepted = []
        for e in events:
            if not isinstance(e, dict) or "name" not in e:
                continue
            e.setdefault("node", node_id)
            e.setdefault("job", self._job)
            accepted.append(e)
        with self._lock:
            self._events.extend(accepted)
            if len(self._events) > self.MAX_EVENTS:
                self._events = self._events[-self.MAX_EVENTS:]
        if self._datastore is not None and accepted:
            try:
                self._datastore.record_timeline_events(
                    self._job, accepted
                )
            except Exception as e:  # noqa: BLE001 - durability is best-effort
                logger.warning("timeline persist failed: %s", e)
            self._maybe_sweep_retention()
        if self._health is not None and accepted:
            try:
                self._health.observe_events(node_id, accepted)
            except Exception as e:  # noqa: BLE001 - derivations are best-effort
                logger.warning("health derivation failed: %s", e)
        if accepted:
            now = time.monotonic()
            if (
                now - self._last_gauge_refresh
                >= self.GAUGE_REFRESH_S
            ):
                self._last_gauge_refresh = now
                self._refresh_gauges()
        return len(accepted)

    def _maybe_sweep_retention(self):
        """Throttled Brain ``timeline_events`` retention sweep — the
        durable timeline must not grow without bound on a week-long
        job (behind the observatory kill-switch like the rest of the
        growth bounds)."""
        from dlrover_tpu.common.env import observatory_enabled

        if not observatory_enabled():
            return
        now = time.monotonic()
        if now - self._last_retention_sweep < self.RETENTION_SWEEP_S:
            return
        self._last_retention_sweep = now
        try:
            self._datastore.sweep_timeline(self._job)
        except Exception as e:  # noqa: BLE001 - hygiene is best-effort
            logger.warning("timeline retention sweep failed: %s", e)

    def events(self, limit: int = 0) -> List[dict]:
        with self._lock:
            if limit and limit > 0:
                return list(self._events[-limit:])
            return list(self._events)

    def size(self) -> int:
        """Ring occupancy without copying it (the self-telemetry
        state-rows sweep runs per scrape — ``len(events())`` would
        copy up to MAX_EVENTS dicts each time)."""
        with self._lock:
            return len(self._events)

    def ledger(self) -> dict:
        """Current goodput ledger over everything merged so far."""
        return compute_ledger(self.events())

    def export_chrome_trace(self, path: str) -> dict:
        return export_chrome_trace(self.events(), path)

    def _refresh_gauges(self):
        if self._registry is None:
            return
        try:
            ledger = self.ledger()
            self._registry.set_gauge(
                "dlrover_tpu_goodput", ledger["goodput"]
            )
            self._registry.set_gauge(
                "dlrover_tpu_timeline_useful_seconds",
                ledger["useful_s"],
            )
            self._registry.set_gauge(
                "dlrover_tpu_timeline_wall_seconds", ledger["wall_s"]
            )
            for phase, sec in ledger["loss_breakdown"].items():
                self._registry.set_gauge(
                    "dlrover_tpu_goodput_loss_seconds",
                    sec,
                    labels={"phase": phase},
                )
        except Exception as e:  # noqa: BLE001 - metrics must never break reports
            logger.warning("ledger gauge refresh failed: %s", e)
