"""Profiling utilities: FLOPs census + XLA trace capture.

Reference parity: ``AProfiler`` (``atorch/atorch/utils/prof.py:38`` —
FLOPs/MACs census by monkey-patching torch.nn.functional) and the
xpu_timer kernel-timing role.  JAX gives both analytically: the
compiled computation's cost analysis reports exact FLOPs/bytes, and
``jax.profiler`` captures device traces for tensorboard — no symbol
interposition needed (SURVEY.md §5.1 TPU equivalent).
"""

import contextlib
import time
from typing import Callable, Dict, Optional

import jax

from dlrover_tpu.common.log import default_logger as logger


class AProfiler:
    """FLOPs/memory census of a jitted function + step timing."""

    def __init__(self, registry=None):
        self._registry = registry
        self._step_times = []

    def cost_analysis(self, fn: Callable, *args, **kwargs) -> Dict:
        """Exact compiled-cost census (replaces the reference's
        monkey-patched per-op accounting)."""
        lowered = jax.jit(fn).lower(*args, **kwargs)
        compiled = lowered.compile()
        costs = compiled.cost_analysis()
        if isinstance(costs, list):  # old jax returns [dict]
            costs = costs[0] if costs else {}
        result = {
            "flops": float(costs.get("flops", 0.0)),
            "bytes_accessed": float(costs.get("bytes accessed", 0.0)),
        }
        try:
            mem = compiled.memory_analysis()
            result["output_bytes"] = float(
                getattr(mem, "output_size_in_bytes", 0)
            )
            result["temp_bytes"] = float(
                getattr(mem, "temp_size_in_bytes", 0)
            )
        except Exception:  # noqa: BLE001
            pass
        return result

    def model_flops_per_token(self, num_params: int) -> float:
        """The 6N rule of thumb for transformer training FLOPs."""
        return 6.0 * num_params

    @contextlib.contextmanager
    def step(self, name: str = "train_step"):
        start = time.perf_counter()
        yield
        elapsed = time.perf_counter() - start
        self._step_times.append(elapsed)
        if len(self._step_times) > 1024:
            self._step_times.pop(0)
        if self._registry is not None:
            self._registry.observe_duration(name, elapsed)

    def mean_step_time(self) -> float:
        if not self._step_times:
            return 0.0
        return sum(self._step_times) / len(self._step_times)

    def mfu(self, flops_per_step: float,
            peak_flops: float = 197e12) -> float:
        """Model FLOPs utilization vs peak (v5e bf16 default)."""
        t = self.mean_step_time()
        if t <= 0:
            return 0.0
        return flops_per_step / t / peak_flops


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture an XLA device trace viewable in tensorboard/xprof
    (the libtpu-level replacement for CUDA-event interposition)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        logger.info("trace written to %s", log_dir)


def start_profiler_server(port: int = 9999) -> Optional[object]:
    """On-demand profiling endpoint (``jax.profiler`` trace server)."""
    try:
        return jax.profiler.start_server(port)
    except Exception as e:  # noqa: BLE001
        logger.warning("profiler server failed: %s", e)
        return None
