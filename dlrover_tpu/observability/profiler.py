"""Profiling utilities: FLOPs census + XLA trace capture.

Reference parity: ``AProfiler`` (``atorch/atorch/utils/prof.py:38`` —
FLOPs/MACs census by monkey-patching torch.nn.functional) and the
xpu_timer kernel-timing role.  JAX gives both analytically: the
compiled computation's cost analysis reports exact FLOPs/bytes, and
``jax.profiler`` captures device traces for tensorboard — no symbol
interposition needed (SURVEY.md §5.1 TPU equivalent).
"""

import contextlib
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, Optional, Tuple

import jax

from dlrover_tpu.common.log import default_logger as logger

#: Per-device-kind peak bf16 FLOP/s (per chip).  ONE table behind
#: every MFU number in the repo — ``AProfiler.mfu``, ``bench_mfu``'s
#: candidate scoring, and the observatory's per-node
#: ``dlrover_tpu_node_mfu`` gauge all route through
#: :func:`peak_flops_for_kind` so the bench and the live job can never
#: disagree about what "peak" means.  Matching is by substring on the
#: lowercased ``device_kind`` string, FIRST match wins — order the
#: specific patterns (v5 lite) before the generic ones (v5).
PEAK_FLOPS_BY_KIND: Tuple[Tuple[str, float], ...] = (
    ("v6", 918e12),     # Trillium / v6e
    ("v5 lite", 197e12),
    ("v5lite", 197e12),
    ("v5e", 197e12),
    ("v5", 459e12),     # v5p
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)

#: the fallback when the kind is unknown (CPU CI, exotic plugin):
#: the v5e number, so MFU is always populated — meaningless off-TPU,
#: flagged by the loud warning below and the backend field in benches
DEFAULT_PEAK_FLOPS = 197e12

PEAK_FLOPS_ENV = "DLROVER_TPU_PEAK_FLOPS"

#: unknown kinds warn ONCE per process, not once per step
_warned_unknown_kinds = set()
_warned_lock = threading.Lock()


def peak_flops_for_kind(kind: str) -> Tuple[float, bool]:
    """``(peak bf16 FLOP/s, known)`` for a ``device_kind`` string.
    ``known=False`` means the table had no entry and the v5e fallback
    was used (logged loudly, once per kind)."""
    lowered = str(kind or "").lower()
    for pattern, peak in PEAK_FLOPS_BY_KIND:
        if pattern in lowered:
            return peak, True
    with _warned_lock:
        if lowered not in _warned_unknown_kinds:
            _warned_unknown_kinds.add(lowered)
            logger.warning(
                "unknown device kind %r: no peak-FLOPs table entry, "
                "falling back to %.0fe12 (v5e) — MFU numbers are NOT "
                "meaningful; set %s to the chip's real bf16 peak",
                kind, DEFAULT_PEAK_FLOPS / 1e12, PEAK_FLOPS_ENV,
            )
    return DEFAULT_PEAK_FLOPS, False


def device_peak_flops(device=None) -> float:
    """Peak bf16 FLOP/s of ONE attached chip: the
    ``DLROVER_TPU_PEAK_FLOPS`` override when set (malformed values
    fall through, loudly), else the table entry for
    ``jax.devices()[0].device_kind``."""
    raw = os.getenv(PEAK_FLOPS_ENV, "")
    if raw:
        try:
            return float(raw)
        except ValueError:
            logger.warning(
                "ignoring malformed %s=%r", PEAK_FLOPS_ENV, raw
            )
    if device is None:
        try:
            device = jax.devices()[0]
        except Exception:  # noqa: BLE001 - no backend at all
            return DEFAULT_PEAK_FLOPS
    kind = getattr(device, "device_kind", "")
    peak, _known = peak_flops_for_kind(kind)
    return peak


class AProfiler:
    """FLOPs/memory census of a jitted function + step timing.

    ``registry`` must expose ``observe_duration`` (the
    ``MetricsRegistry`` contract).  A registry without it is rejected
    at CONSTRUCTION — ``step()`` used to discover the mismatch only
    when it tried to record, which silently lost every sample until
    then."""

    #: step-time window (ring — the old list paid O(n) ``pop(0)``)
    STEP_WINDOW = 1024

    def __init__(self, registry=None):
        if registry is not None and not callable(
            getattr(registry, "observe_duration", None)
        ):
            raise TypeError(
                "AProfiler registry must provide observe_duration() "
                f"(got {type(registry).__name__}); pass a "
                "MetricsRegistry or None"
            )
        self._registry = registry
        self._step_times = deque(maxlen=self.STEP_WINDOW)

    def cost_analysis(self, fn: Callable, *args, **kwargs) -> Dict:
        """Exact compiled-cost census (replaces the reference's
        monkey-patched per-op accounting)."""
        lowered = jax.jit(fn).lower(*args, **kwargs)
        compiled = lowered.compile()
        costs = compiled.cost_analysis()
        if isinstance(costs, list):  # old jax returns [dict]
            costs = costs[0] if costs else {}
        result = {
            "flops": float(costs.get("flops", 0.0)),
            "bytes_accessed": float(costs.get("bytes accessed", 0.0)),
        }
        try:
            mem = compiled.memory_analysis()
            result["output_bytes"] = float(
                getattr(mem, "output_size_in_bytes", 0)
            )
            result["temp_bytes"] = float(
                getattr(mem, "temp_size_in_bytes", 0)
            )
        except Exception:  # noqa: BLE001
            pass
        return result

    def model_flops_per_token(self, num_params: int) -> float:
        """The 6N rule of thumb for transformer training FLOPs."""
        return 6.0 * num_params

    @contextlib.contextmanager
    def step(self, name: str = "train_step"):
        start = time.perf_counter()
        try:
            yield
        finally:
            # a raising step still took its time — drop the sample
            # and the window under-reports exactly the bad steps
            elapsed = time.perf_counter() - start
            self._step_times.append(elapsed)
            if self._registry is not None:
                self._registry.observe_duration(name, elapsed)

    def mean_step_time(self) -> float:
        if not self._step_times:
            return 0.0
        return sum(self._step_times) / len(self._step_times)

    def mfu(self, flops_per_step: float,
            peak_flops: Optional[float] = None) -> float:
        """Model FLOPs utilization vs peak.  ``peak_flops`` defaults
        to the attached chip's table entry
        (:func:`device_peak_flops`: ``DLROVER_TPU_PEAK_FLOPS``
        override → ``device_kind`` table → loud v5e fallback) — the
        hard-coded ``197e12`` default used to make every non-v5e
        number silently wrong."""
        t = self.mean_step_time()
        if t <= 0:
            return 0.0
        if peak_flops is None:
            peak_flops = device_peak_flops()
        return flops_per_step / t / peak_flops


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture an XLA device trace viewable in tensorboard/xprof
    (the libtpu-level replacement for CUDA-event interposition)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        logger.info("trace written to %s", log_dir)


#: the live trace server (jax keeps it alive only while a reference
#: exists — the old API returned it to callers who all dropped it on
#: the floor, so "nothing ever stops it" was really "anything GCing
#: it stops it at an arbitrary moment")
_profiler_server = None
_profiler_server_lock = threading.Lock()


def start_profiler_server(port: int = 9999) -> Optional[object]:
    """On-demand profiling endpoint (``jax.profiler`` trace server).

    Idempotent: a second call returns the already-running server.
    The module holds the reference (jax stops the server when the
    object is collected), so the lifetime is explicit —
    :func:`stop_profiler_server` ends it."""
    global _profiler_server
    with _profiler_server_lock:
        if _profiler_server is not None:
            return _profiler_server
        try:
            _profiler_server = jax.profiler.start_server(port)
        except Exception as e:  # noqa: BLE001
            logger.warning("profiler server failed: %s", e)
            return None
        return _profiler_server


def stop_profiler_server():
    """Stop the trace server started by :func:`start_profiler_server`
    (no-op when none is running)."""
    global _profiler_server
    with _profiler_server_lock:
        server, _profiler_server = _profiler_server, None
    if server is None:
        return
    stop = getattr(server, "stop", None)
    try:
        if callable(stop):
            stop()
        # else: dropping the last reference stops it (jax contract)
    except Exception as e:  # noqa: BLE001
        logger.warning("profiler server stop failed: %s", e)
    logger.info("profiler server stopped")
