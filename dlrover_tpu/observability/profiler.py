"""Profiling utilities: FLOPs census + XLA trace capture.

Reference parity: ``AProfiler`` (``atorch/atorch/utils/prof.py:38`` —
FLOPs/MACs census by monkey-patching torch.nn.functional) and the
xpu_timer kernel-timing role.  JAX gives both analytically: the
compiled computation's cost analysis reports exact FLOPs/bytes, and
``jax.profiler`` captures device traces for tensorboard — no symbol
interposition needed (SURVEY.md §5.1 TPU equivalent).
"""

import contextlib
import threading
import time
from collections import deque
from typing import Callable, Dict, Optional

import jax

from dlrover_tpu.common.log import default_logger as logger


class AProfiler:
    """FLOPs/memory census of a jitted function + step timing.

    ``registry`` must expose ``observe_duration`` (the
    ``MetricsRegistry`` contract).  A registry without it is rejected
    at CONSTRUCTION — ``step()`` used to discover the mismatch only
    when it tried to record, which silently lost every sample until
    then."""

    #: step-time window (ring — the old list paid O(n) ``pop(0)``)
    STEP_WINDOW = 1024

    def __init__(self, registry=None):
        if registry is not None and not callable(
            getattr(registry, "observe_duration", None)
        ):
            raise TypeError(
                "AProfiler registry must provide observe_duration() "
                f"(got {type(registry).__name__}); pass a "
                "MetricsRegistry or None"
            )
        self._registry = registry
        self._step_times = deque(maxlen=self.STEP_WINDOW)

    def cost_analysis(self, fn: Callable, *args, **kwargs) -> Dict:
        """Exact compiled-cost census (replaces the reference's
        monkey-patched per-op accounting)."""
        lowered = jax.jit(fn).lower(*args, **kwargs)
        compiled = lowered.compile()
        costs = compiled.cost_analysis()
        if isinstance(costs, list):  # old jax returns [dict]
            costs = costs[0] if costs else {}
        result = {
            "flops": float(costs.get("flops", 0.0)),
            "bytes_accessed": float(costs.get("bytes accessed", 0.0)),
        }
        try:
            mem = compiled.memory_analysis()
            result["output_bytes"] = float(
                getattr(mem, "output_size_in_bytes", 0)
            )
            result["temp_bytes"] = float(
                getattr(mem, "temp_size_in_bytes", 0)
            )
        except Exception:  # noqa: BLE001
            pass
        return result

    def model_flops_per_token(self, num_params: int) -> float:
        """The 6N rule of thumb for transformer training FLOPs."""
        return 6.0 * num_params

    @contextlib.contextmanager
    def step(self, name: str = "train_step"):
        start = time.perf_counter()
        try:
            yield
        finally:
            # a raising step still took its time — drop the sample
            # and the window under-reports exactly the bad steps
            elapsed = time.perf_counter() - start
            self._step_times.append(elapsed)
            if self._registry is not None:
                self._registry.observe_duration(name, elapsed)

    def mean_step_time(self) -> float:
        if not self._step_times:
            return 0.0
        return sum(self._step_times) / len(self._step_times)

    def mfu(self, flops_per_step: float,
            peak_flops: float = 197e12) -> float:
        """Model FLOPs utilization vs peak (v5e bf16 default)."""
        t = self.mean_step_time()
        if t <= 0:
            return 0.0
        return flops_per_step / t / peak_flops


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture an XLA device trace viewable in tensorboard/xprof
    (the libtpu-level replacement for CUDA-event interposition)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        logger.info("trace written to %s", log_dir)


#: the live trace server (jax keeps it alive only while a reference
#: exists — the old API returned it to callers who all dropped it on
#: the floor, so "nothing ever stops it" was really "anything GCing
#: it stops it at an arbitrary moment")
_profiler_server = None
_profiler_server_lock = threading.Lock()


def start_profiler_server(port: int = 9999) -> Optional[object]:
    """On-demand profiling endpoint (``jax.profiler`` trace server).

    Idempotent: a second call returns the already-running server.
    The module holds the reference (jax stops the server when the
    object is collected), so the lifetime is explicit —
    :func:`stop_profiler_server` ends it."""
    global _profiler_server
    with _profiler_server_lock:
        if _profiler_server is not None:
            return _profiler_server
        try:
            _profiler_server = jax.profiler.start_server(port)
        except Exception as e:  # noqa: BLE001
            logger.warning("profiler server failed: %s", e)
            return None
        return _profiler_server


def stop_profiler_server():
    """Stop the trace server started by :func:`start_profiler_server`
    (no-op when none is running)."""
    global _profiler_server
    with _profiler_server_lock:
        server, _profiler_server = _profiler_server, None
    if server is None:
        return
    stop = getattr(server, "stop", None)
    try:
        if callable(stop):
            stop()
        # else: dropping the last reference stops it (jax contract)
    except Exception as e:  # noqa: BLE001
        logger.warning("profiler server stop failed: %s", e)
    logger.info("profiler server stopped")
