"""Control-plane SELF-telemetry: the master watching itself.

The observatory (``observability/health.py``) can name a slow rank, a
hung rank, and why its device is slow — but every one of those signals
flows through the master, and the master itself was unobserved:
nothing reported RPC latency, how many pool threads parked long-polls
were silently holding, how far the write-behind journal lagged the
mutations it claims durable, or how big a job's control-plane state
had grown.  A shared multi-job control plane without self-telemetry is
the next outage's root cause you can't see (ROADMAP item 2's 256-512
agent fan-in depends on exactly these numbers).

:class:`MasterSelfTelemetry` is the per-master collector the servicer
feeds inline (one histogram observe + a couple of counter bumps per
RPC — no locks beyond the registry's):

- **per-RPC-kind latency + size histograms**
  (``dlrover_tpu_master_rpc_latency_seconds{kind}`` /
  ``_request_bytes{kind}`` / ``_response_bytes{kind}``, log-bucketed,
  classic Prometheus text rendering) — ``kind`` is the request message
  class name, a closed vocabulary;
- **in-flight / parked / pool gauges**: every in-flight RPC holds one
  gRPC pool thread, and a PARKED long-poll holds one for its whole
  wait — ``dlrover_tpu_master_busy_workers`` over
  ``dlrover_tpu_master_worker_pool_size`` is the saturation signal,
  ``dlrover_tpu_master_parked_waits`` says how much of it is parked
  waiters, and ``dlrover_tpu_master_rejected_waits`` counts the
  long-polls degraded to immediate answers at the parked-wait cap;
- **per-job state growth**: row counts of the KV store, rendezvous
  waitlists/world, shard task queues and the in-memory timeline ring
  (``dlrover_tpu_master_state_rows{kind}``);
- **journal & datastore health** (pulled from the components on the
  throttled refresh): write-behind queue depth vs bound, journal lag
  (rows enqueued − rows flushed), last snapshot age and duration.

The derived verdict lives in ``observability/health.py``
:class:`~dlrover_tpu.observability.health.MasterHealth` — sustained
p99 / queue-near-bound / journal-lag / pool-saturation streaks become
a ``master_overload`` diagnosis conclusion + instant.

Everything is behind ``DLROVER_TPU_SELF_OBS=0`` (the master simply
never constructs a collector; the flush-latency record function gates
itself), which reproduces the pre-self-obs metric surface exactly —
pinned by ``tests/test_self_obs.py``.
"""

import threading
import time
from collections import deque
from typing import Deque, Dict, Optional, Tuple

from dlrover_tpu.common.env import env_float, master_workers
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.observability.metrics import (
    SIZE_BOUNDS,
    get_registry,
)

#: rolling window for the deriver's p99 (seconds of recent RPCs)
RPC_WINDOW_ENV = "DLROVER_TPU_MASTER_RPC_WINDOW_S"

#: request kinds that can PARK in a long-poll: their measured latency
#: is the wait window they asked for, by design.  They keep their
#: per-kind histograms, but they are EXCLUDED from the windowed-p99
#: ring the MasterHealth deriver reads — a healthy idle fleet spends
#: most of its RPCs parked for seconds, and folding those in would
#: trip a permanent spurious rpc_p99 overload (the fleet bench's
#: fast-kind knee applies the same exclusion).
WAIT_KINDS = frozenset(
    {
        "KVWaitRequest",
        "WaitingNodeNumRequest",
        "TaskRequest",
        "CommWorldRequest",
        "TrainingStatusRequest",
    }
)


class MasterSelfTelemetry:
    """Collector for one master process.  All observe paths are
    O(1); the component sweeps (row counts, datastore health) run on
    the throttled ``refresh_gauges`` and at scrape time, never on the
    RPC path."""

    #: gauge refresh throttle (the component sweep is O(components))
    GAUGE_REFRESH_S = 5.0
    #: recent-latency ring for the windowed p99 (the cumulative
    #: histograms cannot answer "p99 over the last minute")
    WINDOW_SAMPLES = 4096
    #: below this many fast-kind samples in the window the p99 reads
    #: 0.0: with ≤100 samples ``int(n * 0.99)`` is the MAXIMUM, and
    #: one isolated outlier (a big status serialization) on a
    #: near-idle master must not sustain a spurious rpc_p99 overload
    #: verdict — a p99 needs a distribution, not two points
    MIN_P99_SAMPLES = 20

    def __init__(
        self,
        registry=None,
        pool_size: Optional[int] = None,
        window_s: Optional[float] = None,
    ):
        self._registry = registry if registry is not None else (
            get_registry()
        )
        self.pool_size = (
            pool_size if pool_size is not None else master_workers()
        )
        self.window_s = (
            window_s
            if window_s is not None
            else env_float(RPC_WINDOW_ENV, 60.0)
        )
        self._lock = threading.Lock()
        self._inflight = 0
        self._parked = 0
        self.rejected_waits = 0
        #: kind -> lifetime RPC count (the snapshot's kind roster —
        #: histogram reads key off this, so a kind never observed
        #: costs nothing)
        self._kind_counts: Dict[str, int] = {}
        #: (mono, latency_s) ring for the windowed p99
        self._recent: Deque[Tuple[float, float]] = deque(
            maxlen=self.WINDOW_SAMPLES
        )
        self._last_gauge_refresh = 0.0
        # components wired via attach() after construction (the
        # journal only exists once failover setup ran)
        self._kv = None
        self._rdzv: Dict[str, object] = {}
        self._tasks = None
        self._timeline = None
        self._datastore = None
        self._journal = None

    # ------------------------------------------------------------ wiring
    def attach(
        self,
        kv_store=None,
        rdzv_managers=None,
        task_manager=None,
        timeline_aggregator=None,
        datastore=None,
        journal=None,
    ):
        """Late-bind the components whose state the refresh sweeps;
        every argument is optional and only overwrites when given."""
        if kv_store is not None:
            self._kv = kv_store
        if rdzv_managers is not None:
            self._rdzv = dict(rdzv_managers)
        if task_manager is not None:
            self._tasks = task_manager
        if timeline_aggregator is not None:
            self._timeline = timeline_aggregator
        if datastore is not None:
            self._datastore = datastore
        if journal is not None:
            self._journal = journal

    # ---------------------------------------------------------- RPC path
    def rpc_begin(self):
        with self._lock:
            self._inflight += 1

    def rpc_end(
        self,
        kind: str,
        seconds: float,
        req_bytes: int,
        resp_bytes: Optional[int],
    ):
        """One RPC finished (success or raise): histogram the latency
        and sizes, release the in-flight slot.  Never raises — the
        finally-block caller must not lose the real answer."""
        try:
            with self._lock:
                self._inflight -= 1
                self._kind_counts[kind] = (
                    self._kind_counts.get(kind, 0) + 1
                )
                if kind not in WAIT_KINDS:
                    self._recent.append(
                        (time.monotonic(), seconds)
                    )
            labels = {"kind": kind}
            reg = self._registry
            reg.observe_histogram(
                "dlrover_tpu_master_rpc_latency_seconds",
                seconds,
                labels=labels,
            )
            reg.observe_histogram(
                "dlrover_tpu_master_rpc_request_bytes",
                float(req_bytes),
                labels=labels,
                bounds=SIZE_BOUNDS,
            )
            if resp_bytes is not None:
                reg.observe_histogram(
                    "dlrover_tpu_master_rpc_response_bytes",
                    float(resp_bytes),
                    labels=labels,
                    bounds=SIZE_BOUNDS,
                )
            self._maybe_refresh()
        except Exception as e:  # noqa: BLE001 - telemetry must not break RPCs
            logger.warning("self-telemetry rpc record failed: %s", e)

    def wait_parked(self):
        with self._lock:
            self._parked += 1

    def wait_unparked(self):
        with self._lock:
            self._parked -= 1

    def wait_rejected(self):
        """A long-poll degraded to an immediate answer because every
        parked-wait slot was taken — the saturation precursor."""
        with self._lock:
            self.rejected_waits += 1
        try:
            self._registry.inc_counter(
                "dlrover_tpu_master_rejected_waits"
            )
        except Exception as e:  # noqa: BLE001
            logger.warning("rejected-wait counter failed: %s", e)

    # -------------------------------------------------------- derivations
    def occupancy(self) -> float:
        """Busy pool fraction: in-flight RPCs (each holds one worker,
        parked long-polls included) over the pool size."""
        with self._lock:
            return min(self._inflight / max(self.pool_size, 1), 1.0)

    def window_p99(self) -> float:
        """p99 latency (seconds) of the FAST kinds (``WAIT_KINDS``
        excluded — a parked long-poll's latency is its wait window)
        over the rolling window — the deriver's drift signal; 0.0
        below ``MIN_P99_SAMPLES`` recent samples (too few points to
        call a tail)."""
        horizon = time.monotonic() - self.window_s
        with self._lock:
            lats = sorted(
                lat for t, lat in self._recent if t >= horizon
            )
        if len(lats) < self.MIN_P99_SAMPLES:
            return 0.0
        return lats[min(len(lats) - 1, int(len(lats) * 0.99))]

    def state_rows(self) -> Dict[str, int]:
        """Per-component control-plane row counts (growth watch)."""
        rows: Dict[str, int] = {}
        try:
            if self._kv is not None:
                rows["kv"] = len(
                    getattr(self._kv, "_store", {}) or {}
                )
            for name, manager in self._rdzv.items():
                # read-only accessors on purpose: get_comm_world /
                # num_nodes_waiting run lazy round-completion, and a
                # telemetry sweep must never mutate rendezvous state
                n = 0
                for accessor in ("current_world_ranks",
                                 "fenced_ranks"):
                    fn = getattr(manager, accessor, None)
                    if callable(fn):
                        n += len(fn() or [])
                rows[f"rdzv/{name}"] = n
            if self._tasks is not None:
                rows["tasks"] = self._tasks.row_counts()
            if self._timeline is not None:
                rows["timeline"] = self._timeline.size()
        except Exception as e:  # noqa: BLE001 - a sweep must not break scrape
            logger.warning("state-row sweep failed: %s", e)
        return rows

    def datastore_health(self) -> dict:
        """The write-behind queue's live health (empty dict when no
        datastore is wired)."""
        if self._datastore is None:
            return {}
        try:
            return self._datastore.health()
        except Exception as e:  # noqa: BLE001
            logger.warning("datastore health read failed: %s", e)
            return {}

    def journal_health(self) -> dict:
        """Snapshot age/duration from the control-plane journal
        (empty dict when failover is off / no journal)."""
        if self._journal is None:
            return {}
        try:
            return self._journal.health()
        except Exception as e:  # noqa: BLE001
            logger.warning("journal health read failed: %s", e)
            return {}

    # ------------------------------------------------------------- gauges
    def _maybe_refresh(self):
        now = time.monotonic()
        if now - self._last_gauge_refresh < self.GAUGE_REFRESH_S:
            return
        self._last_gauge_refresh = now
        self.refresh_gauges()

    def refresh_gauges(self):
        """Export the sweep-derived gauges (also called directly at
        scrape time by the status server, so ``/metrics`` never reads
        values staler than the snapshot it could have computed)."""
        try:
            reg = self._registry
            with self._lock:
                inflight, parked = self._inflight, self._parked
            reg.set_gauge(
                "dlrover_tpu_master_inflight_rpcs", float(inflight)
            )
            reg.set_gauge(
                "dlrover_tpu_master_parked_waits", float(parked)
            )
            reg.set_gauge(
                "dlrover_tpu_master_busy_workers", float(inflight)
            )
            reg.set_gauge(
                "dlrover_tpu_master_worker_pool_size",
                float(self.pool_size),
            )
            for kind, n in self.state_rows().items():
                reg.set_gauge(
                    "dlrover_tpu_master_state_rows",
                    float(n),
                    labels={"kind": kind},
                )
            ds = self.datastore_health()
            if ds:
                reg.set_gauge(
                    "dlrover_tpu_datastore_queue_depth",
                    float(ds.get("queue_depth", 0)),
                )
                reg.set_gauge(
                    "dlrover_tpu_journal_lag_rows",
                    float(ds.get("lag_rows", 0)),
                )
            jh = self.journal_health()
            if jh and jh.get("snapshot_age_s") is not None:
                reg.set_gauge(
                    "dlrover_tpu_snapshot_age_seconds",
                    float(jh["snapshot_age_s"]),
                )
                reg.set_gauge(
                    "dlrover_tpu_snapshot_duration_seconds",
                    float(jh.get("snapshot_duration_s", 0.0)),
                )
        except Exception as e:  # noqa: BLE001 - gauges must not break scrape
            logger.warning("self-telemetry gauge refresh failed: %s", e)

    # ----------------------------------------------------------- snapshot
    def rpc_stats(self) -> Dict[str, dict]:
        """Per-kind latency summary from the live histograms:
        ``{kind: {count, p50_ms, p99_ms, mean_ms}}`` — what the fleet
        bench reads per N and the ``master`` status section serves."""
        out: Dict[str, dict] = {}
        with self._lock:
            kinds = dict(self._kind_counts)
        for kind, count in sorted(kinds.items()):
            hist = self._registry.histogram(
                "dlrover_tpu_master_rpc_latency_seconds",
                labels={"kind": kind},
            )
            if hist is None or hist.count == 0:
                out[kind] = {"count": count}
                continue
            out[kind] = {
                "count": hist.count,
                "p50_ms": round(hist.quantile(0.5) * 1e3, 3),
                "p99_ms": round(hist.quantile(0.99) * 1e3, 3),
                "mean_ms": round(
                    hist.sum / hist.count * 1e3, 3
                ),
            }
        return out

    def snapshot(self) -> dict:
        """The ``master`` section of ``/status`` and the
        ``JobStatusResponse``: everything an operator needs to judge
        the control plane's own health at a glance."""
        with self._lock:
            inflight, parked = self._inflight, self._parked
            rejected = self.rejected_waits
        snap = {
            "pool": {
                "size": self.pool_size,
                "busy": inflight,
                "parked_waits": parked,
                "rejected_waits": rejected,
                "occupancy": round(
                    inflight / max(self.pool_size, 1), 4
                ),
            },
            "rpc": self.rpc_stats(),
            "rpc_p99_window_ms": round(self.window_p99() * 1e3, 3),
            "state_rows": self.state_rows(),
        }
        ds = self.datastore_health()
        if ds:
            snap["datastore"] = ds
        jh = self.journal_health()
        if jh:
            snap["journal"] = jh
        return snap
