"""The job observatory: streaming health derivation from the timeline.

PR-1 gave every process a structured event timeline and PR-5 batched
the agent->master reporting path — but nothing consumed them *live*:
the only way to see a running job was to export a Perfetto trace after
the fact, and ``master/diagnosis.py`` ran on its own isolated
``DiagnosisDataStore`` that almost nobody fed.  This module is the
missing consumer (the role the reference splits between
``DiagnosisManager``/``InferenceChain`` and xpu_timer's live kernel
watch): the master streams incoming timeline batches and agent
reports through a :class:`HealthEngine` that maintains rolling
per-node derivations —

- **step-rate and step-time EWMAs** from ``step`` spans (per node, on
  the span's own ``dur``, so a slow rank is visible even while the
  *global* step — the max over ranks the SpeedMonitor sees — still
  advances);
- **data-stall share by stage** (``host_fetch`` / ``h2d``) over a
  rolling window, from the same ``data_stall`` spans the goodput
  ledger charges;
- **restart / fault counts** from ``restart`` spans and
  ``fault_injected`` instants plus the servicer's ``NodeFailure``
  reports;
- a **relative straggler score**: each node's step-time EWMA over the
  across-node median, flagged past ``DLROVER_TPU_STRAGGLER_RATIO``
  (the xpu_timer "one chip is slow" signal, derived from spans
  instead of kernel interposition);
- a **span-heartbeat hang watchdog**: a node whose agent still
  heartbeats but whose processes have emitted *no timeline event* for
  ``DLROVER_TPU_HANG_WATCHDOG_S`` is flagged hung.  This works when
  the SpeedMonitor sees no steps at all (it needs ``GlobalStep``
  reports, and the global step keeps moving while one rank wedges in
  a collective); a node attributably busy inside an *open* non-step
  span (a long compile or restore emitted its ``B`` record) is NOT
  flagged — the ledger already charges that time.

``DiagnosisManager`` sits on top of these derivations through the
``StragglerOperator`` / ``DataStallOperator`` / ``HangWatchdogOperator``
in ``master/diagnosis.py``; the full derived snapshot is served by the
``JobStatusRequest`` RPC, the ``--status_port`` HTTP endpoints
(``observability/status_server.py``) and ``scripts/top.py``.  Gauges
``dlrover_tpu_node_health{node}`` / ``dlrover_tpu_straggler_score{node}``
mirror the snapshot for Prometheus.  Everything here is behind the
``DLROVER_TPU_OBSERVATORY=0`` kill-switch (the master simply never
constructs an engine).
"""

import json
import os
import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

from dlrover_tpu.common.env import env_float
from dlrover_tpu.common.log import default_logger as logger

#: a node whose step-time EWMA exceeds the across-node median by this
#: ratio is a straggler (reference: the network-check manager's 2x
#: round-time rule; xpu_timer flags slow kernels the same way)
STRAGGLER_RATIO_ENV = "DLROVER_TPU_STRAGGLER_RATIO"
#: span-heartbeat watchdog: seconds of total timeline silence from a
#: still-heartbeating node before it is flagged hung
HANG_WATCHDOG_ENV = "DLROVER_TPU_HANG_WATCHDOG_S"
#: rolling derivation window (stall shares, step rates)
HEALTH_WINDOW_ENV = "DLROVER_TPU_HEALTH_WINDOW_S"

#: health gauge encoding (dlrover_tpu_node_health{node=...})
HEALTH_OK = 1.0
HEALTH_STRAGGLER = 0.5
HEALTH_STALLED = 0.4
HEALTH_HUNG = 0.0

#: snapshot status strings, worst wins
STATUS_OK = "healthy"
STATUS_STRAGGLER = "straggler"
STATUS_STALLED = "data_stalled"
STATUS_HUNG = "hung"


class _NodeState:
    """Mutable per-node rolling state (guarded by the engine lock)."""

    __slots__ = (
        "node",
        "step_time_ewma",
        "step_rate_ewma",
        "steps_seen",
        "last_step",
        "last_step_wall",
        "step_walls",
        "stall_windows",
        "restarts",
        "faults",
        "incarnation",
        "last_event_wall",
        "last_event_seen",
        "last_heartbeat",
        "open_spans",
        "rss_mb",
        "cpu_percent",
        "mfu",
        "tflops",
        "device_share",
        "profile_wall",
    )

    def __init__(self, node: int):
        self.node = node
        self.step_time_ewma = 0.0
        self.step_rate_ewma = 0.0
        self.steps_seen = 0
        self.last_step = -1
        self.last_step_wall = 0.0
        #: recent step-end walls for windowed rate
        self.step_walls: Deque[float] = deque(maxlen=256)
        #: stage -> deque[(end_wall, dur)] for windowed stall share
        self.stall_windows: Dict[str, Deque[Tuple[float, float]]] = {}
        self.restarts = 0
        self.faults = 0
        self.incarnation = 0
        #: newest event wall clock from this node (the span heartbeat)
        self.last_event_wall = 0.0
        #: master-local monotonic time the newest event ARRIVED — the
        #: watchdog compares against this, not the event's own wall,
        #: so a node-side clock skew cannot fake (or mask) a hang
        self.last_event_seen = 0.0
        self.last_heartbeat = 0.0
        #: (pid, name) -> (open B count, mono of the newest B) —
        #: suppresses the watchdog while the node is attributably
        #: busy in a long non-step phase that only emits B now and E
        #: much later.  The mono bounds the suppression: a B whose E
        #: never arrives (crashed writer, dropped batch) must not
        #: disarm hang detection forever.
        self.open_spans: Dict[Tuple[int, str], Tuple[int, float]] = {}
        self.rss_mb = 0.0
        self.cpu_percent = 0.0
        #: live attribution (newest step_profile span from this
        #: node): per-category device-time shares + achieved MFU —
        #: what turns "node 3 is slow" into "node 3 is 40% copy"
        self.mfu = 0.0
        self.tflops = 0.0
        self.device_share: Dict[str, float] = {}
        self.profile_wall = 0.0


class HealthEngine:
    """Streaming per-node/per-phase derivations over the live job.

    Fed by the master's report dispatch: ``observe_events`` taps the
    ``TimelineAggregator`` (every ``TimelineEventsReport`` batch, so
    the PR-5 ``BatchedReport`` path feeds it for free),
    ``observe_heartbeat`` / ``observe_step`` / ``observe_fault`` /
    ``observe_resource`` tap the corresponding report messages in the
    servicer.  All methods are thread-safe and O(batch) — the report
    RPC path pays a dict update, never a sweep; the sweeps happen in
    ``snapshot()`` / the throttled gauge refresh.
    """

    #: EWMA smoothing for step time/rate (per new step span)
    EWMA_ALPHA = 0.3
    #: a node must complete this many steps before its EWMA can brand
    #: it a straggler — one cold first step is not a verdict
    MIN_STEPS_FOR_STRAGGLER = 3
    #: gauge refresh throttle (the sweep is O(nodes))
    GAUGE_REFRESH_S = 5.0
    #: a heartbeat older than this no longer proves the node alive
    #: (the job manager's dead-node monitor owns that case)
    HEARTBEAT_FRESH_S = 90.0

    def __init__(
        self,
        job: str = "",
        registry=None,
        straggler_ratio: Optional[float] = None,
        hang_watchdog_s: Optional[float] = None,
        window_s: Optional[float] = None,
    ):
        self._job = job or os.getenv("DLROVER_TPU_JOB_NAME", "default")
        self._registry = registry
        self.straggler_ratio = (
            straggler_ratio
            if straggler_ratio is not None
            else env_float(STRAGGLER_RATIO_ENV, 1.5)
        )
        self.hang_watchdog_s = (
            hang_watchdog_s
            if hang_watchdog_s is not None
            else env_float(HANG_WATCHDOG_ENV, 60.0)
        )
        self.window_s = (
            window_s
            if window_s is not None
            else env_float(HEALTH_WINDOW_ENV, 600.0)
        )
        self._nodes: Dict[int, _NodeState] = {}
        self._lock = threading.Lock()
        self._last_gauge_refresh = 0.0
        #: monotonic instant the engine started observing — a node is
        #: only hang-eligible after it produced at least one event
        self._t0 = time.monotonic()

    @property
    def job(self) -> str:
        return self._job

    # ----------------------------------------------------------- ingest
    def _state(self, node: int) -> _NodeState:
        state = self._nodes.get(node)
        if state is None:
            state = self._nodes[node] = _NodeState(node)
        return state

    def observe_events(self, node_id: int, events: List[dict]):
        """Tap for one node's timeline batch (call with the SAME
        accepted list the ``TimelineAggregator`` merged)."""
        now_mono = time.monotonic()
        with self._lock:
            for e in events:
                if not isinstance(e, dict):
                    continue
                node = int(e.get("node", node_id) or 0)
                state = self._state(node)
                wall = float(e.get("wall", 0.0) or 0.0)
                if wall > state.last_event_wall:
                    state.last_event_wall = wall
                state.last_event_seen = now_mono
                inc = int(e.get("inc", 0) or 0)
                if inc > state.incarnation:
                    state.incarnation = inc
                    # the restart replaced this node's processes: any
                    # B the dead incarnation never closed must not
                    # keep suppressing the watchdog
                    state.open_spans.clear()
                name = e.get("name", "")
                ph = e.get("ph", "")
                if ph == "B":
                    key = (int(e.get("pid", 0) or 0), name)
                    count, _opened = state.open_spans.get(
                        key, (0, now_mono)
                    )
                    state.open_spans[key] = (count + 1, now_mono)
                elif ph == "E":
                    key = (int(e.get("pid", 0) or 0), name)
                    count, opened = state.open_spans.get(
                        key, (0, now_mono)
                    )
                    if count > 1:
                        state.open_spans[key] = (count - 1, opened)
                    else:
                        state.open_spans.pop(key, None)
                if name == "step":
                    self._observe_step_span(state, e, wall)
                elif name == "data_stall":
                    self._observe_stall_span(state, e, wall)
                elif name == "step_profile":
                    self._observe_profile_span(state, e, wall)
                elif name == "restart" and ph in ("B", "X"):
                    state.restarts += 1
                elif name == "fault_injected" and ph == "i":
                    state.faults += 1
        self._maybe_refresh_gauges()

    def _observe_step_span(self, state: _NodeState, e: dict, wall: float):
        """One ``step`` span: the X record carries ``dur``; B/E pairs
        are folded at the E (ends are what mark progress)."""
        ph = e.get("ph")
        dur = e.get("dur")
        if ph == "X" and dur is not None:
            dur = max(float(dur), 0.0)
            end = wall + dur
        elif ph == "E":
            dur = None
            end = wall
        else:
            return  # a B alone is not a completed step
        state.steps_seen += 1
        state.step_walls.append(end)
        if end > state.last_step_wall:
            state.last_step_wall = end
        labels = e.get("labels") or {}
        try:
            step = int(labels.get("step", -1))
        except (TypeError, ValueError):
            step = -1
        if step > state.last_step:
            state.last_step = step
        if dur is not None and dur > 0:
            a = self.EWMA_ALPHA
            if state.step_time_ewma <= 0:
                state.step_time_ewma = dur
            else:
                state.step_time_ewma = (
                    a * dur + (1 - a) * state.step_time_ewma
                )
            rate = 1.0 / dur
            if state.step_rate_ewma <= 0:
                state.step_rate_ewma = rate
            else:
                state.step_rate_ewma = (
                    a * rate + (1 - a) * state.step_rate_ewma
                )

    def _observe_stall_span(self, state: _NodeState, e: dict, wall: float):
        if e.get("ph") != "X" or e.get("dur") is None:
            return  # stalls are emitted as X records (data/prefetch.py)
        dur = max(float(e["dur"]), 0.0)
        stage = str((e.get("labels") or {}).get("stage", "") or "?")
        window = state.stall_windows.setdefault(
            stage, deque(maxlen=1024)
        )
        window.append((wall + dur, dur))

    def _observe_profile_span(
        self, state: _NodeState, e: dict, wall: float
    ):
        """One ``step_profile`` span (the live attribution profiler's
        continuous leg): newest-wins per-category shares + MFU for
        this node."""
        if e.get("ph") != "X":
            return  # emitted as X records (attribution.py)
        if wall < state.profile_wall:
            return  # an older batch arriving late must not regress
        labels = e.get("labels") or {}
        share = {}
        for key, value in labels.items():
            if not str(key).startswith("share_"):
                continue
            try:
                share[str(key)[len("share_"):]] = float(value)
            except (TypeError, ValueError):
                continue
        if not share:
            return
        state.device_share = share
        state.profile_wall = wall
        try:
            state.mfu = float(labels.get("mfu", 0.0) or 0.0)
        except (TypeError, ValueError):
            state.mfu = 0.0
        try:
            state.tflops = float(labels.get("tflops", 0.0) or 0.0)
        except (TypeError, ValueError):
            state.tflops = 0.0

    def observe_heartbeat(self, node_id: int, timestamp: float):
        """Agent heartbeat tap.  Freshness is judged on the master's
        monotonic clock at ARRIVAL, not the agent's ``timestamp`` —
        a skewed agent clock must not fake liveness."""
        del timestamp
        with self._lock:
            state = self._state(int(node_id))
            state.last_heartbeat = max(
                state.last_heartbeat, time.monotonic()
            )

    def observe_step(self, node_id: int, step: int, timestamp: float):
        """``GlobalStep`` report tap — progress evidence even from
        jobs that never emit timeline spans."""
        with self._lock:
            state = self._state(int(node_id))
            if step > state.last_step:
                state.last_step = step
            if timestamp > state.last_step_wall:
                state.last_step_wall = timestamp
            state.last_event_seen = max(
                state.last_event_seen, time.monotonic()
            )

    def observe_fault(self, node_id: int, kind: str = ""):
        del kind  # counted, not classified (the error monitor does that)
        with self._lock:
            self._state(int(node_id)).faults += 1

    def observe_resource(
        self, node_id: int, cpu_percent: float, memory_mb: float
    ):
        with self._lock:
            state = self._state(int(node_id))
            state.cpu_percent = float(cpu_percent)
            state.rss_mb = float(memory_mb)

    # ------------------------------------------------------ derivations
    def _evict_locked(self, state: _NodeState, now_wall: float):
        horizon = now_wall - self.window_s
        for window in state.stall_windows.values():
            while window and window[0][0] < horizon:
                window.popleft()
        while state.step_walls and state.step_walls[0] < horizon:
            state.step_walls.popleft()

    def _median_step_time_locked(self) -> float:
        ewmas = sorted(
            s.step_time_ewma
            for s in self._nodes.values()
            if s.step_time_ewma > 0
            and s.steps_seen >= self.MIN_STEPS_FOR_STRAGGLER
        )
        if not ewmas:
            return 0.0
        return ewmas[len(ewmas) // 2]

    #: open-span suppression expires after this many watchdog windows
    #: — a B whose E never arrives (crashed writer, batch lost to a
    #: master outage or a file rotation) must not disarm the watchdog
    #: for the rest of the job
    OPEN_SPAN_GRACE_WINDOWS = 10.0

    def _hang_suspect_locked(
        self, state: _NodeState, now_mono: float
    ) -> bool:
        """The span-heartbeat watchdog verdict for one node."""
        if state.last_event_seen <= 0:
            return False  # never produced an event: not armed yet
        if now_mono - state.last_event_seen < self.hang_watchdog_s:
            return False
        # attributably busy: an open non-step span (compile, restore,
        # rendezvous...) emitted its B and will emit E when done —
        # the ledger charges that time, the watchdog stays quiet.
        # The suppression is BOUNDED (and stale entries purged): an
        # orphaned B only buys its phase a grace window, not immunity.
        grace = self.hang_watchdog_s * self.OPEN_SPAN_GRACE_WINDOWS
        for key in [
            k
            for k, (_n, opened) in state.open_spans.items()
            if now_mono - opened > grace
        ]:
            state.open_spans.pop(key)
        if any(name != "step" for _pid, name in state.open_spans):
            return False
        # dead vs hung: no fresh heartbeat means the agent is gone too
        # (the job manager's heartbeat monitor owns dead nodes); hung
        # means the agent answers while the workers emit nothing
        if state.last_heartbeat > 0 and (
            now_mono - state.last_heartbeat > self.HEARTBEAT_FRESH_S
        ):
            return False
        return True

    def _stall_share_locked(
        self, state: _NodeState, now_wall: float
    ) -> Dict[str, float]:
        """Windowed stall share by stage (caller holds the lock and
        has evicted): stalled seconds over the stretch of the window
        the oldest retained stall actually covers — ONE definition,
        consumed by both the snapshot and the DataStallOperator."""
        shares = {}
        for stage, window in state.stall_windows.items():
            if not window:
                continue
            span = max(
                now_wall - max(window[0][0] - window[0][1],
                               now_wall - self.window_s),
                1e-9,
            )
            shares[stage] = min(
                sum(d for _t, d in window) / span, 1.0
            )
        return shares

    def node_snapshot_locked(
        self, state: _NodeState, median: float, now_wall: float,
        now_mono: float,
    ) -> dict:
        self._evict_locked(state, now_wall)
        stall_share = {
            stage: round(share, 4)
            for stage, share in self._stall_share_locked(
                state, now_wall
            ).items()
        }
        score = 0.0
        if (
            median > 0
            and state.step_time_ewma > 0
            and state.steps_seen >= self.MIN_STEPS_FOR_STRAGGLER
        ):
            score = state.step_time_ewma / median
        straggler = bool(score >= self.straggler_ratio)
        hung = self._hang_suspect_locked(state, now_mono)
        stalled = any(
            share >= 0.5 for share in stall_share.values()
        )
        if hung:
            status, health = STATUS_HUNG, HEALTH_HUNG
        elif straggler:
            status, health = STATUS_STRAGGLER, HEALTH_STRAGGLER
        elif stalled:
            status, health = STATUS_STALLED, HEALTH_STALLED
        else:
            status, health = STATUS_OK, HEALTH_OK
        # windowed rate: completed steps per second over the window
        rate = 0.0
        if len(state.step_walls) >= 2:
            span = state.step_walls[-1] - state.step_walls[0]
            if span > 0:
                rate = (len(state.step_walls) - 1) / span
        snap = {
            "node": state.node,
            "status": status,
            "health": health,
            "step": state.last_step,
            "steps_seen": state.steps_seen,
            "step_time_s": round(state.step_time_ewma, 6),
            "step_rate": round(rate or state.step_rate_ewma, 6),
            "straggler_score": round(score, 4),
            "straggler": straggler,
            "hung": hung,
            "stall_share": stall_share,
            "restarts": state.restarts,
            "faults": state.faults,
            "inc": state.incarnation,
            "cpu_percent": state.cpu_percent,
            "rss_mb": state.rss_mb,
            "last_event_age_s": round(
                now_mono - state.last_event_seen, 3
            ) if state.last_event_seen > 0 else None,
            "last_step_wall": state.last_step_wall or None,
        }
        # live attribution fields only once a step_profile span
        # arrived: with the profiler off the snapshot is EXACTLY the
        # pre-profiling one (pinned by tests)
        if state.device_share:
            from dlrover_tpu.observability.attribution import (
                dominant_category,
            )

            dom = dominant_category(state.device_share)
            snap["mfu"] = round(state.mfu, 4)
            snap["tflops"] = round(state.tflops, 3)
            snap["device_share"] = dict(state.device_share)
            snap["dominant"] = (
                {"category": dom[0], "share": dom[1]}
                if dom
                else None
            )
        return snap

    def snapshot(self) -> dict:
        """The full derived state — what ``JobStatusRequest``,
        ``/status`` and ``scripts/top.py`` serve."""
        now_wall = time.time()
        now_mono = time.monotonic()
        with self._lock:
            median = self._median_step_time_locked()
            nodes = [
                self.node_snapshot_locked(
                    state, median, now_wall, now_mono
                )
                for state in sorted(
                    self._nodes.values(), key=lambda s: s.node
                )
            ]
        return {
            "job": self._job,
            "t": now_wall,
            "median_step_time_s": round(median, 6),
            "straggler_ratio": self.straggler_ratio,
            "hang_watchdog_s": self.hang_watchdog_s,
            "window_s": self.window_s,
            "nodes": nodes,
            "stragglers": [
                n["node"] for n in nodes if n["straggler"]
            ],
            "hangs": [n["node"] for n in nodes if n["hung"]],
        }

    # ------------------------------------------------- operator queries
    def stragglers(self) -> List[Tuple[int, float]]:
        """``[(node, score)]`` for nodes past the ratio (the
        ``StragglerOperator``'s input)."""
        with self._lock:
            median = self._median_step_time_locked()
            if median <= 0:
                return []
            out = []
            for state in self._nodes.values():
                if (
                    state.step_time_ewma > 0
                    and state.steps_seen
                    >= self.MIN_STEPS_FOR_STRAGGLER
                ):
                    score = state.step_time_ewma / median
                    if score >= self.straggler_ratio:
                        out.append((state.node, round(score, 4)))
            return sorted(out, key=lambda t: -t[1])

    def hang_suspects(self) -> List[Tuple[int, float]]:
        """``[(node, silence_s)]`` flagged by the span-heartbeat
        watchdog (the ``HangWatchdogOperator``'s input)."""
        now_mono = time.monotonic()
        with self._lock:
            return [
                (
                    state.node,
                    round(now_mono - state.last_event_seen, 3),
                )
                for state in self._nodes.values()
                if self._hang_suspect_locked(state, now_mono)
            ]

    def median_step_time(self) -> float:
        """The across-node median step-time EWMA (0 until enough
        nodes have completed ``MIN_STEPS_FOR_STRAGGLER`` steps) — the
        Brain's per-world scaling-history sample."""
        with self._lock:
            return self._median_step_time_locked()

    def attribution(self) -> Dict[int, Tuple[str, float]]:
        """Per-node dominant device-time category from the newest
        ``step_profile`` span: ``{node: (category, share)}``.  The
        straggler/data-stall operators cite this so a conclusion says
        WHY — a straggler at 40% copy share is an offload problem,
        not a bad host.  Empty until the continuous profiling leg is
        on (``DLROVER_TPU_PROFILE_EVERY_N_STEPS`` > 0)."""
        from dlrover_tpu.observability.attribution import (
            dominant_category,
        )

        with self._lock:
            out: Dict[int, Tuple[str, float]] = {}
            for state in self._nodes.values():
                dom = dominant_category(state.device_share)
                if dom is None:
                    continue  # no profile yet / all-zero CPU shares
                out[state.node] = (dom[0], round(dom[1], 4))
            return out

    def stall_shares(self) -> Dict[int, Dict[str, float]]:
        """Per-node windowed data-stall share by stage (the
        ``DataStallOperator``'s input)."""
        now_wall = time.time()
        out: Dict[int, Dict[str, float]] = {}
        with self._lock:
            for state in self._nodes.values():
                self._evict_locked(state, now_wall)
                shares = self._stall_share_locked(state, now_wall)
                if shares:
                    out[state.node] = shares
        return out

    # ------------------------------------------------------------ gauges
    def _maybe_refresh_gauges(self):
        if self._registry is None:
            return
        now = time.monotonic()
        if now - self._last_gauge_refresh < self.GAUGE_REFRESH_S:
            return
        self._last_gauge_refresh = now
        self.refresh_gauges()

    def refresh_gauges(self):
        """Export the per-node health + straggler-score gauges (also
        callable directly — the status server refreshes before
        rendering ``/metrics``)."""
        if self._registry is None:
            return
        try:
            snap = self.snapshot()
            for n in snap["nodes"]:
                labels = {"node": n["node"]}
                self._registry.set_gauge(
                    "dlrover_tpu_node_health",
                    n["health"],
                    labels=labels,
                )
                self._registry.set_gauge(
                    "dlrover_tpu_straggler_score",
                    n["straggler_score"],
                    labels=labels,
                )
                # attribution gauges only once a step_profile span
                # arrived — a profiler-off job exports EXACTLY the
                # pre-profiling series set (pinned by tests)
                if n.get("device_share"):
                    self._registry.set_gauge(
                        "dlrover_tpu_node_mfu",
                        n["mfu"],
                        labels=labels,
                    )
                    for cat, share in n["device_share"].items():
                        self._registry.set_gauge(
                            "dlrover_tpu_device_share",
                            share,
                            labels={
                                "node": n["node"],
                                "category": cat,
                            },
                        )
        except Exception as e:  # noqa: BLE001 - gauges must not break reports
            logger.warning("health gauge refresh failed: %s", e)

    # ------------------------------------------------------------- misc
    def to_json(self) -> str:
        return json.dumps(self.snapshot(), separators=(",", ":"))


#: MasterHealth thresholds (see class docstring)
MASTER_P99_ENV = "DLROVER_TPU_MASTER_OVERLOAD_P99_S"
MASTER_QUEUE_FRAC_ENV = "DLROVER_TPU_MASTER_OVERLOAD_QUEUE_FRAC"
MASTER_LAG_ROWS_ENV = "DLROVER_TPU_MASTER_OVERLOAD_LAG_ROWS"
MASTER_OCCUPANCY_ENV = "DLROVER_TPU_MASTER_OVERLOAD_OCCUPANCY"
MASTER_REJECTS_ENV = "DLROVER_TPU_MASTER_OVERLOAD_REJECTS"
MASTER_SUSTAIN_ENV = "DLROVER_TPU_MASTER_OVERLOAD_SUSTAIN"
MASTER_COOLDOWN_ENV = "DLROVER_TPU_MASTER_OVERLOAD_COOLDOWN_S"


class MasterHealth:
    """The master's own health deriver — the :class:`HealthEngine`
    watches the fleet, this watches the component every fleet signal
    flows through.  Each :meth:`evaluate` call (the DiagnosisManager's
    loop cadence is the derivation interval) reads the live
    self-telemetry (``observability/self_telemetry.py``) and keeps a
    per-reason STREAK; a breach sustained for ``sustain`` consecutive
    evaluations becomes one overload verdict:

    - ``rpc_p99``        — windowed p99 latency of the FAST RPC
      kinds (parked long-polls excluded — their latency is the wait
      window they asked for; ``self_telemetry.WAIT_KINDS``) past
      ``DLROVER_TPU_MASTER_OVERLOAD_P99_S`` (default 0.5 s: a healthy
      dispatch is single-digit ms, half a second means the master is
      the job's critical path);
    - ``queue_depth``    — write-behind queue past
      ``..._QUEUE_FRAC`` (0.8) of its bound: the next burst
      backpressures the report RPC path;
    - ``journal_lag``    — rows enqueued minus rows flushed past
      ``..._LAG_ROWS`` (5000): a crash now loses that much claimed
      durability;
    - ``pool_saturated`` — busy workers (parked long-polls included)
      past ``..._OCCUPANCY`` (0.9) of the pool: mutation RPCs are
      about to queue behind parked waiters;
    - ``parked_rejects`` — at least ``..._REJECTS`` (1) long-polls
      per interval degraded to immediate answers because every
      parked-wait slot was held: the pool is too small for this
      fleet's idle waits (raise ``DLROVER_TPU_MASTER_WORKERS``).
      Occupancy is an instantaneous sample and can flap; the
      rejection COUNTER only moves when the cap was genuinely hit,
      so this is the robust shrunken-pool signature.

    Firing emits a ``master_overload`` instant (labels lint-enforced)
    and starts a per-reason cooldown (``..._COOLDOWN_S``, 300 s); the
    ``MasterOverloadOperator`` in ``master/diagnosis.py`` turns the
    same verdicts into diagnosis conclusions, so the Brain's signal
    chain covers its own substrate.
    """

    def __init__(
        self,
        telemetry,
        p99_s: Optional[float] = None,
        queue_frac: Optional[float] = None,
        lag_rows: Optional[float] = None,
        occupancy: Optional[float] = None,
        sustain: Optional[int] = None,
        cooldown_s: Optional[float] = None,
    ):
        self._telemetry = telemetry
        self.p99_s = (
            p99_s if p99_s is not None
            else env_float(MASTER_P99_ENV, 0.5)
        )
        self.queue_frac = (
            queue_frac if queue_frac is not None
            else env_float(MASTER_QUEUE_FRAC_ENV, 0.8)
        )
        self.lag_rows = (
            lag_rows if lag_rows is not None
            else env_float(MASTER_LAG_ROWS_ENV, 5000.0)
        )
        self.occupancy = (
            occupancy if occupancy is not None
            else env_float(MASTER_OCCUPANCY_ENV, 0.9)
        )
        self.rejects = env_float(MASTER_REJECTS_ENV, 1.0)
        self.sustain = max(
            int(
                sustain if sustain is not None
                else env_float(MASTER_SUSTAIN_ENV, 2.0)
            ),
            1,
        )
        self.cooldown_s = (
            cooldown_s if cooldown_s is not None
            else env_float(MASTER_COOLDOWN_ENV, 300.0)
        )
        self._lock = threading.Lock()
        self._streaks: Dict[str, int] = {}
        self._last_fired: Dict[str, float] = {}
        self._last_verdicts: List[dict] = []
        #: rejected-waits counter at the previous evaluate — the
        #: per-interval delta is the parked_rejects signal
        self._last_rejected = 0

    def _breaches(self) -> List[Tuple[str, float, float]]:
        """Current ``(reason, value, threshold)`` breaches from one
        telemetry read."""
        tel = self._telemetry
        out: List[Tuple[str, float, float]] = []
        p99 = tel.window_p99()
        if p99 >= self.p99_s:
            out.append(("rpc_p99", p99, self.p99_s))
        ds = tel.datastore_health()
        if ds:
            cap = max(float(ds.get("queue_cap", 0) or 0), 1.0)
            depth = float(ds.get("queue_depth", 0) or 0)
            if depth / cap >= self.queue_frac:
                out.append(
                    ("queue_depth", depth, self.queue_frac * cap)
                )
            lag = float(ds.get("lag_rows", 0) or 0)
            if lag >= self.lag_rows:
                out.append(("journal_lag", lag, self.lag_rows))
        occ = tel.occupancy()
        if occ >= self.occupancy:
            out.append(("pool_saturated", occ, self.occupancy))
        rejected = getattr(tel, "rejected_waits", 0)
        delta = rejected - self._last_rejected
        self._last_rejected = rejected
        if delta >= self.rejects:
            out.append(("parked_rejects", float(delta), self.rejects))
        return out

    def evaluate(self) -> List[dict]:
        """One derivation interval: update streaks, fire sustained
        breaches past their cooldown.  Returns the verdicts fired
        THIS call (each also emitted as a ``master_overload``
        instant)."""
        now = time.monotonic()
        breaches = self._breaches()
        fired: List[dict] = []
        with self._lock:
            current = {r for r, _v, _t in breaches}
            for reason in list(self._streaks):
                if reason not in current:
                    self._streaks.pop(reason)
            for reason, value, threshold in breaches:
                streak = self._streaks.get(reason, 0) + 1
                self._streaks[reason] = streak
                if streak < self.sustain:
                    continue
                last = self._last_fired.get(reason, -1e18)
                if now - last < self.cooldown_s:
                    continue
                self._last_fired[reason] = now
                # acting consumes the streak (like the Brain's rules)
                self._streaks[reason] = 0
                fired.append(
                    {
                        "reason": reason,
                        "value": round(float(value), 6),
                        "threshold": round(float(threshold), 6),
                        "streak": streak,
                        "t": time.time(),
                    }
                )
            if fired:
                self._last_verdicts = fired
        for v in fired:
            try:
                from dlrover_tpu.observability.events import (
                    get_event_logger,
                )

                get_event_logger().instant(
                    "master_overload",
                    reason=v["reason"],
                    value=v["value"],
                    threshold=v["threshold"],
                    streak=v["streak"],
                )
            except Exception as e:  # noqa: BLE001 - telemetry only
                logger.warning(
                    "master_overload instant emit failed: %s", e
                )
        return fired

    def status(self) -> dict:
        """Streaks + newest verdicts for the ``master`` status
        section."""
        with self._lock:
            return {
                "streaks": dict(self._streaks),
                "last_verdicts": list(self._last_verdicts),
                "sustain": self.sustain,
                "cooldown_s": self.cooldown_s,
            }


# --------------------------------------------------------------------------
# serving-plane health (ISSUE 16): the replica observatory
# --------------------------------------------------------------------------

SERVING_SLO_RATIO_ENV = "DLROVER_TPU_SERVING_SLO_RATIO"
SERVING_DEAD_AIR_ENV = "DLROVER_TPU_SERVING_DEAD_AIR_S"
SERVING_KV_PRESSURE_ENV = "DLROVER_TPU_SERVING_KV_PRESSURE"
SERVING_PREEMPT_RATE_ENV = "DLROVER_TPU_SERVING_PREEMPT_RATE"
SERVING_SUSTAIN_ENV = "DLROVER_TPU_SERVING_SUSTAIN"
SERVING_COOLDOWN_ENV = "DLROVER_TPU_SERVING_COOLDOWN_S"
SERVING_DERIVE_ENV = "DLROVER_TPU_SERVING_DERIVE_S"

#: Per-replica SLO samples kept for the rolling p99 (one sample per
#: completed request); enough for a stable tail, small enough that a
#: recovered replica sheds its bad history within ~2 windows.
SERVING_SAMPLE_WINDOW = 128
#: A p99 over fewer completions than this is noise, not a signal.
MIN_SLO_SAMPLES = 3


def _tail_q(samples, q: float) -> float:
    """Nearest-rank quantile of a small sample deque (0.0 when
    empty)."""
    if not samples:
        return 0.0
    ordered = sorted(samples)
    return ordered[min(len(ordered) - 1, int(q * (len(ordered) - 1) + 0.5))]


class _ServingReplicaState:
    """Per-replica derivation state (mirrors ``_NodeState``)."""

    __slots__ = (
        "idx",
        "ttft",
        "tbt",
        "e2e",
        "last_progress_t",
        "last_preempts",
        "preempt_delta",
        "kv_utilization",
        "prefix_hit_rate",
        "outstanding",
        "alive",
        "drained",
        "verdict",
        "why",
        "slo_score",
        "streaks",
        "role",
    )

    def __init__(self, idx: int, now: float):
        self.idx = idx
        self.ttft: Deque[float] = deque(maxlen=SERVING_SAMPLE_WINDOW)
        self.tbt: Deque[float] = deque(maxlen=SERVING_SAMPLE_WINDOW)
        self.e2e: Deque[float] = deque(maxlen=SERVING_SAMPLE_WINDOW)
        # seeded at first sight so a freshly spawned replica gets a
        # full dead-air grace window before the watchdog may name it
        self.last_progress_t = now
        self.last_preempts = 0
        self.preempt_delta = 0
        self.kv_utilization = 0.0
        self.prefix_hit_rate = 0.0
        self.outstanding = 0
        self.alive = True
        self.drained = False
        self.verdict = "ok"
        self.why = "ok"
        self.slo_score = 0.0
        self.streaks: Dict[str, int] = {}
        # fleet role (ISSUE 17): a designated prefill worker is
        # judged against PREFILL-fleet medians — it completes no
        # requests itself (no TTFT/TBT series) and must never read
        # as a decode straggler
        self.role = "decode"


class ServingHealthEngine:
    """Streaming per-replica health derivation for the serving plane —
    the :class:`HealthEngine` pattern (per-node state + fleet-median
    straggler scoring + a silence watchdog) crossed with
    :class:`MasterHealth`'s streak/sustain/cooldown verdict machinery,
    fed by the dispatcher instead of an RPC stream:

    - ``note_result`` per completed request (TTFT / request-level TBT
      p99 / e2e / queue-wait off the response ring);
    - ``note_stats`` per replica STATS window (KV pressure, cumulative
      preemptions, prefix hit rate; a window with tokens flowing
      refreshes the progress clock);
    - ``evaluate(fleet)`` once per derivation interval
      (``DLROVER_TPU_SERVING_DERIVE_S``, default 1 s; internally
      throttled so the dispatcher may call it every pump) with the
      dispatcher's live view (alive/drained/outstanding per replica).

    Derivations per replica:

    - **slo_straggler** — rolling TTFT or TBT p99 at least
      ``DLROVER_TPU_SERVING_SLO_RATIO`` (2.0) times the fleet median
      of the same quantile (needs >= 2 replicas with
      ``MIN_SLO_SAMPLES`` completions — a fleet of one has no peers
      to be slower than);
    - **dead_air** — outstanding requests, a live worker process, and
      no token progress (no completion, no tokens-flowing STATS
      window) for ``DLROVER_TPU_SERVING_DEAD_AIR_S`` (5 s) — the
      wedged-mid-decode signature a throughput gauge can't show;
    - **kv_pressure** — pool utilization at or past
      ``DLROVER_TPU_SERVING_KV_PRESSURE`` (0.95);
    - **preempt_storm** — at least ``DLROVER_TPU_SERVING_PREEMPT_RATE``
      (3) NEW preemptions within one derivation interval.

    A reason sustained ``DLROVER_TPU_SERVING_SUSTAIN`` (2) consecutive
    derivations becomes the replica's verdict (priority: dead_air >
    slo_straggler > kv_pressure > preempt_storm), emits one
    ``slo_breach`` instant per reason under a per-(replica, reason)
    cooldown (``DLROVER_TPU_SERVING_COOLDOWN_S``, 30 s), and every
    verdict CHANGE emits a ``serving_health`` instant — the trace
    shows the observatory naming the replica next to the spans that
    convicted it.  Fleet-level: median TTFT/TBT p99 and the weighted
    prefix hit rate."""

    _VERDICT_GAUGE = {
        "ok": 1.0,
        "preempt_storm": 0.7,
        "kv_pressure": 0.6,
        "slo_straggler": 0.4,
        "dead_air": 0.1,
    }

    def __init__(
        self,
        slo_ratio: Optional[float] = None,
        dead_air_s: Optional[float] = None,
        kv_pressure: Optional[float] = None,
        preempt_rate: Optional[float] = None,
        sustain: Optional[int] = None,
        cooldown_s: Optional[float] = None,
        interval_s: Optional[float] = None,
    ):
        self.slo_ratio = (
            slo_ratio if slo_ratio is not None
            else env_float(SERVING_SLO_RATIO_ENV, 2.0)
        )
        self.dead_air_s = (
            dead_air_s if dead_air_s is not None
            else env_float(SERVING_DEAD_AIR_ENV, 5.0)
        )
        self.kv_pressure = (
            kv_pressure if kv_pressure is not None
            else env_float(SERVING_KV_PRESSURE_ENV, 0.95)
        )
        self.preempt_rate = (
            preempt_rate if preempt_rate is not None
            else env_float(SERVING_PREEMPT_RATE_ENV, 3.0)
        )
        self.sustain = max(
            int(
                sustain if sustain is not None
                else env_float(SERVING_SUSTAIN_ENV, 2.0)
            ),
            1,
        )
        self.cooldown_s = (
            cooldown_s if cooldown_s is not None
            else env_float(SERVING_COOLDOWN_ENV, 30.0)
        )
        self.interval_s = max(
            interval_s if interval_s is not None
            else env_float(SERVING_DERIVE_ENV, 1.0),
            0.05,
        )
        self._lock = threading.Lock()
        self._replicas: Dict[int, _ServingReplicaState] = {}
        self._last_eval = 0.0
        self._last_fired: Dict[Tuple[int, str], float] = {}
        self._fleet: Dict[str, float] = {}
        self.derivations = 0

    def _state(self, idx: int) -> _ServingReplicaState:
        st = self._replicas.get(idx)
        if st is None:
            st = self._replicas[idx] = _ServingReplicaState(
                idx, time.monotonic()
            )
        return st

    # ------------------------------------------------------- ingest
    def note_result(self, idx: int, ttft_s: float = 0.0,
                    tbt_p99_s: float = 0.0, e2e_s: float = 0.0,
                    queue_wait_s: float = 0.0):
        """One completed request from replica ``idx`` (dispatcher's
        RESULT path)."""
        with self._lock:
            st = self._state(idx)
            st.ttft.append(float(ttft_s))
            st.tbt.append(float(tbt_p99_s))
            st.e2e.append(float(e2e_s))
            st.last_progress_t = time.monotonic()

    def note_ship(self, idx: int):
        """One shipped-KV manifest from prefill worker ``idx``
        (dispatcher's SHIP path) — a ship IS the prefill worker's
        completion, so it refreshes the progress clock the same way a
        RESULT refreshes a decode replica's (without it a busy
        prefill worker would read as dead air: it never answers
        RESULT)."""
        with self._lock:
            self._state(idx).last_progress_t = time.monotonic()

    def note_stats(self, idx: int, stats: Dict):
        """One replica STATS window.  Tokens flowing refresh the
        progress clock; a zero-throughput window with work outstanding
        deliberately does NOT — that silence is the dead-air signal."""
        with self._lock:
            st = self._state(idx)
            now = time.monotonic()
            if float(stats.get("tokens_per_s", 0.0) or 0.0) > 0.0:
                st.last_progress_t = now
            st.kv_utilization = float(
                stats.get("kv_utilization", 0.0) or 0.0
            )
            st.prefix_hit_rate = float(
                stats.get("prefix_hit_rate", 0.0) or 0.0
            )
            preempts = int(stats.get("preemptions", 0) or 0)
            st.preempt_delta += max(preempts - st.last_preempts, 0)
            st.last_preempts = preempts

    # ----------------------------------------------------- derivation
    def _breaches(self, st: _ServingReplicaState, now: float,
                  med_ttft: float, med_tbt: float, peers: int):
        """Current (reason, value, threshold) breaches for one LIVE
        replica."""
        out: List[Tuple[str, float, float]] = []
        if (
            st.outstanding > 0
            and now - st.last_progress_t >= self.dead_air_s
        ):
            out.append(
                ("dead_air", now - st.last_progress_t,
                 self.dead_air_s)
            )
        score = 0.0
        if peers >= 2 and len(st.ttft) >= MIN_SLO_SAMPLES:
            if med_ttft > 0:
                score = _tail_q(st.ttft, 0.99) / med_ttft
            if med_tbt > 0:
                score = max(
                    score, _tail_q(st.tbt, 0.99) / med_tbt
                )
        st.slo_score = round(score, 3)
        if score >= self.slo_ratio:
            out.append(("slo_straggler", score, self.slo_ratio))
        if st.kv_utilization >= self.kv_pressure:
            out.append(
                ("kv_pressure", st.kv_utilization, self.kv_pressure)
            )
        if st.preempt_delta >= self.preempt_rate:
            out.append(
                ("preempt_storm", float(st.preempt_delta),
                 self.preempt_rate)
            )
        return out

    _PRIORITY = ("dead_air", "slo_straggler", "kv_pressure",
                 "preempt_storm")

    def evaluate(self, fleet: List[Dict]) -> List[dict]:
        """One derivation pass over the dispatcher's live fleet view
        (``[{idx, alive, drained, outstanding, ...stats}]``);
        internally throttled to the derivation interval, so callers
        may invoke it every dispatch pump.  Returns the ``slo_breach``
        verdicts fired THIS pass."""
        now = time.monotonic()
        fired: List[dict] = []
        instants: List[Tuple[str, Dict]] = []
        with self._lock:
            if now - self._last_eval < self.interval_s:
                return []
            self._last_eval = now
            self.derivations += 1
            live = []
            for row in fleet:
                st = self._state(int(row["idx"]))
                st.alive = bool(row.get("alive", True))
                st.drained = bool(row.get("drained", False))
                st.outstanding = int(row.get("outstanding", 0))
                st.role = str(row.get("role", "decode")) or "decode"
                if st.alive and not st.drained:
                    live.append(st)
            # straggler medians are ROLE-SPLIT (ISSUE 17): a prefill
            # worker's peers are the other prefill workers — judging
            # it against decode medians would convict it on series it
            # cannot have (it never completes a request itself)
            role_meds: Dict[str, Tuple[float, float, int]] = {}
            for role in {st.role for st in live}:
                pool = [st for st in live if st.role == role]
                ttft_p99s = [
                    _tail_q(st.ttft, 0.99) for st in pool
                    if len(st.ttft) >= MIN_SLO_SAMPLES
                ]
                tbt_p99s = [
                    _tail_q(st.tbt, 0.99) for st in pool
                    if len(st.tbt) >= MIN_SLO_SAMPLES
                ]
                role_meds[role] = (
                    _tail_q(ttft_p99s, 0.5),
                    _tail_q(tbt_p99s, 0.5),
                    len(ttft_p99s),
                )
            med_ttft, med_tbt, peers = role_meds.get(
                "decode", (0.0, 0.0, 0)
            )
            hit_rates = [st.prefix_hit_rate for st in live]
            self._fleet = {
                "ttft_p99_median_s": round(med_ttft, 4),
                "tbt_p99_median_s": round(med_tbt, 4),
                "prefix_hit_rate": round(
                    sum(hit_rates) / len(hit_rates), 4
                ) if hit_rates else 0.0,
                "replicas_alive": len(live),
            }
            for st in self._replicas.values():
                prev_verdict = st.verdict
                if not st.alive or st.drained:
                    st.verdict = "drained" if st.drained else "dead"
                    st.why = st.verdict
                    st.streaks.clear()
                    st.preempt_delta = 0
                    if st.verdict != prev_verdict:
                        instants.append(
                            (
                                "serving_health",
                                {
                                    "replica": st.idx,
                                    "verdict": st.verdict,
                                    "reason": st.verdict,
                                    "role": st.role,
                                },
                            )
                        )
                    continue
                r_ttft, r_tbt, r_peers = role_meds.get(
                    st.role, (0.0, 0.0, 0)
                )
                breaches = self._breaches(
                    st, now, r_ttft, r_tbt, r_peers
                )
                st.preempt_delta = 0
                current = {r for r, _v, _t in breaches}
                for reason in list(st.streaks):
                    if reason not in current:
                        st.streaks.pop(reason)
                sustained: Dict[str, Tuple[float, float]] = {}
                for reason, value, threshold in breaches:
                    streak = st.streaks.get(reason, 0) + 1
                    st.streaks[reason] = streak
                    if streak < self.sustain:
                        continue
                    sustained[reason] = (value, threshold)
                    key = (st.idx, reason)
                    last = self._last_fired.get(key, -1e18)
                    if now - last < self.cooldown_s:
                        continue
                    self._last_fired[key] = now
                    verdict = {
                        "replica": st.idx,
                        "reason": reason,
                        "value": round(float(value), 4),
                        "threshold": round(float(threshold), 4),
                        "streak": streak,
                        "role": st.role,
                        "t": time.time(),
                    }
                    fired.append(verdict)
                    instants.append(("slo_breach", dict(verdict)))
                st.verdict = next(
                    (r for r in self._PRIORITY if r in sustained),
                    "ok",
                )
                if st.verdict == "ok":
                    st.why = "ok"
                    st.slo_score = round(st.slo_score, 3)
                else:
                    value, threshold = sustained[st.verdict]
                    st.why = (
                        f"{st.verdict} {value:.3g} vs {threshold:.3g}"
                    )
                if st.verdict != prev_verdict:
                    instants.append(
                        (
                            "serving_health",
                            {
                                "replica": st.idx,
                                "verdict": st.verdict,
                                "reason": (
                                    st.verdict
                                    if st.verdict != "ok"
                                    else "recovered"
                                ),
                                "role": st.role,
                            },
                        )
                    )
            gauge_rows = [
                (st.idx, st.role,
                 self._VERDICT_GAUGE.get(st.verdict, 0.0))
                for st in self._replicas.values()
                if st.alive and not st.drained
            ]
        for name, labels in instants:
            try:
                from dlrover_tpu.observability.events import (
                    get_event_logger,
                )

                # literal names so the schema lint can see them;
                # labels carry every required key (built above)
                if name == "slo_breach":
                    get_event_logger().instant("slo_breach", **labels)
                else:
                    get_event_logger().instant(
                        "serving_health", **labels
                    )
            except Exception as e:  # noqa: BLE001 - telemetry only
                logger.warning("%s instant emit failed: %s", name, e)
        try:
            from dlrover_tpu.observability.metrics import get_registry

            reg = get_registry()
            for idx, role, value in gauge_rows:
                # the role label rides along; per-replica retirement
                # still matches (retire_series is a subset match)
                reg.set_gauge(
                    "dlrover_tpu_serving_health",
                    value,
                    labels={"replica": str(idx), "role": role},
                )
        except Exception as e:  # noqa: BLE001 - telemetry only
            logger.warning("serving health gauge export failed: %s", e)
        return fired

    def reset(self):
        """Forget all derivation history — per-replica SLO windows,
        streaks, verdicts, breach cooldowns.  For the moment a fleet's
        past stops being representative: after warmup (compile-era
        TTFTs would otherwise sit in the p99 windows for ~128
        requests) or a redeploy."""
        with self._lock:
            self._replicas.clear()
            self._last_fired.clear()
            self._fleet = {}

    # -------------------------------------------------------- readers
    def snapshot(self) -> Dict:
        """The ``health`` section of the serving status: per-replica
        verdict + why + the numbers behind them, plus the fleet
        medians."""
        with self._lock:
            return {
                "replicas": [
                    {
                        "replica": st.idx,
                        "verdict": st.verdict,
                        "why": st.why,
                        "role": st.role,
                        "slo_score": st.slo_score,
                        "ttft_p99_s": round(
                            _tail_q(st.ttft, 0.99), 4
                        ),
                        "tbt_p99_s": round(_tail_q(st.tbt, 0.99), 4),
                        "e2e_p99_s": round(_tail_q(st.e2e, 0.99), 4),
                        "kv_utilization": round(
                            st.kv_utilization, 4
                        ),
                        "prefix_hit_rate": round(
                            st.prefix_hit_rate, 4
                        ),
                        "outstanding": st.outstanding,
                        "silent_s": round(
                            max(
                                time.monotonic()
                                - st.last_progress_t,
                                0.0,
                            ),
                            2,
                        ),
                        "streaks": dict(st.streaks),
                    }
                    for st in sorted(
                        self._replicas.values(),
                        key=lambda s: s.idx,
                    )
                ],
                "fleet": dict(self._fleet),
                "derivations": self.derivations,
                "interval_s": self.interval_s,
                "sustain": self.sustain,
            }
