"""Coworker data plane: remote CPU preprocessing for TPU trainers.

Reference parity: ``atorch/atorch/service/coworker_data_service.py:43``
(``CoworkerRpcServicer`` — CPU pods preprocess batches into a queue,
GPU pods pull them over gRPC), ``data/coworker_dataset.py:13``
(``CoworkerDataset`` round-robin client) and the DataInfoService
registration path.

TPU form: the accelerator host's cores are busy feeding the chips, so
preprocessing (tokenization, augmentation, decoding) runs on cheap CPU
pods.  Each coworker runs :class:`CoworkerServer` — a bounded queue
filled by a preprocessing thread, served over a one-request TCP
protocol — and registers its address in the master KV store; trainers
pull with :class:`CoworkerClient` round-robin and fail over when a
coworker dies.

Wire format: batches are pytrees of numpy arrays serialized with
``numpy.savez`` (flat keystr keys) — array-native, NO pickle on the
data path, so a compromised coworker cannot execute code in the
trainer.

Protocol (one request per connection, like the replica service):
  ``GET\n``  -> ``<8-byte big-endian len><npz bytes>``
  len 0       = data source cleanly exhausted
  len 2^64-1  = coworker preprocessing FAILED (clients fail over and
                raise if every coworker failed — a crashed pipeline
                must not masquerade as end-of-epoch)
"""

import io
import queue
import socket
import threading
from typing import Callable, Dict, Iterable, List, Optional

import numpy as np

from dlrover_tpu.common.env import get_free_port
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.netio import (
    LEN as _LEN,
    recv_exact as _recv_exact,
    recv_line as _recv_line,
)

_ERR_SENTINEL = (1 << 64) - 1
KV_PREFIX = "coworker/"


class CoworkerFailedError(RuntimeError):
    """The coworker's preprocessing pipeline crashed (distinct from
    being unreachable, which failover tolerates)."""


def encode_batch(batch: Dict[str, np.ndarray]) -> bytes:
    """Flat {name: ndarray} -> npz bytes (allow_pickle stays off)."""
    buf = io.BytesIO()
    np.savez(buf, **{k: np.asarray(v) for k, v in batch.items()})
    return buf.getvalue()


def decode_batch(payload: bytes) -> Dict[str, np.ndarray]:
    with np.load(io.BytesIO(payload), allow_pickle=False) as z:
        return {k: z[k] for k in z.files}


class CoworkerServer:
    """CPU-pod side: preprocess ``source`` items with ``preprocess_fn``
    into a bounded queue; serve one batch per TCP request."""

    def __init__(
        self,
        source: Iterable,
        preprocess_fn: Callable[[object], Dict[str, np.ndarray]],
        host: str = "0.0.0.0",
        port: int = 0,
        queue_size: int = 8,
    ):
        self._source = source
        self._preprocess = preprocess_fn
        self._queue: "queue.Queue" = queue.Queue(maxsize=queue_size)
        self._host = host
        self._port = port or get_free_port()
        self._srv: Optional[socket.socket] = None
        self._threads: List[threading.Thread] = []
        self._stopped = threading.Event()
        self._exhausted = threading.Event()
        self._failed = threading.Event()

    @property
    def port(self) -> int:
        return self._port

    def start(self):
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((self._host, self._port))
        self._srv.listen(8)
        self._srv.settimeout(0.5)
        for target, name in (
            (self._fill_loop, "coworker-preprocess"),
            (self._serve_loop, "coworker-serve"),
        ):
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        logger.info("coworker serving on port %d", self._port)

    def stop(self):
        self._stopped.set()
        if self._srv is not None:
            self._srv.close()

    # -- preprocessing ----------------------------------------------------
    def _fill_loop(self):
        try:
            for item in self._source:
                if self._stopped.is_set():
                    return
                payload = encode_batch(self._preprocess(item))
                while not self._stopped.is_set():
                    try:
                        self._queue.put(payload, timeout=0.5)
                        break
                    except queue.Full:
                        continue
        except Exception as e:  # noqa: BLE001
            logger.error("coworker preprocessing failed: %s", e)
            self._failed.set()
        finally:
            self._exhausted.set()

    # -- serving ----------------------------------------------------------
    def _serve_loop(self):
        while not self._stopped.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                self._handle(conn)
            except (ConnectionError, OSError) as e:
                logger.warning("coworker request failed: %s", e)
            finally:
                conn.close()

    def _handle(self, conn: socket.socket):
        try:
            line = _recv_line(conn)
        except ConnectionError:
            return
        if line != "GET":
            return
        payload = None
        while payload is None and not self._stopped.is_set():
            try:
                payload = self._queue.get(timeout=0.5)
            except queue.Empty:
                if self._exhausted.is_set() and self._queue.empty():
                    break
        if payload is None:
            if self._failed.is_set():
                # a crashed pipeline must not look like a clean end of
                # the source — the client raises on the sentinel
                conn.sendall(_LEN.pack(_ERR_SENTINEL))
            elif self._exhausted.is_set():
                conn.sendall(_LEN.pack(0))  # clean end of data
            # stopping with data still queued: close WITHOUT replying —
            # the client treats the broken connection as unreachable
            # and fails over, never as end-of-data
            return
        try:
            conn.sendall(_LEN.pack(len(payload)))
            conn.sendall(payload)
        except (OSError, ConnectionError):
            # the client vanished mid-send (timeout/restart): the batch
            # was popped but not delivered — put it back so the sample
            # is not silently dropped from the epoch
            try:
                self._queue.put_nowait(payload)
            except queue.Full:
                logger.warning(
                    "dropping one batch: send failed and queue full"
                )
            raise

    # -- registration -----------------------------------------------------
    def register(self, master_client, coworker_id: int,
                 advertise_host: Optional[str] = None) -> bool:
        """Publish this coworker's address in the master KV store (the
        reference's DataInfoService registration)."""
        host = advertise_host or socket.gethostbyname(
            socket.gethostname()
        )
        return master_client.kv_store_set(
            f"{KV_PREFIX}{coworker_id}",
            f"{host}:{self._port}".encode(),
        )


class CoworkerClient:
    """Trainer side: round-robin batch pulls with failover."""

    def __init__(self, addrs: List[str], timeout: float = 60.0):
        if not addrs:
            raise ValueError("no coworker addresses")
        self._addrs = list(addrs)
        self._timeout = timeout
        self._next = 0
        self._dead: set = set()  # unreachable (tolerated: failover)
        self._failed: set = set()  # reported pipeline FAILURE

    @classmethod
    def from_master(cls, master_client, max_coworkers: int = 64,
                    **kwargs) -> "CoworkerClient":
        """Discover coworker addresses from the master KV store."""
        addrs = []
        for i in range(max_coworkers):
            raw = master_client.kv_store_get(f"{KV_PREFIX}{i}")
            if not raw:
                break
            addrs.append(raw.decode())
        return cls(addrs, **kwargs)

    def _fetch(self, addr: str) -> Optional[Dict[str, np.ndarray]]:
        host, _, port = addr.rpartition(":")
        with socket.create_connection(
            (host, int(port)), timeout=self._timeout
        ) as conn:
            conn.sendall(b"GET\n")
            size = _LEN.unpack(_recv_exact(conn, _LEN.size))[0]
            if size == _ERR_SENTINEL:
                raise CoworkerFailedError(
                    f"coworker {addr} reports preprocessing failure"
                )
            if size == 0:
                return None
            return decode_batch(_recv_exact(conn, size))

    def next_batch(self) -> Optional[Dict[str, np.ndarray]]:
        """The next preprocessed batch, or None when every live
        coworker reports an exhausted source."""
        exhausted = 0
        attempts = 0
        n = len(self._addrs)
        while attempts < 2 * n and exhausted < n - len(self._dead):
            idx = self._next % n
            self._next += 1
            attempts += 1
            if idx in self._dead:
                continue
            addr = self._addrs[idx]
            try:
                batch = self._fetch(addr)
            except CoworkerFailedError as e:
                logger.error("coworker %s: %s", addr, e)
                self._dead.add(idx)
                self._failed.add(idx)
                continue
            except (OSError, ConnectionError) as e:
                logger.warning(
                    "coworker %s unreachable (%s); failing over",
                    addr, e,
                )
                self._dead.add(idx)
                continue
            if batch is None:
                exhausted += 1
                continue
            return batch
        if self._failed:
            # ANY coworker that reported a preprocessing failure means
            # part of the dataset was dropped — surfacing end-of-epoch
            # here would silently truncate training data
            raise RuntimeError(
                f"coworker(s) {sorted(self._failed)} reported "
                "preprocessing failures; refusing to present a crashed "
                "pipeline as end-of-data"
            )
        if exhausted == 0 and len(self._dead) >= n:
            # no coworker ever finished cleanly and all are gone: a
            # fully-dead data plane is an outage, not end-of-epoch
            raise RuntimeError(
                "all coworkers unreachable with none cleanly "
                "exhausted; data plane is down"
            )
        return None


class CoworkerDataset:
    """Iterator facade over :class:`CoworkerClient` (reference
    ``CoworkerDataset``): ``for batch in CoworkerDataset(client)``."""

    def __init__(self, client: CoworkerClient):
        self._client = client

    def __iter__(self):
        while True:
            batch = self._client.next_batch()
            if batch is None:
                return
            yield batch
