"""Cross-process shared-memory batch channel.

Reference parity: ``atorch/atorch/data/shm_dataloader.py:138`` +
``shm_context.py`` — a producer process (data worker) materializes
batches into a shared-memory ring; consumer (training proc) reads
without pickling tensors through a pipe.  On TPU hosts this feeds the
single training process from CPU-side preprocessing workers without
the GIL or copy chains.

Design: a fixed-slot ring over one ``SharedMemory`` segment.  The
batch schema (shapes/dtypes) is declared up front so slot size is
static (XLA-friendly static shapes end to end).

Data plane (this is the input-side sibling of the flash-checkpoint
rewire in ``common/parallel_io.py``):

- **Zero-copy slots.**  Writer and reader address each slot's fields
  through cached ``np.ndarray`` views directly over the shm buffer;
  large fields move with ``parallel_memcpy`` (chunked, GIL-releasing).
  The legacy ``tobytes()``/``bytes()+frombuffer`` round trips — four
  full serial copies per batch — survive only behind
  ``zero_copy=False`` (benchmark reference + escape hatch).
- **RPC-free steady state.**  Per-slot full/free/writing states live
  in an atomic header region at the front of the segment itself
  (aligned ``uint64`` stores), so ``put`` and ``next_batch`` never
  touch the ``SharedDict``.  The dict is retained only for the
  spec/num_slots/closed *handshake* at attach/close time.  Ordering:
  x86-TSO already guarantees the payload stores become visible before
  the ``FULL`` publication store; for weakly-ordered ISAs the
  producer issues an explicit full barrier (:func:`_memory_fence`, a
  pthread-mutex round trip) between the payload write and the state
  flip, and the consumer issues one between observing ``FULL`` and
  reading the payload — a release/acquire pair.
- **Distinct end-of-stream vs timeout.**  A clean producer ``close``
  yields ``None`` / ends iteration; a slot that never fills raises
  :class:`ShmSlotTimeout` — a slow producer can no longer silently
  truncate an epoch.
"""

import pickle
import threading
import time
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.multi_process import SharedDict, SharedMemory
from dlrover_tpu.common.parallel_io import (
    input_copy_workers,
    parallel_memcpy,
)

# header slot states (uint64 stores are single aligned movs — atomic
# on every platform CPython runs on)
SLOT_FREE = 0
SLOT_WRITING = 1
SLOT_FULL = 2

#: header word 0: 0 = open, 1 = producer closed cleanly
_HDR_CLOSED = 0
#: payload begins at the next 64-byte boundary after the header words
_HDR_ALIGN = 64


_fence_lock = threading.Lock()


def _memory_fence():
    """Full memory barrier via a pthread-mutex round trip.

    NumPy stores carry no ordering guarantees of their own; on
    weakly-ordered ISAs (ARM) the producer's ``FULL`` flip could
    otherwise become visible before the payload bytes.  A mutex
    acquire/release is a full fence on every platform CPython runs
    on, and at one round trip per *batch* (not per chunk) the cost is
    noise.  On x86-TSO this is belt-and-braces.
    """
    with _fence_lock:
        pass


class ShmSlotTimeout(TimeoutError):
    """A ring slot did not change state within the timeout.

    Raised instead of returning ``None`` so a merely-slow (or crashed
    mid-slot) producer is never mistaken for a clean end of stream.
    """


class BatchSpec:
    """Static schema: {name: (shape, dtype)} per batch element."""

    def __init__(self, fields: Dict[str, Tuple[tuple, str]]):
        self.fields = {
            name: (tuple(shape), np.dtype(dtype))
            for name, (shape, dtype) in fields.items()
        }
        self.slot_bytes = sum(
            int(np.prod(shape)) * dtype.itemsize
            for shape, dtype in self.fields.values()
        )

    def serialize(self) -> bytes:
        return pickle.dumps(
            {
                name: (shape, dtype.str)
                for name, (shape, dtype) in self.fields.items()
            }
        )

    @classmethod
    def deserialize(cls, raw: bytes) -> "BatchSpec":
        return cls(pickle.loads(raw))


def _attach_ring(name: str, timeout: float = 60.0) -> "_ShmRing":
    """Writer-side attach: block until the consumer's ring exists.

    Exponential backoff 0.1 -> 2 s (the ``wait_for_persist`` pattern)
    instead of a fixed 200 ms poll: attach storms from a large worker
    pool stay cheap, and the common fast path still reacts in 100 ms.
    """
    deadline = time.monotonic() + timeout
    poll = 0.1
    while True:
        try:
            meta = SharedDict(f"shm_ring_meta_{name}", create=False)
            raw = meta.get("spec")
            num_slots = meta.get("num_slots")
            meta.close()
            if raw and num_slots:
                spec = BatchSpec.deserialize(raw)
                return _ShmRing(
                    name, spec, int(num_slots), create=False
                )
        except (FileNotFoundError, TimeoutError, ConnectionError):
            pass
        if time.monotonic() > deadline:
            raise TimeoutError(f"shm ring {name!r} never appeared")
        time.sleep(poll)
        poll = min(poll * 2, 2.0)


class _ShmRing:
    def __init__(self, name: str, spec: BatchSpec, num_slots: int,
                 create: bool):
        self.spec = spec
        self.num_slots = num_slots
        # header: [closed, state_0 .. state_{n-1}] as aligned uint64
        hdr_words = 1 + num_slots
        self.payload_off = (
            (hdr_words * 8 + _HDR_ALIGN - 1) // _HDR_ALIGN * _HDR_ALIGN
        )
        total = self.payload_off + spec.slot_bytes * num_slots
        self.shm = SharedMemory(
            name=f"shm_ring_{name}", create=create, size=total
        )
        self._hdr = np.frombuffer(
            self.shm.buf, dtype=np.uint64, count=hdr_words
        )
        self.meta = SharedDict(f"shm_ring_meta_{name}", create=create)
        if create:
            self._hdr[:] = 0
            # the dict carries only the attach/close HANDSHAKE; slot
            # states live in the header so the steady path is RPC-free
            self.meta.update(
                {
                    "spec": spec.serialize(),
                    "num_slots": num_slots,
                    "closed": False,
                }
            )
        # per-slot, per-field zero-copy views over the segment
        self._views: List[Dict[str, np.ndarray]] = []
        for slot in range(num_slots):
            views = {}
            for name_, shape, dtype, off, _ in self._offsets():
                views[name_] = np.frombuffer(
                    self.shm.buf,
                    dtype=dtype,
                    count=int(np.prod(shape)) or 1,
                    offset=self.payload_off
                    + slot * spec.slot_bytes
                    + off,
                ).reshape(shape)
            self._views.append(views)

    def _offsets(self):
        off = 0
        for name, (shape, dtype) in self.spec.fields.items():
            nbytes = int(np.prod(shape)) * dtype.itemsize
            yield name, shape, dtype, off, nbytes
            off += nbytes

    # ------------------------------------------------------ header ops
    def slot_state(self, slot: int) -> int:
        return int(self._hdr[1 + slot])

    def set_slot_state(self, slot: int, state: int):
        self._hdr[1 + slot] = state

    def closed(self) -> bool:
        return bool(self._hdr[_HDR_CLOSED])

    def mark_closed(self):
        self._hdr[_HDR_CLOSED] = 1

    # ------------------------------------------------------- payload
    def slot_views(self, slot: int) -> Dict[str, np.ndarray]:
        """The slot's fields as zero-copy views over the segment."""
        return self._views[slot]

    def write_slot(self, slot: int, batch: Dict[str, np.ndarray],
                   zero_copy: bool = True):
        views = self._views[slot]
        for name, shape, dtype, off, nbytes in self._offsets():
            arr = np.ascontiguousarray(batch[name], dtype=dtype)
            if arr.shape != shape:
                raise ValueError(
                    f"batch field {name}: {arr.shape} != spec {shape}"
                )
            if zero_copy:
                # one chunked GIL-releasing copy straight into the
                # segment (parallel for large fields)
                parallel_memcpy(
                    views[name], arr, workers=input_copy_workers()
                )
            else:
                # legacy reference path: tobytes materializes a full
                # intermediate copy, then the buffer assignment copies
                # again
                base = self.payload_off + slot * self.spec.slot_bytes
                self.shm.buf[base + off : base + off + nbytes] = (
                    arr.tobytes()
                )

    def read_slot(self, slot: int, copy: bool = True,
                  zero_copy: bool = True) -> Dict[str, np.ndarray]:
        if not copy:
            return self._views[slot]
        out = {}
        for name, shape, dtype, off, nbytes in self._offsets():
            if zero_copy:
                dst = np.empty(shape, dtype=dtype)
                parallel_memcpy(
                    dst,
                    self._views[slot][name],
                    workers=input_copy_workers(),
                )
                out[name] = dst
            else:
                base = self.payload_off + slot * self.spec.slot_bytes
                raw = bytes(
                    self.shm.buf[base + off : base + off + nbytes]
                )
                out[name] = np.frombuffer(raw, dtype=dtype).reshape(
                    shape
                )
        return out

    def close(self, unlink: bool = False):
        # drop the views before closing: a live export keeps the mmap
        # pinned (BufferError); a consumer still holding copy=False
        # views is its own problem — warn, don't crash
        self._views = []
        self._hdr = None
        try:
            self.shm.close()
        except BufferError:
            logger.warning(
                "shm ring close deferred: batch views still alive"
            )
        if unlink:
            try:
                self.shm.unlink()
            except FileNotFoundError:
                pass
        self.meta.close()


def _backoff_sleep(delay: float, cap: float = 0.005) -> float:
    """One poll sleep; returns the next (exponentially grown) delay.
    Same pattern as ``wait_for_persist``'s 0.1 -> 2 s, scaled to input
    latencies: 0.2 ms first response so a just-freed slot is picked up
    almost immediately, 5 ms cap — an oversleep at the cap costs under
    a tenth of a large-batch copy, while an idle poll at 5 ms is
    negligible CPU.  (The header poll is a plain shm load; the old
    code paid a SharedDict RPC per 2 ms poll.)"""
    time.sleep(delay)
    return min(delay * 2, cap)


class ShmBatchWriter:
    """Producer side (data-worker process).  The CONSUMER owns the
    ring and its meta service (the training process outlives data
    workers); the writer attaches — pass ``create=True`` only for
    producer-owned standalone rings.  One writer per ring: slots are
    claimed round-robin without cross-producer arbitration."""

    def __init__(self, name: str, spec: Optional[BatchSpec] = None,
                 num_slots: int = 4, create: bool = False,
                 zero_copy: bool = True):
        if create:
            if spec is None:
                raise ValueError("create=True requires a spec")
            self._ring = _ShmRing(name, spec, num_slots, create=True)
        else:
            self._ring = _attach_ring(name)
        self._zero_copy = zero_copy
        self._next = 0

    def put(self, batch: Dict[str, np.ndarray],
            timeout: float = 300.0) -> bool:
        """Write one batch; blocks while the ring is full.  Steady
        state touches only the shm header — zero SharedDict RPCs."""
        slot = self._next
        deadline = time.monotonic() + timeout
        delay = 0.0002
        while self._ring.slot_state(slot) != SLOT_FREE:
            if time.monotonic() > deadline:
                return False
            delay = _backoff_sleep(delay)
        # WRITING marks the slot torn until the payload is complete:
        # a consumer never sees a half-written batch, and a producer
        # crash mid-slot leaves WRITING behind (consumer times out
        # loudly instead of reading garbage)
        self._ring.set_slot_state(slot, SLOT_WRITING)
        self._ring.write_slot(slot, batch, zero_copy=self._zero_copy)
        _memory_fence()  # payload visible before the FULL publication
        self._ring.set_slot_state(slot, SLOT_FULL)
        self._next = (slot + 1) % self._ring.num_slots
        return True

    def close(self):
        self._ring.mark_closed()  # consumer's RPC-free fast check
        try:
            self._ring.meta.set("closed", True)  # handshake parity
        except (ConnectionError, OSError, TimeoutError):
            pass  # consumer already gone; the header flag is durable
        self._ring.close()


class ShmDataLoader:
    """Consumer side (training process) — iterate numpy batches.

    ``next_batch(copy=True)`` hands back private arrays (one chunked
    parallel copy out of the slot).  ``copy=False`` returns zero-copy
    views over the slot itself; the slot is recycled on the following
    ``next_batch``/``release_slot`` call, so at most one batch of
    views is live at a time.
    """

    def __init__(self, name: str, spec: BatchSpec,
                 num_slots: int = 4, timeout: float = 300.0,
                 zero_copy: bool = True):
        # the consumer CREATES the ring: it owns the meta service and
        # outlives producer processes
        self._ring = _ShmRing(name, spec, num_slots, create=True)
        self._next = 0
        self._timeout = timeout
        self._zero_copy = zero_copy
        self._held_slot: Optional[int] = None

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        while True:
            batch = self.next_batch()
            if batch is None:
                return
            yield batch

    def release_slot(self):
        """Recycle the slot behind the last ``copy=False`` batch; its
        views must no longer be used."""
        if self._held_slot is not None:
            self._ring.set_slot_state(self._held_slot, SLOT_FREE)
            self._held_slot = None

    def next_batch(
        self, copy: bool = True
    ) -> Optional[Dict[str, np.ndarray]]:
        """The next batch, or ``None`` after a clean producer close.

        Raises :class:`ShmSlotTimeout` when the slot stays unfilled
        past the loader timeout — a slow or crashed-mid-slot producer
        must surface as an error, not truncate the epoch the way a
        silent ``None`` would.
        """
        self.release_slot()
        slot = self._next
        deadline = time.monotonic() + self._timeout
        delay = 0.0002
        while self._ring.slot_state(slot) != SLOT_FULL:
            # producer publishes FULL before closed (program order +
            # total store order), so closed with a non-FULL slot means
            # the stream genuinely ended
            if self._ring.closed():
                if self._ring.slot_state(slot) == SLOT_FULL:
                    break
                return None
            if time.monotonic() > deadline:
                logger.warning(
                    "shm dataloader timed out on slot %d "
                    "(producer slow or crashed mid-batch)", slot
                )
                raise ShmSlotTimeout(
                    f"slot {slot} not filled within "
                    f"{self._timeout}s and producer has not closed"
                )
            delay = _backoff_sleep(delay)
        _memory_fence()  # acquire: FULL observed before payload reads
        batch = self._ring.read_slot(
            slot, copy=copy, zero_copy=self._zero_copy
        )
        if copy:
            self._ring.set_slot_state(slot, SLOT_FREE)
        else:
            self._held_slot = slot
        self._next = (slot + 1) % self._ring.num_slots
        return batch

    def close(self):
        self.release_slot()
        self._ring.close(unlink=True)
