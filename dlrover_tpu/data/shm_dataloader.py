"""Cross-process shared-memory batch channel.

Reference parity: ``atorch/atorch/data/shm_dataloader.py:138`` +
``shm_context.py`` — a producer process (data worker) materializes
batches into a shared-memory ring; consumer (training proc) reads
without pickling tensors through a pipe.  On TPU hosts this feeds the
single training process from CPU-side preprocessing workers without
the GIL or copy chains.

Design: a fixed-slot ring over one ``SharedMemory`` segment; slot
states live in a ``SharedDict``; batch schema (shapes/dtypes) is
declared up front so slot size is static (XLA-friendly static shapes
end to end).
"""

import pickle
import time
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.multi_process import SharedDict, SharedMemory

_META_PREFIX = "slot_state_"  # 0 free, 1 full


class BatchSpec:
    """Static schema: {name: (shape, dtype)} per batch element."""

    def __init__(self, fields: Dict[str, Tuple[tuple, str]]):
        self.fields = {
            name: (tuple(shape), np.dtype(dtype))
            for name, (shape, dtype) in fields.items()
        }
        self.slot_bytes = sum(
            int(np.prod(shape)) * dtype.itemsize
            for shape, dtype in self.fields.values()
        )

    def serialize(self) -> bytes:
        return pickle.dumps(
            {
                name: (shape, dtype.str)
                for name, (shape, dtype) in self.fields.items()
            }
        )

    @classmethod
    def deserialize(cls, raw: bytes) -> "BatchSpec":
        return cls(pickle.loads(raw))


def _attach_ring(name: str, timeout: float = 60.0,
                 poll: float = 0.2) -> "_ShmRing":
    """Writer-side attach: block until the consumer's ring exists."""
    deadline = time.time() + timeout
    while True:
        try:
            meta = SharedDict(f"shm_ring_meta_{name}", create=False)
            raw = meta.get("spec")
            num_slots = meta.get("num_slots")
            meta.close()
            if raw and num_slots:
                spec = BatchSpec.deserialize(raw)
                return _ShmRing(
                    name, spec, int(num_slots), create=False
                )
        except (FileNotFoundError, TimeoutError, ConnectionError):
            pass
        if time.time() > deadline:
            raise TimeoutError(f"shm ring {name!r} never appeared")
        time.sleep(poll)


class _ShmRing:
    def __init__(self, name: str, spec: BatchSpec, num_slots: int,
                 create: bool):
        self.spec = spec
        self.num_slots = num_slots
        total = spec.slot_bytes * num_slots
        self.shm = SharedMemory(
            name=f"shm_ring_{name}", create=create, size=total
        )
        self.meta = SharedDict(f"shm_ring_meta_{name}", create=create)
        if create:
            init = {f"{_META_PREFIX}{i}": 0 for i in range(num_slots)}
            init["spec"] = spec.serialize()
            init["num_slots"] = num_slots
            init["closed"] = False
            self.meta.update(init)

    def _offsets(self):
        off = 0
        for name, (shape, dtype) in self.spec.fields.items():
            nbytes = int(np.prod(shape)) * dtype.itemsize
            yield name, shape, dtype, off, nbytes
            off += nbytes

    def write_slot(self, slot: int, batch: Dict[str, np.ndarray]):
        base = slot * self.spec.slot_bytes
        for name, shape, dtype, off, nbytes in self._offsets():
            arr = np.ascontiguousarray(batch[name], dtype=dtype)
            if arr.shape != shape:
                raise ValueError(
                    f"batch field {name}: {arr.shape} != spec {shape}"
                )
            self.shm.buf[base + off : base + off + nbytes] = (
                arr.tobytes()
            )

    def read_slot(self, slot: int) -> Dict[str, np.ndarray]:
        base = slot * self.spec.slot_bytes
        out = {}
        for name, shape, dtype, off, nbytes in self._offsets():
            raw = bytes(self.shm.buf[base + off : base + off + nbytes])
            out[name] = np.frombuffer(raw, dtype=dtype).reshape(shape)
        return out

    def close(self, unlink: bool = False):
        self.shm.close()
        if unlink:
            try:
                self.shm.unlink()
            except FileNotFoundError:
                pass
        self.meta.close()


class ShmBatchWriter:
    """Producer side (data-worker process).  The CONSUMER owns the
    ring and its meta service (the training process outlives data
    workers); the writer attaches — pass ``create=True`` only for
    producer-owned standalone rings."""

    def __init__(self, name: str, spec: Optional[BatchSpec] = None,
                 num_slots: int = 4, create: bool = False):
        if create:
            if spec is None:
                raise ValueError("create=True requires a spec")
            self._ring = _ShmRing(name, spec, num_slots, create=True)
        else:
            self._ring = _attach_ring(name)
        self._next = 0

    def put(self, batch: Dict[str, np.ndarray],
            timeout: float = 300.0) -> bool:
        slot = self._next
        key = f"{_META_PREFIX}{slot}"
        deadline = time.time() + timeout
        while self._ring.meta.get(key) == 1:
            if time.time() > deadline:
                return False
            time.sleep(0.002)
        self._ring.write_slot(slot, batch)
        self._ring.meta.set(key, 1)
        self._next = (slot + 1) % self._ring.num_slots
        return True

    def close(self):
        self._ring.meta.set("closed", True)
        self._ring.close()


class ShmDataLoader:
    """Consumer side (training process) — iterate numpy batches."""

    def __init__(self, name: str, spec: BatchSpec,
                 num_slots: int = 4, timeout: float = 300.0):
        # the consumer CREATES the ring: it owns the meta service and
        # outlives producer processes
        self._ring = _ShmRing(name, spec, num_slots, create=True)
        self._next = 0
        self._timeout = timeout

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        while True:
            batch = self.next_batch()
            if batch is None:
                return
            yield batch

    def next_batch(self) -> Optional[Dict[str, np.ndarray]]:
        slot = self._next
        key = f"{_META_PREFIX}{slot}"
        deadline = time.time() + self._timeout
        while self._ring.meta.get(key) != 1:
            if self._ring.meta.get("closed"):
                return None
            if time.time() > deadline:
                logger.warning("shm dataloader timed out on slot %d",
                               slot)
                return None
            time.sleep(0.002)
        batch = self._ring.read_slot(slot)
        self._ring.meta.set(key, 0)
        self._next = (slot + 1) % self._ring.num_slots
        return batch

    def close(self):
        self._ring.close()
