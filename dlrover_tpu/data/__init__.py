from dlrover_tpu.data.elastic_dataloader import (  # noqa: F401
    ElasticDataLoader,
)
from dlrover_tpu.data.prefetch import (  # noqa: F401
    batch_nbytes,
    device_prefetch,
    host_prefetch,
)
from dlrover_tpu.data.shm_dataloader import (  # noqa: F401
    ShmDataLoader,
    ShmBatchWriter,
    ShmSlotTimeout,
)
from dlrover_tpu.data.coworker import (  # noqa: F401
    CoworkerClient,
    CoworkerDataset,
    CoworkerServer,
)
