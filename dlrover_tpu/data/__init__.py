from dlrover_tpu.data.elastic_dataloader import (  # noqa: F401
    ElasticDataLoader,
)
from dlrover_tpu.data.prefetch import device_prefetch  # noqa: F401
from dlrover_tpu.data.shm_dataloader import (  # noqa: F401
    ShmDataLoader,
    ShmBatchWriter,
)
from dlrover_tpu.data.coworker import (  # noqa: F401
    CoworkerClient,
    CoworkerDataset,
    CoworkerServer,
)
