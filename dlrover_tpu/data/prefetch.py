"""Device prefetch: overlap host batch prep with device compute.

Reference parity: ``atorch/atorch/data/preloader.py`` (GPU data
preloader with a side CUDA stream).  On TPU the idiom is simpler:
``jax.device_put`` is async — keep N batches in flight so the host
pipeline never stalls the device (double/triple buffering).
"""

import collections
from typing import Iterable, Iterator, Optional

import jax


def device_prefetch(
    iterator: Iterable,
    size: int = 2,
    sharding: Optional[object] = None,
) -> Iterator:
    """Yield device-resident batches with ``size`` transfers in flight.

    ``sharding`` (a NamedSharding / prefix pytree) places each batch
    directly in its training layout — no host-side reshard later.
    """
    queue = collections.deque()

    def _put(batch):
        if sharding is not None:
            return jax.device_put(batch, sharding)
        return jax.device_put(batch)

    it = iter(iterator)
    try:
        for _ in range(size):
            queue.append(_put(next(it)))
    except StopIteration:
        pass
    while queue:
        out = queue.popleft()
        try:
            queue.append(_put(next(it)))
        except StopIteration:
            pass
        yield out
