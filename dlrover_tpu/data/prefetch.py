"""Device prefetch: overlap host batch prep with device compute.

Reference parity: ``atorch/atorch/data/preloader.py`` (GPU data
preloader with a side CUDA stream).  On TPU the idiom is simpler:
``jax.device_put`` is async — keep N batches in flight so the host
pipeline never stalls the device (double/triple buffering).
"""

import collections
import time
from typing import Iterable, Iterator, Optional

import jax

from dlrover_tpu.observability.events import get_event_logger


def device_prefetch(
    iterator: Iterable,
    size: int = 2,
    sharding: Optional[object] = None,
    stall_threshold_s: float = 0.05,
) -> Iterator:
    """Yield device-resident batches with ``size`` transfers in flight.

    ``sharding`` (a NamedSharding / prefix pytree) places each batch
    directly in its training layout — no host-side reshard later.

    A host fetch (``next(iterator)``) slower than
    ``stall_threshold_s`` is emitted as a ``data_stall`` span on the
    job timeline: with ``size`` batches in flight a slow fetch here is
    exactly the input pipeline failing to hide behind device compute.
    """
    queue = collections.deque()
    events = get_event_logger()

    def _put(batch):
        if sharding is not None:
            return jax.device_put(batch, sharding)
        return jax.device_put(batch)

    def _fetch(it):
        """next(it) with stall accounting; raises StopIteration."""
        if not events.enabled:
            return next(it)
        t0_wall, t0_mono = time.time(), time.monotonic()
        batch = next(it)
        dur = time.monotonic() - t0_mono
        if dur >= stall_threshold_s:
            events.complete("data_stall", t0_wall, dur)
        return batch

    it = iter(iterator)
    try:
        for _ in range(size):
            queue.append(_put(_fetch(it)))
    except StopIteration:
        pass
    while queue:
        out = queue.popleft()
        try:
            queue.append(_put(_fetch(it)))
        except StopIteration:
            pass
        yield out
