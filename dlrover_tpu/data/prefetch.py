"""Device prefetch: overlap host batch prep with device compute.

Reference parity: ``atorch/atorch/data/preloader.py`` (GPU data
preloader with a side CUDA stream).  On TPU the idiom is simpler:
``jax.device_put`` is async — keep N batches in flight so the host
pipeline never stalls the device (double/triple buffering).

Two pipeline stages, separately attributable on the job timeline:

- ``host_fetch`` — producing the next host batch
  (``next(iterator)``; with ``pipelined=True`` a bounded
  background-thread producer runs it concurrently, so the fetch of
  batch k+1 overlaps the ``device_put`` of batch k and the compute of
  batch k−1);
- ``h2d`` — staging the host batch onto devices (``jax.device_put``
  dispatch; normally asynchronous and ~free, so a slow dispatch is a
  transfer-queue backpressure signal).

A stage slower than ``stall_threshold_s`` emits a ``data_stall`` span
tagged ``stage=host_fetch`` / ``stage=h2d`` — the split tells a
too-slow storage read apart from a saturated host-to-device link.
The measured host-fetch bandwidth is exported as the
``dlrover_tpu_input_gbps{stage="host_fetch"}`` gauge.
"""

import collections
import queue
import threading
import time
from typing import Iterable, Iterator, Optional

from dlrover_tpu.observability.events import (
    anchored_now,
    get_event_logger,
)
from dlrover_tpu.observability.metrics import record_input_io

#: gauge refresh window: batch rates are noisy, export ~1/s
_METER_WINDOW_S = 1.0


def batch_nbytes(batch) -> int:
    """Total array bytes in a (possibly nested) batch structure; 0 for
    leaves without ``nbytes`` (lists of strings, scalars, ...)."""
    if hasattr(batch, "nbytes"):
        return int(batch.nbytes)
    if isinstance(batch, dict):
        return sum(batch_nbytes(v) for v in batch.values())
    if isinstance(batch, (list, tuple)):
        return sum(batch_nbytes(v) for v in batch)
    return 0


class _ThroughputMeter:
    """Windowed bytes/s accumulator feeding the input-gbps gauge."""

    def __init__(self, stage: str):
        self._stage = stage
        self._bytes = 0
        self._seconds = 0.0
        self._last_export = time.monotonic()

    def observe(self, nbytes: int, seconds: float):
        self._bytes += nbytes
        self._seconds += seconds
        now = time.monotonic()
        if (
            now - self._last_export >= _METER_WINDOW_S
            and self._bytes > 0
            and self._seconds > 0.0
        ):
            record_input_io(self._stage, self._bytes, self._seconds)
            self._bytes = 0
            self._seconds = 0.0
            self._last_export = now


class _EndOfStream:
    """Queue sentinel: clean iterator end, or carries the exception."""

    def __init__(self, error: Optional[BaseException] = None):
        self.error = error


def host_prefetch(
    iterator: Iterable,
    size: int = 2,
    stall_threshold_s: float = 0.05,
) -> Iterator:
    """Yield host batches produced by a bounded background thread.

    The producer thread runs ``next(iterator)`` up to ``size`` batches
    ahead; the consumer blocks only when the producer cannot keep up —
    that wait is the true pipeline stall and is emitted as a
    ``data_stall`` span tagged ``stage=host_fetch``.  Batch order is
    exactly the serial iteration order; an iterator exception is
    re-raised at the consuming call site.
    """
    events = get_event_logger()
    meter = _ThroughputMeter("host_fetch")
    q: "queue.Queue" = queue.Queue(maxsize=max(1, size))
    stop = threading.Event()

    def _put_until_stopped(item):
        """Blocking put that still notices consumer shutdown — the
        END/ERROR sentinels MUST land (a dropped error sentinel would
        leave the consumer blocked on q.get() forever)."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.5)
                return
            except queue.Full:
                continue

    def _produce():
        it = iter(iterator)
        try:
            while not stop.is_set():
                t0 = time.monotonic()
                try:
                    batch = next(it)
                except StopIteration:
                    _put_until_stopped(_EndOfStream())
                    return
                # the gauge measures the PRODUCTION bandwidth (the
                # fetch itself), not the backpressure wait below
                meter.observe(
                    batch_nbytes(batch), time.monotonic() - t0
                )
                _put_until_stopped(batch)
        except BaseException as e:  # noqa: BLE001 - re-raised consumer-side
            _put_until_stopped(_EndOfStream(e))

    thread = threading.Thread(
        target=_produce, name="input-host-prefetch", daemon=True
    )
    thread.start()
    try:
        while True:
            t0_mono = time.monotonic()
            t0_wall = anchored_now(t0_mono)
            item = q.get()
            wait = time.monotonic() - t0_mono
            if isinstance(item, _EndOfStream):
                if item.error is not None:
                    raise item.error
                return
            if events.enabled and wait >= stall_threshold_s:
                events.complete(
                    "data_stall", t0_wall, wait, stage="host_fetch"
                )
            yield item
    finally:
        stop.set()


def device_prefetch(
    iterator: Iterable,
    size: int = 2,
    sharding: Optional[object] = None,
    stall_threshold_s: float = 0.05,
    pipelined: bool = False,
) -> Iterator:
    """Yield device-resident batches with ``size`` transfers in flight.

    ``sharding`` (a NamedSharding / prefix pytree) places each batch
    directly in its training layout — no host-side reshard later.

    ``pipelined=True`` adds the background host producer
    (:func:`host_prefetch`): ``next(iterator)`` for batch k+1 runs
    concurrently with the ``device_put`` of batch k and the compute of
    batch k−1.  ``pipelined=False`` is the serial fallback — identical
    batch order, host fetch inline on the consumer thread.

    A host fetch slower than ``stall_threshold_s`` is emitted as a
    ``data_stall`` span tagged ``stage=host_fetch``; a ``device_put``
    dispatch slower than the threshold as ``stage=h2d``.
    """
    import jax

    q = collections.deque()
    events = get_event_logger()

    def _put(batch):
        t0_mono = time.monotonic()
        t0_wall = anchored_now(t0_mono)
        if sharding is not None:
            out = jax.device_put(batch, sharding)
        else:
            out = jax.device_put(batch)
        dur = time.monotonic() - t0_mono
        if events.enabled and dur >= stall_threshold_s:
            events.complete("data_stall", t0_wall, dur, stage="h2d")
        return out

    def _fetch(it):
        """next(it) with stall accounting; raises StopIteration."""
        if not events.enabled:
            return next(it)
        t0_mono = time.monotonic()
        t0_wall = anchored_now(t0_mono)
        batch = next(it)
        dur = time.monotonic() - t0_mono
        if dur >= stall_threshold_s:
            events.complete(
                "data_stall", t0_wall, dur, stage="host_fetch"
            )
        return batch

    if pipelined:
        # host_prefetch already accounts the host_fetch stalls (the
        # queue wait); fetching from it again through _fetch would
        # double-book the same wall clock
        it = iter(
            host_prefetch(
                iterator, size=size,
                stall_threshold_s=stall_threshold_s,
            )
        )
        fetch = next
    else:
        it = iter(iterator)
        fetch = _fetch
    try:
        for _ in range(size):
            q.append(_put(fetch(it)))
    except StopIteration:
        pass
    while q:
        out = q.popleft()
        try:
            q.append(_put(fetch(it)))
        except StopIteration:
            pass
        yield out
