"""Elastic dataloader: batch size re-tuned at runtime by the master.

Reference parity: ``dlrover/trainer/torch/elastic/dataloader.py:26``
(``ElasticDataLoader.load_config`` re-reads the JSON config file the
``ParalConfigTuner`` writes — ``elastic_agent/config/
paral_config_tuner.py:30``) so the master's auto-tuned dataloader
parameters take effect without restarting training.
"""

import json
import os
import threading
import time
from typing import Callable, Iterator, Optional

import numpy as np

from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.trainer.elastic.sampler import (
    ElasticDistributedSampler,
)

DEFAULT_CONFIG_FILE = "/tmp/dlrover_tpu_paral_config.json"


class ParalConfigTuner:
    """Agent-side: polls master ``ParallelConfig`` and writes the
    config file the dataloader watches (reference ``:30,70``)."""

    def __init__(self, client=None, config_file: str = "",
                 interval: float = 30.0):
        from dlrover_tpu.agent.master_client import MasterClient

        self._client = client or MasterClient.singleton_instance()
        self.config_file = config_file or os.getenv(
            "DLROVER_TPU_PARAL_CONFIG_FILE", DEFAULT_CONFIG_FILE
        )
        self._interval = interval
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _tick(self):
        config = self._client.get_paral_config()
        dataloader = getattr(config, "dataloader", None)
        payload = {
            "version": getattr(config, "version", 0),
            "dataloader": {
                "batch_size": getattr(dataloader, "batch_size", 0),
                "num_workers": getattr(dataloader, "num_workers", 0),
            }
            if dataloader
            else {},
        }
        tmp = self.config_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, self.config_file)

    def start(self):
        if self._thread is not None:
            return

        def _loop():
            while not self._stopped.wait(self._interval):
                try:
                    self._tick()
                except (ConnectionError, OSError) as e:
                    logger.warning("paral tuner tick failed: %s", e)

        self._thread = threading.Thread(
            target=_loop, name="paral-tuner", daemon=True
        )
        self._thread.start()

    def stop(self):
        self._stopped.set()


class ElasticDataLoader:
    """Batched index loader whose batch size follows the tuned config.

    ``read_batch(indices) -> batch`` turns sampled indices into arrays
    (user-supplied — file reads, tokenization, ...).  Each ``__iter__``
    re-checks the config file; mid-epoch batch-size changes take
    effect on the next epoch (matching the reference's
    ``load_config``-on-init + set_batch_size semantics).
    """

    def __init__(
        self,
        dataset_size: int,
        batch_size: int,
        read_batch: Callable[[np.ndarray], object],
        sampler: Optional[ElasticDistributedSampler] = None,
        num_replicas: int = 1,
        rank: int = 0,
        shuffle: bool = True,
        config_file: str = "",
        drop_last: bool = True,
    ):
        self._read_batch = read_batch
        self.batch_size = batch_size
        self._config_file = config_file or os.getenv(
            "DLROVER_TPU_PARAL_CONFIG_FILE", DEFAULT_CONFIG_FILE
        )
        self.sampler = sampler or ElasticDistributedSampler(
            dataset_size,
            num_replicas=num_replicas,
            rank=rank,
            shuffle=shuffle,
        )
        self._drop_last = drop_last
        self.load_config()

    def load_config(self):
        if not os.path.exists(self._config_file):
            return
        try:
            with open(self._config_file) as f:
                config = json.load(f)
            new_bs = int(
                config.get("dataloader", {}).get("batch_size", 0)
            )
            if new_bs > 0 and new_bs != self.batch_size:
                logger.info(
                    "dataloader batch size tuned %d -> %d",
                    self.batch_size,
                    new_bs,
                )
                self.batch_size = new_bs
        except (OSError, ValueError) as e:
            logger.warning("paral config read failed: %s", e)

    def __iter__(self) -> Iterator:
        self.load_config()
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield self._read_batch(np.asarray(batch))
                batch = []
        if batch and not self._drop_last:
            yield self._read_batch(np.asarray(batch))

    def __len__(self) -> int:
        n = len(self.sampler)
        if self._drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def state_dict(self) -> dict:
        return {"sampler": self.sampler.state_dict(),
                "batch_size": self.batch_size}

    def load_state_dict(self, state: dict):
        self.sampler.load_state_dict(state.get("sampler", {}))
        bs = int(state.get("batch_size", 0))
        if bs > 0:
            self.batch_size = bs
