"""Elastic dataloader: batch size re-tuned at runtime by the master.

Reference parity: ``dlrover/trainer/torch/elastic/dataloader.py:26``
(``ElasticDataLoader.load_config`` re-reads the JSON config file the
``ParalConfigTuner`` writes — ``elastic_agent/config/
paral_config_tuner.py:30``) so the master's auto-tuned dataloader
parameters take effect without restarting training.

The loader is **pipelined**: a bounded producer pool (size =
``num_workers``, also tuned live through the config file) runs
``read_batch`` in the background so batch k+1 is being fetched while
batch k is consumed.  Batches are yielded strictly in the serial
order; ``DLROVER_TPU_INPUT_PIPELINE=0`` (or ``pipeline=False``) is
the byte-identical serial fallback.  ``state_dict`` always reports
the sampler position of the last batch actually *yielded* — the
loader's own producer read-ahead can never over-advance a mid-epoch
checkpoint.  Batches the CONSUMER buffers after the yield (e.g.
``device_prefetch``'s in-flight window) are beyond the loader's
horizon: checkpoint at consumed-step boundaries, or accept replaying
up to one prefetch window after a mid-buffer crash — the same
exposure any buffered iterator has.
"""

import collections
import json
import os
import threading
import time
from typing import Callable, Iterator, Optional

import numpy as np

from dlrover_tpu.common.env import input_pipeline_enabled
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.data.prefetch import _ThroughputMeter, batch_nbytes
from dlrover_tpu.trainer.elastic.sampler import (
    ElasticDistributedSampler,
)

DEFAULT_CONFIG_FILE = "/tmp/dlrover_tpu_paral_config.json"


class ParalConfigTuner:
    """Agent-side: polls master ``ParallelConfig`` and writes the
    config file the dataloader watches (reference ``:30,70``)."""

    def __init__(self, client=None, config_file: str = "",
                 interval: float = 30.0):
        from dlrover_tpu.agent.master_client import MasterClient

        self._client = client or MasterClient.singleton_instance()
        self.config_file = config_file or os.getenv(
            "DLROVER_TPU_PARAL_CONFIG_FILE", DEFAULT_CONFIG_FILE
        )
        self._interval = interval
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _tick(self):
        config = self._client.get_paral_config()
        dataloader = getattr(config, "dataloader", None)
        payload = {
            "version": getattr(config, "version", 0),
            "dataloader": {
                "batch_size": getattr(dataloader, "batch_size", 0),
                "num_workers": getattr(dataloader, "num_workers", 0),
            }
            if dataloader
            else {},
        }
        tmp = self.config_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, self.config_file)

    def start(self):
        if self._thread is not None:
            return

        def _loop():
            while not self._stopped.wait(self._interval):
                try:
                    self._tick()
                except (ConnectionError, OSError) as e:
                    logger.warning("paral tuner tick failed: %s", e)

        self._thread = threading.Thread(
            target=_loop, name="paral-tuner", daemon=True
        )
        self._thread.start()

    def stop(self):
        self._stopped.set()


class ElasticDataLoader:
    """Batched index loader whose batch size follows the tuned config.

    ``read_batch(indices) -> batch`` turns sampled indices into arrays
    (user-supplied — file reads, tokenization, ...).  Each ``__iter__``
    re-checks the config file; mid-epoch batch-size / num_workers
    changes take effect on the next epoch (matching the reference's
    ``load_config``-on-init + set_batch_size semantics).

    With the pipeline enabled (default; kill-switch
    ``DLROVER_TPU_INPUT_PIPELINE=0``) a producer pool of
    ``num_workers`` threads runs ``read_batch`` up to
    ``prefetch_depth`` batches ahead.  Batches are yielded in exactly
    the serial order, so the pipelined and serial paths are
    byte-identical for a deterministic ``read_batch``.  With
    ``num_workers > 1``, ``read_batch`` must be thread-safe (calls for
    different index batches run concurrently).
    """

    def __init__(
        self,
        dataset_size: int,
        batch_size: int,
        read_batch: Callable[[np.ndarray], object],
        sampler: Optional[ElasticDistributedSampler] = None,
        num_replicas: int = 1,
        rank: int = 0,
        shuffle: bool = True,
        config_file: str = "",
        drop_last: bool = True,
        num_workers: int = 1,
        prefetch_depth: int = 2,
        pipeline: Optional[bool] = None,
    ):
        self._read_batch = read_batch
        self.batch_size = batch_size
        self.num_workers = max(1, int(num_workers))
        self._prefetch_depth = max(1, int(prefetch_depth))
        self._pipeline = pipeline
        self._config_file = config_file or os.getenv(
            "DLROVER_TPU_PARAL_CONFIG_FILE", DEFAULT_CONFIG_FILE
        )
        self.sampler = sampler or ElasticDistributedSampler(
            dataset_size,
            num_replicas=num_replicas,
            rank=rank,
            shuffle=shuffle,
        )
        self._drop_last = drop_last
        # sampler state of the last batch YIELDED to the consumer —
        # the checkpointable position (the live sampler may have been
        # advanced further by producer read-ahead)
        self._consumed_state: Optional[dict] = None
        self.load_config()

    def _pipeline_on(self) -> bool:
        if self._pipeline is not None:
            return bool(self._pipeline)
        return input_pipeline_enabled()

    def load_config(self):
        if not os.path.exists(self._config_file):
            return
        try:
            with open(self._config_file) as f:
                config = json.load(f)
            dataloader = config.get("dataloader", {})
            new_bs = int(dataloader.get("batch_size", 0))
            if new_bs > 0 and new_bs != self.batch_size:
                logger.info(
                    "dataloader batch size tuned %d -> %d",
                    self.batch_size,
                    new_bs,
                )
                self.batch_size = new_bs
            # the tuner also writes num_workers — apply it to the
            # producer pool (live on the next epoch, like batch_size)
            new_workers = int(dataloader.get("num_workers", 0))
            if new_workers > 0 and new_workers != self.num_workers:
                logger.info(
                    "dataloader num_workers tuned %d -> %d",
                    self.num_workers,
                    new_workers,
                )
                self.num_workers = new_workers
        except (OSError, ValueError) as e:
            logger.warning("paral config read failed: %s", e)

    # ------------------------------------------------------- iteration
    def _index_batches(self):
        """Yield ``(indices, sampler_state_after_draw)`` in the serial
        batch order — the single source of ordering for both paths."""
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield np.asarray(batch), self.sampler.state_dict()
                batch = []
        if batch and not self._drop_last:
            yield np.asarray(batch), self.sampler.state_dict()

    def _iter_serial(self) -> Iterator:
        for indices, watermark in self._index_batches():
            out = self._read_batch(indices)
            self._consumed_state = watermark
            yield out

    def _iter_pipelined(self) -> Iterator:
        from concurrent.futures import ThreadPoolExecutor

        workers = self.num_workers
        depth = max(self._prefetch_depth, workers)
        meter = _ThroughputMeter("read_batch")
        gen = self._index_batches()
        pending = collections.deque()
        pool = ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="input-fetch"
        )

        def _job(indices):
            t0 = time.monotonic()
            out = self._read_batch(indices)
            return out, time.monotonic() - t0

        def _submit_next() -> bool:
            try:
                indices, watermark = next(gen)
            except StopIteration:
                return False
            pending.append((pool.submit(_job, indices), watermark))
            return True

        try:
            for _ in range(depth):
                if not _submit_next():
                    break
            while pending:
                fut, watermark = pending.popleft()
                out, fetch_s = fut.result()
                _submit_next()
                self._consumed_state = watermark
                meter.observe(batch_nbytes(out), fetch_s)
                yield out
        finally:
            pool.shutdown(wait=False, cancel_futures=True)

    def __iter__(self) -> Iterator:
        self.load_config()
        if self._pipeline_on():
            return self._iter_pipelined()
        return self._iter_serial()

    def __len__(self) -> int:
        n = len(self.sampler)
        if self._drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def state_dict(self) -> dict:
        sampler_state = (
            dict(self._consumed_state)
            if self._consumed_state is not None
            else self.sampler.state_dict()
        )
        return {"sampler": sampler_state,
                "batch_size": self.batch_size}

    def load_state_dict(self, state: dict):
        self.sampler.load_state_dict(state.get("sampler", {}))
        self._consumed_state = None
        bs = int(state.get("batch_size", 0))
        if bs > 0:
            self.batch_size = bs
