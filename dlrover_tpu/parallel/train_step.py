"""Jitted sharded train step — what ``auto_accelerate`` returns.

Reference parity: the *output* of atorch's ``auto_accelerate``
(``auto/accelerate.py:406``) — a transformed (model, optim, dataloader)
triple ready to step.  Here the equivalent artifact is a single jitted
function: params/optimizer state sharded per the rule table (GSPMD
inserts the ZeRO gather/scatter and TP collectives), gradient
accumulation as a ``lax.scan`` over microbatches (global batch
invariance under elasticity — reference ``ElasticTrainer``), buffers
donated so optimizer update is in-place in HBM.
"""

from dataclasses import dataclass
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax

from dlrover_tpu.parallel.mesh import AxisName, MeshContext
from dlrover_tpu.parallel.sharding import (
    BATCH,
    LogicalAxisRules,
    logical_sharding,
    param_sharding_with_fsdp,
    rules_scope,
    shard_pytree,
)


@dataclass
class TrainStepFns:
    """The compiled artifacts handed back to the user."""

    train_step: Callable  # (state, batch) -> (state, metrics)
    init_state: Callable  # (rng) -> sharded TrainState pytree
    state_shardings: Any
    batch_sharding: Any
    # forward-only loss under the SAME shardings (no donation: eval
    # must not consume the train state's buffers); None on artifacts
    # built before eval existed
    eval_step: Optional[Callable] = None  # (state, batch) -> metrics
    # eval_shape of the train state (ShapeDtypeStructs) — what the AOT
    # path lowers against; None on artifacts built before AOT existed
    state_shape: Any = None

    def aot_compile(self, sample_batch):
        """AOT-compile the train step from shape specs alone:
        ``jit(...).lower(state_specs, batch_specs).compile()``.

        Needs NO live state and NO data — only the mesh — so it can
        run on a background thread the moment the mesh exists,
        concurrently with the restore byte stream (the restart
        critical path, ``trainer/restart_path.py``).  A warm
        ``JAX_COMPILATION_CACHE_DIR`` turns this into a cache load;
        cold, it is the full XLA compile that would otherwise
        serialize in front of the first step.

        ``sample_batch``: a pytree of arrays OR ShapeDtypeStructs
        giving the batch layout.  Returns the compiled executable —
        call it exactly like ``train_step`` (same shardings, same
        donation); inputs with other shapes must go through the
        retracing ``train_step`` instead.
        """
        if self.state_shape is None:
            raise ValueError(
                "artifacts built before the AOT path existed "
                "(rebuild with build_train_step)"
            )
        batch_shape = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
            sample_batch,
        )
        return self.train_step.lower(
            self.state_shape, batch_shape
        ).compile()


def make_train_state(params, optimizer):
    return {
        "step": jnp.zeros((), dtype=jnp.int32),
        "params": params,
        "opt_state": optimizer.init(params),
    }


def build_train_step(
    loss_fn: Callable,  # (params, batch) -> scalar loss
    optimizer,  # optax.GradientTransformation
    init_params_fn: Callable,  # (rng) -> params pytree
    param_axes,  # logical-axes pytree matching params
    mesh_ctx: MeshContext,
    rules: LogicalAxisRules,
    num_micro_steps: int = 1,
    batch_logical_axes=(BATCH,),
) -> TrainStepFns:
    mesh = mesh_ctx.mesh
    # publish the rule table so in-model activation constraints
    # (apply_sharding_constraint via _current_rules) match param shardings
    mesh_ctx.rules = rules

    def _init_state(rng):
        params = init_params_fn(rng)
        return make_train_state(params, optimizer)

    state_shape = jax.eval_shape(
        _init_state, jax.ShapeDtypeStruct((2,), jnp.uint32)
    )

    _is_axes_leaf = lambda x: isinstance(x, (tuple, type(None)))  # noqa: E731
    if rules.uses_axis(AxisName.FSDP):
        # ZeRO-3 strategy: params whose logical axes don't map onto the
        # fsdp axis still shard over it on their largest divisible dim
        # (shape-aware placement — every param shards, the all-gather
        # rides the biggest dim)
        param_shardings = jax.tree_util.tree_map(
            lambda axes, leaf: param_sharding_with_fsdp(
                mesh, rules, axes, leaf.shape
            ),
            param_axes,
            state_shape["params"],
            is_leaf=_is_axes_leaf,
        )
    else:
        param_shardings = jax.tree_util.tree_map(
            lambda axes: logical_sharding(mesh, rules, axes),
            param_axes,
            is_leaf=_is_axes_leaf,
        )
    batch_sharding = logical_sharding(mesh, rules, batch_logical_axes)
    replicated = logical_sharding(mesh, rules, ())

    def _opt_state_shardings(params_shape):
        """Optimizer state inherits params' shardings structurally:
        optax moment trees mirror the params pytree (match by tree
        structure, NOT by leaf shape — distinct params often share a
        shape, e.g. llama wq/wo, but have transposed layouts); scalar
        leaves (counts) replicate."""
        opt_shape = jax.eval_shape(optimizer.init, params_shape)
        params_def = jax.tree_util.tree_structure(params_shape)

        def is_params_like(sub):
            try:
                return (
                    jax.tree_util.tree_structure(sub) == params_def
                )
            except Exception:  # noqa: BLE001
                return False

        def pick(sub):
            return param_shardings if is_params_like(sub) else replicated

        return jax.tree_util.tree_map(
            pick, opt_shape, is_leaf=is_params_like
        )

    state_shardings = {
        "step": replicated,
        "params": param_shardings,
        "opt_state": _opt_state_shardings(state_shape["params"]),
    }

    init_state = jax.jit(_init_state, out_shardings=state_shardings)

    def _loss_and_grad(params, batch):
        # rules bound at trace time: the model's activation constraints
        # resolve against this build's table even if another strategy
        # is built before this step is first called
        with rules_scope(rules):
            return jax.value_and_grad(loss_fn)(params, batch)

    def _train_step(state, batch):
        params = state["params"]
        if num_micro_steps > 1:
            micro = jax.tree_util.tree_map(
                lambda x: x.reshape(
                    (num_micro_steps, x.shape[0] // num_micro_steps)
                    + x.shape[1:]
                ),
                batch,
            )

            def accum(carry, mb):
                loss_sum, grad_sum = carry
                loss, grads = _loss_and_grad(params, mb)
                grad_sum = jax.tree_util.tree_map(
                    jnp.add, grad_sum, grads
                )
                return (loss_sum + loss, grad_sum), None

            zeros = jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, dtype=jnp.float32), params
            )
            (loss_sum, grad_sum), _ = jax.lax.scan(
                accum, (jnp.zeros((), jnp.float32), zeros), micro
            )
            scale = 1.0 / num_micro_steps
            loss = loss_sum * scale
            grads = jax.tree_util.tree_map(
                lambda g: g * scale, grad_sum
            )
        else:
            loss, grads = _loss_and_grad(params, batch)
        updates, new_opt_state = optimizer.update(
            grads, state["opt_state"], params
        )
        new_params = optax.apply_updates(params, updates)
        new_state = {
            "step": state["step"] + 1,
            "params": new_params,
            "opt_state": new_opt_state,
        }
        grad_norm = optax.global_norm(grads)
        return new_state, {"loss": loss, "grad_norm": grad_norm}

    train_step = jax.jit(
        _train_step,
        in_shardings=(state_shardings, batch_sharding),
        out_shardings=(state_shardings, replicated),
        donate_argnums=(0,),
    )

    def _eval_step(state, batch):
        with rules_scope(rules):
            loss = loss_fn(state["params"], batch)
        return {"loss": loss}

    # no donation: evaluation reads the live train state and must not
    # invalidate its buffers mid-run
    eval_step = jax.jit(
        _eval_step,
        in_shardings=(state_shardings, batch_sharding),
        out_shardings=replicated,
    )
    return TrainStepFns(
        train_step=train_step,
        init_state=init_state,
        state_shardings=state_shardings,
        batch_sharding=batch_sharding,
        eval_step=eval_step,
        state_shape=state_shape,
    )
