"""Named device-mesh construction — the process-group factory, TPU-way.

Reference parity: ``atorch/atorch/distributed/distributed.py:323``
(``create_parallel_group``: N-dim named process groups from
``[(name, size), ...]`` + rank order, with strided rank slicing
``_get_pg_ranks:266``) and the ``_DistributedContext`` registry
(``:19``).

TPU-native redesign: there are no process groups to create — a single
``jax.sharding.Mesh`` with named axes expresses every parallel
dimension at once, and XLA emits the collectives (SURVEY.md §2.8 row
"Mixed / 3D").  ``create_parallel_mesh([("data", -1), ("tensor", 4)])``
is the whole API: ``-1`` infers the remaining factor from the device
count, axis order controls ICI locality (the *last* axis is
innermost = most-local, so put tensor/seq there and data/pipe
outermost over DCN).
"""

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from dlrover_tpu.common.log import default_logger as logger


class AxisName:
    """Canonical mesh-axis names (reference group names
    ``distributed.py`` "data"/"tensor"/"pipe"/"sequence"/"expert")."""

    DATA = "data"
    FSDP = "fsdp"  # parameter-sharding (ZeRO-3) sub-axis of data
    TENSOR = "tensor"
    SEQUENCE = "seq"
    EXPERT = "expert"
    PIPELINE = "pipe"

    ALL = (DATA, FSDP, TENSOR, SEQUENCE, EXPERT, PIPELINE)


@dataclass
class MeshContext:
    """What ``_DistributedContext`` kept for process groups, kept for
    the mesh instead."""

    mesh: "object"  # jax.sharding.Mesh
    dims: List[Tuple[str, int]] = field(default_factory=list)
    # active LogicalAxisRules; set by the strategy engine /
    # build_train_step so in-model activation constraints resolve
    # against the same table that sharded the params
    rules: Optional[object] = None
    # pipeline microbatch count when pipe > 1 (set by the strategy
    # engine; None -> 2 x pipe stages, a reasonable bubble/memory
    # trade: bubble fraction (P-1)/(M+P-1))
    pipeline_microbatches: Optional[int] = None

    def axis_size(self, name: str) -> int:
        return dict(self.dims).get(name, 1)

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return tuple(n for n, _ in self.dims)

    @property
    def num_devices(self) -> int:
        return int(np.prod([s for _, s in self.dims])) if self.dims else 1


_context: Optional[MeshContext] = None
_lock = threading.Lock()


def _resolve_dims(
    parallel_config: Sequence[Tuple[str, int]], num_devices: int
) -> List[Tuple[str, int]]:
    dims: List[Tuple[str, int]] = []
    infer_index = -1
    known = 1
    for i, (name, size) in enumerate(parallel_config):
        if size == -1:
            if infer_index >= 0:
                raise ValueError("at most one axis size may be -1")
            infer_index = i
            dims.append((name, -1))
        else:
            if size <= 0:
                raise ValueError(f"axis {name!r} size must be >0 or -1")
            known *= size
            dims.append((name, size))
    if infer_index >= 0:
        if num_devices % known != 0:
            raise ValueError(
                f"{num_devices} devices not divisible by fixed axes {known}"
            )
        name = dims[infer_index][0]
        dims[infer_index] = (name, num_devices // known)
        known *= dims[infer_index][1]
    if known != num_devices:
        raise ValueError(
            f"mesh {dims} covers {known} devices, have {num_devices}"
        )
    return dims


def _build_mesh_context(
    device_array: np.ndarray,
    dims: List[Tuple[str, int]],
    set_global: bool,
) -> MeshContext:
    """Shared tail of the mesh builders: dup-name check, Mesh +
    MeshContext construction, global-context install."""
    from jax.sharding import Mesh

    names = tuple(n for n, _ in dims)
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate axis names in {names}")
    mesh = Mesh(device_array.reshape([s for _, s in dims]), names)
    ctx = MeshContext(mesh=mesh, dims=list(dims))
    if set_global:
        global _context
        with _lock:
            _context = ctx
    return ctx


def create_parallel_mesh(
    parallel_config: Optional[Sequence[Tuple[str, int]]] = None,
    devices=None,
    set_global: bool = True,
) -> MeshContext:
    """Build a named ``jax.sharding.Mesh``.

    ``parallel_config`` is ``[(axis_name, size), ...]``; one size may be
    ``-1`` (inferred).  Default: pure data parallelism over all devices.
    Axis order = ``parallel_config`` order; the last axis maps to the
    innermost (most ICI-local) device dimension, matching the
    reference's rank-order semantics for strided groups.
    """
    import jax

    if devices is None:
        devices = jax.devices()
    if parallel_config is None:
        parallel_config = [(AxisName.DATA, -1)]
    dims = _resolve_dims(parallel_config, len(devices))
    ctx = _build_mesh_context(
        np.asarray(devices), dims, set_global
    )
    logger.info(
        "parallel mesh: %s over %d devices",
        dict(dims),
        len(devices),
    )
    return ctx


def create_hybrid_parallel_mesh(
    dcn_config: Sequence[Tuple[str, int]],
    ici_config: Sequence[Tuple[str, int]],
    devices=None,
    set_global: bool = True,
    granule_fn=None,
) -> MeshContext:
    """Multi-slice mesh: DCN axes stride ACROSS slices, ICI axes stay
    INSIDE a slice.

    The reference expresses the same hierarchy with nested NCCL groups
    (intra-node rings under inter-node trees); on TPU pods the
    physical boundary is the slice: collectives on the ``ici_config``
    axes ride the torus, collectives on the ``dcn_config`` axes cross
    the data-center network — so put data/pipeline in ``dcn_config``
    and tensor/seq/expert/fsdp in ``ici_config``.

    ``granule_fn(device) -> key`` groups devices into slices (default:
    ``slice_index`` where the runtime exposes it, else
    ``process_index`` — the CPU-mesh test seam).  Mesh axis order is
    dcn axes (outermost) then ici axes, consistent with
    ``create_parallel_mesh``'s locality convention.
    """
    import jax

    if devices is None:
        devices = jax.devices()
    if granule_fn is None:
        def granule_fn(d):
            s = getattr(d, "slice_index", None)
            return s if s is not None else d.process_index

    granules: Dict[object, list] = {}
    for d in devices:
        granules.setdefault(granule_fn(d), []).append(d)
    # numeric-aware ordering: str-sorting integer slice ids would put
    # slice 10 before slice 2, permuting DCN coordinates vs slice
    # numbering on 10+-slice pods
    granule_keys = sorted(
        granules,
        key=lambda k: (0, k, "") if isinstance(k, int)
        else (1, 0, str(k)),
    )
    per = {len(g) for g in granules.values()}
    if len(per) != 1:
        raise ValueError(
            f"uneven slices: {sorted(per)} devices per granule"
        )
    per_granule = per.pop()

    dcn_dims = _resolve_dims(dcn_config, len(granule_keys))
    ici_dims = _resolve_dims(ici_config, per_granule)
    device_array = np.asarray([granules[k] for k in granule_keys])
    ctx = _build_mesh_context(
        device_array, list(dcn_dims) + list(ici_dims), set_global
    )
    logger.info(
        "hybrid mesh: dcn %s x ici %s over %d slices",
        dict(dcn_dims),
        dict(ici_dims),
        len(granule_keys),
    )
    return ctx


def get_mesh_context() -> Optional[MeshContext]:
    return _context


def get_mesh():
    if _context is None:
        raise RuntimeError(
            "no parallel mesh: call create_parallel_mesh() first"
        )
    return _context.mesh


def axis_size(name: str) -> int:
    return _context.axis_size(name) if _context else 1


def destroy_parallel_mesh():
    global _context
    with _lock:
        _context = None


def data_parallel_size() -> int:
    """Total batch-sharding factor: data * fsdp axes (ZeRO shards
    params over the same replicas that shard the batch)."""
    return axis_size(AxisName.DATA) * axis_size(AxisName.FSDP)


def build_device_mesh_dims(
    num_devices: int,
    data: int = -1,
    fsdp: int = 1,
    tensor: int = 1,
    seq: int = 1,
    expert: int = 1,
    pipe: int = 1,
) -> List[Tuple[str, int]]:
    """Convenience: the canonical axis ordering (outermost→innermost =
    pipe, data, fsdp, expert, seq, tensor) with one inferred dim."""
    dims = [
        (AxisName.PIPELINE, pipe),
        (AxisName.DATA, data),
        (AxisName.FSDP, fsdp),
        (AxisName.EXPERT, expert),
        (AxisName.SEQUENCE, seq),
        (AxisName.TENSOR, tensor),
    ]
    return _resolve_dims(dims, num_devices)
