"""Sequence/expert-parallel collectives for use inside ``shard_map``.

Reference parity:

- Ulysses all-to-all: ``atorch/atorch/distributed/distributed.py:474``
  (``_SeqAllToAll`` autograd: scatter_idx/gather_idx exchange) and
  ``seq_all_to_all:500``.  Here it is a single ``lax.all_to_all`` whose
  transpose rule gives the backward pass for free — no custom autograd.
- Ring primitives: the micro-Q all-gather ring of
  ``modules/distributed_transformer/commu_utils.py`` becomes
  ``lax.ppermute`` rotation (the idiomatic ICI ring).
- Distributed softmax: ``distributed_attention.py:21``
  (``DistributedSoftmax``: global max+sum via allreduce over the
  sharded sequence) becomes two ``psum``/``pmax`` calls.
- Expert dispatch: ``modules/moe/moe_layer.py:87`` (``_AllToAll``)
  becomes ``lax.all_to_all`` over the "expert" axis.
"""

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def device_varying(x, axis_name):
    """Mark a freshly-created array as device-varying over ``axis_name``
    (shard_map vma typing for scan carries)."""
    try:
        return lax.pcast(x, axis_name, to="varying")
    except (AttributeError, TypeError):  # older jax
        return lax.pvary(x, axis_name)


def seq_all_to_all(
    x: jnp.ndarray,
    axis_name: str,
    scatter_axis: int,
    gather_axis: int,
    tiled: bool = True,
) -> jnp.ndarray:
    """Ulysses exchange: scatter ``scatter_axis`` over the mesh axis,
    gather ``gather_axis`` from it.

    Attention usage (inside shard_map, seq sharded per device):
    ``q,k,v: [B, S/p, H, D] -> [B, S, H/p, D]`` via
    ``seq_all_to_all(x, "seq", scatter_axis=2, gather_axis=1)`` —
    full sequence per head-group; inverse after attention.
    """
    return lax.all_to_all(
        x,
        axis_name,
        split_axis=scatter_axis,
        concat_axis=gather_axis,
        tiled=tiled,
    )


def ring_permute(x: jnp.ndarray, axis_name: str, shift: int = 1):
    """Rotate a block to the next device on the ring (ppermute); the
    building block of ring attention's KV rotation."""
    n = lax.psum(1, axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def distributed_softmax(
    logits: jnp.ndarray, axis_name: str, axis: int = -1
) -> jnp.ndarray:
    """Softmax over an axis that is sharded across ``axis_name``:
    global max (pmax) then global sum (psum) — numerically identical to
    a softmax over the gathered axis (reference ``DistributedSoftmax``).
    """
    local_max = jnp.max(logits, axis=axis, keepdims=True)
    global_max = lax.pmax(local_max, axis_name)
    unnorm = jnp.exp(logits - global_max)
    denom = lax.psum(
        jnp.sum(unnorm, axis=axis, keepdims=True), axis_name
    )
    return unnorm / denom


def expert_all_to_all(
    x: jnp.ndarray, axis_name: str, split_axis: int = 0, concat_axis: int = 0
):
    """MoE dispatch/combine exchange over the expert mesh axis."""
    return lax.all_to_all(
        x,
        axis_name,
        split_axis=split_axis,
        concat_axis=concat_axis,
        tiled=True,
    )


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    inner_attention: Optional[callable] = None,
    causal: bool = True,
) -> jnp.ndarray:
    """Ulysses sequence parallelism (inside shard_map): exchange the
    sharded seq dim for the head dim around any attention kernel.

    q,k,v ``[B, S/p, H, D]`` -> attention sees ``[B, S, H/p, D]``
    (full sequence, head subset) -> output back to ``[B, S/p, H, D]``.
    Reference: ``SequenceParallelOptimization`` + ``_SeqAllToAll``
    (``distributed/distributed.py:474``).
    """
    if inner_attention is None:
        from dlrover_tpu.models.llama import dot_product_attention

        inner_attention = dot_product_attention
    q, k, v = (
        seq_all_to_all(x, axis_name, scatter_axis=2, gather_axis=1)
        for x in (q, k, v)
    )
    out = inner_attention(q, k, v, causal=causal)
    return seq_all_to_all(out, axis_name, scatter_axis=1, gather_axis=2)


def grad_sync(grads, axis_names):
    """Mean-reduce gradients over the given data-flavored axes — what
    DDP's bucketed allreduce becomes (a single pmean per leaf; XLA
    fuses and schedules them)."""
    if not axis_names:
        return grads
    return jax.tree_util.tree_map(
        lambda g: lax.pmean(g, axis_names), grads
    )


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    causal: bool = False,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Blockwise ring attention over a sequence-sharded mesh axis.

    Reference parity: ``DistributedSelfAttention``
    (``distributed_attention.py:79``) — the reference all-gathers Q in
    micro-chunks and reduce-scatters the context; the TPU-idiomatic
    dual keeps Q resident and rotates the KV shard around the ring with
    ``ppermute`` (one hop per step, overlapping compute), carrying
    running max/sum statistics so the softmax is exact (flash-attention
    style log-sum-exp accumulation).

    Shapes (inside shard_map): q ``[B, S/p, H, D]``, k/v
    ``[B, S/p, KV, D]`` with KV dividing H (GQA: each KV head serves
    ``H/KV`` query heads); returns the context for the local Q chunk
    ``[B, S/p, H, D]``.

    ``causal`` masking uses the ring step to decide whole-block
    visibility: block j attends block i only when i <= j (diagonal
    blocks use the intra-block triangular mask).
    """
    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    if scale is None:
        scale = q.shape[-1] ** -0.5
    q = q * scale

    b, s, h, d = q.shape
    kv_heads = k.shape[2]
    g = h // kv_heads
    qg = q.reshape(b, s, kv_heads, g, d)

    neg_inf = jnp.finfo(jnp.float32).max * -1.0

    def block(carry, step):
        kc, vc, acc, m, denom = carry
        # after `step` rotations (shift=+1) the chunk we hold
        # originated `step` positions behind us on the ring
        src_idx = (my_idx - step) % n
        logits = jnp.einsum(
            "bqkgd,bxkd->bkgqx", qg, kc,
            preferred_element_type=jnp.float32,
        ).astype(jnp.float32)  # [b,kv,g,q,x]
        if causal:
            q_pos = my_idx * s + jnp.arange(s)
            k_pos = src_idx * s + jnp.arange(s)
            mask = q_pos[:, None] >= k_pos[None, :]
            logits = jnp.where(mask[None, None, None], logits, neg_inf)
        new_m = jnp.maximum(m, jnp.max(logits, axis=-1, keepdims=True))
        correction = jnp.exp(m - new_m)
        p = jnp.exp(logits - new_m)
        acc = acc * correction + jnp.einsum(
            "bkgqx,bxkd->bkgqd", p, vc.astype(jnp.float32)
        )
        denom = denom * correction + jnp.sum(p, axis=-1, keepdims=True)
        # rotate KV to the next ring position
        kc = ring_permute(kc, axis_name)
        vc = ring_permute(vc, axis_name)
        return (kc, vc, acc, new_m, denom), None

    acc0 = device_varying(
        jnp.zeros((b, kv_heads, g, s, d), dtype=jnp.float32), axis_name
    )
    m0 = device_varying(
        jnp.full((b, kv_heads, g, s, 1), neg_inf, dtype=jnp.float32),
        axis_name,
    )
    den0 = device_varying(
        jnp.zeros((b, kv_heads, g, s, 1), dtype=jnp.float32), axis_name
    )
    (kc, vc, acc, m, denom), _ = lax.scan(
        block, (k, v, acc0, m0, den0), jnp.arange(n)
    )
    out = (acc / denom).transpose(0, 3, 1, 2, 4).reshape(b, s, h, d)
    return out.astype(q.dtype)
