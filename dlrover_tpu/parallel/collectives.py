"""Sequence/expert-parallel collectives for use inside ``shard_map``.

Reference parity:

- Ulysses all-to-all: ``atorch/atorch/distributed/distributed.py:474``
  (``_SeqAllToAll`` autograd: scatter_idx/gather_idx exchange) and
  ``seq_all_to_all:500``.  Here it is a single ``lax.all_to_all`` whose
  transpose rule gives the backward pass for free — no custom autograd.
- Ring primitives: the micro-Q all-gather ring of
  ``modules/distributed_transformer/commu_utils.py`` becomes
  ``lax.ppermute`` rotation (the idiomatic ICI ring).
- Distributed softmax: ``distributed_attention.py:21``
  (``DistributedSoftmax``: global max+sum via allreduce over the
  sharded sequence) becomes two ``psum``/``pmax`` calls.
- Expert dispatch: ``modules/moe/moe_layer.py:87`` (``_AllToAll``)
  becomes ``lax.all_to_all`` over the "expert" axis.
"""

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def device_varying(x, axis_name):
    """Mark a freshly-created array as device-varying over ``axis_name``
    (shard_map vma typing for scan carries)."""
    try:
        return lax.pcast(x, axis_name, to="varying")
    except (AttributeError, TypeError):  # older jax
        pass
    try:
        return lax.pvary(x, axis_name)
    except AttributeError:
        # pre-vma jax (<=0.4.x): replication typing does not exist,
        # the array is already usable as a manual-region carry
        return x


def seq_all_to_all(
    x: jnp.ndarray,
    axis_name: str,
    scatter_axis: int,
    gather_axis: int,
    tiled: bool = True,
) -> jnp.ndarray:
    """Ulysses exchange: scatter ``scatter_axis`` over the mesh axis,
    gather ``gather_axis`` from it.

    Attention usage (inside shard_map, seq sharded per device):
    ``q,k,v: [B, S/p, H, D] -> [B, S, H/p, D]`` via
    ``seq_all_to_all(x, "seq", scatter_axis=2, gather_axis=1)`` —
    full sequence per head-group; inverse after attention.
    """
    return lax.all_to_all(
        x,
        axis_name,
        split_axis=scatter_axis,
        concat_axis=gather_axis,
        tiled=tiled,
    )


def ring_permute(x: jnp.ndarray, axis_name: str, shift: int = 1):
    """Rotate a block to the next device on the ring (ppermute); the
    building block of ring attention's KV rotation."""
    n = lax.psum(1, axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def distributed_softmax(
    logits: jnp.ndarray, axis_name: str, axis: int = -1
) -> jnp.ndarray:
    """Softmax over an axis that is sharded across ``axis_name``:
    global max (pmax) then global sum (psum) — numerically identical to
    a softmax over the gathered axis (reference ``DistributedSoftmax``).
    """
    local_max = jnp.max(logits, axis=axis, keepdims=True)
    global_max = lax.pmax(local_max, axis_name)
    unnorm = jnp.exp(logits - global_max)
    denom = lax.psum(
        jnp.sum(unnorm, axis=axis, keepdims=True), axis_name
    )
    return unnorm / denom


def expert_all_to_all(
    x: jnp.ndarray, axis_name: str, split_axis: int = 0, concat_axis: int = 0
):
    """MoE dispatch/combine exchange over the expert mesh axis."""
    return lax.all_to_all(
        x,
        axis_name,
        split_axis=split_axis,
        concat_axis=concat_axis,
        tiled=True,
    )


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    inner_attention: Optional[callable] = None,
    causal: bool = True,
) -> jnp.ndarray:
    """Ulysses sequence parallelism (inside shard_map): exchange the
    sharded seq dim for the head dim around any attention kernel.

    q,k,v ``[B, S/p, H, D]`` -> attention sees ``[B, S, H/p, D]``
    (full sequence, head subset) -> output back to ``[B, S/p, H, D]``.
    Reference: ``SequenceParallelOptimization`` + ``_SeqAllToAll``
    (``distributed/distributed.py:474``).
    """
    if inner_attention is None:
        from dlrover_tpu.models.llama import dot_product_attention

        inner_attention = dot_product_attention
    q, k, v = (
        seq_all_to_all(x, axis_name, scatter_axis=2, gather_axis=1)
        for x in (q, k, v)
    )
    out = inner_attention(q, k, v, causal=causal)
    return seq_all_to_all(out, axis_name, scatter_axis=1, gather_axis=2)


def grad_sync(grads, axis_names):
    """Mean-reduce gradients over the given data-flavored axes — what
    DDP's bucketed allreduce becomes (a single pmean per leaf; XLA
    fuses and schedules them)."""
    if not axis_names:
        return grads
    return jax.tree_util.tree_map(
        lambda g: lax.pmean(g, axis_names), grads
    )


def _dense_block_lse(q, k, v, causal: bool, scale: float):
    """Dense (out, lse) for one KV block — the ring's inner kernel when
    flash attention is disabled (DLROVER_TPU_FLASH_ATTENTION=0).
    q [B,S,H,D], k/v [B,X,KV,D]; lse [B,S,H]."""
    b, s, h, d = q.shape
    kv = k.shape[2]
    g = h // kv
    qg = q.reshape(b, s, kv, g, d)
    logits = (
        jnp.einsum(
            "bqkgd,bxkd->bqkgx", qg, k,
            preferred_element_type=jnp.float32,
        ).astype(jnp.float32)
        * scale
    )
    if causal:
        x = k.shape[1]
        mask = jnp.arange(s)[:, None] >= jnp.arange(x)[None, :]
        logits = jnp.where(
            mask[None, :, None, None], logits,
            jnp.finfo(jnp.float32).max * -1.0,
        )
    lse = jax.scipy.special.logsumexp(logits, axis=-1)  # [b,s,kv,g]
    p = jnp.exp(logits - lse[..., None])
    out = jnp.einsum(
        "bqkgx,bxkd->bqkgd", p, v.astype(jnp.float32)
    )
    return (
        out.reshape(b, s, h, d).astype(q.dtype),
        lse.reshape(b, s, h),
    )


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    causal: bool = False,
    scale: Optional[float] = None,
    use_flash: Optional[bool] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
) -> jnp.ndarray:
    """Blockwise ring attention over a sequence-sharded mesh axis.

    Reference parity: ``DistributedSelfAttention``
    (``distributed_attention.py:79``) — the reference all-gathers Q in
    micro-chunks and reduce-scatters the context; the TPU-idiomatic
    dual keeps Q resident and rotates the KV shard around the ring with
    ``ppermute`` (one hop per step, overlapping compute), merging each
    block's contribution with log-sum-exp statistics so the softmax is
    exact.

    The per-block computation is the Pallas flash-attention kernel
    (``flash_attention_lse`` — its lse output is exactly the residual
    the merge needs); under ``causal``, blocks strictly above the
    diagonal are skipped entirely (no QK^T, no PV — ~2x FLOPs saved),
    the diagonal block runs the kernel's internal triangular mask, and
    blocks below run unmasked.

    Shapes (inside shard_map): q ``[B, S/p, H, D]``, k/v
    ``[B, S/p, KV, D]`` with KV dividing H (GQA handled inside the
    kernel); returns the context for the local Q chunk.
    """
    from dlrover_tpu.ops.flash_attention import flash_attention_lse

    if use_flash is None:
        from dlrover_tpu.accelerate.module_replace import _flash_enabled

        use_flash = _flash_enabled(None)

    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    if scale is None:
        scale = q.shape[-1] ** -0.5

    b, s, h, d = q.shape
    neg_inf = jnp.finfo(jnp.float32).max * -1.0

    def inner(qq, kc, vc, causal_):
        if use_flash:
            return flash_attention_lse(
                qq, kc, vc, causal=causal_, sm_scale=scale,
                block_q=block_q, block_k=block_k,
            )
        return _dense_block_lse(qq, kc, vc, causal_, scale)

    def full_block(kv_pair):
        kc, vc = kv_pair
        return inner(q, kc, vc, False)

    def diag_block(kv_pair):
        kc, vc = kv_pair
        return inner(q, kc, vc, True)

    def skip_block(kv_pair):
        # invisible under causal: contributes nothing (lse = -inf)
        return (
            jnp.zeros((b, s, h, d), dtype=q.dtype),
            jnp.full((b, s, h), neg_inf, dtype=jnp.float32),
        )

    def block(carry, step):
        kc, vc, acc, m_run, den = carry
        # after `step` rotations (shift=+1) the chunk we hold
        # originated `step` positions behind us on the ring
        src_idx = (my_idx - step) % n
        if causal:
            # whole-block visibility by ring position: src > my is
            # strictly above the diagonal
            branch = jnp.where(
                src_idx > my_idx, 0, jnp.where(src_idx < my_idx, 1, 2)
            )
            out_i, lse_i = lax.switch(
                branch, [skip_block, full_block, diag_block], (kc, vc)
            )
        else:
            out_i, lse_i = full_block((kc, vc))
        # online merge of normalized block outputs via lse
        m_new = jnp.maximum(m_run, lse_i)
        alpha = jnp.exp(m_run - m_new)[..., None]
        beta = jnp.exp(lse_i - m_new)[..., None]
        acc = acc * alpha + out_i.astype(jnp.float32) * beta
        den = den * alpha[..., 0] + beta[..., 0]
        # rotate KV to the next ring position
        kc = ring_permute(kc, axis_name)
        vc = ring_permute(vc, axis_name)
        return (kc, vc, acc, m_new, den), None

    acc0 = device_varying(
        jnp.zeros((b, s, h, d), dtype=jnp.float32), axis_name
    )
    m0 = device_varying(
        jnp.full((b, s, h), neg_inf, dtype=jnp.float32), axis_name
    )
    den0 = device_varying(
        jnp.zeros((b, s, h), dtype=jnp.float32), axis_name
    )
    (kc, vc, acc, m_run, den), _ = lax.scan(
        block, (k, v, acc0, m0, den0), jnp.arange(n)
    )
    return (acc / den[..., None]).astype(q.dtype)
