"""Logical-axis sharding rules — the strategy engine's output format.

Reference parity: the role of atorch's opt_lib transforms
(``zero_optimization.py:115,240`` ZeRO/FSDP,
``tensor_parallel_optimization.py:23`` TP module replacement,
``mixed_parallel_optimization.py:57``): deciding *how each tensor is
laid out across the cluster*.  In the reference that is a module
rewrite + process-group plumbing; on TPU it is a table mapping
**logical array axes** ("embed", "heads", "mlp", ...) to **mesh axes**,
compiled by GSPMD into collectives.  Strategies differ only in the
table:

- DDP        -> params replicated, batch over ("data","fsdp")
- ZeRO-3/FSDP-> params sharded on "fsdp" along their largest dim
- TP         -> Megatron-style: qkv/mlp-in column, proj/mlp-out row
- SP/EP      -> sequence/expert dims on "seq"/"expert"

so "auto_accelerate" becomes: pick a rule table, shard_pytree, jit.
"""

import contextlib
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from dlrover_tpu.parallel.mesh import AxisName

def shard_map_compat(fn, mesh, in_specs, out_specs,
                     manual_axes=None, check=False):
    """``shard_map`` across jax versions.

    The modern API (``jax.shard_map`` with ``axis_names``/
    ``check_vma``) when present; ``jax.experimental.shard_map``
    (``auto``/``check_rep``) otherwise.  ``manual_axes``: the mesh
    axes the body handles manually (None = all of them)."""
    import jax

    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        kw = dict(
            mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check,
        )
        if manual_axes is not None:
            kw["axis_names"] = set(manual_axes)
        return sm(fn, **kw)
    from jax.experimental.shard_map import shard_map as legacy_sm

    kw = dict(
        mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check,
    )
    if manual_axes is not None:
        auto = frozenset(mesh.axis_names) - set(manual_axes)
        if auto:
            kw["auto"] = auto
    return legacy_sm(fn, **kw)


# logical axis vocabulary used by model definitions
BATCH = "batch"
SEQ = "seq_len"
EMBED = "embed"
MLP = "mlp"
HEADS = "heads"
KV_HEADS = "kv_heads"
HEAD_DIM = "head_dim"
VOCAB = "vocab"
EXPERT = "expert"
LAYERS = "layers"


class LogicalAxisRules:
    """Ordered mapping logical-axis -> mesh axis (or tuple of axes).

    First match wins; unlisted logical axes are replicated (None).
    """

    def __init__(self, rules: Sequence[Tuple[str, Optional[object]]]):
        self._rules: List[Tuple[str, Optional[object]]] = list(rules)

    def mesh_axes(self, logical: Optional[str]):
        if logical is None:
            return None
        for name, axes in self._rules:
            if name == logical:
                return axes
        return None

    def spec(self, logical_axes: Sequence[Optional[str]]):
        """PartitionSpec from a tuple of logical axis names."""
        from jax.sharding import PartitionSpec

        used = set()
        entries = []
        for ax in logical_axes:
            target = self.mesh_axes(ax)
            # a mesh axis may appear at most once in a spec
            if target is None:
                entries.append(None)
                continue
            flat = target if isinstance(target, tuple) else (target,)
            if any(a in used for a in flat):
                entries.append(None)
                continue
            used.update(flat)
            entries.append(target)
        return PartitionSpec(*entries)

    def extend(self, extra: Sequence[Tuple[str, Optional[object]]]):
        return LogicalAxisRules(list(extra) + self._rules)

    def uses_axis(self, mesh_axis: str,
                  exclude: Sequence[str] = (BATCH,)) -> bool:
        """True when some rule (outside ``exclude``) targets
        ``mesh_axis`` — i.e. the strategy actively shards params over
        it (BATCH is excluded by default: it always carries the data
        axes for activations regardless of the param strategy)."""
        for name, axes in self._rules:
            if name in exclude:
                continue
            flat = axes if isinstance(axes, tuple) else (axes,)
            if mesh_axis in flat:
                return True
        return False


def default_rules(
    fsdp: bool = True,
    tensor_parallel: bool = False,
    sequence_parallel: bool = False,
    expert_parallel: bool = False,
    pipeline: bool = False,
) -> LogicalAxisRules:
    """The canonical rule tables (strategy selection in one place)."""
    rules: List[Tuple[str, Optional[object]]] = [
        # batch is always sharded over every data-flavored axis
        (BATCH, (AxisName.DATA, AxisName.FSDP)),
    ]
    if pipeline:
        # stacked layer dim becomes the stage dim; the layer executor
        # (module_replace.select_layer_executor) runs the GPipe
        # shard_map over it
        rules.append((LAYERS, AxisName.PIPELINE))
    if sequence_parallel:
        rules.append((SEQ, AxisName.SEQUENCE))
    if tensor_parallel:
        rules += [
            (HEADS, AxisName.TENSOR),
            (KV_HEADS, AxisName.TENSOR),
            (MLP, AxisName.TENSOR),
            (VOCAB, AxisName.TENSOR),
        ]
    if expert_parallel:
        rules.append((EXPERT, AxisName.EXPERT))
    if fsdp:
        # ZeRO-3: shard the big parameter dim over the fsdp axis
        rules.append((EMBED, AxisName.FSDP))
    return LogicalAxisRules(rules)


_scope = threading.local()


@contextlib.contextmanager
def rules_scope(rules: "LogicalAxisRules"):
    """Bind the active rule table for the duration of a trace.

    ``build_train_step`` wraps its loss invocation in this scope so the
    activation constraints a model emits are resolved against the same
    table that sharded its params — captured at trace time, immune to
    later builds mutating shared context (two train steps built against
    different strategies each bake in their own rules)."""
    stack = getattr(_scope, "stack", None)
    if stack is None:
        stack = _scope.stack = []
    stack.append(rules)
    try:
        yield rules
    finally:
        stack.pop()


def active_rules() -> Optional["LogicalAxisRules"]:
    stack = getattr(_scope, "stack", None)
    return stack[-1] if stack else None


def filter_spec_for_mesh(spec, mesh):
    """Drop spec entries referencing axes the mesh doesn't have (a rule
    table is strategy-global; the mesh picks which axes exist)."""
    from jax.sharding import PartitionSpec

    mesh_axes = set(mesh.axis_names)
    entries = []
    for e in spec:
        flat = e if isinstance(e, tuple) else (e,)
        if e is None or all(a in mesh_axes for a in flat):
            entries.append(e)
        else:
            present = tuple(a for a in flat if a in mesh_axes)
            entries.append(
                present if len(present) > 1
                else (present[0] if present else None)
            )
    return PartitionSpec(*entries)


def logical_sharding(mesh, rules: LogicalAxisRules, logical_axes):
    from jax.sharding import NamedSharding

    return NamedSharding(
        mesh, filter_spec_for_mesh(rules.spec(logical_axes), mesh)
    )


def param_sharding_with_fsdp(
    mesh,
    rules: LogicalAxisRules,
    logical_axes,
    shape,
    fsdp_axis: str = AxisName.FSDP,
):
    """Parameter sharding with shape-aware ZeRO-3 placement.

    The rule table maps logical axes to mesh axes; on top of that, the
    fsdp axis is placed on the param's LARGEST still-unsharded,
    divisible dim (reference ``zero_optimization.py:240`` FSDP shards
    the flattened param; the GSPMD dual is choosing the dim so every
    parameter — not only those carrying a designated logical axis —
    shards over fsdp, and the all-gather rides the biggest dim).
    """
    from jax.sharding import NamedSharding, PartitionSpec

    spec = filter_spec_for_mesh(rules.spec(logical_axes), mesh)
    mesh_axes = dict(zip(mesh.axis_names, mesh.devices.shape))
    fsdp_size = mesh_axes.get(fsdp_axis, 1)
    if fsdp_size <= 1:
        return NamedSharding(mesh, spec)
    used = set()
    for e in spec:
        for a in e if isinstance(e, tuple) else (e,):
            if a is not None:
                used.add(a)
    if fsdp_axis in used:
        return NamedSharding(mesh, spec)
    # candidate dims: unsharded, divisible by the fsdp size; biggest wins
    candidates = [
        (dim_size, i)
        for i, (dim_size, e) in enumerate(zip(shape, spec))
        if e is None and dim_size % fsdp_size == 0 and dim_size > 1
    ]
    if not candidates:
        return NamedSharding(mesh, spec)
    _, dim = max(candidates)
    entries = list(spec)
    entries[dim] = fsdp_axis
    return NamedSharding(mesh, PartitionSpec(*entries))


def shard_pytree(pytree, axes_pytree, mesh, rules: LogicalAxisRules):
    """Produce a NamedSharding pytree from a logical-axes pytree with
    the same structure (the model exports the latter)."""
    import jax

    return jax.tree_util.tree_map(
        lambda axes: logical_sharding(mesh, rules, axes),
        axes_pytree,
        is_leaf=lambda x: isinstance(x, (tuple, type(None))),
    )


def apply_sharding_constraint(x, logical_axes, rules: LogicalAxisRules):
    """In-graph activation-sharding constraint; a no-op when no global
    mesh is set (eager debugging / single device).

    Inside a partial-manual ``shard_map`` region (the GPipe layer
    executor runs the stage body with the "pipe" axis manual) the
    constraint must be expressed against the ambient abstract mesh —
    a NamedSharding over the outer all-Auto mesh trips a mesh-type
    mismatch — with the manual axes dropped from the spec (the array
    is already per-device along them)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from dlrover_tpu.parallel.mesh import get_mesh_context

    ctx = get_mesh_context()
    if ctx is None:
        return x
    spec = filter_spec_for_mesh(rules.spec(logical_axes), ctx.mesh)
    try:
        amesh = jax.sharding.get_abstract_mesh()
        manual = {
            name
            for name, t in zip(amesh.axis_names, amesh.axis_types)
            if "Manual" in str(t)
        }
    except Exception:  # noqa: BLE001
        amesh, manual = None, set()
    if manual:
        entries = []
        for e in spec:
            flat = e if isinstance(e, tuple) else (e,)
            keep = tuple(
                a for a in flat if a is not None and a not in manual
            )
            entries.append(
                keep if len(keep) > 1 else (keep[0] if keep else None)
            )
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(amesh, PartitionSpec(*entries))
        )
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(ctx.mesh, spec)
    )
