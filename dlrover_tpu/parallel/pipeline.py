"""Pipeline parallelism without RPC: collective-permute microbatching.

Reference parity: ``atorch/atorch/auto/opt_lib/
pipeline_parallel_optimization.py:56`` (PiPPy graph-split pipeline over
an RPC mesh, ``distributed/distributed.py:504``).  PiPPy's RPC design
has no JAX analog (SURVEY.md §7 hard parts); the TPU-native form is
GPipe-style SPMD: every pipeline stage is one slice of a "pipe" mesh
axis, microbatch activations hop stage-to-stage with ``lax.ppermute``
inside a ``lax.scan`` over clock ticks, and autodiff through the
scan+ppermute yields the 1F1B-equivalent backward schedule for free.

The model contributes a single ``stage_fn(stage_params, x)``; stage
params live stacked on a leading "layers/stage" dim sharded over the
"pipe" axis, so the same jitted program runs on every stage (SPMD, no
per-stage programs to compile).
"""

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_spmd(
    stage_fn: Callable,
    stage_params,
    microbatches: jnp.ndarray,
    axis_name: str = "pipe",
):
    """Run ``microbatches`` through the pipeline; call inside
    ``shard_map`` over the "pipe" axis.

    Args:
      stage_fn: ``(stage_params, x) -> y`` — this stage's chunk of
        layers; activations keep one shape across stages.
      stage_params: the local stage's params (already sharded).
      microbatches: ``[M, mb, ...]`` — the full microbatch stream
        (present on all stages; only stage 0 reads it).

    Returns ``[M, mb, ...]`` outputs (valid on every stage after the
    final psum-broadcast).
    """
    n_stages = lax.psum(1, axis_name)
    stage_idx = lax.axis_index(axis_name)
    num_mb = microbatches.shape[0]
    total_ticks = num_mb + n_stages - 1

    # send to next stage only (no wraparound; missing sources give 0)
    fwd_perm_fn = lambda n: [(i, i + 1) for i in range(n - 1)]  # noqa: E731

    act_shape = microbatches.shape[1:]
    out_buf = jnp.zeros(
        (num_mb,) + act_shape, dtype=microbatches.dtype
    )

    def tick(carry, t):
        incoming, out_buf = carry
        # stage 0 ingests microbatch t while the stream lasts
        mb_idx = jnp.clip(t, 0, num_mb - 1)
        ingest = microbatches[mb_idx]
        x = jnp.where(stage_idx == 0, ingest, incoming)
        y = stage_fn(stage_params, x)
        # the microbatch this stage just finished is (t - stage_idx);
        # drop ticks where this stage was idle (bubble)
        done_idx = t - stage_idx
        valid = jnp.logical_and(done_idx >= 0, done_idx < num_mb)
        is_last = stage_idx == n_stages - 1
        out_buf = lax.cond(
            jnp.logical_and(valid, is_last),
            lambda b: b.at[jnp.clip(done_idx, 0, num_mb - 1)].set(y),
            lambda b: b,
            out_buf,
        )
        nxt = lax.ppermute(
            y, axis_name, fwd_perm_fn(n_stages)
        )
        return (nxt, out_buf), None

    from dlrover_tpu.parallel.collectives import device_varying

    incoming0 = device_varying(
        jnp.zeros(act_shape, dtype=microbatches.dtype), axis_name
    )
    out_buf = device_varying(out_buf, axis_name)
    (_, out_buf), _ = lax.scan(
        tick, (incoming0, out_buf), jnp.arange(total_ticks)
    )
    # only the last stage holds real outputs; broadcast over the axis.
    # f32 for the collective: a bf16 psum under partial-manual
    # shard_map trips an XLA CPU float-normalization bug ("Invalid
    # binary instruction opcode copy"); the cast costs one convert on
    # a buffer that crosses the network anyway
    return lax.psum(
        out_buf.astype(jnp.float32), axis_name
    ).astype(microbatches.dtype)


def split_microbatches(batch, num_microbatches: int):
    """[B, ...] -> [M, B/M, ...] pytree-wise."""

    def _split(x):
        b = x.shape[0]
        if b % num_microbatches != 0:
            raise ValueError(
                f"batch {b} not divisible into {num_microbatches} microbatches"
            )
        return x.reshape((num_microbatches, b // num_microbatches) + x.shape[1:])

    return jax.tree_util.tree_map(_split, batch)


def merge_microbatches(stream):
    """[M, mb, ...] -> [M*mb, ...] pytree-wise."""

    def _merge(x):
        return x.reshape((x.shape[0] * x.shape[1],) + x.shape[2:])

    return jax.tree_util.tree_map(_merge, stream)


def stack_stage_params(per_stage_params):
    """List of per-stage param pytrees -> stacked pytree with a leading
    stage dim (shard it on "pipe")."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *per_stage_params
    )
