"""Pipeline parallelism without RPC: collective-permute microbatching.

Reference parity: ``atorch/atorch/auto/opt_lib/
pipeline_parallel_optimization.py:56`` (PiPPy graph-split pipeline over
an RPC mesh, ``distributed/distributed.py:504``).  PiPPy's RPC design
has no JAX analog (SURVEY.md §7 hard parts); the TPU-native form is
GPipe-style SPMD: every pipeline stage is one slice of a "pipe" mesh
axis, microbatch activations hop stage-to-stage with ``lax.ppermute``
inside a ``lax.scan`` over clock ticks, and autodiff through the
scan+ppermute yields the 1F1B-equivalent backward schedule for free.

The model contributes a single ``stage_fn(stage_params, x)``; stage
params live stacked on a leading "layers/stage" dim sharded over the
"pipe" axis, so the same jitted program runs on every stage (SPMD, no
per-stage programs to compile).
"""

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_spmd(
    stage_fn: Callable,
    stage_params,
    microbatches: jnp.ndarray,
    axis_name: str = "pipe",
    schedule: str = "chunked",
):
    """Run ``microbatches`` through the pipeline; call inside
    ``shard_map`` over the "pipe" axis.

    Args:
      stage_fn: ``(stage_params, x) -> y`` — this stage's chunk of
        layers; activations keep one shape across stages.
      stage_params: the local stage's params (already sharded).
      microbatches: ``[M, mb, ...]`` — the full microbatch stream
        (present on all stages; only stage 0 reads it — the stream
        is one boundary activation per microbatch, small next to the
        layer residuals the schedule bounds).
      schedule: ``"chunked"`` (default) bounds backward residency to
        ~``n_stages`` microbatches; ``"gpipe"`` is the naive scan
        whose autodiff stores every tick's stage intermediates —
        kept for the residency-accounting test and as a remat-free
        fallback.

    Returns ``[M, mb, ...]`` outputs (valid on every stage after the
    final psum-broadcast).

    Memory discipline (VERDICT-r4 weak #6): autodiff of a plain
    tick-scan saves each of the ``M + S - 1`` ticks' stage
    intermediates — activation memory grows with the microbatch
    COUNT, which defeats the point of microbatching.  The chunked
    schedule is the 1F1B-equivalent residency bound in functional
    form: the tick scan is nested inside an outer scan over chunks
    of ``S`` ticks whose body is ``jax.checkpoint``-ed, so forward
    saves only one boundary activation per chunk and backward
    recomputes one chunk at a time — at any moment at most ~``S``
    microbatches of stage intermediates are live, like 1F1B's
    in-flight window (ref: the DeepSpeed 3D schedule the reference
    adopts, ``atorch/atorch/auto/opt_lib/
    ds_3d_parallel_optimization.py:184``).
    """
    import functools

    n_stages = lax.psum(1, axis_name)
    stage_idx = lax.axis_index(axis_name)
    num_mb = microbatches.shape[0]
    total_ticks = num_mb + n_stages - 1

    # send to next stage only (no wraparound; missing sources give 0)
    fwd_perm = [(i, i + 1) for i in range(n_stages - 1)]
    act_shape = microbatches.shape[1:]

    def tick(incoming, t):
        # stage 0 ingests microbatch t while the stream lasts
        mb_idx = jnp.clip(t, 0, num_mb - 1)
        x = jnp.where(stage_idx == 0, microbatches[mb_idx], incoming)
        y = stage_fn(stage_params, x)
        nxt = lax.ppermute(y, axis_name, fwd_perm)
        return nxt, y

    from dlrover_tpu.parallel.collectives import device_varying

    incoming0 = device_varying(
        jnp.zeros(act_shape, dtype=microbatches.dtype), axis_name
    )

    if schedule == "gpipe":
        _, ys = lax.scan(tick, incoming0, jnp.arange(total_ticks))
    elif schedule == "chunked":
        chunk = max(int(n_stages), 1)
        n_chunks = -(-total_ticks // chunk)
        ts = jnp.arange(n_chunks * chunk).reshape(n_chunks, chunk)

        @functools.partial(jax.checkpoint, prevent_cse=False)
        def chunk_body(carry, ts_chunk):
            return lax.scan(tick, carry, ts_chunk)

        _, ys = lax.scan(chunk_body, incoming0, ts)
        # [C, S, ...] -> [C*S, ...]; padding ticks (< S-1 of them)
        # ran on stale data and are sliced away below
        ys = ys.reshape((n_chunks * chunk,) + ys.shape[2:])
    else:
        raise ValueError(f"unknown pipeline schedule {schedule!r}")

    # the last stage finished microbatch m at tick m + (S-1); the
    # other stages' ys rows are mid-pipeline activations — masked out
    # before the broadcast
    outs = lax.slice_in_dim(ys, n_stages - 1, n_stages - 1 + num_mb)
    outs = jnp.where(stage_idx == n_stages - 1, outs, 0)
    # f32 for the collective: a bf16 psum under partial-manual
    # shard_map trips an XLA CPU float-normalization bug ("Invalid
    # binary instruction opcode copy"); the cast costs one convert on
    # a buffer that crosses the network anyway
    return lax.psum(
        outs.astype(jnp.float32), axis_name
    ).astype(microbatches.dtype)


def split_microbatches(batch, num_microbatches: int):
    """[B, ...] -> [M, B/M, ...] pytree-wise."""

    def _split(x):
        b = x.shape[0]
        if b % num_microbatches != 0:
            raise ValueError(
                f"batch {b} not divisible into {num_microbatches} microbatches"
            )
        return x.reshape((num_microbatches, b // num_microbatches) + x.shape[1:])

    return jax.tree_util.tree_map(_split, batch)


def merge_microbatches(stream):
    """[M, mb, ...] -> [M*mb, ...] pytree-wise."""

    def _merge(x):
        return x.reshape((x.shape[0] * x.shape[1],) + x.shape[2:])

    return jax.tree_util.tree_map(_merge, stream)


def stack_stage_params(per_stage_params):
    """List of per-stage param pytrees -> stacked pytree with a leading
    stage dim (shard it on "pipe")."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *per_stage_params
    )
