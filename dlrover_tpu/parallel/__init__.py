from dlrover_tpu.parallel.mesh import (  # noqa: F401
    AxisName,
    MeshContext,
    create_hybrid_parallel_mesh,
    create_parallel_mesh,
    destroy_parallel_mesh,
    get_mesh,
    get_mesh_context,
)
from dlrover_tpu.parallel.sharding import (  # noqa: F401
    LogicalAxisRules,
    default_rules,
    logical_sharding,
    shard_pytree,
)
