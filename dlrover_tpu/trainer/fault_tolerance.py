"""In-trainer fault-tolerance utilities.

Reference parity:
- ``HangingDetector`` (``atorch/atorch/fault_tolerance/
  hanging_detector.py:86``): a side thread watches step progress and
  triggers a relaunch RPC when stuck.
- loss-spike capture (``atorch/atorch/utils/loss_spike_utils.py``):
  record batches around abnormal losses for offline repro.
- numeric checker (``atorch/atorch/utils/numberic_checker.py``): drift
  detection between runs/layouts — here a cross-host step hash check,
  the deterministic-replay gap called out in SURVEY.md §5.2.
"""

import hashlib
import json
import os
import threading
import time
from typing import Callable, List, Optional

import numpy as np

from dlrover_tpu.common.log import default_logger as logger


class HangDetector:
    """Watches a monotonically-increasing step counter from a side
    thread; fires ``on_hang`` when no progress within ``timeout``."""

    def __init__(
        self,
        timeout: float = 1800.0,
        check_interval: float = 30.0,
        on_hang: Optional[Callable[[], None]] = None,
    ):
        self._timeout = timeout
        self._interval = check_interval
        self._on_hang = on_hang
        self._last_step = -1
        self._last_progress = time.time()
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.hang_detected = False

    def report_step(self, step: int):
        if step > self._last_step:
            self._last_step = step
            self._last_progress = time.time()
            self.hang_detected = False

    def _loop(self):
        while not self._stopped.wait(self._interval):
            stalled = time.time() - self._last_progress
            if self._last_step >= 0 and stalled > self._timeout:
                self.hang_detected = True
                logger.error(
                    "hang: no step progress for %.0fs (step %d)",
                    stalled,
                    self._last_step,
                )
                if self._on_hang is not None:
                    self._on_hang()
                self._last_progress = time.time()  # don't refire hot

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="hang-detector", daemon=True
            )
            self._thread.start()

    def stop(self):
        self._stopped.set()


def default_hang_action():
    """Report a hang to the master (process restart verdict) — the
    reference's relaunch RPC."""
    from dlrover_tpu.agent.master_client import MasterClient

    try:
        client = MasterClient.singleton_instance()
        client.report_failure(
            "training hang detected", level="process_error"
        )
    except Exception as e:  # noqa: BLE001
        logger.warning("hang report failed: %s", e)


class LossSpikeCapture:
    """Records (step, loss, batch digest) around loss spikes."""

    def __init__(
        self,
        out_dir: str,
        window: int = 16,
        spike_factor: float = 3.0,
        min_history: int = 20,
    ):
        self._out_dir = out_dir
        self._window = window
        self._factor = spike_factor
        self._min_history = min_history
        self._history: List[float] = []
        os.makedirs(out_dir, exist_ok=True)

    def observe(self, step: int, loss: float, batch=None) -> bool:
        """Returns True when this step is a spike (and was captured)."""
        spiked = False
        if len(self._history) >= self._min_history:
            recent = self._history[-self._window :]
            mean = float(np.mean(recent))
            std = float(np.std(recent)) + 1e-12
            if loss > mean + self._factor * std:
                spiked = True
                self._capture(step, loss, mean, std, batch)
        self._history.append(float(loss))
        if len(self._history) > 4096:
            self._history.pop(0)
        return spiked

    def _capture(self, step, loss, mean, std, batch):
        record = {
            "step": int(step),
            "loss": float(loss),
            "window_mean": mean,
            "window_std": std,
            "timestamp": time.time(),
        }
        if batch is not None:
            import jax

            record["batch_digest"] = {
                str(path): hashlib.sha1(
                    np.asarray(leaf).tobytes()
                ).hexdigest()[:16]
                for path, leaf in jax.tree_util.tree_leaves_with_path(
                    batch
                )
            }
            np.savez(
                os.path.join(self._out_dir, f"spike_{step}.npz"),
                **{
                    f"arr_{i}": np.asarray(leaf)
                    for i, leaf in enumerate(
                        jax.tree_util.tree_leaves(batch)
                    )
                },
            )
        with open(
            os.path.join(self._out_dir, "spikes.jsonl"), "a"
        ) as f:
            f.write(json.dumps(record) + "\n")
        logger.warning("loss spike at step %s: %.4f", step, loss)


def pytree_digest(tree) -> str:
    """Deterministic digest of a pytree's values — cross-host / cross-
    layout consistency checks (DP vs FSDP must produce identical
    states; compare digests instead of shipping tensors)."""
    import jax

    h = hashlib.sha256()
    for path, leaf in jax.tree_util.tree_leaves_with_path(tree):
        h.update(str(path).encode())
        h.update(np.ascontiguousarray(jax.device_get(leaf)).tobytes())
    return h.hexdigest()


class NumericChecker:
    """Step-wise numeric drift detection between two runs."""

    def __init__(self, rtol: float = 1e-5, atol: float = 1e-6):
        self._rtol = rtol
        self._atol = atol
        self.records: List[dict] = []

    def compare_trees(self, name: str, a, b) -> bool:
        import jax

        leaves_a = jax.tree_util.tree_leaves(a)
        leaves_b = jax.tree_util.tree_leaves(b)
        if len(leaves_a) != len(leaves_b):
            self.records.append(
                {"name": name, "match": False, "reason": "structure"}
            )
            return False
        worst = 0.0
        for la, lb in zip(leaves_a, leaves_b):
            da = np.asarray(jax.device_get(la), dtype=np.float64)
            db = np.asarray(jax.device_get(lb), dtype=np.float64)
            if da.shape != db.shape:
                self.records.append(
                    {"name": name, "match": False, "reason": "shape"}
                )
                return False
            denom = np.maximum(np.abs(da), np.abs(db))
            err = np.max(
                np.abs(da - db) / np.maximum(denom, self._atol)
            ) if da.size else 0.0
            worst = max(worst, float(err))
        ok = worst <= self._rtol or np.isclose(worst, 0)
        self.records.append(
            {"name": name, "match": bool(ok), "max_rel_err": worst}
        )
        return ok
