"""Checkpointable elastic sampler — resumes mid-epoch after re-mesh.

Reference parity: ``dlrover/trainer/torch/elastic/sampler.py:25``
(``ElasticDistributedSampler``: state_dict ``:118`` / load_state_dict
``:130`` keep the consumed-sample offset so a job that restarts with a
different world size continues from the same global position).

Framework-agnostic: works over any sized dataset (only ``len`` is
needed) and yields integer indices, so it feeds numpy/grain/torch
loaders alike.
"""

from typing import Iterator, Optional

import numpy as np


class ElasticDistributedSampler:
    def __init__(
        self,
        dataset_size: int,
        num_replicas: int = 1,
        rank: int = 0,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = False,
    ):
        if rank >= num_replicas or rank < 0:
            raise ValueError(
                f"rank {rank} out of range for {num_replicas} replicas"
            )
        self.dataset_size = dataset_size
        self.num_replicas = num_replicas
        self.rank = rank
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        # samples of *this epoch* already consumed across ALL replicas
        self.completed_num = 0
        if drop_last:
            self.num_samples = dataset_size // num_replicas
        else:
            self.num_samples = (
                dataset_size + num_replicas - 1
            ) // num_replicas
        self.total_size = self.num_samples * num_replicas

    # ------------------------------------------------------------ protocol
    def set_epoch(self, epoch: int):
        self.epoch = epoch
        self.completed_num = 0

    def _global_indices(self) -> np.ndarray:
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            indices = rng.permutation(self.dataset_size)
        else:
            indices = np.arange(self.dataset_size)
        if not self.drop_last:
            pad = self.total_size - len(indices)
            if pad > 0:
                indices = np.concatenate([indices, indices[:pad]])
        return indices[: self.total_size]

    def __iter__(self) -> Iterator[int]:
        indices = self._global_indices()
        # skip what the previous incarnation already consumed, then
        # stride by the *current* replica count — the remaining work is
        # redistributed evenly over the new world
        start = self.completed_num + self.rank
        for idx in indices[start :: self.num_replicas]:
            self.completed_num += self.num_replicas
            yield int(idx)

    def __len__(self) -> int:
        remaining = self.total_size - self.completed_num
        return max(0, remaining // self.num_replicas)

    # ---------------------------------------------------------- checkpoint
    def state_dict(self) -> dict:
        return {
            "epoch": self.epoch,
            "completed_num": self.completed_num,
        }

    def load_state_dict(self, state: dict):
        self.epoch = int(state.get("epoch", 0))
        completed = int(state.get("completed_num", 0))
        # align to the new replica stride so every rank starts from the
        # same global offset
        completed -= completed % self.num_replicas
        self.completed_num = completed


class ElasticBatchIterator:
    """Batches an ``ElasticDistributedSampler`` into index arrays; the
    per-step granularity the checkpoint engine snapshots."""

    def __init__(
        self,
        sampler: ElasticDistributedSampler,
        batch_size: int,
        drop_last: bool = True,
    ):
        self.sampler = sampler
        self.batch_size = batch_size
        self.drop_last = drop_last

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield np.asarray(batch)
                batch = []
        if batch and not self.drop_last:
            yield np.asarray(batch)

    def __len__(self) -> int:
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


def state_dict_with_sampler(
    state: dict, sampler: Optional[ElasticDistributedSampler]
) -> dict:
    """Attach dataset position to a checkpoint state dict (reference
    checkpoints the sampler alongside the model — SURVEY.md §5.4)."""
    if sampler is not None:
        state = dict(state)
        state["_sampler"] = sampler.state_dict()
    return state


def restore_sampler_from_state(
    state: dict, sampler: Optional[ElasticDistributedSampler]
):
    if sampler is not None and isinstance(state, dict) and "_sampler" in state:
        sampler.load_state_dict(state["_sampler"])
