"""Training-process bootstrap: ``jax.distributed`` from the agent's env.

Reference parity: the torch side reads MASTER_ADDR/MASTER_PORT that the
agent's ``MasterKVStore`` handed out (``elastic_agent/torch/training.py``);
here the agent exports ``DLROVER_TPU_COORDINATOR_ADDR`` /
``PROCESS_RANK`` / ``PROCESS_COUNT`` (see
``dlrover_tpu.agent.training._worker_env``) and the trainer calls
``jax.distributed.initialize`` with them — device discovery replaces
NCCL init (SURVEY.md §2.9).
"""

import os
from dataclasses import dataclass
from typing import Optional

from dlrover_tpu.common.constants import NodeEnv
from dlrover_tpu.common.log import default_logger as logger


def process_rank() -> int:
    return int(os.getenv(NodeEnv.PROCESS_RANK, "0"))


def process_count() -> int:
    return int(os.getenv(NodeEnv.PROCESS_COUNT, "1"))


def local_rank() -> int:
    return int(os.getenv(NodeEnv.LOCAL_RANK, "0"))


def node_rank() -> int:
    return int(os.getenv(NodeEnv.NODE_RANK, "0"))


def restart_count() -> int:
    return int(os.getenv("DLROVER_TPU_RESTART_COUNT", "0"))


@dataclass
class ElasticContext:
    rank: int
    world_size: int
    local_rank: int
    node_rank: int
    restart_count: int
    coordinator_addr: str
    master_addr: str


_context: Optional[ElasticContext] = None


def init_distributed(initialize_jax: bool = True) -> ElasticContext:
    """Initialize multi-process JAX from the agent-provided env.

    Safe to call when launched standalone (single process, no
    coordinator): it becomes a no-op world of size 1.
    """
    global _context
    if _context is not None:
        return _context
    rank = process_rank()
    world = process_count()
    coord = os.getenv(NodeEnv.COORDINATOR_ADDR, "")
    if initialize_jax and world > 1 and coord:
        import jax

        from dlrover_tpu.observability.events import get_event_logger

        # trainer-side rendezvous: connecting to the coordinator and
        # assembling the device world is restart overhead the goodput
        # ledger must see
        with get_event_logger().span("rendezvous"):
            jax.distributed.initialize(
                coordinator_address=coord,
                num_processes=world,
                process_id=rank,
            )
        logger.info(
            "jax.distributed initialized: rank %d/%d via %s",
            rank,
            world,
            coord,
        )
    _context = ElasticContext(
        rank=rank,
        world_size=world,
        local_rank=local_rank(),
        node_rank=node_rank(),
        restart_count=restart_count(),
        coordinator_addr=coord,
        master_addr=os.getenv(NodeEnv.MASTER_ADDR, ""),
    )
    return _context


def get_context() -> Optional[ElasticContext]:
    return _context


def reset_context():
    global _context
    _context = None


def coordination_client():
    """The jax.distributed coordination-service client, or None when
    the process is not in a distributed world.  The service's KV store
    and barriers are CONTROL-PLANE primitives: they work on every
    backend, including CPU worlds where XLA multiprocess computations
    (and therefore every ``multihost_utils`` collective) are
    unavailable."""
    try:
        from jax._src import distributed

        return distributed.global_state.client
    except Exception:  # noqa: BLE001 - private API drift across jax versions
        return None


def control_plane_barrier(
    name: str, timeout_s: float = 600.0
) -> bool:
    """Block at a named coordination-service barrier until every
    process arrives; returns False (no-op) outside a distributed
    world.  ``name`` must be unique per barrier instance (suffix a
    step/round counter).  Unlike ``sync_global_devices`` this never
    launches an XLA computation, so it also COUPLES processes on CPU
    CI exactly like a data-plane collective does on TPU: when a peer
    dies, the survivors stall here until the agent tears them down."""
    client = coordination_client()
    if client is None:
        return False
    client.wait_at_barrier(name, int(timeout_s * 1000))
    return True
