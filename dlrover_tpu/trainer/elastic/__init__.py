from dlrover_tpu.trainer.elastic.context import (  # noqa: F401
    ElasticContext,
    init_distributed,
    local_rank,
    process_count,
    process_rank,
)
from dlrover_tpu.trainer.elastic.sampler import (  # noqa: F401
    ElasticDistributedSampler,
)
from dlrover_tpu.trainer.elastic.trainer import ElasticTrainer  # noqa: F401
