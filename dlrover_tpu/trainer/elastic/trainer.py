"""Step-level elasticity: global-batch-invariant accumulation + progress.

Reference parity: ``dlrover/trainer/torch/elastic/trainer.py:181``
(``ElasticTrainer``) and ``GradientState:53`` — gradient accumulation is
re-derived from the *current* world size so the effective global batch
stays constant as nodes join/leave; the step counter is reported to the
master's SpeedMonitor.

JAX redesign: instead of wrapping an optimizer object, the trainer
exposes ``num_micro_steps`` (for a ``lax.scan`` micro-batch loop — the
idiomatic XLA way to accumulate) and ``accumulate_gradients`` for an
eager loop.  Progress reporting goes straight to the master over gRPC
from rank 0 and to a step file the agent's TrainingMonitor watches.
"""

import json
import os
import time
from typing import Callable, Optional

from dlrover_tpu.common.constants import NodeEnv
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.observability.events import (
    anchored_now,
    get_event_logger,
)
from dlrover_tpu.trainer.elastic.context import (
    process_count,
    process_rank,
)

DEFAULT_STEP_FILE = "/tmp/dlrover_tpu_global_step.json"


class ElasticTrainer:
    def __init__(
        self,
        global_batch_size: int,
        micro_batch_size: int,
        world_size: Optional[int] = None,
        rank: Optional[int] = None,
        step_file: str = "",
        report_interval: float = 15.0,
        master_client=None,
    ):
        self.global_batch_size = global_batch_size
        self.micro_batch_size = micro_batch_size
        self.world_size = world_size or process_count()
        self.rank = rank if rank is not None else process_rank()
        per_step = self.micro_batch_size * self.world_size
        if global_batch_size % per_step != 0:
            logger.warning(
                "global batch %d not divisible by micro*world %d; "
                "rounding accumulation up",
                global_batch_size,
                per_step,
            )
        self.num_micro_steps = max(
            1, (global_batch_size + per_step - 1) // per_step
        )
        self.global_step = 0
        self._step_file = step_file or os.getenv(
            "DLROVER_TPU_STEP_FILE", DEFAULT_STEP_FILE
        )
        self._report_interval = report_interval
        self._last_report = 0.0
        self._client = master_client
        # timeline: each step_done closes a `step` span back to the
        # previous one — the useful-time side of the goodput ledger
        self._events = get_event_logger()
        self._step_mark = None  # (wall, mono) of the last step_done

    # ------------------------------------------------------------ progress
    def _master_client(self):
        if self._client is None and os.getenv(NodeEnv.MASTER_ADDR):
            from dlrover_tpu.agent.master_client import MasterClient

            self._client = MasterClient.singleton_instance()
        return self._client

    def step_done(self, steps: int = 1):
        """Advance the global step; rank 0 reports progress."""
        self.global_step += steps
        if self._events.enabled:
            now_m = time.monotonic()
            now_w = anchored_now(now_m)
            if self._step_mark is not None:
                dur = now_m - self._step_mark[1]
                self._events.complete(
                    "step", now_w - dur, dur, step=self.global_step
                )
            self._step_mark = (now_w, now_m)
        if self.rank != 0:
            return
        now = time.time()
        if now - self._last_report < self._report_interval:
            return
        self._last_report = now
        try:
            with open(self._step_file, "w") as f:
                json.dump(
                    {"step": self.global_step, "timestamp": now}, f
                )
        except OSError:
            pass
        client = self._master_client()
        if client is not None:
            try:
                client.report_global_step(self.global_step, now)
            except ConnectionError:
                pass

    # -------------------------------------------------------- accumulation
    def accumulate_gradients(
        self,
        grad_fn: Callable,
        params,
        micro_batches,
    ):
        """Eager accumulation over ``micro_batches`` (an iterable of
        pytrees); returns (mean_loss, mean_grads).  Prefer a
        ``lax.scan`` inside jit for the hot path — see
        ``dlrover_tpu.parallel.train_step``."""
        import jax

        total_loss = None
        total_grads = None
        count = 0
        for batch in micro_batches:
            loss, grads = grad_fn(params, batch)
            if total_grads is None:
                total_loss, total_grads = loss, grads
            else:
                total_loss = total_loss + loss
                total_grads = jax.tree_util.tree_map(
                    lambda a, b: a + b, total_grads, grads
                )
            count += 1
        scale = 1.0 / max(count, 1)
        mean_grads = jax.tree_util.tree_map(
            lambda g: g * scale, total_grads
        )
        return total_loss * scale, mean_grads

    def state_dict(self) -> dict:
        return {"global_step": self.global_step}

    def load_state_dict(self, state: dict):
        self.global_step = int(state.get("global_step", 0))
