from dlrover_tpu.trainer.checkpoint.checkpointer import (  # noqa: F401
    Checkpointer,
    StorageType,
)
