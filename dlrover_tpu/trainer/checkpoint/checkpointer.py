"""User-facing flash-checkpoint API.

Reference parity: ``dlrover/trainer/torch/flash_checkpoint/
checkpointer.py:18,23`` (Checkpointer ABC + StorageType) and the DDP
flavor ``ddp.py:25``.  One ``Checkpointer`` covers JAX train states:
each process snapshots its addressable view of the pytree, so the same
class serves data-parallel (replicated; rank-0 shard suffices) and
GSPMD-sharded states (every process's shard is needed).
"""

import os
from enum import Enum
from typing import Optional

from dlrover_tpu.common.env import (
    get_local_process_count,
    get_node_rank,
    get_process_count,
    get_process_rank,
)
from dlrover_tpu.common.storage import is_remote_url
from dlrover_tpu.trainer.checkpoint.engine import CheckpointEngine


class StorageType(Enum):
    MEMORY = 0
    DISK = 1


class Checkpointer:
    """Flash checkpointer for an arbitrary JAX pytree (e.g. a flax
    TrainState or an optax (params, opt_state) tuple).

    - ``save_checkpoint(step, state, StorageType.MEMORY)``: pause only
      for the device->host shm copy; survives process crashes/restarts.
    - ``save_checkpoint(step, state, StorageType.DISK)``: same pause,
      then the agent persists asynchronously with a two-phase commit.
    - ``load_checkpoint(target)``: newest of shm/disk, mapped onto the
      ``target`` pytree.
    """

    def __init__(
        self,
        checkpoint_dir: str,
        process_rank: Optional[int] = None,
        process_count: Optional[int] = None,
        node_rank: Optional[int] = None,
        local_shard_num: Optional[int] = None,
        name: str = "default",
        storage=None,
    ):
        self.checkpoint_dir = checkpoint_dir
        if not is_remote_url(checkpoint_dir):  # URLs need no local dir
            os.makedirs(checkpoint_dir, exist_ok=True)
        rank = get_process_rank() if process_rank is None else process_rank
        world = (
            get_process_count() if process_count is None else process_count
        )
        node = get_node_rank() if node_rank is None else node_rank
        local = (
            get_local_process_count()
            if local_shard_num is None
            else local_shard_num
        )
        self._engine = CheckpointEngine(
            checkpoint_dir,
            process_rank=rank,
            process_count=world,
            node_rank=node,
            local_shard_num=local,
            name=name,
            storage=storage,
        )

    def save_checkpoint(self, step: int, state,
                        storage_type: StorageType = StorageType.DISK) -> bool:
        if storage_type == StorageType.MEMORY:
            return self._engine.save_to_memory(step, state)
        return self._engine.save_to_storage(step, state)

    def load_checkpoint(self, target=None):
        """Returns (step, state); (-1, None) when no checkpoint exists."""
        return self._engine.load(target)

    def latest_persisted_step(self) -> int:
        return self._engine.latest_persisted_step()

    def wait_latest_checkpoint(self, step: int, timeout: float = 120) -> bool:
        return self._engine.wait_for_persist(step, timeout)

    def close(self):
        self._engine.close()
