"""Training-process side of flash checkpoint.

Reference parity: ``dlrover/trainer/torch/flash_checkpoint/engine.py:136``
(CheckpointEngine: shm handler in the train proc, agent notification,
``save_to_memory:391`` / ``save_to_storage:409`` / ``load:428``) and
``full_ckpt_engine.py``.

TPU design: a snapshot is ``jax.device_get`` of the process's
addressable view of the train-state pytree, memcpy'd into host shared
memory guarded by the agent's SharedLock.  Persistence is asynchronous
in the agent process, so the training step is blocked only for the
device->host copy (seconds for 7B-class states), and the snapshot
survives a crashed or preempted training process.
"""

import os
import time
from typing import Optional

from dlrover_tpu.common.constants import CheckpointConstant
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.multi_process import SharedQueue
from dlrover_tpu.common.storage import get_checkpoint_storage
from dlrover_tpu.agent.ckpt_saver import (
    AsyncCheckpointSaver,
    CheckpointEvent,
    EVENT_QUEUE,
    FACTORY_QUEUE,
    SaverConfig,
    find_latest_checkpoint,
)
from dlrover_tpu.agent.ckpt_shm import (
    SharedMemoryHandler,
    read_shard_file,
    restore_to_target,
    shard_lock,
)


def _agent_factory_queue_exists() -> bool:
    """True only if an agent is actually listening — a stale socket
    file from a SIGKILLed agent must not make the standalone path
    block on a dead queue."""
    import socket as _socket

    from dlrover_tpu.common.multi_process import _socket_path

    path = _socket_path("queue_" + FACTORY_QUEUE)
    if not os.path.exists(path):
        return False
    probe = _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM)
    try:
        probe.settimeout(2.0)
        probe.connect(path)
        return True
    except OSError:
        try:
            os.unlink(path)
        except OSError:
            pass
        return False
    finally:
        probe.close()


class CheckpointEngine:
    """Save/restore a pytree through shm + the async agent saver."""

    def __init__(
        self,
        checkpoint_dir: str,
        process_rank: int = 0,
        process_count: int = 1,
        node_rank: int = 0,
        local_shard_num: int = 1,
        name: str = "default",
        storage=None,
    ):
        self.checkpoint_dir = checkpoint_dir
        self._rank = process_rank
        self._world = process_count
        self._node_rank = node_rank
        self._name = name
        self._storage = storage or get_checkpoint_storage()
        self._local_saver: Optional[AsyncCheckpointSaver] = None

        # the saver serves shm/lock endpoints for global ranks
        # [node_rank*local_shard_num, ...); this process's rank must be
        # one of them or its lock/meta sockets will never exist
        local_rank = process_rank - node_rank * local_shard_num
        if not 0 <= local_rank < local_shard_num:
            raise ValueError(
                f"process_rank {process_rank} outside node {node_rank}'s "
                f"local shard range (local_shard_num={local_shard_num}); "
                "expected contiguous rank assignment "
                "rank = node_rank*local_shard_num + local_rank"
            )

        config = SaverConfig(
            checkpoint_dir=checkpoint_dir,
            local_shard_num=local_shard_num,
            global_shard_num=process_count,
            node_rank=node_rank,
            name=name,
        )
        if _agent_factory_queue_exists():
            # running under an agent: ask its factory to build the saver
            factory = SharedQueue(FACTORY_QUEUE, create=False)
            factory.put(config)
            factory.close()
        elif local_rank == 0:
            # standalone (no dlrover-tpu-run): local rank 0 hosts the
            # saver in-process; async persist still works, crash
            # resilience does not (reference: engine.py:114
            # start_saver_process).  Other local ranks connect to its
            # shm/lock endpoints as clients.
            self._local_saver = AsyncCheckpointSaver(config,
                                                     storage=self._storage)
            self._local_saver.start()
            AsyncCheckpointSaver._instance = self._local_saver
        self._shm_handler = SharedMemoryHandler(
            process_rank, name=name, host=False
        )
        self._lock = shard_lock(process_rank, name=name, create=False)
        self._event_queue = SharedQueue(
            f"{EVENT_QUEUE}_{name}", create=False
        )

    # -- save --------------------------------------------------------------
    def save_to_memory(self, step: int, state) -> bool:
        """Block only for device->host copy into shm."""
        start = time.time()
        if not self._lock.acquire(timeout=60):
            logger.warning(
                "rank %s: saver still busy; skip memory save of step %s",
                self._rank, step,
            )
            return False
        try:
            nbytes = self._shm_handler.save_state(step, state)
        finally:
            self._lock.release()
        logger.info(
            "rank %s: step %s snapshot (%.1f MB) to shm in %.3fs",
            self._rank, step, nbytes / 1e6, time.time() - start,
        )
        return True

    def save_to_storage(self, step: int, state,
                        checkpoint_dir: Optional[str] = None) -> bool:
        if not self.save_to_memory(step, state):
            return False
        self._event_queue.put(
            CheckpointEvent(
                event_type="save",
                step=step,
                checkpoint_dir=checkpoint_dir or self.checkpoint_dir,
            )
        )
        return True

    # -- load --------------------------------------------------------------
    def load(self, target=None, checkpoint_dir: Optional[str] = None):
        """Restore the newest state: shm first (seconds), storage next.

        Returns (step, state) where state is ``target``-shaped if a
        target pytree was given, else {keypath: ndarray}; (-1, None)
        when nothing exists.
        """
        step, arrays = self._shm_handler.load_state()
        if step < 0:
            step, arrays = self._load_from_storage(checkpoint_dir)
        if step < 0:
            return -1, None
        if target is not None:
            return step, restore_to_target(target, arrays)
        return step, arrays

    def _load_from_storage(self, checkpoint_dir: Optional[str] = None):
        root = checkpoint_dir or self.checkpoint_dir
        latest = find_latest_checkpoint(root, self._storage)
        if latest is None:
            return -1, {}
        path = os.path.join(latest, f"shard_{self._rank}.drckpt")
        if not self._storage.exists(path):
            logger.warning("no shard file %s in %s", self._rank, latest)
            return -1, {}
        return read_shard_file(path, self._storage)

    def latest_persisted_step(self) -> int:
        tracker = os.path.join(
            self.checkpoint_dir, CheckpointConstant.TRACKER_FILE
        )
        content = self._storage.read(tracker)
        return int(content) if content else -1

    def wait_for_persist(self, step: int, timeout: float = 120) -> bool:
        deadline = time.time() + timeout
        while time.time() < deadline:
            if self.latest_persisted_step() >= step:
                return True
            time.sleep(0.1)
        return False

    def close(self):
        self._shm_handler.close()
        self._lock.close()
        self._event_queue.close()
        if self._local_saver is not None:
            self._local_saver.close(unlink=True)
            AsyncCheckpointSaver._instance = None
