"""Training-process side of flash checkpoint.

Reference parity: ``dlrover/trainer/torch/flash_checkpoint/engine.py:136``
(CheckpointEngine: shm handler in the train proc, agent notification,
``save_to_memory:391`` / ``save_to_storage:409`` / ``load:428``) and
``full_ckpt_engine.py``.

TPU design: a snapshot is ``jax.device_get`` of the process's
addressable view of the train-state pytree, memcpy'd into host shared
memory guarded by the agent's SharedLock.  Persistence is asynchronous
in the agent process, so the training step is blocked only for the
device->host copy (seconds for 7B-class states), and the snapshot
survives a crashed or preempted training process.
"""

import os
import threading
import time
from typing import Dict, List, Optional

from dlrover_tpu.common.constants import CheckpointConstant
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.observability.events import (
    anchored_now,
    get_event_logger,
)
from dlrover_tpu.common.multi_process import SharedQueue
from dlrover_tpu.common.storage import (
    get_checkpoint_storage,
    is_remote_url,
)
from dlrover_tpu.agent.ckpt_saver import (
    AsyncCheckpointSaver,
    CheckpointEvent,
    EVENT_QUEUE,
    FACTORY_QUEUE,
    SaverConfig,
    find_latest_checkpoint,
)
from dlrover_tpu.agent.ckpt_shm import (
    SharedMemoryHandler,
    read_shard_file,
    restore_to_target,
    shard_lock,
    stream_shard_leaves,
)
from dlrover_tpu.common.env import (
    ckpt_close_timeout_s,
    reshard_enabled,
)
from dlrover_tpu.trainer.checkpoint import reshard as _reshard


def _newest_common_step(pairs) -> int:
    """Max step present in every rank's availability row ([P, 2] of
    {shm_step, storage_step}), or -1 when no step is restorable on all
    ranks (a torn post-crash state: everyone starts fresh together)."""
    import numpy as np

    rows = np.asarray(pairs)
    candidates = sorted(
        {int(v) for v in rows.reshape(-1) if v >= 0}, reverse=True
    )
    for c in candidates:
        if all((row == c).any() for row in rows):
            return c
    return -1


def _agent_factory_queue_exists() -> bool:
    """True only if an agent is actually listening — a stale socket
    file from a SIGKILLed agent must not make the standalone path
    block on a dead queue."""
    import socket as _socket

    from dlrover_tpu.common.multi_process import _socket_path

    path = _socket_path("queue_" + FACTORY_QUEUE)
    if not os.path.exists(path):
        return False
    probe = _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM)
    try:
        probe.settimeout(2.0)
        probe.connect(path)
        return True
    except OSError:
        try:
            os.unlink(path)
        except OSError:
            pass
        return False
    finally:
        probe.close()


class _StagedCandidate:
    """Leaves of one restorable step, published as their bytes land.

    The prefetch thread is the single producer; ``finish_restore`` is
    the single consumer.  A condition variable lets the consumer
    ``device_put`` leaf k while the producer is still streaming leaf
    k+1 — the restore's device transfers pipeline against the tail of
    the byte read instead of waiting on a whole-state barrier."""

    def __init__(self, source: str, zero_copy: bool):
        self.source = source  # "shm" | "storage"
        #: True when arrays are views onto live shm (the consumer must
        #: copy any leaf that stays on host, like the serial path)
        self.zero_copy = zero_copy
        self.arrays: Dict[str, object] = {}
        self._order: List[str] = []
        self._cv = threading.Condition()
        self._done = False
        self.failed = False

    def publish(self, key: str, arr):
        with self._cv:
            self.arrays[key] = arr
            self._order.append(key)
            self._cv.notify_all()

    def finish(self, failed: bool = False):
        with self._cv:
            if self._done:
                return
            self.failed = failed
            self._done = True
            self._cv.notify_all()

    def iter_leaves(self, timeout: float = 600.0):
        """Yield ``(key, array)`` in arrival order, blocking for the
        next leaf while the producer is still streaming."""
        i = 0
        deadline = time.monotonic() + timeout
        while True:
            with self._cv:
                while i >= len(self._order) and not self._done:
                    if time.monotonic() > deadline:
                        raise TimeoutError(
                            f"restore prefetch ({self.source}) stalled"
                        )
                    self._cv.wait(0.5)
                if i >= len(self._order):
                    if self.failed:
                        raise RuntimeError(
                            f"prefetch candidate ({self.source}) failed"
                        )
                    return
                key = self._order[i]
            yield key, self.arrays[key]
            i += 1

    def wait_all(self, timeout: float = 600.0) -> Dict[str, object]:
        for _ in self.iter_leaves(timeout):
            pass
        return self.arrays


class RestorePrefetch:
    """Background staging of restore bytes into host RAM, started the
    moment the worker knows its rank and checkpoint dir — before the
    device world exists, so the byte stream overlaps rendezvous and
    compilation (the restart critical path's other legs).

    Stages the newest shm snapshot (zero-copy views: the bytes already
    live in host shared memory, and the early attach fronts the
    MADV_WILLNEED page population) and, when storage holds a step shm
    does not, streams that shard file leaf-by-leaf into one private
    buffer.  Everything here is preparation only — no jax arrays, no
    consensus; :meth:`CheckpointEngine.finish_restore` consumes the
    staged leaves after the cross-rank step agreement, and ANY failure
    in this thread degrades the restore to the serial ``load`` path
    (``error`` is set, nothing is ever half-applied)."""

    def __init__(self, engine: "CheckpointEngine",
                 checkpoint_dir: Optional[str] = None,
                 start_gate=None, layouts=None):
        self._engine = engine
        self._dir = checkpoint_dir
        self._gate = start_gate
        #: requested per-leaf global layouts for THIS rank's new
        #: slices (reshard-aware restore); None = legacy same-world
        self._layouts = layouts
        self.error: Optional[BaseException] = None
        self.shm_steps: List[int] = []
        self.storage_step = -1
        self.storage_dir: Optional[str] = None
        self.staged_bytes = 0
        self._avail = threading.Event()
        self._candidates: Dict[int, _StagedCandidate] = {}
        self._thread = threading.Thread(
            target=self._run, name="ckpt-restore-prefetch", daemon=True
        )
        self._thread.start()

    def wait_available(self, timeout: float = 300.0) -> bool:
        """Block until the availability snapshot (shm steps + latest
        storage step) is resolved — the input the consensus needs."""
        return self._avail.wait(timeout)

    def candidate(self, step: int) -> Optional[_StagedCandidate]:
        cand = self._candidates.get(step)
        if cand is None or cand.failed:
            return None
        return cand

    def join(self, timeout: float = 300.0):
        self._thread.join(timeout)

    @property
    def done(self) -> bool:
        return not self._thread.is_alive()

    # ------------------------------------------------------- producer
    def _run(self):
        if self._gate is not None:
            try:
                # start-alignment gate (restart_path coordinator's
                # barrier): both overlapped legs begin together so the
                # timeline shows the real concurrency
                self._gate()
            except Exception:  # noqa: BLE001 - alignment is best-effort
                pass
        t0_mono = time.monotonic()
        t0_wall = anchored_now(t0_mono)
        eng = self._engine
        try:
            self.shm_steps = eng._usable_shm_steps(self._layouts)
            self.storage_step, self.storage_dir = (
                eng._latest_storage_step(self._dir)
            )
        except Exception as e:  # noqa: BLE001 - degrade, never corrupt
            self.error = e
            self._avail.set()
            logger.warning(
                "rank %s: restore prefetch failed resolving "
                "availability: %s (serial fallback)", eng._rank, e,
            )
            return
        # register EMPTY candidates for every step about to be staged
        # BEFORE publishing availability: a near-instant consensus on
        # the main thread would otherwise see an empty candidate map
        # and silently take the serial path (the consumer blocks on
        # iter_leaves until the bytes land instead)
        newest_shm = self.shm_steps[0] if self.shm_steps else -1
        shm_cand = None
        if newest_shm >= 0:
            shm_cand = _StagedCandidate("shm", zero_copy=True)
            self._candidates[newest_shm] = shm_cand
        storage_cand = None
        if (
            self.storage_step >= 0
            and self.storage_dir
            # stage storage only when shm cannot serve the newest
            # step: a warm restart (live shm snapshot, older committed
            # storage) must not pay a full state-sized download that
            # consensus will almost surely discard — the rare
            # consensus-picks-older case falls back to the serial
            # fetch of exactly that step
            and self.storage_step > newest_shm
        ):
            storage_cand = _StagedCandidate("storage", zero_copy=False)
            self._candidates[self.storage_step] = storage_cand
        self._avail.set()
        if shm_cand is not None:
            self._stage_shm(newest_shm, shm_cand)
        if storage_cand is not None:
            self._stage_storage(
                self.storage_step, self.storage_dir, storage_cand
            )
        dur = time.monotonic() - t0_mono
        get_event_logger().complete(
            "restore_prefetch",
            t0_wall,
            dur,
            bytes=self.staged_bytes,
            steps=sorted(self._candidates),
        )

    def _stage_shm(self, step: int, cand: _StagedCandidate):
        try:
            got, arrays = self._engine._shm_handler.load_state(
                copy=False, step=step
            )
            if got != step:
                cand.finish(failed=True)
                return
            for key, value in arrays.items():
                self.staged_bytes += int(getattr(value, "nbytes", 0))
                cand.publish(key, value)
            cand.finish()
        except Exception as e:  # noqa: BLE001
            cand.finish(failed=True)
            logger.warning(
                "rank %s: shm prefetch of step %s failed: %s",
                self._engine._rank, step, e,
            )

    def _stage_storage(self, step: int, ckpt_dir: str,
                       cand: _StagedCandidate):
        eng = self._engine
        try:
            stream = eng._storage_leaf_stream(ckpt_dir, self._layouts)
            got = -1
            for item in stream:
                if item[0] == "meta":
                    got = item[1]
                else:
                    self.staged_bytes += int(item[2].nbytes)
                    cand.publish(item[1], item[2])
            cand.finish(failed=(got != step))
        except Exception as e:  # noqa: BLE001
            cand.finish(failed=True)
            logger.warning(
                "rank %s: storage prefetch of step %s failed: %s",
                eng._rank, step, e,
            )


class CheckpointEngine:
    """Save/restore a pytree through shm + the async agent saver."""

    def __init__(
        self,
        checkpoint_dir: str,
        process_rank: int = 0,
        process_count: int = 1,
        node_rank: int = 0,
        local_shard_num: int = 1,
        name: str = "default",
        storage=None,
        step_sync_fn=None,
    ):
        self.checkpoint_dir = checkpoint_dir
        self._rank = process_rank
        self._world = process_count
        self._node_rank = node_rank
        if name == "default" and checkpoint_dir:
            # namespace the shm/lock/queue names by checkpoint dir:
            # /dev/shm is machine-global, so two jobs both called
            # "default" would collide — observed as one job's exit
            # (close(unlink=True)) deleting the other's live 3 GB
            # snapshot segment.  Hashing the dir keeps the name stable
            # across restarts of the SAME job (resume depends on it).
            import hashlib

            # URLs (gs://…, memory://…) are already absolute; abspath
            # would prepend the cwd and de-sync the name across ranks
            dir_key = (
                checkpoint_dir
                if is_remote_url(checkpoint_dir)
                else os.path.abspath(checkpoint_dir)
            )
            digest = hashlib.sha1(dir_key.encode()).hexdigest()[:8]
            name = f"d{digest}"
        self._name = name
        self._storage = storage or get_checkpoint_storage(
            path=checkpoint_dir
        )
        self._local_saver: Optional[AsyncCheckpointSaver] = None
        # cross-rank restore-step consensus hook:
        # (avail_row: List[int]) -> agreed step, where avail_row is
        # this rank's full availability set (shm slots + storage step,
        # -1 padded); default uses a jax multihost allgather when
        # distributed
        self._step_sync_fn = step_sync_fn
        self._snapshot_thread = None
        self._last_drain_ok = True
        # per-process consensus round counter: namespaces the
        # coordination-service fallback's keys so repeated load()
        # calls in one world never read a stale row
        self._consensus_seq = 0
        # saves dropped because the previous drain was still running or
        # the saver held the lock — the effective RPO degrades with each
        # skip, so it must be observable (exported as
        # dlrover_tpu_ckpt_skipped_snapshots)
        self.skipped_snapshots = 0

        # the saver serves shm/lock endpoints for global ranks
        # [node_rank*local_shard_num, ...); this process's rank must be
        # one of them or its lock/meta sockets will never exist
        local_rank = process_rank - node_rank * local_shard_num
        if not 0 <= local_rank < local_shard_num:
            raise ValueError(
                f"process_rank {process_rank} outside node {node_rank}'s "
                f"local shard range (local_shard_num={local_shard_num}); "
                "expected contiguous rank assignment "
                "rank = node_rank*local_shard_num + local_rank"
            )

        config = SaverConfig(
            checkpoint_dir=checkpoint_dir,
            local_shard_num=local_shard_num,
            global_shard_num=process_count,
            node_rank=node_rank,
            name=name,
        )
        if _agent_factory_queue_exists():
            # running under an agent: ask its factory to build the saver
            factory = SharedQueue(FACTORY_QUEUE, create=False)
            factory.put(config)
            factory.close()
        elif local_rank == 0:
            # standalone (no dlrover-tpu-run): local rank 0 hosts the
            # saver in-process; async persist still works, crash
            # resilience does not (reference: engine.py:114
            # start_saver_process).  Other local ranks connect to its
            # shm/lock endpoints as clients.
            self._local_saver = AsyncCheckpointSaver(config,
                                                     storage=self._storage)
            self._local_saver.start()
            AsyncCheckpointSaver._instance = self._local_saver
        self._shm_handler = SharedMemoryHandler(
            process_rank, name=name, host=False
        )
        self._lock = shard_lock(process_rank, name=name, create=False)
        self._event_queue = SharedQueue(
            f"{EVENT_QUEUE}_{name}", create=False
        )

    def preallocate_like(self, state) -> int:
        """Create + fault in the shm segment sized for ``state`` ahead
        of the first snapshot (moves ~80 s of first-save page allocation
        off the training hot path; a preemption arriving before step 1
        then still finds a live segment).  Returns the reserved bytes."""
        import jax
        import numpy as _np

        total = sum(
            leaf.size * _np.dtype(leaf.dtype).itemsize
            for leaf in jax.tree_util.tree_leaves(state)
            if hasattr(leaf, "size")
        )
        if total:
            self._shm_handler.preallocate(total)
        return total

    # -- save --------------------------------------------------------------
    def save_to_memory(self, step: int, state,
                       blocking: bool = True, layouts=None) -> bool:
        """Snapshot ``state`` into shm.

        ``blocking=True`` waits for the device->host copy (safe with
        donated-buffer train steps: the snapshot completes before the
        caller can dispatch a step that invalidates ``state``).
        ``blocking=False`` launches all device->host transfers async and
        drains them into shm on a background thread — training is
        blocked only for the dispatch (~ms); the caller must keep
        ``state`` alive and un-donated until the drain finishes
        (``wait_for_snapshot``).

        ``layouts`` ({keypath: global-layout dict}, see
        ``trainer/checkpoint/reshard.py``) stamps the snapshot — and
        every shard file persisted from it — with each leaf's global
        shape and this shard's index slice, making the checkpoint
        restorable by ANY world size.  None = legacy world-locked
        format.
        """
        if not self._snapshot_slot_free(step):
            return False
        if not reshard_enabled():
            layouts = None  # kill-switch: today's format, byte for byte
        if blocking:
            return self._drain_snapshot(step, state, None, layouts)
        return self._launch_async_snapshot(step, state, None, layouts)

    def _snapshot_slot_free(self, step: int) -> bool:
        if self._snapshot_thread is not None:
            if self._snapshot_thread.is_alive():
                self._count_skip()
                logger.warning(
                    "rank %s: snapshot still draining; skip step %s "
                    "(%s skipped so far)",
                    self._rank, step, self.skipped_snapshots,
                )
                return False
            self._snapshot_thread = None
        return True

    def _count_skip(self):
        self.skipped_snapshots += 1
        try:
            from dlrover_tpu.observability.metrics import get_registry

            get_registry().inc_counter(
                "dlrover_tpu_ckpt_skipped_snapshots"
            )
        except Exception:  # noqa: BLE001 - metrics must never break saves
            pass

    def _launch_async_snapshot(self, step: int, state,
                               persist_dir: Optional[str],
                               layouts=None) -> bool:
        # launch every transfer before returning so D2H overlaps with
        # whatever the training loop does next
        import threading

        import jax

        for leaf in jax.tree_util.tree_leaves(state):
            if hasattr(leaf, "copy_to_host_async"):
                leaf.copy_to_host_async()
        self._snapshot_thread = threading.Thread(
            target=self._drain_snapshot,
            args=(step, state, persist_dir, layouts),
            name=f"ckpt-snapshot-{step}",
            daemon=True,
        )
        self._snapshot_thread.start()
        return True

    def _drain_snapshot(self, step: int, state,
                        persist_dir: Optional[str],
                        layouts=None) -> bool:
        start = time.time()
        start_mono = time.monotonic()
        self._last_drain_ok = False
        if not self._lock.acquire(timeout=60):
            self._count_skip()
            logger.warning(
                "rank %s: saver still busy; skip memory save of step %s",
                self._rank, step,
            )
            return False
        try:
            nbytes = self._shm_handler.save_state(
                step, state, layouts=layouts
            )
        finally:
            self._lock.release()
        from dlrover_tpu.common.parallel_io import throughput_gbps
        from dlrover_tpu.observability.metrics import record_ckpt_io

        dur = time.monotonic() - start_mono
        get_event_logger().complete(
            "checkpoint_save",
            start,
            dur,
            step=step,
            bytes=nbytes,
            throughput_gbps=throughput_gbps(nbytes, dur),
        )
        record_ckpt_io("drain", nbytes, dur)
        logger.info(
            "rank %s: step %s snapshot (%.1f MB) to shm in %.3fs "
            "(%.2f GB/s)",
            self._rank, step, nbytes / 1e6, dur,
            throughput_gbps(nbytes, dur),
        )
        if persist_dir is not None:
            self._event_queue.put(
                CheckpointEvent(
                    event_type="save", step=step,
                    checkpoint_dir=persist_dir,
                )
            )
        self._last_drain_ok = True
        return True

    def wait_for_snapshot(self, timeout: Optional[float] = None) -> bool:
        """Join an in-flight non-blocking snapshot drain.  Returns True
        only when the drain actually wrote the snapshot (a drain that
        lost the saver lock returns False so callers don't wait on a
        persist that will never come)."""
        t = self._snapshot_thread
        if t is None:
            return True
        t.join(timeout)
        return not t.is_alive() and self._last_drain_ok

    def save_to_storage(self, step: int, state,
                        checkpoint_dir: Optional[str] = None,
                        blocking: bool = True, layouts=None) -> bool:
        target_dir = checkpoint_dir or self.checkpoint_dir
        if blocking:
            if not self.save_to_memory(step, state, layouts=layouts):
                return False
            self._event_queue.put(
                CheckpointEvent(
                    event_type="save", step=step,
                    checkpoint_dir=target_dir,
                )
            )
            return True
        # async: the persist event must trail the shm write, so the
        # drain thread enqueues it
        if not self._snapshot_slot_free(step):
            return False
        if not reshard_enabled():
            layouts = None  # kill-switch: today's format, byte for byte
        return self._launch_async_snapshot(
            step, state, target_dir, layouts
        )

    # -- load --------------------------------------------------------------
    def load(self, target=None, checkpoint_dir: Optional[str] = None,
             layouts=None):
        """Restore the newest globally-agreed state: shm first
        (zero-copy views fed straight to device), storage next.

        The restore step is reconciled across processes before any data
        moves: after a node replacement, surviving ranks may hold a
        newer uncommitted shm snapshot than the relaunched node's last
        committed storage step — restoring it would silently resume a
        mixed-step global state.  Every process restores the newest
        step available on ALL ranks (each rank's set = its two shm
        slots + its latest committed storage step).

        ``layouts`` describes the per-leaf global slices THIS rank
        wants on the (possibly new) world; when the stored shards'
        placement differs, the restore reassembles each leaf from
        whichever shards cover its new slices (reshard leg, gated by
        ``DLROVER_TPU_RESHARD``).

        Returns (step, state) where state is ``target``-shaped if a
        target pytree was given, else {keypath: ndarray}; (-1, None)
        when nothing exists.
        """
        t0_mono = time.monotonic()
        t0_wall = anchored_now(t0_mono)
        shm_steps = self._usable_shm_steps(layouts)
        storage_step, latest_dir = self._latest_storage_step(
            checkpoint_dir
        )
        agreed = self._sync_restore_step(shm_steps, storage_step)
        if agreed < 0:
            return -1, None
        return self._restore_agreed(
            agreed, target, checkpoint_dir, shm_steps, storage_step,
            latest_dir, t0_wall, t0_mono, layouts=layouts,
        )

    def _restore_agreed(self, agreed, target, checkpoint_dir,
                        shm_steps, storage_step, latest_dir,
                        t0_wall, t0_mono, layouts=None):
        """Fetch + apply an already-agreed restore step (the serial
        data path, shared by ``load`` and ``finish_restore``'s
        fallback)."""
        shm_step = shm_steps[0] if shm_steps else -1
        zero_copy = False
        step, arrays = -1, {}
        if agreed in shm_steps:
            # zero-copy: views onto shm, batched device_put in
            # restore_to_target (blocks before returning, so the next
            # snapshot can't clobber the views mid-transfer)
            zero_copy = target is not None
            step, arrays = self._shm_handler.load_state(
                copy=not zero_copy, step=agreed
            )
        if step != agreed and storage_step == agreed:
            # shm miss (or invalidated between get_step and load_state):
            # storage holds the agreed step too
            zero_copy = False
            step, arrays = self._read_storage_step_dir(
                latest_dir, layouts
            )
        if step != agreed:
            zero_copy = False
            step, arrays = self._load_storage_step(
                agreed, checkpoint_dir, layouts
            )
        if step != agreed or not arrays:
            # peers WILL resume from `agreed`; silently starting fresh
            # here would be exactly the mixed-step divergence the
            # consensus exists to prevent — fail loudly instead
            raise RuntimeError(
                f"rank {self._rank}: globally-agreed restore step "
                f"{agreed} unavailable locally (shm={shm_step} "
                f"storage={storage_step})"
            )
        restored_bytes = sum(
            int(getattr(v, "nbytes", 0)) for v in arrays.values()
        )
        if target is not None:
            # copy_host guards non-device leaves from aliasing live shm
            arrays = restore_to_target(
                target, arrays, copy_host=zero_copy
            )
        from dlrover_tpu.common.parallel_io import throughput_gbps
        from dlrover_tpu.observability.metrics import record_ckpt_io

        dur = time.monotonic() - t0_mono
        get_event_logger().complete(
            "checkpoint_restore",
            t0_wall,
            dur,
            step=agreed,
            bytes=restored_bytes,
            throughput_gbps=throughput_gbps(restored_bytes, dur),
        )
        record_ckpt_io("restore", restored_bytes, dur)
        return step, arrays

    def start_prefetch(self, checkpoint_dir: Optional[str] = None,
                       start_gate=None, layouts=None) -> RestorePrefetch:
        """Begin streaming restore bytes into host RAM on a background
        thread — the first leg of the overlapped restart critical path
        (see ``trainer/restart_path.py``).  Callable before the mesh
        or ``jax.distributed`` exist: it touches only shm and storage.
        ``layouts`` makes the staging reshard-aware: the byte stream
        reads whichever shard files cover this rank's NEW slices.
        Pair with :meth:`finish_restore`; ``load`` stays the serial
        equivalent."""
        return RestorePrefetch(
            self, checkpoint_dir=checkpoint_dir,
            start_gate=start_gate, layouts=layouts,
        )

    def finish_restore(self, prefetch: Optional[RestorePrefetch],
                       target=None,
                       checkpoint_dir: Optional[str] = None,
                       layouts=None):
        """Complete an overlapped restore started by
        :meth:`start_prefetch`.

        Runs the SAME cross-rank step consensus as ``load`` (over the
        prefetch's availability snapshot — the row this rank publishes
        must describe the bytes it staged), then applies the staged
        leaves with per-leaf ``jax.device_put`` pipelined against any
        still-streaming tail.  Any prefetch failure, consensus miss on
        the staged step, or staging error degrades to the serial
        ``_restore_agreed``/``load`` path — byte-identical result,
        never a half-applied state.

        ``layouts`` supersedes the prefetch's (a caller may only learn
        its target slices AFTER the blind prefetch launched — e.g. the
        Trainer derives them from the freshly-initialized state): the
        consensus row is re-filtered through the layout gate and every
        fallback is layout-aware, so a blind prefetch that staged the
        wrong world's shard degrades into the reshard leg instead of
        a mis-sharded (or failed) restore."""
        t0_mono = time.monotonic()
        t0_wall = anchored_now(t0_mono)
        if layouts is None and prefetch is not None:
            layouts = prefetch._layouts
        if (
            prefetch is None
            or not prefetch.wait_available(300)
            or prefetch.error is not None
        ):
            if prefetch is not None:
                prefetch.join()
            return self.load(
                target=target, checkpoint_dir=checkpoint_dir,
                layouts=layouts,
            )
        shm_steps = prefetch.shm_steps
        if layouts is not None and layouts is not prefetch._layouts:
            # stricter than what the prefetch staged: drop shm steps
            # whose placement does not serve the requested slices
            usable = set(self._usable_shm_steps(layouts))
            shm_steps = [s for s in shm_steps if s in usable]
        agreed = self._sync_restore_step(
            shm_steps, prefetch.storage_step
        )
        if agreed < 0:
            prefetch.join()
            return -1, None

        def _serial():
            prefetch.join()
            return self._restore_agreed(
                agreed, target, checkpoint_dir, shm_steps,
                prefetch.storage_step, prefetch.storage_dir,
                t0_wall, t0_mono, layouts=layouts,
            )

        cand = prefetch.candidate(agreed)
        if (
            cand is not None
            and cand.source == "shm"
            and agreed not in shm_steps
        ):
            # the blind prefetch staged this step from a shm slot the
            # override's layout gate rejected (valid bytes, wrong
            # placement) — the step is only restorable via storage
            cand = None
        if cand is None:
            return _serial()
        try:
            step, state, nbytes = self._consume_staged(
                cand, agreed, target
            )
        except Exception as e:  # noqa: BLE001 - degrade, never corrupt
            logger.warning(
                "rank %s: staged restore of step %s failed (%s); "
                "serial fallback", self._rank, agreed, e,
            )
            return _serial()
        from dlrover_tpu.common.parallel_io import throughput_gbps
        from dlrover_tpu.observability.metrics import record_ckpt_io

        dur = time.monotonic() - t0_mono
        events = get_event_logger()
        events.complete(
            "checkpoint_restore",
            t0_wall,
            dur,
            step=agreed,
            bytes=nbytes,
            throughput_gbps=throughput_gbps(nbytes, dur),
            stage="overlap",
        )
        events.complete(
            "finish_restore", t0_wall, dur, step=agreed, bytes=nbytes
        )
        record_ckpt_io("restore", nbytes, dur)
        return step, state

    def _consume_staged(self, cand: _StagedCandidate, agreed: int,
                        target):
        """Apply one staged candidate.  With a target, each leaf is
        ``device_put`` the moment its bytes land (async dispatch; one
        completion barrier at the end) — same values, same sharding,
        same host-copy discipline as ``restore_to_target``."""
        import numpy as np

        if target is None:
            arrays = dict(cand.wait_all())
            if cand.zero_copy:
                # serial parity: load(target=None) returns standalone
                # copies (shm may be overwritten afterwards)
                arrays = {
                    k: np.array(v, copy=True) if isinstance(
                        v, np.ndarray
                    ) else v
                    for k, v in arrays.items()
                }
            nbytes = sum(
                int(getattr(v, "nbytes", 0)) for v in arrays.values()
            )
            return agreed, arrays, nbytes
        import jax

        flat, treedef = jax.tree_util.tree_flatten_with_path(target)
        targets = {
            jax.tree_util.keystr(path): (i, leaf)
            for i, (path, leaf) in enumerate(flat)
        }
        out = [None] * len(flat)
        puts = []
        nbytes = 0
        seen = set()
        for key, value in cand.iter_leaves():
            slot = targets.get(key)
            if slot is None:
                continue  # extra leaves are ignored, like the serial path
            i, leaf = slot
            nbytes += int(getattr(value, "nbytes", 0))
            if hasattr(leaf, "dtype") and value.dtype != leaf.dtype:
                value = value.astype(leaf.dtype)
            if isinstance(leaf, jax.Array):
                value = jax.device_put(value, leaf.sharding)
                puts.append(value)
            elif cand.zero_copy and isinstance(value, np.ndarray):
                value = np.array(value, copy=True)
            out[i] = value
            seen.add(key)
        missing = sorted(set(targets) - seen)
        if missing:
            raise KeyError(f"checkpoint missing leaf {missing[0]}")
        if puts:
            jax.block_until_ready(puts)
        return agreed, jax.tree_util.tree_unflatten(treedef, out), nbytes

    def _sync_restore_step(self, shm_steps, storage_step: int) -> int:
        """Cross-process consensus on the restore step: the NEWEST step
        that every rank can actually restore.

        min-of-maxes is not enough: after a mid-save crash the shards
        can be torn — rank 0's newest shm slot holds step N+1 while the
        relaunched rank 1 holds step N; the min (N) must be restored
        from rank 0's OTHER slot (the double buffer keeps it).  Each
        rank publishes its availability set {shm slots, storage_step}
        and all pick the max step present in every set (-1 = none:
        every rank starts fresh, consistently)."""
        avail = [
            *shm_steps[: SharedMemoryHandler.NUM_SLOTS],
            storage_step,
        ]
        # fixed-width row for the allgather
        width = SharedMemoryHandler.NUM_SLOTS + 1
        avail += [-1] * (width - len(avail))
        if self._step_sync_fn is not None:
            # the hook sees the FULL availability row — a consensus
            # restricted to the newest shm slot could pick a step this
            # rank only holds in its second buffer
            return self._step_sync_fn(avail)
        import jax

        if jax.process_count() <= 1:
            return max(avail)
        try:
            import jax.numpy as jnp
            from jax.experimental import multihost_utils

            rows = multihost_utils.process_allgather(
                jnp.array(avail, jnp.int32)
            )  # [P, width]
            return _newest_common_step(rows)
        except Exception as exc:
            # data-plane collective unavailable (CPU backends lack
            # multiprocess XLA computations): run the SAME all-to-all
            # consensus over the jax coordination-service KV store —
            # still never one-sided, every rank reads every row
            agreed = self._coordination_consensus(avail)
            if agreed is not None:
                logger.info(
                    "rank %s: restore-step consensus via coordination"
                    " service (collective unavailable: %s)",
                    self._rank, exc,
                )
                return agreed
            # a one-sided fallback to the local step would recreate the
            # mixed-step divergence this sync exists to prevent (and
            # peers may be blocked inside the collective) — fail loudly
            raise RuntimeError(
                f"rank {self._rank}: restore-step consensus failed"
            ) from exc

    def _coordination_consensus(self, avail) -> Optional[int]:
        """Availability-row exchange over the coordination-service KV
        (control plane).  Returns the agreed step, or None when no
        coordination client exists / a peer never published."""
        import json as _json

        from dlrover_tpu.trainer.elastic.context import (
            coordination_client,
        )

        client = coordination_client()
        if client is None:
            return None
        self._consensus_seq += 1
        ns = (
            f"dlrover_ckpt_consensus/{self._name}/"
            f"{self._consensus_seq}"
        )
        try:
            client.key_value_set(
                f"{ns}/{self._rank}", _json.dumps(avail)
            )
            rows = []
            for r in range(self._world):
                raw = client.blocking_key_value_get(
                    f"{ns}/{r}", 120_000
                )
                rows.append(_json.loads(raw))
        except Exception as e:  # noqa: BLE001 - jax runtime error types vary
            logger.warning(
                "rank %s: coordination-service consensus failed: %s",
                self._rank, e,
            )
            return None
        return _newest_common_step(rows)

    def _latest_storage_step(self, checkpoint_dir: Optional[str] = None):
        root = checkpoint_dir or self.checkpoint_dir
        latest = find_latest_checkpoint(root, self._storage)
        if latest is None:
            return -1, None
        try:
            step = int(os.path.basename(latest).split("-")[-1])
        except ValueError:
            step = -1
        return step, latest

    def _read_storage_shard(self, ckpt_path: Optional[str]):
        if ckpt_path is None:
            return -1, {}
        path = os.path.join(ckpt_path, f"shard_{self._rank}.drckpt")
        if not self._storage.exists(path):
            logger.warning("no shard file %s in %s", self._rank, ckpt_path)
            return -1, {}
        return read_shard_file(path, self._storage)

    def _load_storage_step(self, step: int,
                           checkpoint_dir: Optional[str] = None,
                           layouts=None):
        """Read a specific committed step (an older step may be the
        globally-agreed one when this rank's storage is ahead)."""
        root = checkpoint_dir or self.checkpoint_dir
        path = os.path.join(
            root, f"{CheckpointConstant.CKPT_DIR_PREFIX}{step}"
        )
        if not self._storage.exists(path):
            return -1, {}
        return self._read_storage_step_dir(path, layouts)

    # -- reshard ------------------------------------------------------------
    def _reshard_active(self, layouts) -> bool:
        return bool(layouts) and reshard_enabled()

    def _usable_shm_steps(self, layouts=None):
        """Steps restorable from THIS rank's shm segment under the
        requested layouts.  After a world change the segment may hold
        a snapshot of the OLD world's slices — its bytes are valid but
        placed wrong, and using them would silently resume a
        mis-sharded state.  A slot is usable when its layout header
        matches the request, or (headerless legacy slot) when every
        spec's local shape matches the requested local shape.  Without
        requested layouts (or with the reshard kill-switch off) this
        is exactly ``steps_available()`` — today's behavior."""
        steps = self._shm_handler.steps_available()
        if not self._reshard_active(layouts):
            return steps
        usable = []
        for step in steps:
            slot_layouts = self._shm_handler.slot_layouts(step)
            if slot_layouts is not None:
                if _reshard.layouts_equal(slot_layouts, layouts):
                    usable.append(step)
                continue
            # legacy slot: shape-compare against the request straight
            # off the meta specs (no shm attach, no leaf views)
            shapes = self._shm_handler.slot_shapes(step)
            if shapes is None:
                continue
            ok = True
            for key, raw in layouts.items():
                want_shape = tuple(
                    int(d) for d in (
                        raw["shape"] if isinstance(raw, dict)
                        else raw.shape
                    )
                )
                if shapes.get(key) != want_shape:
                    ok = False
                    break
            if ok:
                usable.append(step)
        return usable

    def _read_storage_step_dir(self, ckpt_path: Optional[str],
                               layouts=None):
        """Read one committed checkpoint dir onto this rank: the
        direct per-rank shard when its placement matches the request,
        the resharded overlap-range read otherwise."""
        if ckpt_path is None:
            return -1, {}
        if not self._reshard_active(layouts):
            return self._read_storage_shard(ckpt_path)
        step, arrays = -1, {}
        try:
            for item in self._storage_leaf_stream(ckpt_path, layouts):
                if item[0] == "meta":
                    step = item[1]
                else:
                    arrays[item[1]] = item[2]
        except Exception as e:  # noqa: BLE001 - degrade, never corrupt
            logger.warning(
                "rank %s: storage read of %s failed: %s",
                self._rank, ckpt_path, e,
            )
            return -1, {}
        return step, arrays

    def _direct_shard_compatible(self, ckpt_dir: str, layouts) -> bool:
        """Whether ``shard_{rank}`` in ``ckpt_dir`` already holds
        exactly the requested slices (same-world restart): header-only
        check, KBs against GB shards."""
        path = os.path.join(ckpt_dir, f"shard_{self._rank}.drckpt")
        if not self._storage.exists(path):
            return False
        try:
            info = _reshard.read_shard_header(path, self._storage)
        except Exception:  # noqa: BLE001 - unreadable header
            return False
        if info.layouts is not None:
            want = {
                k: (v if isinstance(v, dict) else v.as_dict())
                for k, v in layouts.items()
            }
            have = {k: v.as_dict() for k, v in info.layouts.items()}
            return _reshard.layouts_equal(have, want)
        # legacy file: usable iff every requested local shape matches
        for key, raw in layouts.items():
            shape = tuple(
                raw["shape"] if isinstance(raw, dict) else raw.shape
            )
            spec = info.specs.get(key)
            if spec is None or tuple(spec[1]) != shape:
                return False
        return True

    def _storage_leaf_stream(self, ckpt_dir: str, layouts=None):
        """Leaf stream over one committed checkpoint dir: the direct
        per-rank shard file when it already matches the requested
        layouts (or none were requested), else the resharded
        overlap-range read across whichever shards cover this rank's
        new slices.  The reshard leg emits a ``reshard`` span with
        the world transition and the moved bytes."""
        direct = os.path.join(
            ckpt_dir, f"shard_{self._rank}.drckpt"
        )
        if not self._reshard_active(layouts) or (
            self._direct_shard_compatible(ckpt_dir, layouts)
        ):
            yield from stream_shard_leaves(direct, self._storage)
            return
        t0_mono = time.monotonic()
        t0_wall = anchored_now(t0_mono)
        shards = _reshard.scan_checkpoint_shards(
            ckpt_dir, self._storage
        )
        from_world = _reshard.checkpoint_world_size(shards)
        moved = 0
        for item in _reshard.stream_resharded_leaves(
            ckpt_dir, layouts, storage=self._storage, shards=shards
        ):
            if item[0] == "leaf":
                moved += int(item[2].nbytes)
            yield item
        from dlrover_tpu.common.parallel_io import throughput_gbps
        from dlrover_tpu.observability.metrics import record_reshard_io

        dur = time.monotonic() - t0_mono
        get_event_logger().complete(
            "reshard",
            t0_wall,
            dur,
            from_world=from_world,
            to_world=self._world,
            bytes=moved,
            throughput_gbps=throughput_gbps(moved, dur),
        )
        record_reshard_io(from_world, self._world, moved, dur)
        logger.info(
            "rank %s: resharded restore %s -> %s ranks (%.1f MB in "
            "%.3fs)", self._rank, from_world, self._world,
            moved / 1e6, dur,
        )

    def latest_persisted_step(self) -> int:
        tracker = os.path.join(
            self.checkpoint_dir, CheckpointConstant.TRACKER_FILE
        )
        content = self._storage.read(tracker)
        return int(content) if content else -1

    def wait_for_persist(self, step: int, timeout: float = 120) -> bool:
        """Block until the tracker shows ``step`` persisted.

        Exponential backoff (0.1 s → 2 s cap): each poll is a storage
        read, and on a remote tracker (gs://) a flat 100 ms cadence
        hammers the object store for the full timeout."""
        deadline = time.time() + timeout
        delay = 0.1
        while time.time() < deadline:
            if self.latest_persisted_step() >= step:
                return True
            time.sleep(min(delay, max(deadline - time.time(), 0.01)))
            delay = min(delay * 2, 2.0)
        # one post-deadline read: the persist may have landed during
        # the final (long) sleep
        return self.latest_persisted_step() >= step

    def close(self):
        budget = ckpt_close_timeout_s()
        self.wait_for_snapshot(timeout=budget)
        t = self._snapshot_thread
        if t is not None and t.is_alive():
            # the drain thread still holds live views over the shm
            # buffer and will touch the lock and event queue when it
            # finishes — closing ANY of them now would make the drain
            # fail on a closed handle (persist event lost) or raise
            # BufferError; leak all three and let process exit reclaim.
            # The leak is deliberate but must be OBSERVABLE: a fleet
            # where closes keep timing out is leaking multi-GB shm
            # segments (dlrover_tpu_ckpt_drain_stuck alerts on it),
            # and DLROVER_TPU_CKPT_CLOSE_TIMEOUT_S tunes the budget
            # (tests use a tiny one to pin this path).
            try:
                from dlrover_tpu.observability.metrics import (
                    get_registry,
                )

                get_registry().inc_counter(
                    "dlrover_tpu_ckpt_drain_stuck"
                )
            except Exception:  # noqa: BLE001 - metrics never break close
                pass
            logger.error(
                "rank %s: snapshot drain still running after %.0fs; "
                "leaving shm/lock/queue handles open", self._rank,
                budget,
            )
            return  # saver side must stay up too: drain uses its
            # locks/queue service and the shm segments it would unlink
        self._shm_handler.close()
        self._lock.close()
        self._event_queue.close()
        if self._local_saver is not None:
            self._local_saver.close(unlink=True)
            AsyncCheckpointSaver._instance = None
