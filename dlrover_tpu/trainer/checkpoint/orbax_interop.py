"""Orbax-interoperable checkpoint layout.

Reference parity: the reference's flash checkpoints are readable by
the surrounding ecosystem — FSDP engine writes torch DCP format
(``dlrover/trainer/torch/flash_checkpoint/fsdp.py:289``), Megatron/HF
adapters keep their native layouts.  The TPU dual: the JAX ecosystem's
standard is Orbax, so this adapter converts between the private
``.drckpt`` shard format (the crash path — raw shm bytes, written by
the agent without touching the training process) and an Orbax
checkpoint any JAX tool can read.

- :func:`export_orbax`   — latest (or given) committed ``.drckpt``
  step -> ``dest/<step>/`` in Orbax StandardCheckpointer layout.
- :func:`import_orbax`   — Orbax checkpoint -> (step, nested state).

Keypaths: shm snapshots store flat ``{jax.tree_util.keystr: ndarray}``
maps; export re-nests them (dict keys + list indexes) so the Orbax
tree matches the original train-state structure.

Shard merge caveat: shards are merged by keypath, which is exact for
replicated state (DP/ZeRO-1 jobs — every rank holds the full tree);
parameter-sharded states (FSDP/TP) need the mesh to reassemble and
should be restored through the engine onto a sharded target instead.
"""

import os
import re
from typing import Dict, Optional, Tuple

import numpy as np

from dlrover_tpu.agent.ckpt_saver import find_latest_checkpoint
from dlrover_tpu.agent.ckpt_shm import read_shard_file
from dlrover_tpu.common.constants import CheckpointConstant
from dlrover_tpu.common.log import default_logger as logger

_KEY_TOKEN = re.compile(
    r"\['([^']*)'\]"  # dict key: ['name']
    r"|\[(\d+)\]"  # sequence index: [0]
    r"|\.([A-Za-z_][A-Za-z0-9_]*)"  # namedtuple/dataclass field: .mu
)


def _parse_keystr(keystr: str):
    """``"['opt'].mu['w'][0]"`` -> ("opt", "mu", "w", 0).

    Attribute tokens (optax namedtuple states, flax dataclasses) become
    dict keys in the exported tree — dropping them would collide
    sibling fields (``.mu``/``.nu``) onto one path."""
    tokens = []
    for m in _KEY_TOKEN.finditer(keystr):
        if m.group(1) is not None:
            tokens.append(m.group(1))
        elif m.group(2) is not None:
            tokens.append(int(m.group(2)))
        else:
            tokens.append(m.group(3))
    return tuple(tokens)


def unflatten_keystrs(arrays: Dict[str, np.ndarray]):
    """Rebuild the nested pytree from flat keystr-keyed arrays (lists
    are materialized from integer tokens)."""
    root: Dict = {}
    for keystr, value in arrays.items():
        tokens = _parse_keystr(keystr)
        if not tokens:
            # scalar state saved at the root (rare); keep flat
            root[keystr] = value
            continue
        node = root
        for i, tok in enumerate(tokens[:-1]):
            node = node.setdefault(tok, {})
        node[tokens[-1]] = value

    def listify(node):
        if not isinstance(node, dict):
            return node
        out = {k: listify(v) for k, v in node.items()}
        if out and all(isinstance(k, int) for k in out):
            return [out[i] for i in sorted(out)]
        return out

    return listify(root)


def _read_step_arrays(
    checkpoint_dir: str, step: Optional[int]
) -> Tuple[int, Dict[str, np.ndarray]]:
    """Merge every ``shard_*.drckpt`` of the chosen committed step."""
    if step is None:
        path = find_latest_checkpoint(checkpoint_dir)
        if path is None:
            return -1, {}
    else:
        path = os.path.join(
            checkpoint_dir,
            f"{CheckpointConstant.CKPT_DIR_PREFIX}{step}",
        )
    if not os.path.isdir(path):
        return -1, {}
    merged: Dict[str, np.ndarray] = {}
    found_step = -1
    for entry in sorted(os.listdir(path)):
        if not entry.endswith(".drckpt"):
            continue
        shard_step, arrays = read_shard_file(
            os.path.join(path, entry)
        )
        found_step = max(found_step, shard_step)
        merged.update(arrays)
    return found_step, merged


def export_orbax(
    checkpoint_dir: str,
    dest_dir: str,
    step: Optional[int] = None,
) -> int:
    """Convert a committed ``.drckpt`` checkpoint into an Orbax
    checkpoint at ``dest_dir/<step>``; returns the exported step
    (-1 when nothing committed)."""
    import orbax.checkpoint as ocp

    found_step, arrays = _read_step_arrays(checkpoint_dir, step)
    if found_step < 0 or not arrays:
        logger.warning(
            "no committed checkpoint to export under %s", checkpoint_dir
        )
        return -1
    tree = unflatten_keystrs(arrays)
    dest = os.path.join(os.path.abspath(dest_dir), str(found_step))
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(dest, tree, force=True)
    logger.info("exported step %s -> %s (orbax)", found_step, dest)
    return found_step


def import_orbax(
    src_dir: str, step: Optional[int] = None
) -> Tuple[int, Optional[Dict]]:
    """Load an Orbax checkpoint written by :func:`export_orbax` (or any
    StandardCheckpointer layout with integer step dirs); returns
    (step, nested state) or (-1, None)."""
    import orbax.checkpoint as ocp

    src_dir = os.path.abspath(src_dir)
    if step is None:
        steps = [
            int(e) for e in os.listdir(src_dir) if e.isdigit()
        ] if os.path.isdir(src_dir) else []
        if not steps:
            return -1, None
        step = max(steps)
    path = os.path.join(src_dir, str(step))
    with ocp.StandardCheckpointer() as ckptr:
        tree = ckptr.restore(path)
    return step, tree
