"""Device-count-agnostic checkpoint layouts + elastic reshard reads.

A classic flash-checkpoint shard (``shard_{rank}.drckpt``) is only
readable by the rank that wrote it: the file records local shapes and
byte offsets, nothing about WHERE the shard sits in the global state.
A job that loses a host therefore cannot read its own storage
checkpoint on the new world — every world-size change degenerates to
restart-from-scratch (PAPER.md §1's headline promise, inverted).

This module makes the format world-agnostic and implements the
resharded read:

- :class:`LeafLayout` — the per-leaf global-layout header: the leaf's
  GLOBAL shape plus this shard's index slice (start + local shape per
  dim).  Layout dicts ride the shm slot meta and the ``.drckpt``
  header (``agent/ckpt_shm.py``), so both the periodic persist and the
  emergency crash flush produce world-agnostic shards.  Old files
  simply lack the header and keep restoring on an unchanged world.
- layout constructors — :func:`replicated_layouts` (every rank holds
  the full leaf: the data-parallel case), :func:`axis0_layouts` (the
  leading dim sharded evenly across ranks: the FSDP host-sharding
  case, and the simulated-host harness in ``tests/test_reshard.py``),
  :func:`derive_layouts` (from live ``jax.Array`` shardings).
- :func:`iter_copy_runs` — the N-d intersection math: given a source
  shard's block and a target block of the same global leaf, yield the
  ``(src_offset, dst_offset, nbytes)`` contiguous runs that move
  exactly the overlapping bytes, nothing else.
- :func:`plan_reshard` / :func:`stream_resharded_leaves` — scan every
  shard header in a checkpoint dir (headers only — a header read is
  KBs against GB shards), claim each target leaf's uncovered region
  greedily across the sources, and stream only the overlapping byte
  ranges (seek + readinto) into one preallocated buffer per leaf,
  yielding leaves as their bytes land so the restore's ``device_put``
  pipelines against the read tail exactly like the same-world
  prefetch (``stream_shard_leaves``).

Nothing here imports jax at module level: the reshard plan and the
byte movement are pure host work, runnable pre-mesh on the restart
critical path (``trainer/restart_path.py``).
"""

import os
import pickle
import re
import struct
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from dlrover_tpu.common import parallel_io
from dlrover_tpu.common.log import default_logger as logger

_HDR = struct.Struct("<Q")
_SHARD_RE = re.compile(r"^shard_(\d+)\.drckpt$")


class ReshardError(RuntimeError):
    """The checkpoint cannot be reassembled onto the requested
    layouts (missing coverage, conflicting global shapes, mixed
    steps, or shards without layout headers)."""


@dataclass(frozen=True)
class LeafLayout:
    """One leaf's place in the global state: the global shape and
    this shard's index slice (``start`` + local ``shape`` per dim).
    A replicated leaf is ``start == 0`` with ``shape ==
    global_shape`` — any single shard covers it."""

    global_shape: Tuple[int, ...]
    start: Tuple[int, ...]
    shape: Tuple[int, ...]

    def __post_init__(self):
        if not (
            len(self.global_shape) == len(self.start) == len(self.shape)
        ):
            raise ValueError(
                f"rank mismatch: global={self.global_shape} "
                f"start={self.start} shape={self.shape}"
            )
        for g, s, e in zip(self.global_shape, self.start, self.shape):
            if s < 0 or e <= 0 or s + e > g:
                raise ValueError(
                    f"block [{self.start}+{self.shape}] outside "
                    f"global {self.global_shape}"
                )

    @property
    def replicated(self) -> bool:
        return self.shape == self.global_shape

    def as_dict(self) -> Dict:
        """JSON/pickle-safe form that rides shm meta and the shard
        header."""
        return {
            "global_shape": list(self.global_shape),
            "start": list(self.start),
            "shape": list(self.shape),
        }

    @classmethod
    def from_dict(cls, d: Dict) -> "LeafLayout":
        return cls(
            global_shape=tuple(int(v) for v in d["global_shape"]),
            start=tuple(int(v) for v in d["start"]),
            shape=tuple(int(v) for v in d["shape"]),
        )


def _keyed_leaves(tree) -> List[Tuple[str, object]]:
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [(jax.tree_util.keystr(p), leaf) for p, leaf in flat]


def _leaf_shape(leaf) -> Tuple[int, ...]:
    if hasattr(leaf, "shape"):
        return tuple(int(v) for v in leaf.shape)
    return tuple(np.asarray(leaf).shape)


def replicated_layouts(tree) -> Dict[str, Dict]:
    """Every leaf fully replicated (the data-parallel snapshot: each
    rank's shard holds the complete state).  Under this layout ANY
    world size restores from any one shard — the job that shrinks
    2→1 reads the survivor's (or any) shard file."""
    return {
        key: LeafLayout(
            global_shape=_leaf_shape(leaf),
            start=tuple(0 for _ in _leaf_shape(leaf)),
            shape=_leaf_shape(leaf),
        ).as_dict()
        for key, leaf in _keyed_leaves(tree)
    }


def axis0_layouts(local_tree, rank: int, world: int,
                  min_shard_dim0: int = 1) -> Dict[str, Dict]:
    """The leading dim of every (large-enough) leaf holds this rank's
    ``1/world`` slice; leaves whose dim0 is smaller than
    ``min_shard_dim0 * world`` (scalars, tiny vectors) are treated as
    replicated.  ``local_tree`` is THIS rank's local block — the
    caller's per-rank snapshot, exactly what ``save_state`` writes."""
    out: Dict[str, Dict] = {}
    for key, leaf in _keyed_leaves(local_tree):
        shape = _leaf_shape(leaf)
        if shape and shape[0] >= min_shard_dim0:
            d0 = shape[0]
            out[key] = LeafLayout(
                global_shape=(d0 * world,) + shape[1:],
                start=(rank * d0,) + tuple(0 for _ in shape[1:]),
                shape=shape,
            ).as_dict()
        else:
            out[key] = LeafLayout(
                global_shape=shape,
                start=tuple(0 for _ in shape),
                shape=shape,
            ).as_dict()
    return out


def derive_layouts(state) -> Optional[Dict[str, Dict]]:
    """Best-effort layouts from live ``jax.Array`` leaves: replicated
    leaves map to a full-block layout; block-sharded leaves map to
    this process's contiguous block (union of its addressable
    shards).  Returns None when any leaf's addressable region is not
    one contiguous block (the caller then saves without layouts —
    same-world restore only, exactly the legacy behavior)."""
    import jax

    try:
        proc = jax.process_index()
    except Exception:  # noqa: BLE001 - uninitialized backend
        proc = 0
    out: Dict[str, Dict] = {}
    for key, leaf in _keyed_leaves(state):
        shape = _leaf_shape(leaf)
        if not isinstance(leaf, jax.Array):
            # host leaf: the caller already localized it; without a
            # sharding we can only claim replication when there is no
            # evidence otherwise — leave the decision to the caller
            out[key] = LeafLayout(
                global_shape=shape,
                start=tuple(0 for _ in shape),
                shape=shape,
            ).as_dict()
            continue
        try:
            if leaf.is_fully_replicated:
                out[key] = LeafLayout(
                    global_shape=shape,
                    start=tuple(0 for _ in shape),
                    shape=shape,
                ).as_dict()
                continue
            index_map = leaf.sharding.devices_indices_map(shape)
            # normalize each index to hashable (start, stop) boxes:
            # slice objects are unhashable before Python 3.12, and
            # replicated placements repeat the same box per device —
            # dedupe so coverage is not double-counted
            mine = {
                tuple(
                    (
                        sl.start or 0,
                        sl.stop if sl.stop is not None else dim,
                    )
                    for sl, dim in zip(idx, shape)
                )
                for dev, idx in index_map.items()
                if dev.process_index == proc
            }
            if not mine:
                return None
            lo = tuple(
                min(box[d][0] for box in mine)
                for d in range(len(shape))
            )
            hi = tuple(
                max(box[d][1] for box in mine)
                for d in range(len(shape))
            )
            block = tuple(h - l for l, h in zip(lo, hi))
            # the union bounding box must be exactly covered by the
            # shards (a strided placement would smuggle foreign bytes)
            covered = sum(
                int(np.prod([b - a for a, b in box] or [1]))
                for box in mine
            )
            if covered < int(np.prod(block or (1,))):
                return None
            out[key] = LeafLayout(
                global_shape=shape, start=lo, shape=block
            ).as_dict()
        except Exception as e:  # noqa: BLE001 - sharding API drift
            logger.warning("layout derivation failed for %s: %s", key, e)
            return None
    return out


# ----------------------------------------------------- box arithmetic
def _intersect(a_start, a_shape, b_start, b_shape):
    """Intersection of two boxes, or None."""
    lo = tuple(max(x, y) for x, y in zip(a_start, b_start))
    hi = tuple(
        min(x + w, y + v)
        for x, w, y, v in zip(a_start, a_shape, b_start, b_shape)
    )
    if any(h <= l for l, h in zip(lo, hi)):
        return None
    return lo, tuple(h - l for l, h in zip(lo, hi))


def _subtract_box(box, hole):
    """``box`` minus ``hole`` (both (start, shape)) as disjoint boxes.
    Standard axis-sweep split: slabs strictly below/above the hole on
    each dim, shrinking toward the intersection."""
    inter = _intersect(box[0], box[1], hole[0], hole[1])
    if inter is None:
        return [box]
    out = []
    cur_start = list(box[0])
    cur_shape = list(box[1])
    for d in range(len(cur_start)):
        i_lo = inter[0][d]
        i_hi = inter[0][d] + inter[1][d]
        c_lo = cur_start[d]
        c_hi = cur_start[d] + cur_shape[d]
        if c_lo < i_lo:
            s, sh = list(cur_start), list(cur_shape)
            sh[d] = i_lo - c_lo
            out.append((tuple(s), tuple(sh)))
        if i_hi < c_hi:
            s, sh = list(cur_start), list(cur_shape)
            s[d] = i_hi
            sh[d] = c_hi - i_hi
            out.append((tuple(s), tuple(sh)))
        cur_start[d] = i_lo
        cur_shape[d] = i_hi - i_lo
    return out


def iter_copy_runs(
    src_start: Sequence[int],
    src_shape: Sequence[int],
    dst_start: Sequence[int],
    dst_shape: Sequence[int],
    itemsize: int,
    box: Optional[Tuple[Tuple[int, ...], Tuple[int, ...]]] = None,
) -> Iterator[Tuple[int, int, int]]:
    """Contiguous ``(src_off_bytes, dst_off_bytes, nbytes)`` runs
    moving ``box`` (default: the src∩dst intersection) of a row-major
    global leaf from the source block to the target block.  Offsets
    are relative to each block's own first byte.  A run folds every
    trailing dim the box spans fully in BOTH blocks, so a replicated
    source feeding a replicated target is ONE run."""
    src_start = tuple(src_start)
    src_shape = tuple(src_shape)
    dst_start = tuple(dst_start)
    dst_shape = tuple(dst_shape)
    if box is None:
        box = _intersect(src_start, src_shape, dst_start, dst_shape)
        if box is None:
            return
    b_start, b_shape = box
    n = len(b_start)
    if n == 0:  # scalar leaf
        yield 0, 0, itemsize
        return
    # j = first dim index such that every dim AFTER j is spanned
    # fully in both blocks (runs are contiguous over dims [j..n))
    j = n - 1
    while j > 0 and (
        b_shape[j] == src_shape[j] == dst_shape[j]
    ):
        j -= 1
    run_elems = b_shape[j]
    for d in range(j + 1, n):
        run_elems *= b_shape[d]
    src_strides = [1] * n
    dst_strides = [1] * n
    for d in range(n - 2, -1, -1):
        src_strides[d] = src_strides[d + 1] * src_shape[d + 1]
        dst_strides[d] = dst_strides[d + 1] * dst_shape[d + 1]
    rel_src = tuple(b - s for b, s in zip(b_start, src_start))
    rel_dst = tuple(b - s for b, s in zip(b_start, dst_start))
    outer = b_shape[:j]
    for idx in np.ndindex(*outer) if outer else [()]:
        src_off = sum(
            (rel_src[d] + (idx[d] if d < j else 0)) * src_strides[d]
            for d in range(j)
        )
        dst_off = sum(
            (rel_dst[d] + (idx[d] if d < j else 0)) * dst_strides[d]
            for d in range(j)
        )
        src_off += rel_src[j] * src_strides[j]
        dst_off += rel_dst[j] * dst_strides[j]
        yield (
            src_off * itemsize,
            dst_off * itemsize,
            run_elems * itemsize,
        )


# ------------------------------------------------------ shard headers
@dataclass
class ShardInfo:
    """One shard file's header: enough to plan range reads without
    touching its raw section."""

    rank: int
    path: str
    step: int
    #: {key: (dtype_str, shape, offset, nbytes)} from the 5-tuple specs
    specs: Dict[str, Tuple[str, Tuple[int, ...], int, int]]
    #: {key: LeafLayout} — None when the file predates layout headers
    layouts: Optional[Dict[str, LeafLayout]]
    #: file offset where the raw section begins
    data_offset: int


def read_shard_header(path: str, storage=None) -> ShardInfo:
    """Header-only read of one ``.drckpt`` (KBs, never the raw GB)."""
    f = storage.open_read(path) if storage is not None else open(path, "rb")
    with f:
        hdr = f.read(_HDR.size)
        if not hdr or len(hdr) < _HDR.size:
            raise ReshardError(f"no header in {path}")
        (hdr_len,) = _HDR.unpack(hdr)
        meta = pickle.loads(f.read(hdr_len))
    m = _SHARD_RE.match(os.path.basename(path))
    rank = int(m.group(1)) if m else -1
    raw_layouts = meta.get("layouts")
    layouts = (
        {
            k: LeafLayout.from_dict(v)
            for k, v in raw_layouts.items()
        }
        if raw_layouts
        else None
    )
    return ShardInfo(
        rank=rank,
        path=path,
        step=int(meta.get("step", -1)),
        specs={
            key: (str(dt), tuple(shape), int(off), int(nb))
            for key, dt, shape, off, nb in meta["specs"]
        },
        layouts=layouts,
        data_offset=_HDR.size + hdr_len,
    )


def scan_checkpoint_shards(ckpt_dir: str, storage=None) -> List[ShardInfo]:
    """Every shard header in a committed checkpoint dir, rank order."""
    if storage is not None:
        names = storage.listdir(ckpt_dir)
    else:
        names = sorted(os.listdir(ckpt_dir)) if os.path.isdir(
            ckpt_dir
        ) else []
    shards = []
    for name in names:
        if _SHARD_RE.match(name):
            shards.append(
                read_shard_header(
                    os.path.join(ckpt_dir, name), storage
                )
            )
    shards.sort(key=lambda s: s.rank)
    return shards


# ------------------------------------------------------ reshard plan
@dataclass
class _LeafPlan:
    key: str
    dtype: np.dtype
    shape: Tuple[int, ...]  # target local shape
    #: per source: (path, [(src_file_off, dst_buf_off, nbytes)])
    reads: List[Tuple[str, List[Tuple[int, int, int]]]]
    nbytes: int


def plan_reshard(
    shards: Sequence[ShardInfo],
    target_layouts: Dict[str, Dict],
) -> Tuple[int, List[_LeafPlan]]:
    """Claim every target leaf's region across the source shards.

    Greedy with explicit remainder subtraction: replicated sources
    overlap each other completely, and double-reading their bytes
    would both waste IO and (harmlessly but wastefully) rewrite the
    same destination — each source only claims what previous sources
    left uncovered.  Raises :class:`ReshardError` on mixed steps,
    missing layout headers, conflicting global shapes/dtypes, or any
    uncovered remainder."""
    if not shards:
        raise ReshardError("no shard files to reshard from")
    steps = {s.step for s in shards}
    if len(steps) > 1:
        raise ReshardError(
            f"mixed steps across shard files: {sorted(steps)}"
        )
    step = steps.pop()
    plans: List[_LeafPlan] = []
    for key, raw in target_layouts.items():
        want = (
            raw if isinstance(raw, LeafLayout)
            else LeafLayout.from_dict(raw)
        )
        dtype: Optional[np.dtype] = None
        remainder = [(want.start, want.shape)]
        reads: List[Tuple[str, List[Tuple[int, int, int]]]] = []
        for shard in shards:
            if not remainder:
                break
            if shard.layouts is None:
                raise ReshardError(
                    f"{shard.path} has no layout header (old-format "
                    "shard): restore is only possible on an "
                    "unchanged world"
                )
            if key not in shard.specs or key not in shard.layouts:
                continue
            dt, sshape, soff, _snb = shard.specs[key]
            src = shard.layouts[key]
            if src.global_shape != want.global_shape:
                raise ReshardError(
                    f"leaf {key}: global shape {src.global_shape} in "
                    f"{shard.path} != requested {want.global_shape}"
                )
            if tuple(sshape) != src.shape:
                raise ReshardError(
                    f"leaf {key}: spec shape {sshape} != layout "
                    f"block {src.shape} in {shard.path}"
                )
            if dtype is None:
                dtype = np.dtype(dt)
            elif np.dtype(dt) != dtype:
                raise ReshardError(
                    f"leaf {key}: dtype {dt} in {shard.path} != "
                    f"{dtype}"
                )
            runs: List[Tuple[int, int, int]] = []
            next_remainder = []
            for box in remainder:
                inter = _intersect(
                    src.start, src.shape, box[0], box[1]
                )
                if inter is None:
                    next_remainder.append(box)
                    continue
                for s_off, d_off, nb in iter_copy_runs(
                    src.start, src.shape, want.start, want.shape,
                    dtype.itemsize, box=inter,
                ):
                    runs.append(
                        (
                            shard.data_offset + soff + s_off,
                            d_off,
                            nb,
                        )
                    )
                next_remainder.extend(_subtract_box(box, inter))
            remainder = next_remainder
            if runs:
                runs.sort()  # sequential file access
                reads.append((shard.path, runs))
        if remainder:
            raise ReshardError(
                f"leaf {key}: region {remainder} covered by no shard "
                f"({len(shards)} shards scanned)"
            )
        if dtype is None:
            raise ReshardError(f"leaf {key}: found in no shard")
        nbytes = int(np.prod(want.shape or (1,))) * dtype.itemsize
        plans.append(
            _LeafPlan(
                key=key,
                dtype=dtype,
                shape=want.shape,
                reads=reads,
                nbytes=nbytes,
            )
        )
    return step, plans


def stream_resharded_leaves(
    ckpt_dir: str,
    target_layouts: Dict[str, Dict],
    storage=None,
    shards: Optional[List[ShardInfo]] = None,
):
    """Generator mirroring ``ckpt_shm.stream_shard_leaves`` for a
    WORLD-CHANGED restore: yields ``("meta", step, specs, layouts)``
    first, then ``("leaf", key, ndarray)`` as each leaf's overlap
    reads complete.  Each leaf owns one freshly-allocated private
    buffer; only the overlapping byte ranges ever cross the storage
    boundary.  File handles are opened once per source shard and
    shared across leaves."""
    if shards is None:
        shards = scan_checkpoint_shards(ckpt_dir, storage)
    step, plans = plan_reshard(shards, target_layouts)
    specs = [
        (p.key, str(p.dtype), p.shape, 0, p.nbytes) for p in plans
    ]
    layouts = {
        k: (
            v.as_dict() if isinstance(v, LeafLayout) else dict(v)
        )
        for k, v in target_layouts.items()
    }
    yield "meta", step, specs, layouts
    handles: Dict[str, object] = {}
    chunk = parallel_io.chunk_nbytes()
    try:
        for plan in plans:
            dst = np.empty(plan.shape, dtype=plan.dtype)
            mv = memoryview(dst.reshape(-1).view(np.uint8))
            for path, runs in plan.reads:
                f = handles.get(path)
                if f is None:
                    f = (
                        storage.open_read(path)
                        if storage is not None
                        else open(path, "rb")
                    )
                    handles[path] = f
                for src_off, dst_off, nb in runs:
                    f.seek(src_off)
                    filled = 0
                    while filled < nb:
                        want = min(chunk, nb - filled)
                        view = mv[
                            dst_off + filled : dst_off + filled + want
                        ]
                        if hasattr(f, "readinto"):
                            got = f.readinto(view)
                        else:  # buffered remote reader
                            data = f.read(want)
                            got = len(data)
                            if got:
                                view[:got] = data
                        if not got:
                            raise ReshardError(
                                f"short read in {path} at "
                                f"{src_off + filled}"
                            )
                        filled += got
            yield "leaf", plan.key, dst
    finally:
        for f in handles.values():
            try:
                f.close()
            except Exception:  # noqa: BLE001
                pass


def checkpoint_world_size(shards: Sequence[ShardInfo]) -> int:
    """The world that WROTE a checkpoint (max shard rank + 1)."""
    return max((s.rank for s in shards), default=-1) + 1


def layouts_equal(a: Optional[Dict], b: Optional[Dict]) -> bool:
    """Whether two layout dicts describe the same placement (the gate
    for 'this shard/snapshot already matches what the restore wants —
    read it directly, no reshard')."""
    if a is None or b is None:
        return False
    if set(a) != set(b):
        return False
    for key in a:
        la = a[key] if isinstance(a[key], dict) else a[key].as_dict()
        lb = b[key] if isinstance(b[key], dict) else b[key].as_dict()
        if (
            list(la["global_shape"]) != list(lb["global_shape"])
            or list(la["start"]) != list(lb["start"])
            or list(la["shape"]) != list(lb["shape"])
        ):
            return False
    return True
