"""``dlrover-tpu-run`` — the elastic launcher CLI (torchrun analog).

Reference parity: ``dlrover/trainer/torch/elastic_run.py`` —
``parse_args:125``, auto-launch of a local master on the rank-0 node
``:245``, reachability check + standalone fallback ``:335``, ``run:351``
and ``main:399``.

Usage::

    python -m dlrover_tpu.run --nnodes=1:4 --nproc_per_node=1 \
        [--network-check] [--max-restarts=3] train.py --flag ...

The launcher starts (on node rank 0, when no master address is set) a
local job master subprocess, then runs the per-node
``ElasticTrainingAgent`` which spawns/monitors ``nproc_per_node``
training processes wired up for ``jax.distributed.initialize``.
"""

import argparse
import os
import re
import subprocess
import sys
import time
from typing import List, Optional, Tuple

from dlrover_tpu.agent.training import (
    ElasticLaunchConfig,
    launch_agent,
)
from dlrover_tpu.common.comm import addr_connectable, wait_channel_ready
from dlrover_tpu.common.constants import NodeEnv
from dlrover_tpu.common.env import control_longpoll_enabled, get_free_port
from dlrover_tpu.common.log import default_logger as logger


def parse_nnodes(value: str) -> Tuple[int, int]:
    if ":" in value:
        lo, hi = value.split(":", 1)
        return int(lo), int(hi)
    n = int(value)
    return n, n


def parse_args(argv=None):
    parser = argparse.ArgumentParser(
        prog="dlrover-tpu-run", description="elastic TPU training launcher"
    )
    parser.add_argument(
        "--nnodes", default="1", help="N or MIN:MAX node range"
    )
    parser.add_argument("--nproc_per_node", type=int, default=1)
    parser.add_argument(
        "--master_addr",
        default="",
        help="job master host:port; empty = auto (env, then local spawn)",
    )
    parser.add_argument("--node_rank", type=int, default=-1)
    parser.add_argument("--max_restarts", type=int, default=3)
    parser.add_argument("--node_unit", type=int, default=1)
    parser.add_argument("--rdzv_timeout", type=int, default=600)
    parser.add_argument(
        "--rdzv_waiting_timeout", type=float, default=-1.0,
        help="master window rule: seconds after the last join before "
        "an under-max round completes with what it has (<0 = "
        "rdzv_timeout); shorten for fast elastic re-mesh after a "
        "preemption without shrinking the join wait",
    )
    parser.add_argument("--monitor_interval", type=float, default=3.0)
    parser.add_argument(
        "--stop_timeout", type=float, default=15.0,
        help="SIGTERM->SIGKILL grace when stopping workers; workers "
        "blocked in collectives always eat the full grace, so this "
        "bounds restart latency",
    )
    parser.add_argument(
        "--failure_stop_timeout", type=float, default=1.0,
        help="shorter grace used when restarting after a worker "
        "FAILURE (the group is already broken; survivors are wedged "
        "in collectives and the shm ckpt is flushed agent-side)",
    )
    parser.add_argument(
        "--prefork",
        action="store_true",
        help="fork restarted workers from a pre-imported zygote "
        "(removes the Python/jax import chain from restart latency)",
    )
    parser.add_argument(
        "--no_restart_overlap",
        action="store_true",
        help="disable the overlapped restart critical path (restore "
        "prefetch + background AOT compile; trainer/restart_path.py) "
        "— workers then run the serial restore->compile order "
        "(exports DLROVER_TPU_RESTART_OVERLAP=0)",
    )
    parser.add_argument(
        "--network-check",
        "--network_check",
        dest="network_check",
        action="store_true",
        help="run a chip/ICI health check round before training",
    )
    parser.add_argument(
        "--standalone",
        action="store_true",
        help="single-node without any master (plain spawn)",
    )
    parser.add_argument(
        "--compile_cache_dir",
        default=os.getenv("JAX_COMPILATION_CACHE_DIR", ""),
        help="persistent XLA compile cache (keeps restarts cheap)",
    )
    parser.add_argument(
        "--events_file",
        default=os.getenv("DLROVER_TPU_EVENTS_FILE", ""),
        help="node-local JSONL timeline every process appends to "
        "(spans: step/compile/rendezvous/checkpoint/restart...); the "
        "agent ships it to the master's goodput ledger",
    )
    # torchrun-style: with -m/--module the positional IS the module
    # name; the required positional keeps REMAINDER working for
    # option-like script/module args, and a "-m" token after the
    # script stays in REMAINDER (belongs to the script).
    parser.add_argument(
        "-m",
        "--module",
        dest="module",
        action="store_true",
        help="treat the entrypoint as 'python -m MODULE'",
    )
    parser.add_argument(
        "training_script", help="training script path (or module with -m)"
    )
    parser.add_argument(
        "training_script_args", nargs=argparse.REMAINDER
    )
    return parser.parse_args(argv)


def _launch_local_master(node_num: int) -> Tuple[subprocess.Popen, str]:
    """Spawn ``python -m dlrover_tpu.master.main`` and parse its address
    line (reference ``_launch_dlrover_local_master`` ``elastic_run.py:245``)."""
    port = get_free_port()
    proc = subprocess.Popen(  # noqa: S603
        [
            sys.executable,
            "-m",
            "dlrover_tpu.master.main",
            "--platform",
            "local",
            "--port",
            str(port),
            "--node_num",
            str(node_num),
        ],
        stdout=subprocess.PIPE,
        stderr=None,
        text=True,
    )
    addr = f"127.0.0.1:{port}"
    deadline = time.time() + 30
    # trailing whitespace required: a 4096-byte read chunk can split
    # the line mid-address and \S+ would happily capture the prefix
    # (e.g. '127.0' instead of '127.0.0.1:8080')
    pattern = re.compile(rb"DLROVER_TPU_MASTER_ADDR=(\S+)\s")
    # non-blocking reads on the RAW fd: a live master that never prints
    # the address line must not hang the launcher past the deadline
    # (the pre-computed 127.0.0.1:port stays the fallback).  select on
    # the raw fd + os.read avoids both TextIOWrapper buffering (a line
    # already buffered would never wake select) and readline blocking
    # on a partial line.
    import select as _select

    fd = proc.stdout.fileno()
    buf = b""
    while time.time() < deadline:
        if proc.poll() is not None:
            raise RuntimeError("local master exited during startup")
        readable, _, _ = _select.select([fd], [], [], 0.5)
        if not readable:
            continue
        chunk = os.read(fd, 4096)
        if not chunk:
            # EOF: stdout closed without the address line; select would
            # report the fd readable forever — fall back to the
            # precomputed address instead of hot-spinning
            break
        buf += chunk
        m = pattern.search(buf)
        if m:
            addr = m.group(1).decode()
            break
    # stop consuming stdout; master logs go to stderr
    return proc, addr


def _wait_master(addr: str, timeout: float = 60.0) -> bool:
    """Wait for the master's gRPC port to come up.  Default: park on
    grpc's channel-ready future (its own reconnect backoff drives the
    probing); ``DLROVER_TPU_CONTROL_LONGPOLL=0`` restores the 0.5 s
    TCP-connect polling loop."""
    if control_longpoll_enabled():
        return wait_channel_ready(addr, timeout=timeout)
    deadline = time.time() + timeout
    while time.time() < deadline:
        if addr_connectable(addr):
            return True
        time.sleep(0.5)
    return False


def _build_entrypoint(args) -> List[str]:
    script_args = list(args.training_script_args)
    if args.module:
        return [
            sys.executable, "-m", args.training_script, *script_args
        ]
    return [sys.executable, args.training_script, *script_args]


def run(args) -> int:
    min_nodes, max_nodes = parse_nnodes(args.nnodes)
    node_rank = args.node_rank
    if node_rank < 0:
        node_rank = int(os.getenv(NodeEnv.NODE_RANK, "0"))

    entrypoint = _build_entrypoint(args)

    if args.events_file:
        # exported BEFORE any spawn so the master, the agent, and every
        # training process append to the same node-local timeline
        os.environ["DLROVER_TPU_EVENTS_FILE"] = os.path.abspath(
            args.events_file
        )

    if args.standalone:
        # no master / agent: spawn procs directly with local coordinator
        return _run_standalone(args, entrypoint)

    master_addr = args.master_addr or os.getenv(NodeEnv.MASTER_ADDR, "")
    master_proc: Optional[subprocess.Popen] = None
    if not master_addr:
        if node_rank != 0:
            raise SystemExit(
                "no master address: set --master_addr or "
                f"${NodeEnv.MASTER_ADDR} on non-zero node ranks"
            )
        master_proc, master_addr = _launch_local_master(max_nodes)
        logger.info("launched local master at %s", master_addr)
    if not _wait_master(master_addr):
        raise SystemExit(f"master at {master_addr} is unreachable")

    os.environ[NodeEnv.MASTER_ADDR] = master_addr
    os.environ[NodeEnv.NODE_RANK] = str(node_rank)

    config = ElasticLaunchConfig(
        min_nodes=min_nodes,
        max_nodes=max_nodes,
        nproc_per_node=args.nproc_per_node,
        rdzv_timeout=args.rdzv_timeout,
        rdzv_waiting_timeout=args.rdzv_waiting_timeout,
        node_unit=args.node_unit,
        network_check=args.network_check,
        max_restarts=args.max_restarts,
        monitor_interval=args.monitor_interval,
        stop_timeout=args.stop_timeout,
        failure_stop_timeout=args.failure_stop_timeout,
        prefork=args.prefork,
        node_rank=node_rank,
        compile_cache_dir=args.compile_cache_dir,
        restart_overlap=not args.no_restart_overlap,
    )
    from dlrover_tpu.observability.events import get_event_logger

    events = get_event_logger()
    events.instant(
        "job_start",
        nnodes=args.nnodes,
        nproc_per_node=args.nproc_per_node,
        node_rank=node_rank,
    )
    rc = 1
    try:
        rc = launch_agent(config, entrypoint, master_addr)
        return rc
    finally:
        events.instant("job_end", exit_code=rc)
        if master_proc is not None and master_proc.poll() is None:
            master_proc.terminate()
            try:
                master_proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                master_proc.kill()


def _run_standalone(args, entrypoint: List[str]) -> int:
    """Plain local spawn without elasticity (reference falls back to
    vanilla torchrun — ``elastic_run.py:335``)."""
    nproc = args.nproc_per_node
    coord = f"127.0.0.1:{get_free_port()}"
    procs = []
    for rank in range(nproc):
        env = dict(os.environ)
        env.update(
            {
                NodeEnv.PROCESS_RANK: str(rank),
                NodeEnv.PROCESS_COUNT: str(nproc),
                NodeEnv.LOCAL_RANK: str(rank),
                NodeEnv.LOCAL_PROCESS_COUNT: str(nproc),
                NodeEnv.COORDINATOR_ADDR: coord,
            }
        )
        procs.append(subprocess.Popen(entrypoint, env=env))  # noqa: S603
    rc = 0
    for proc in procs:
        rc = proc.wait() or rc
    return rc


def main(argv=None) -> int:
    return run(parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
